"""Elastic fleet: SLO-burn-driven autoscaler, preemptible members,
scale-to-zero (fleet/autoscaler.py behind --autoscale).

The elasticity contract under test: the fleet grows one member at a
time on SUSTAINED SLO burn or backlog, shrinks only by drain ->
migrate-off -> retire (never a kill, streams stay byte-identical), a
preemption notice on a spot member costs zero dropped streams, the bulk
tier may scale to zero with its queued work PARKED at the router until
the pending-work signal wakes it, and an oscillating load produces ZERO
scale events — all journaled (scale_up / scale_down / preempt_notice)
and audited by tools/journal.py's scale-pairing checker.
"""

import asyncio
import dataclasses
import json
import time
import types

import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.engine.health import HealthMonitor
from ollamamq_tpu.fleet import FleetRouter, LocalMember
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.telemetry import schema as tm
from ollamamq_tpu.telemetry.slo import AlertManager
from ollamamq_tpu.testing.faults import FaultPlan
from ollamamq_tpu.tools.journal import (check_no_dropped_streams,
                                        check_scale_pairing)
from testutil import collect

TINY = dict(model="test-tiny", max_slots=4, num_pages=64, page_size=8,
            max_pages_per_seq=8, prefill_buckets=(16, 32),
            decode_steps_per_iter=2)

FAST = dict(probe_period_s=0.05, eject_heartbeat_s=5.0,
            reprobe_backoff_s=0.1, evac_grace_s=1.0)

# One fast burn window so an untiered fleet's own TTFT objective fires
# within a test's patience: (label, long_s, short_s, factor, severity).
# Legs stay >= 2s: the objective counts in one-second buckets, so a
# sub-second leg would flicker empty depending on the clock's fraction.
FAST_WINDOWS = (("fast", 5.0, 2.0, 1.0, "page"),)

# Tight hysteresis for the scaling tests; the anti-flap test overrides
# with deliberately LARGE windows.
FAST_SCALE = dict(tick_period_s=0.02, cooldown_s=0.2, sustain_s=0.05,
                  idle_sustain_s=0.15, windows=FAST_WINDOWS)


def _elastic_fleet(n=1, tiers=None, token_latency_s=0.0, plan=None,
                   autoscale_kw=None, router_kw=None, **ecfg_over):
    """Fleet with --autoscale on and factory-bearing members, so the
    router's LocalProvisioner fallback can grow it."""
    cfg = dict(TINY)
    cfg.setdefault("autoscale", True)
    cfg.setdefault("min_replicas", 1)
    cfg.setdefault("max_replicas", 4)
    cfg.update(ecfg_over)
    ecfg = EngineConfig(fault_plan=plan, tiers=tiers, **cfg)
    member_cfg = dataclasses.replace(ecfg, fault_plan=None, max_queued=0,
                                     max_queued_per_user=0, tiers=None,
                                     autoscale=False)

    def mkfactory():
        def build(tp=None):
            mcfg = (member_cfg if tp in (None, member_cfg.tp)
                    else dataclasses.replace(member_cfg, tp=tp))
            return FakeEngine(mcfg, blocklist_path=None,
                              token_latency_s=token_latency_s)
        return build

    members = []
    for i in range(n):
        f = mkfactory()
        members.append(LocalMember(f"r{i}", f(), engine_factory=f))
    kw = dict(FAST)
    kw.update(router_kw or {})
    akw = dict(FAST_SCALE)
    akw.update(autoscale_kw or {})
    router = FleetRouter(members, ecfg, blocklist_path=None, tiers=tiers,
                         tiering_kw=dict(balance=False) if tiers else None,
                         autoscale_kw=akw, **kw)
    router.start()
    return router


def _run(router, user, prompt="the quick brown fox jumps over",
         max_tokens=8, deadline_ms=None):
    from ollamamq_tpu.engine.tokenizer import ByteTokenizer

    tokens = ByteTokenizer().encode(prompt)
    sp = SamplingParams(max_tokens=max_tokens)
    if deadline_ms is not None:
        sp.deadline_ms = deadline_ms
    return router.enqueue_request(user, "", "test-tiny",
                                  prompt_tokens=tokens, sampling=sp,
                                  raw_prompt=prompt)


def _text(items):
    return "".join(i.text for i in items if i.kind == "token")


def _wait(pred, budget=30.0, period=0.01):
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(period)
    return False


def _scale_recs(router, kind):
    return router.journal.tail(None, kind=kind)


# --------------------------------------------------------- burn scale-up
def test_burn_driven_scale_up_adds_member_e2e():
    """Sustained TTFT burn on an untiered fleet provisions ONE new
    member (a0) through the LocalProvisioner; the join is journaled as
    a paired scale_up start -> done plus a replica_join, the metric
    counts it, and the new member serves traffic."""
    up_before = tm.FLEET_SCALE_EVENTS_TOTAL.labels(
        direction="up", outcome="done").value
    # slo_ttft_ms microscopically small: every request violates, so the
    # objective burns at ~100x (target 0.99) over both window legs.
    router = _elastic_fleet(n=1, max_replicas=2, slo_ttft_ms=0.0001,
                            token_latency_s=0.01)
    try:
        # A trickle of violating requests keeps the burn lit while the
        # sustain window (0.05s) and the scaler's tick both elapse.
        deadline = time.monotonic() + 30
        i = 0
        while len(router.members) < 2 and time.monotonic() < deadline:
            req = _run(router, f"burn{i}", max_tokens=2)
            assert collect(req)[-1].kind == "done"
            i += 1
        assert len(router.members) == 2
        assert [m.name for m in router.members] == ["r0", "a0"]
        # The provisioned member went through start() and serves.
        assert _wait(lambda: router.fleet_counts()["healthy"] == 2)
        recs = _scale_recs(router, "scale_up")
        start = next(r for r in recs if r["phase"] == "start")
        done = next(r for r in recs if r["phase"] == "done")
        assert start["replica"] == done["replica"] == "a0"
        assert start["why"] == "burn"
        assert start["queued"] is not None
        assert done["spawn_ms"] >= 0
        joins = router.journal.tail(None, kind="replica_join")
        assert any(r["replica"] == "a0" and r["why"] == "scale_up"
                   for r in joins)
        assert tm.FLEET_SCALE_EVENTS_TOTAL.labels(
            direction="up", outcome="done").value == up_before + 1
        # Ceiling respected: max_replicas=2 means no further growth
        # while the burn keeps firing (going idle afterwards would
        # legitimately shrink the fleet back to the floor).
        for j in range(14):
            collect(_run(router, f"post{j}", max_tokens=2))
        assert len(router.members) == 2
        assert check_scale_pairing(router.journal.tail(None)) == []
        # The new member lands in the fleet status surface.
        st = router.fleet_status()
        assert st["autoscaler"]["fleet"] == 2
        assert any(r["name"] == "a0" for r in st["replicas"])
    finally:
        router.stop()


# ------------------------------------------------------- idle scale-down
def test_idle_scale_down_drains_and_migrates_byte_identical():
    """An idle 2-member fleet (floor 1) retires one member by drain ->
    migrate-off; a stream caught mid-decode on the victim continues on
    the survivor BYTE-IDENTICAL, and the retire journals as a paired
    scale_down start -> done with why="idle"."""
    router = _elastic_fleet(n=2, min_replicas=1, max_replicas=2,
                            slo_ttft_ms=60_000.0, token_latency_s=0.05)
    try:
        # Reference text from a completed stream (FakeEngine output is
        # deterministic per token count).
        ref = _text(collect(_run(router, "ref", max_tokens=30)))
        # Two long streams spread over both members; load (2) is within
        # the survivor's half-capacity low-water mark (1*4*0.5), so the
        # idle rule fires mid-decode and the victim's stream migrates.
        reqs = [_run(router, f"long{i}", max_tokens=30) for i in range(2)]
        assert _wait(lambda: len(router.members) == 1, budget=30)
        for r in reqs:
            items = collect(r)
            assert items[-1].kind == "done"
            assert _text(items) == ref
        recs = _scale_recs(router, "scale_down")
        start = next(r for r in recs if r["phase"] == "start")
        done = next(r for r in recs if r["phase"] == "done")
        assert start["replica"] == done["replica"]
        assert start["why"] == "idle"
        assert done["fleet"] == 1
        assert check_no_dropped_streams(router.journal.tail(None)) == []
        assert check_scale_pairing(router.journal.tail(None)) == []
        # Floor respected: the last member never retires, however idle.
        time.sleep(0.5)
        assert len(router.members) == 1
    finally:
        router.stop()


# ------------------------------------------------------ preemption notice
def test_preemption_notice_chaos_mid_decode_zero_drops():
    """faults.py site "preempt" serves r1 (flagged --preemptible) a
    termination notice mid-decode on a FIXED fleet (no autoscaler —
    preemption is a router capability): the member migrates its streams
    off and retires within the window; zero drops, byte-identical
    continuations, journal carries preempt_notice + paired scale_down
    why="preempt"."""
    pre_before = tm.FLEET_PREEMPTIONS_TOTAL.value
    # Draws number 1.. per probe sweep over 2 members: even draws land
    # on r1. at=[6] fires on sweep 3 (~0.2s in) — streams are mid-decode.
    plan = FaultPlan([{"site": "preempt", "kind": "exception", "at": [6]}])
    cfg = dict(TINY)
    ecfg = EngineConfig(fault_plan=plan, preemptible="r1", **cfg)
    member_cfg = dataclasses.replace(ecfg, fault_plan=None, max_queued=0,
                                     max_queued_per_user=0)
    members = [LocalMember(f"r{i}",
                           FakeEngine(member_cfg, blocklist_path=None,
                                      token_latency_s=0.05))
               for i in range(2)]
    router = FleetRouter(members, ecfg, blocklist_path=None, **FAST)
    router.start()
    try:
        assert router.members[1].preemptible is True
        ref = _text(collect(_run(router, "ref", max_tokens=24)))
        reqs = [_run(router, f"p{i}", max_tokens=24) for i in range(4)]
        assert _wait(lambda: len(router.members) == 1, budget=30)
        assert [m.name for m in router.members] == ["r0"]
        for r in reqs:
            items = collect(r)
            assert items[-1].kind == "done"
            assert _text(items) == ref
        notice = router.journal.tail(None, kind="preempt_notice")[-1]
        assert notice["replica"] == "r1"
        assert notice["notice_s"] > 0
        recs = _scale_recs(router, "scale_down")
        start = next(r for r in recs if r["phase"] == "start")
        assert (start["replica"], start["why"]) == ("r1", "preempt")
        assert any(r["phase"] == "done" and r["replica"] == "r1"
                   for r in recs)
        assert tm.FLEET_PREEMPTIONS_TOTAL.value == pre_before + 1
        assert check_no_dropped_streams(router.journal.tail(None)) == []
        assert check_scale_pairing(router.journal.tail(None)) == []
    finally:
        router.stop()


def test_preempt_requires_preemptible_flag():
    cfg = dict(TINY)
    ecfg = EngineConfig(**cfg)
    member_cfg = dataclasses.replace(ecfg, max_queued=0,
                                     max_queued_per_user=0)
    members = [LocalMember(f"r{i}",
                           FakeEngine(member_cfg, blocklist_path=None))
               for i in range(2)]
    router = FleetRouter(members, ecfg, blocklist_path=None, **FAST)
    router.start()
    try:
        with pytest.raises(ValueError):
            router.preempt_replica("r0")
        with pytest.raises(KeyError):
            router.preempt_replica("nope")
    finally:
        router.stop()


# -------------------------------------------------- scale-to-zero / wake
def test_scale_to_zero_parks_and_wakes_over_http():
    """The bulk tier idles to ZERO members; queued bulk work parks at
    the router (503 Retry-After covers the wake+spawn time) and the
    pending-work signal wakes the tier — bypassing cooldown — so the
    parked stream completes. Interactive keeps its --min-replicas
    floor throughout."""
    from aiohttp.test_utils import TestClient, TestServer

    from ollamamq_tpu.server.app import Server

    router = _elastic_fleet(
        n=2, tiers="interactive=r0;bulk=r1", min_replicas=1,
        max_replicas=3, slo_ttft_ms=60_000.0, token_latency_s=0.02)
    try:
        # Phase A: nothing queued -> bulk (floor 0) drains to zero;
        # interactive (floor 1) never shrinks.
        assert _wait(lambda: router.tiers.scaled_to_zero == {"bulk"},
                     budget=30)
        assert [m.name for m in router.members] == ["r0"]
        down = _scale_recs(router, "scale_down")[-1]
        assert (down["replica"], down["tier"]) == ("r1", "bulk")
        # Retry-After for the parked tier accounts for wake + spawn.
        wake = router.autoscaler.wake_wait_s()
        assert wake > 0
        assert router.retry_after_s() >= wake

        # Phase B: a bulk request over HTTP parks, wakes the tier, and
        # streams to completion on the woken member.
        async def main():
            cl = TestClient(
                TestServer(Server(router, timeout_s=60).build_app()))
            await cl.start_server()
            try:
                texts = []
                async with cl.post("/api/generate", json={
                        "model": "test-tiny", "prompt": "wake up",
                        "options": {"num_predict": 6}},
                        headers={"X-User-ID": "bulkuser"}) as resp:
                    assert resp.status == 200
                    async for line in resp.content:
                        if not line.strip():
                            continue
                        obj = json.loads(line)
                        texts.append(obj.get("response", ""))
                        if obj.get("done"):
                            assert obj["done_reason"] in ("length",
                                                          "stop")
                return "".join(texts)
            finally:
                await cl.close()

        text = asyncio.new_event_loop().run_until_complete(main())
        assert text.startswith("word0 word1 ")
        ups = _scale_recs(router, "scale_up")
        wake_start = next(r for r in ups if r["phase"] == "start")
        assert (wake_start["why"], wake_start["tier"]) == ("wake", "bulk")
        assert any(r["phase"] == "done" for r in ups)
        assert "bulk" not in router.tiers.scaled_to_zero
        woken = next(m for m in router.members if m.name == "a0")
        assert woken.tier == "bulk"
        assert check_scale_pairing(router.journal.tail(None)) == []
    finally:
        router.stop()


# -------------------------------------------------------------- anti-flap
def test_oscillating_load_produces_zero_scale_events():
    """Hysteresis: bursts shorter than the sustain window, separated by
    idle gaps shorter than the idle window, must produce ZERO scale
    events in either direction — the one-knob cooldown discipline."""
    router = _elastic_fleet(
        n=2, min_replicas=1, max_replicas=3, slo_ttft_ms=0.0001,
        token_latency_s=0.01,
        autoscale_kw=dict(tick_period_s=0.02, cooldown_s=30.0,
                          sustain_s=10.0, idle_sustain_s=30.0,
                          windows=FAST_WINDOWS))
    try:
        for burst in range(3):
            # Burn fires (every TTFT violates) + backlog spikes past
            # backlog_high for a moment...
            reqs = [_run(router, f"o{burst}-{i}", max_tokens=2)
                    for i in range(6)]
            for r in reqs:
                assert collect(r)[-1].kind == "done"
            # ...then the fleet goes fully idle for a moment.
            time.sleep(0.15)
        assert len(router.members) == 2
        assert _scale_recs(router, "scale_up") == []
        assert _scale_recs(router, "scale_down") == []
    finally:
        router.stop()


# -------------------------------------------------------- CLI validation
def test_cli_autoscale_validation_fails_fast():
    from ollamamq_tpu.cli import main

    base = ["--no-tui", "--replicas", "2"]
    assert main(base + ["--autoscale", "--min-replicas", "0"]) == 2
    assert main(base + ["--autoscale", "--min-replicas", "3",
                        "--max-replicas", "2"]) == 2
    assert main(base + ["--autoscale", "--scale-cooldown-s", "0"]) == 2
    # Starting fleet larger than the ceiling.
    assert main(["--no-tui", "--replicas", "5", "--autoscale",
                 "--max-replicas", "4"]) == 2
    # Preemptible flags: unknown member name; no fleet to flag.
    assert main(base + ["--preemptible", "r5"]) == 2
    assert main(["--no-tui", "--preemptible", "r0"]) == 2


# ------------------------------------------------- scale_storm watchdog
def test_scale_storm_watchdog_fires_and_resolves():
    """health.py scale_storm: a flapping autoscaler (rate above
    SCALE_STORM_PER_MIN) fires the warn alert and counts ONE
    ollamamq_watchdog_stalls_total{kind="scale"} per firing transition;
    the alert resolves when the rate drops."""
    rate = {"v": 12.0}
    stub = types.SimpleNamespace(
        alerts=AlertManager(),
        autoscaler=types.SimpleNamespace(
            scale_rate_per_min=lambda: rate["v"]))
    mon = HealthMonitor(stub)
    before = tm.WATCHDOG_STALLS_TOTAL.labels(kind="scale").value
    mon._check_scale_storm()
    assert any(a.name == "scale_storm" for a in stub.alerts.active())
    assert tm.WATCHDOG_STALLS_TOTAL.labels(
        kind="scale").value == before + 1
    # Still firing: no double count.
    mon._check_scale_storm()
    assert tm.WATCHDOG_STALLS_TOTAL.labels(
        kind="scale").value == before + 1
    rate["v"] = 0.0
    mon._check_scale_storm()
    assert not any(a.name == "scale_storm"
                   for a in stub.alerts.active())
    # A non-elastic engine (no .autoscaler) is a clean no-op.
    HealthMonitor(types.SimpleNamespace(
        alerts=AlertManager()))._check_scale_storm()


# ------------------------------------------------- journal scale pairing
def test_check_scale_pairing_rules():
    def rec(kind, rep, seq, **kw):
        return {"kind": kind, "replica": rep, "seq": seq, **kw}

    # Paired up + paired down + resolved notice: clean.
    ok = [
        rec("scale_up", "a0", 1, phase="start"),
        rec("scale_up", "a0", 2, phase="done"),
        rec("preempt_notice", "r1", 3),
        rec("scale_down", "r1", 4, phase="start"),
        rec("scale_down", "r1", 5, phase="done"),
        rec("scale_up", "a1", 6, phase="start"),
        rec("scale_up", "a1", 7, phase="aborted"),
    ]
    assert check_scale_pairing(ok) == []
    # Hanging scale_up start.
    bad = check_scale_pairing([rec("scale_up", "a0", 1, phase="start")])
    assert len(bad) == 1 and "UNRESOLVED" in bad[0]
    # A notice the fleet never acted on (window lapsed, member serving).
    bad = check_scale_pairing([rec("preempt_notice", "r1", 1)])
    assert len(bad) == 1 and "r1" in bad[0]
    # Double start for the same (direction, replica).
    bad = check_scale_pairing([
        rec("scale_down", "r0", 1, phase="start"),
        rec("scale_down", "r0", 2, phase="start"),
        rec("scale_down", "r0", 3, phase="done"),
    ])
    assert len(bad) == 1 and "never resolved" in bad[0]
    # A bare resolution (spill ring tail) is tolerated.
    assert check_scale_pairing(
        [rec("scale_down", "r0", 9, phase="done")]) == []


def test_subprocess_provisioner_scrubs_router_env(monkeypatch):
    # A provisioned member is a plain single-engine server. Router-level
    # env leaking into it is fatal (TIERS without a fleet fail-fasts the
    # child CLI) or corrupting (a shared JOURNAL_FILE / WAL_DIR has two
    # processes appending to one log), so the provisioner must scrub it
    # the same way the in-process path strips member_cfg fields.
    from ollamamq_tpu.fleet.autoscaler import SubprocessProvisioner

    monkeypatch.setenv("TIERS", "interactive=r0;bulk=r1")
    monkeypatch.setenv("AUTOSCALE", "true")
    monkeypatch.setenv("REPLICAS", "2")
    monkeypatch.setenv("JOURNAL_FILE", "/tmp/router-spill.jsonl")
    monkeypatch.setenv("MODELS", "test-tiny")
    prov = SubprocessProvisioner(["--fake-engine"],
                                 env={"JAX_PLATFORMS": "cpu"})
    env = prov.child_env()
    for key in ("TIERS", "AUTOSCALE", "REPLICAS", "JOURNAL_FILE"):
        assert key not in env
    assert env["MODELS"] == "test-tiny"      # member config still rides
    assert env["JAX_PLATFORMS"] == "cpu"     # explicit overlay wins
