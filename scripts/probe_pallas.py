"""Iterate the Pallas decode kernel against the real TPU's Mosaic
compiler: AOT-compile (no execution, no donation) at the bench shapes,
then optionally execute and cross-check numerics vs the jnp reference
path. Usage: python scripts/probe_pallas.py [--run]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--run", action="store_true",
                   help="execute + compare against the jnp reference")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--heads", type=int, default=32)
    p.add_argument("--kv-heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-pages", type=int, default=26)
    p.add_argument("--bench", action="store_true",
                   help="time pallas vs jnp attention at these shapes")
    args = p.parse_args()

    from ollamamq_tpu.ops.attention import paged_decode_attention
    from ollamamq_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas,
    )

    B, H, Hk, hd = args.batch, args.heads, args.kv_heads, args.head_dim
    ps, MP = args.page_size, args.max_pages
    S = B * MP + 2  # slot pool incl. trash page

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, hd)), jnp.bfloat16)
    kc = jnp.asarray(rng.standard_normal((S * ps, Hk, hd)), jnp.bfloat16)
    vc = jnp.asarray(rng.standard_normal((S * ps, Hk, hd)), jnp.bfloat16)
    # Ragged lengths; page tables point at disjoint pages (page 0 = trash).
    seq_lens = jnp.asarray(rng.integers(1, MP * ps, size=(B,)), jnp.int32)
    pt = np.zeros((B, MP), np.int32)
    next_page = 1
    for b in range(B):
        n = -(-int(seq_lens[b]) // ps)
        for i in range(n):
            pt[b, i] = next_page
            next_page += 1
    pt = jnp.asarray(pt)

    t0 = time.monotonic()
    lowered = jax.jit(
        lambda q, kc, vc, pt, sl: paged_decode_attention_pallas(
            q, kc, vc, pt, sl, page_size=ps
        )
    ).lower(q, kc, vc, pt, seq_lens)
    compiled = lowered.compile()
    print(f"COMPILE OK in {time.monotonic() - t0:.1f}s", flush=True)

    if args.run or args.bench:
        t0 = time.monotonic()
        out = np.asarray(compiled(q, kc, vc, pt, seq_lens))
        print(f"RUN OK in {time.monotonic() - t0:.2f}s", flush=True)
        ref = np.asarray(
            paged_decode_attention(q, kc, vc, pt, seq_lens, page_size=ps)
        )
        err = np.abs(out.astype(np.float32) - ref.astype(np.float32)).max()
        print(f"MAX ABS DIFF vs jnp: {err:.5f}", flush=True)
        if err > 0.1:
            print("NUMERIC MISMATCH", flush=True)
            return 1

    if args.bench:
        jref = jax.jit(
            lambda q, kc, vc, pt, sl: paged_decode_attention(
                q, kc, vc, pt, sl, page_size=ps
            )
        )
        np.asarray(jref(q, kc, vc, pt, seq_lens))
        for name, fn in (("pallas", compiled), ("jnp", jref)):
            # block_until_ready is NOT a reliable fence through the axon
            # tunnel; a device->host fetch of the result is. Chain the
            # timed calls on q so they cannot overlap-reorder, and fetch.
            qi = q
            t0 = time.monotonic()
            for _ in range(50):
                r = fn(qi, kc, vc, pt, seq_lens)
                qi = r
            np.asarray(r)
            dt = (time.monotonic() - t0) / 50
            print(f"{name}: {dt * 1e6:.0f} us/call", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
