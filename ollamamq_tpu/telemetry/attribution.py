"""Per-request latency attribution: where did this request's time go?

Builds phase timelines from the lifecycle span events the engine already
drops into each request's Trace (telemetry/tracing.py). Consecutive
events define contiguous spans — gapless by construction — so summing
the spans opened by each phase's events reconstructs the wall-clock
end-to-end latency EXACTLY (the /debug/requests/{id} contract: phases
sum to e2e within tolerance; the tolerance only absorbs float noise).

The phase vocabulary is deliberately small and closed: every event name
the engine emits maps to one of PHASES, and scripts/check_metrics_docs.py
pins this module's PHASES against the README phase table the same way it
pins the metric registry — no silently undocumented phase.

Stdlib-only, like the rest of telemetry: imported by the doc checker and
by worker hosts with no jax.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ollamamq_tpu.telemetry import schema as tm

# Canonical attribution phases, in lifecycle order. "other" catches spans
# opened by event names this table does not know — a nonzero "other" in a
# timeline means an engine event was added without updating EVENT_PHASE
# (and the doc gate makes that loud).
PHASES = (
    "queue",         # fair-share queue wait: enqueue/requeue -> admit
    "admission",     # scheduler placement + runtime pending queue
    "prefix_cache",  # prefix-cache lookup/pin on a cache-hit admission
    "prefill",       # prompt forward(s): batched, chunked, or sp
    "decode",        # token generation: first token -> finish
    "stream",        # stream-write stall: consumer not draining tokens
    "other",
)

# Event name -> phase of the span that event OPENS (the span lasts until
# the next event). Terminal events open no span.
EVENT_PHASE = {
    "enqueue": "queue",
    "requeue": "queue",
    "admit": "admission",
    "place": "admission",
    "prefix_hit": "prefix_cache",
    "prefill": "prefill",
    "prefill_chunk": "prefill",
    "embed_batch": "prefill",
    "first_token": "decode",
    "decode": "decode",
    "stream_stall": "stream",
    "stream_resume": "decode",
    # Graceful degradation: a preempted request heads back to the queue
    # (its recompute wait is queue time), a retry waits out its backoff
    # in the queue, and a page-starved slot holding its reservation is
    # still inside generation.
    "preempt": "queue",
    "retry": "queue",
    "kv_stall": "decode",
    # Fleet-router spans (tracing.ROUTER_EVENTS): a failover opens the
    # recompute-replay wait (queue time until the re-dispatch lands); an
    # overflow span is the cross-tier placement decision; a migration or
    # regroup evacuation happens mid-decode — the stream keeps decoding
    # on the target, so those spans stay in the decode phase.
    "failover": "queue",
    "overflow": "admission",
    "migrate": "decode",
    "regroup": "decode",
}

TERMINAL_EVENTS = ("stop", "length", "cancelled", "error",
                   "kv_exhausted", "deadline")


def phase_of(event_name: str) -> str:
    return EVENT_PHASE.get(event_name, "other")


def phase_totals(events: List[tuple], now: Optional[float] = None) -> Dict[str, float]:
    """Per-phase milliseconds from a trace's (name, t, args) event list.

    The span opened by event i is attributed to phase_of(events[i]) and
    closed by events[i+1]; for an unfinished trace the last event's span
    runs to `now`. Terminal events close the chain and open nothing, so
    sum(phase_totals.values()) == (end - events[0].t) exactly.
    """
    out: Dict[str, float] = {}
    if not events:
        return out
    for i, (name, t, _args) in enumerate(events):
        if name in TERMINAL_EVENTS:
            break
        if i + 1 < len(events):
            end = events[i + 1][1]
        elif now is not None:
            end = max(now, t)
        else:
            break  # unfinished trace and no "now": last span unknowable
        dur = (end - t) * 1e3
        if dur <= 0:
            continue
        ph = phase_of(name)
        out[ph] = out.get(ph, 0.0) + dur
    return out


def observe_phases(model: str, events: List[tuple]) -> None:
    """Fold a finished trace's phase totals into the
    ollamamq_request_phase_ms histogram (called by Tracer._finished)."""
    for phase, ms in phase_totals(events).items():
        tm.REQUEST_PHASE_MS.labels(model=model or "?", phase=phase).observe(ms)


def _outcome(events: List[tuple]) -> Optional[str]:
    if events and events[-1][0] in TERMINAL_EVENTS:
        return events[-1][0]
    return None


def timeline(trace, now: Optional[float] = None,
             include_events: bool = True) -> dict:
    """Full JSON-able timeline for one request (/debug/requests/{id}).

    `trace` is a telemetry.tracing.Trace; its events list is copied (the
    engine thread may still be appending). Timestamps are reported
    relative to the request's enqueue event, in milliseconds.
    """
    if now is None:
        now = time.monotonic()
    events = list(trace.events)
    outcome = _outcome(events)
    t0 = events[0][1] if events else now
    end = events[-1][1] if outcome is not None else now
    phases = phase_totals(events, now=now)
    out = {
        "req_id": trace.req_id,
        "user": trace.user,
        "model": trace.model,
        "kind": trace.kind,
        "state": outcome or "inflight",
        "e2e_ms": round((end - t0) * 1e3, 3),
        "phases_ms": {p: round(phases[p], 3) for p in PHASES if p in phases},
        "dropped_events": trace.dropped,
    }
    if outcome is None and events:
        last_name, last_t, _ = events[-1]
        out["current_phase"] = phase_of(last_name)
        out["phase_age_ms"] = round((now - last_t) * 1e3, 3)
    if include_events:
        out["events"] = [
            {"name": name, "t_ms": round((t - t0) * 1e3, 3),
             **({"args": args} if args else {})}
            for name, t, args in events
        ]
    return out


def summarize(tracer, recent: int = 50) -> dict:
    """Compact listing for GET /debug/requests: every in-flight request
    plus the most recent `recent` finished traces, newest first."""
    now = time.monotonic()
    inflight, finished = [], []
    for tr in tracer.traces():
        row = timeline(tr, now=now, include_events=False)
        (finished if tr.finished else inflight).append(row)
    finished.sort(key=lambda r: r["req_id"], reverse=True)
    return {"inflight": inflight, "recent": finished[:max(0, recent)]}
