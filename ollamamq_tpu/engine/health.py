"""Health monitor + stall watchdog: device liveness, engine progress,
stuck requests, stale SPMD workers — all raised as alerts.

The reference polls each backend every 10 s (GET /api/tags | /api/ps | /
— dispatcher.rs:261-387) and logs online/offline transitions. The TPU
analogue watches the things that can actually fail here:

  - device liveness: a trivial jitted op must complete within a deadline
    (a wedged TPU runtime/tunnel hangs rather than erroring);
  - engine-step progress: work exists but no token has been produced —
    or the engine loop's liveness tick has gone stale (a dispatch wedged
    INSIDE a step blocks the loop thread without erroring);
  - requests stuck in a phase: an in-flight trace whose last lifecycle
    event is older than the deadline (the phase it is stuck in reads
    straight off the attribution layer);
  - SPMD worker hosts whose KV-store heartbeats stopped advancing;
  - HBM headroom: page-pool exhaustion pressure.

Every detection raises a named alert through the engine's AlertManager
(telemetry/slo.py) — the same table the SLO burn-rate evaluator feeds —
so /health, /metrics (`ollamamq_slo_alerts_firing`), /debug/bundle, and
the TUI alerts panel all show one consistent picture. Transitions are
logged like the reference's "Backend ... is now ONLINE / OFFLINE".
"""

from __future__ import annotations

import logging
import threading
import time

from ollamamq_tpu.telemetry import schema as tm
from ollamamq_tpu.telemetry import stepprof
from ollamamq_tpu.telemetry.attribution import phase_of

log = logging.getLogger("ollamamq.health")

CHECK_PERIOD_S = 10.0  # reference cadence (dispatcher.rs:385)
DEVICE_DEADLINE_S = 30.0
STALL_DEADLINE_S = 30.0
# A request whose trace has not moved to a new lifecycle event in this
# long is stuck-in-phase. Generous: a long chunked prefill emits an event
# per chunk and a decode stream an event every 16 tokens, so any healthy
# request beats this by orders of magnitude.
REQUEST_STALL_S = 120.0
# Preemption-storm rule: occasional KV-pressure preemptions are the
# system degrading gracefully; this many per minute means the page pool
# is undersized for the live workload and recompute is eating throughput
# (alert "preempt_storm", resolves when the rate drops).
PREEMPT_STORM_PER_MIN = 30.0
PREEMPT_STORM_WINDOW_S = 60.0
# Regroup-storm rule (tiered fleets): each tier regroup costs a drain +
# stream migrations + an engine restart — a healthy balancer regroups
# occasionally as the class mix shifts; this many per minute means the
# hysteresis is mis-tuned (or the mix is adversarial) and the fleet is
# burning capacity on churn (alert "regroup_storm", resolves when the
# rate drops).
REGROUP_STORM_PER_MIN = 4.0
# Scale-storm rule (elastic fleets): the autoscaler's hysteresis exists
# so an oscillating load produces ZERO scale events — sustained churn
# above this rate means the cooldown/sustain windows are mis-tuned for
# the workload and the fleet is paying spawn + drain + migration costs
# in a loop (alert "scale_storm", resolves when the rate drops). Unlike
# the preempt/regroup storms this one counts into
# ollamamq_watchdog_stalls_total{kind="scale"}: a flapping scaler is a
# watchdog-grade malfunction, not graceful degradation.
SCALE_STORM_PER_MIN = 6.0
# Compile-storm rule (engine performance plane): the compile ladder
# front-loads its cost — every rung XLA-compiles exactly once during
# warmup, then the jit caches serve steady state for free. Recompiles
# still arriving at this rate past the warmup window mean the ladder is
# broken (unbounded shape keys, pallas-probe thrash, an injected
# `compile`-site eviction loop) and dispatches are paying seconds of
# XLA wall each (alert "compile_storm", resolves when the rate drops).
# Counts into ollamamq_watchdog_stalls_total{kind="compile"} like
# scale_storm: a malfunction to tune out, not pressure to absorb.
COMPILE_STORM_PER_MIN = 6.0
COMPILE_WARMUP_S = 120.0
# Router-HA rules (--ha primaries): a standby whose replication cursor
# trails the primary by more than this many records — or that stopped
# polling entirely — would lose that much admitted/progress state at
# takeover (alert "standby_lag", kind "standby"). And a promotion that
# has been in flight longer than this is wedged, not slow: recovery
# re-admission is hung while the fleet has no serving router (alert
# "takeover_stuck", kind "takeover").
STANDBY_LAG_ALERT_RECORDS = 2048
TAKEOVER_STUCK_S = 30.0


class HealthMonitor:
    def __init__(self, engine, period_s: float = CHECK_PERIOD_S,
                 stall_s: float | None = None,
                 request_stall_s: float | None = None):
        self.engine = engine
        self.period_s = period_s
        # None = read the module globals at check time (tests monkeypatch
        # those); an explicit value pins this instance.
        self._stall_s = stall_s
        self._request_stall_s = request_stall_s
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.device_online = True
        self.engine_stalled = False
        self.last_device_check = 0.0
        self._last_progress = (0, time.monotonic())  # (tokens, ts)
        # (ts, cumulative preemptions) samples for the storm-rate window.
        self._preempt_samples: list = []

    @property
    def stall_s(self) -> float:
        return self._stall_s if self._stall_s is not None else STALL_DEADLINE_S

    @property
    def request_stall_s(self) -> float:
        return (self._request_stall_s if self._request_stall_s is not None
                else REQUEST_STALL_S)

    def start(self) -> None:
        if self._thread:
            return
        self._thread = threading.Thread(target=self._loop, name="health", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    # ------------------------------------------------------------------
    def _alert(self, name: str, firing: bool, severity: str, message: str,
               kind: str) -> None:
        """Raise/clear one watchdog alert; the firing transition counts
        into ollamamq_watchdog_stalls_total{kind}. No-op on engines
        without an alert table (unit-test stubs)."""
        alerts = getattr(self.engine, "alerts", None)
        if alerts is None:
            return
        if firing:
            if alerts.fire(name, severity, message, source="watchdog"):
                tm.WATCHDOG_STALLS_TOTAL.labels(kind=kind).inc()
        else:
            alerts.resolve(name)

    def _probe_device(self) -> bool:
        """Run a trivial computation with a deadline on a side thread — a
        hung runtime must not take the monitor down with it. While a probe
        thread is still blocked (runtime wedged), no new probe is spawned;
        the device stays marked offline."""
        prev = getattr(self, "_probe_thread", None)
        if prev is not None and prev.is_alive():
            self.last_device_check = time.time()
            return False
        result = {}

        def go():
            try:
                import jax.numpy as jnp

                x = jnp.ones((8, 8))
                (x @ x).block_until_ready()
                result["ok"] = True
            except Exception as e:  # noqa: BLE001
                result["err"] = str(e)

        t = threading.Thread(target=go, daemon=True)
        self._probe_thread = t
        t.start()
        t.join(timeout=DEVICE_DEADLINE_S)
        self.last_device_check = time.time()
        return result.get("ok", False)

    def _check_progress(self) -> bool:
        """True if the engine is making progress (or rightly idle)."""
        # Snapshot: /api/pull and /api/delete mutate runtimes concurrently.
        runtimes = list(self.engine.runtimes.values())
        tokens = sum(getattr(rt, "tokens_generated", 0) for rt in runtimes)
        has_work = any(rt.has_work() for rt in runtimes) or bool(
            self.engine.core.total_queued()
        )
        last_tokens, last_ts = self._last_progress
        now = time.monotonic()
        if tokens != last_tokens or not has_work:
            self._last_progress = (tokens, now)
            return True
        if (now - last_ts) < self.stall_s:
            return True
        # No token for stall_s with work pending. Distinguish "loop alive
        # but starved" from "loop thread wedged inside a dispatch": the
        # liveness tick at the top of _loop_once goes stale in the latter.
        tick = getattr(self.engine, "last_tick_at", None)
        if tick is not None and (now - tick) > self.stall_s:
            return False  # loop thread itself is stuck
        return False

    def _check_stuck_requests(self) -> list:
        """In-flight traces whose last lifecycle event is older than the
        request-stall deadline: (req_id, phase, age_s) rows, worst first."""
        tracer = getattr(self.engine, "tracer", None)
        if tracer is None:
            return []
        now = time.monotonic()
        out = []
        for tr in tracer.traces():
            if tr.finished:
                continue
            evs = tr.events  # engine thread appends; index reads are safe
            if not evs:
                continue
            name, t = evs[-1][0], evs[-1][1]
            age = now - t
            if age > self.request_stall_s:
                out.append((tr.req_id, phase_of(name), age))
        out.sort(key=lambda r: -r[2])
        return out

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.check_once()
            except Exception:
                # The watchdog must outlive anything it watches.
                log.exception("health check iteration failed")

    def check_once(self) -> None:
        """One full watchdog pass (the loop cadence; callable directly in
        tests)."""
        ok = self._probe_device()
        if ok != self.device_online:
            if ok:
                log.info("TPU device is back ONLINE")
            else:
                log.error("TPU device probe FAILED (runtime hung or lost)")
            self.device_online = ok
        self._alert("device_offline", not ok, "page",
                    "device probe failed: runtime hung or lost", "device")

        progressing = self._check_progress()
        if not progressing and not self.engine_stalled:
            log.error(
                "engine STALLED: %d queued, work pending, no tokens for %ds",
                self.engine.core.total_queued(), int(self.stall_s),
            )
        self.engine_stalled = not progressing
        self._alert(
            "engine_stall", self.engine_stalled, "page",
            f"work pending but no token produced for {self.stall_s:g}s "
            "(wedged engine step?)", "engine_step")

        stuck = self._check_stuck_requests()
        for r, p, a in stuck:
            # req_id rides as a structured field so the JSON log line
            # correlates with /debug/requests/{id}.
            log.error("request %d stuck in phase '%s' for %.0fs",
                      r, p, a, extra={"req_id": r})
        self._alert(
            "request_stall", bool(stuck), "warn",
            (f"{len(stuck)} request(s) stuck; worst: req {stuck[0][0]} in "
             f"'{stuck[0][1]}' for {stuck[0][2]:.0f}s") if stuck else "",
            "request_phase")

        stale = []
        hosts_fn = getattr(self.engine, "stale_worker_hosts", None)
        if hosts_fn is not None:
            stale = hosts_fn() or []
        self._alert(
            "worker_stale", bool(stale), "page",
            f"SPMD worker host(s) {stale} stopped publishing registry "
            "snapshots/heartbeats", "worker_host")

        # Fleet-level analogue of worker_stale: engine replicas whose
        # heartbeat went stale or that the router ejected from rotation
        # (fleet/router.py stale_replicas). Single-engine deployments
        # have no such hook and skip this check entirely.
        stale_reps = []
        reps_fn = getattr(self.engine, "stale_replicas", None)
        if reps_fn is not None:
            stale_reps = reps_fn() or []
        self._alert(
            "replica_stale", bool(stale_reps), "page",
            f"fleet replica(s) {stale_reps} heartbeat-stale or ejected "
            "from rotation (in-flight streams fail over; capacity is "
            "reduced until they heal)", "replica")

        self._check_preempt_storm()
        self._check_regroup_storm()
        self._check_scale_storm()
        self._check_compile_storm()
        self._check_router_overhead()
        self._check_ha()
        self._check_journal_invariants()

        slo = getattr(self.engine, "slo", None)
        if slo is not None:
            slo.evaluate()

    def preempt_rate_per_min(self) -> float:
        """Preemptions per minute over the storm window, from cumulative
        engine counts sampled at the check cadence."""
        count_fn = getattr(self.engine, "preemption_count", None)
        if count_fn is None:
            return 0.0
        now = time.monotonic()
        self._preempt_samples.append((now, int(count_fn())))
        cutoff = now - PREEMPT_STORM_WINDOW_S
        self._preempt_samples = [
            (t, c) for t, c in self._preempt_samples if t >= cutoff
        ][-64:]
        if len(self._preempt_samples) < 2:
            return 0.0
        t0, c0 = self._preempt_samples[0]
        t1, c1 = self._preempt_samples[-1]
        span = t1 - t0
        if span <= 0:
            return 0.0
        # Rebuilds reset per-runtime counters; a negative delta is a
        # reset, not negative preemptions.
        return max(0, c1 - c0) * 60.0 / span

    def _check_preempt_storm(self) -> None:
        """AlertManager rule for preemption storms: sustained KV-pressure
        preemptions above PREEMPT_STORM_PER_MIN mean the pool is
        undersized and recompute is eating throughput. Not routed through
        _alert: a storm is degradation pressure, not a watchdog stall, so
        it must not count into ollamamq_watchdog_stalls_total."""
        alerts = getattr(self.engine, "alerts", None)
        if alerts is None:
            return
        rate = self.preempt_rate_per_min()
        if rate > PREEMPT_STORM_PER_MIN:
            alerts.fire(
                "preempt_storm", "warn",
                f"preemption storm: {rate:.0f} preemptions/min under KV "
                "pressure (pool undersized for the live workload; "
                "recompute is eating throughput)", source="watchdog")
        else:
            alerts.resolve("preempt_storm")

    def _check_regroup_storm(self) -> None:
        """AlertManager rule for tier-regroup storms (tiered fleets
        only: the engine exposes a TierManager at `.tiers`). Like the
        preemption storm, this is degradation pressure rather than a
        watchdog stall, so it bypasses _alert and its stall counter."""
        alerts = getattr(self.engine, "alerts", None)
        tiers = getattr(self.engine, "tiers", None)
        if alerts is None or tiers is None:
            return
        try:
            rate = tiers.regroup_rate_per_min()
        except Exception:  # noqa: BLE001
            log.exception("regroup-rate read failed")
            return
        if rate > REGROUP_STORM_PER_MIN:
            alerts.fire(
                "regroup_storm", "warn",
                f"tier regroup storm: {rate:.0f} regroups/min — the "
                "balancer is flapping members between tiers (hysteresis "
                "mis-tuned for this class mix); each regroup costs a "
                "drain + migrations + a restart", source="watchdog")
        else:
            alerts.resolve("regroup_storm")

    def _check_scale_storm(self) -> None:
        """Watchdog rule for autoscaler flap (elastic fleets only: the
        engine exposes an AutoscalerManager at `.autoscaler`). Routed
        through _alert — each fire transition counts into
        ollamamq_watchdog_stalls_total{kind="scale"} — because a scaler
        churning members is a control-loop malfunction the operator
        must tune out, not load the fleet absorbs gracefully."""
        scaler = getattr(self.engine, "autoscaler", None)
        if scaler is None:
            return
        try:
            rate = scaler.scale_rate_per_min()
        except Exception:  # noqa: BLE001
            log.exception("scale-rate read failed")
            return
        self._alert(
            "scale_storm", rate > SCALE_STORM_PER_MIN, "warn",
            f"scale storm: {rate:.0f} scale events/min — the autoscaler "
            "is flapping fleet size (cooldown/sustain mis-tuned for "
            "this load); each flap costs a spawn or a drain + "
            "migrations", "scale")

    def _check_compile_storm(self) -> None:
        """Watchdog rule for compile-ladder thrash. Steady state compiles
        NOTHING — each jit rung fills its cache exactly once during
        warmup — so a recompile rate sustained past COMPILE_WARMUP_S
        (module globals, monkeypatchable like the other thresholds)
        means shape churn or a cache-eviction loop is taxing dispatches
        with XLA wall time. Same _alert routing as scale_storm: a
        control-plane malfunction, not graceful degradation."""
        started = getattr(self.engine, "started_at", None)
        if started is None or time.time() - started < COMPILE_WARMUP_S:
            return  # ladder warmup: first-serve compiles are the design
        rate = stepprof.PROFILER.compile_rate_per_min()
        self._alert(
            "compile_storm", rate > COMPILE_STORM_PER_MIN, "warn",
            f"compile storm: {rate:.1f} jit recompiles/min past warmup — "
            "the compile ladder is thrashing (shape churn or cache "
            "eviction); every hit stalls its dispatch for the XLA wall",
            "compile")

    def _check_router_overhead(self) -> None:
        """Overhead-storm rule (fleet routers only: the engine exposes
        router_overhead_p99_ms). The router's own placement-decision
        cost is supposed to be noise next to serving; a windowed p99
        above --router-overhead-budget-ms means the router hot path
        itself is eating the latency budget (an affinity probe scanning
        a huge radix tree, GIL contention with co-located members, a
        journal spill on a dying disk). Degradation pressure like the
        preempt storm — it bypasses _alert and its stall counter — and
        it RESOLVES as the window ages the spike out."""
        alerts = getattr(self.engine, "alerts", None)
        p99_fn = getattr(self.engine, "router_overhead_p99_ms", None)
        if alerts is None or p99_fn is None:
            return
        budget = getattr(getattr(self.engine, "ecfg", None),
                         "router_overhead_budget_ms", None)
        if not budget:
            return
        try:
            p99 = p99_fn()
        except Exception:  # noqa: BLE001
            log.exception("router overhead read failed")
            return
        if p99 is not None and p99 > budget:
            alerts.fire(
                "router_overhead", "warn",
                f"router overhead storm: placement p99 {p99:.2f}ms over "
                f"the {budget:g}ms budget — the router hot path itself "
                "is eating the latency budget", source="watchdog")
        else:
            alerts.resolve("router_overhead")

    def _check_ha(self) -> None:
        """Router-HA watchdog rules (engines exposing ha_status; None =
        HA off). Both route through _alert — a lagging/lost standby and
        a wedged promotion are exactly the failures HA exists to
        prevent, so each fire transition counts into
        ollamamq_watchdog_stalls_total{kind="standby"|"takeover"}."""
        hs_fn = getattr(self.engine, "ha_status", None)
        hs = hs_fn() if hs_fn is not None else None
        if hs is None:
            return
        role = hs.get("role")
        if role == "primary":
            lag = hs.get("sync_lag_records")
            # lag None = no standby has EVER polled (single-router HA
            # primary is a config choice, not a fault); once one has,
            # losing it or trailing past the threshold is alert-worthy.
            bad = lag is not None and (
                lag > STANDBY_LAG_ALERT_RECORDS
                or not hs.get("standby_connected", True))
            self._alert(
                "standby_lag", bad, "warn",
                (f"standby replication lag {lag} record(s) (threshold "
                 f"{STANDBY_LAG_ALERT_RECORDS}) or standby disconnected "
                 "— a takeover NOW would replay from that far behind"),
                "standby")
        stuck = (role == "promoting"
                 and hs.get("promote_elapsed_s", 0.0) > TAKEOVER_STUCK_S)
        self._alert(
            "takeover_stuck", stuck, "page",
            (f"router takeover in flight for "
             f"{hs.get('promote_elapsed_s', 0):.0f}s (budget "
             f"{TAKEOVER_STUCK_S:g}s) — recovery re-admission is wedged "
             "while the fleet has no serving router"), "takeover")

    def _check_journal_invariants(self) -> None:
        """Flight-recorder invariant sweep over the decision-journal ring
        (telemetry/journal.py check_invariants): pages conserved, no slot
        double-assignment, preempt victim never the VIP, sheds only over
        bounds, no starvation. A violation means a scheduler bug is live
        in production — alert loudly (every chaos/fault-injection run
        becomes a checked artifact through the same sweep), resolve when
        the offending records age out of the ring."""
        alerts = getattr(self.engine, "alerts", None)
        journal = getattr(self.engine, "journal", None)
        if alerts is None or journal is None:
            return
        from ollamamq_tpu.telemetry.journal import check_invariants

        try:
            bad = check_invariants(journal.tail(None))
        except Exception:
            log.exception("journal invariant sweep failed")
            return
        if bad:
            log.error("scheduler invariant violation(s): %s", "; ".join(
                bad[:3]))
            alerts.fire(
                "journal_invariant", "page",
                f"{len(bad)} scheduler invariant violation(s) in the "
                f"decision journal; first: {bad[0]}", source="watchdog")
        else:
            alerts.resolve("journal_invariant")

    def status(self) -> dict:
        alerts = getattr(self.engine, "alerts", None)
        active = alerts.active() if alerts is not None else []
        return {
            "status": "degraded" if active else "ok",
            "device_online": self.device_online,
            "engine_stalled": self.engine_stalled,
            "last_device_check": self.last_device_check,
            "alerts": [a.to_dict() for a in active],
        }
