"""THE declaration site for every ollamamq_* metric.

Everything the process exports lives here, so (a) the engine/server grab
handles instead of re-declaring names inline, and (b)
scripts/check_metrics_docs.py can enumerate the full metric surface by
importing this one module — no engine, no jax — and diff it against the
README's Observability table.

Naming: `ollamamq_` prefix; latencies in milliseconds carry an `_ms`
suffix; counters carry `_total`. Per-model series are labeled
{model=...}; per-user queue depth {user=...}; per-chip HBM {chip=,host=}.
"""

from __future__ import annotations

from ollamamq_tpu.telemetry.metrics import (DEFAULT_LATENCY_BUCKETS_MS,
                                            REGISTRY)

# -- request latency histograms (re-bucketable via --metrics-buckets) ------
TTFT_MS = REGISTRY.histogram(
    "ollamamq_ttft_ms",
    "Time to first token per request, enqueue to first sampled token (ms)",
    buckets=DEFAULT_LATENCY_BUCKETS_MS, labels=("model",))
TPOT_MS = REGISTRY.histogram(
    "ollamamq_tpot_ms",
    "Time per output token: decode step latency per emitted token (ms)",
    buckets=DEFAULT_LATENCY_BUCKETS_MS, labels=("model",))
STEP_LATENCY_MS = REGISTRY.histogram(
    "ollamamq_step_latency_ms",
    "Decode step device latency, blocked-collect time per fused step (ms)",
    buckets=DEFAULT_LATENCY_BUCKETS_MS, labels=("model",))
PREFILL_LATENCY_MS = REGISTRY.histogram(
    "ollamamq_prefill_latency_ms",
    "Prefill forward latency per dispatched batch or chunk (ms)",
    buckets=DEFAULT_LATENCY_BUCKETS_MS, labels=("model",))

# -- engine occupancy / utilization gauges ---------------------------------
BATCH_OCCUPANCY = REGISTRY.gauge(
    "ollamamq_batch_occupancy",
    "Active decode slots / max_slots (0..1), sampled per decode step",
    labels=("model",))
BATCH_PADDING_WASTE = REGISTRY.gauge(
    "ollamamq_batch_padding_waste",
    "Fraction of the last dispatched batch's token positions that were "
    "padding (0..1): bucket rows minus real tokens on the bucketed path, "
    "the granule tail on the ragged path — the compute burned for shape "
    "stability", labels=("model",))
KV_PAGES_USED = REGISTRY.gauge(
    "ollamamq_kv_pages_used",
    "KV cache pages currently allocated", labels=("model",))
KV_PAGE_UTILIZATION = REGISTRY.gauge(
    "ollamamq_kv_page_utilization",
    "KV cache pages allocated / pool size (0..1)", labels=("model",))
MFU = REGISTRY.gauge(
    "ollamamq_mfu",
    "Model FLOPs utilization (0..1): analytic FLOPs/token x tokens per "
    "decode step over per-chip peak FLOPs x chips (0 when the peak for "
    "this accelerator is unknown; override with OLLAMAMQ_PEAK_FLOPS)",
    labels=("model",))
FLOPS_PER_TOKEN = REGISTRY.gauge(
    "ollamamq_model_flops_per_token",
    "Analytic forward FLOPs per generated token at zero context "
    "(2 x active params; attention adds ~4 x layers x ctx x q_dim)",
    labels=("model",))

# -- queue / request flow --------------------------------------------------
QUEUE_DEPTH = REGISTRY.gauge(
    "ollamamq_queue_depth",
    "Requests waiting in the fair-share queue, per user",
    labels=("user",))
REQUESTS_INFLIGHT = REGISTRY.gauge(
    "ollamamq_requests_inflight",
    "Requests accepted and not yet finished (any kind)")
REQUESTS_TOTAL = REGISTRY.counter(
    "ollamamq_requests_total",
    "Finished requests by outcome (stop/length/cancelled/error)",
    labels=("model", "outcome"))
TOKENS_GENERATED_TOTAL = REGISTRY.counter(
    "ollamamq_tokens_generated_total",
    "Tokens sampled across all requests", labels=("model",))
PROMPT_TOKENS_TOTAL = REGISTRY.counter(
    "ollamamq_prompt_tokens_total",
    "Prompt tokens prefilled across all requests", labels=("model",))

# -- prefix cache (engine/prefix_cache.py; series exist only when
# --prefix-cache is on) ----------------------------------------------------
PREFIX_CACHE_HITS_TOTAL = REGISTRY.counter(
    "ollamamq_prefix_cache_hits_total",
    "Admissions that reused a cached prompt prefix (≥ min-pages match)",
    labels=("model",))
PREFIX_CACHE_MISSES_TOTAL = REGISTRY.counter(
    "ollamamq_prefix_cache_misses_total",
    "Admissions with no (or below-threshold) cached prefix",
    labels=("model",))
PREFIX_CACHE_EVICTIONS_TOTAL = REGISTRY.counter(
    "ollamamq_prefix_cache_evictions_total",
    "Cached KV pages evicted back to the free list (LRU, on allocator "
    "pressure or flush)", labels=("model",))
PREFIX_CACHE_HIT_RATIO = REGISTRY.gauge(
    "ollamamq_prefix_cache_hit_ratio",
    "Prefix-cache hits / lookups since start (0..1)", labels=("model",))
PREFIX_CACHE_TOKENS_SAVED = REGISTRY.gauge(
    "ollamamq_prefix_cache_tokens_saved",
    "Cumulative prompt tokens served from cached KV pages instead of "
    "recomputed", labels=("model",))
PREFIX_CACHE_PAGES = REGISTRY.gauge(
    "ollamamq_prefix_cache_pages",
    "KV pages currently owned by the prefix-cache radix tree",
    labels=("model",))

# -- graceful degradation under load (engine preemption / bounded
# admission / deadlines / retry containment) -------------------------------
# Closed vocabulary for ollamamq_shed_total{reason}; the doc gate
# (scripts/check_metrics_docs.py) pins the README table to this tuple.
SHED_REASONS = ("queue_full", "user_queue_full", "deadline", "kv_exhausted")
PREEMPTIONS_TOTAL = REGISTRY.counter(
    "ollamamq_preemptions_total",
    "Decode slots preempted under KV-pool pressure (victim requeued to "
    "the front of its user's queue for recompute)", labels=("model",))
SHED_TOTAL = REGISTRY.counter(
    "ollamamq_shed_total",
    "Requests shed instead of served, by reason (queue_full / "
    "user_queue_full / deadline / kv_exhausted)", labels=("reason",))
RETRIES_TOTAL = REGISTRY.counter(
    "ollamamq_retries_total",
    "Requests re-dispatched after a contained runtime-step failure "
    "(once each with backoff; repeat offenders are poisoned and errored)",
    labels=("model",))
DEADLINE_DROPS_TOTAL = REGISTRY.counter(
    "ollamamq_deadline_drops_total",
    "Requests dropped because their per-request deadline expired "
    "(at admission, before prefill dispatch, before composing a "
    "speculative verify span, or at preemption re-admission)",
    labels=("model",))

# -- speculative decoding (--spec; n-gram draft + ragged verify) -----------
SPEC_TOKENS_TOTAL = REGISTRY.counter(
    "ollamamq_spec_tokens_total",
    "Speculative draft tokens by outcome: proposed (composed into a "
    "verify span), accepted (matched the model's greedy argmax and "
    "emitted), rejected (KV pages rolled back)",
    labels=("model", "outcome"))
SPEC_ACCEPT_RATE = REGISTRY.gauge(
    "ollamamq_spec_accept_rate",
    "Accepted / proposed speculative draft tokens since start (0..1); "
    "the per-user auto-throttle (--spec-min-accept) keys off the same "
    "accounting", labels=("model",))


# -- size-aware scheduling (engine/scheduler.py; --scheduler) --------------
SCHED_PRED_ERR = REGISTRY.histogram(
    "ollamamq_sched_pred_err",
    "Output-length predictor absolute error in tokens (|predicted - "
    "actual|), observed at request finish — the srpt/edf scheduling "
    "policies order by these predictions, so this histogram is the "
    "promotion guardrail's live twin",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512), labels=("model",))
SCHED_DECISIONS_TOTAL = REGISTRY.counter(
    "ollamamq_sched_decisions_total",
    "Scheduling-policy reorder decisions applied (admission windows, "
    "pending-queue reorders), by policy; fcfs never reorders so its "
    "series stays 0", labels=("policy",))


def total_shed() -> float:
    """Sum of ollamamq_shed_total over all reasons (TUI chip)."""
    return sum(child.value for _, child in SHED_TOTAL.series())


# -- latency attribution / SLO / alerting (telemetry/attribution.py,
# telemetry/slo.py, engine/health.py watchdog) ------------------------------
REQUEST_PHASE_MS = REGISTRY.histogram(
    "ollamamq_request_phase_ms",
    "Per-request latency attribution: milliseconds spent in each lifecycle "
    "phase (queue/admission/prefix_cache/prefill/decode/stream), observed "
    "at request finish; phases sum to end-to-end latency",
    buckets=DEFAULT_LATENCY_BUCKETS_MS, labels=("model", "phase"))
SLO_VIOLATIONS_TOTAL = REGISTRY.counter(
    "ollamamq_slo_violations_total",
    "Observations over the configured SLO threshold (--slo-ttft-ms / "
    "--slo-tpot-ms), by objective; series exist only with SLOs configured",
    labels=("objective",))
SLO_BURN_RATE = REGISTRY.gauge(
    "ollamamq_slo_burn_rate",
    "Error-budget burn rate over each alerting window's long leg "
    "(bad/total over window / (1 - target)); 1.0 = spending exactly the "
    "budget, above the window's factor = alert", labels=("objective",
                                                         "window"))
SLO_ALERTS_FIRING = REGISTRY.gauge(
    "ollamamq_slo_alerts_firing",
    "Active alerts (SLO burn, watchdog stalls, device loss): 1 per "
    "firing alert, rebuilt each scrape so resolved alerts disappear",
    labels=("alert", "severity"))
WATCHDOG_STALLS_TOTAL = REGISTRY.counter(
    "ollamamq_watchdog_stalls_total",
    "Stall watchdog firings by kind (engine_step, request_phase, "
    "worker_host, device, replica, scale, standby, takeover)",
    labels=("kind",))

# -- decision journal (telemetry/journal.py; GET /debug/journal) -----------
JOURNAL_EVENTS_TOTAL = REGISTRY.counter(
    "ollamamq_journal_events_total",
    "Scheduler decision-journal records appended, by event kind (the "
    "flight recorder's write rate; tail the ring at /debug/journal)",
    labels=("kind",))

# -- int8 quantization (serving density; --weights-dtype / --kv-dtype) -----
HBM_WEIGHT_BYTES = REGISTRY.gauge(
    "ollamamq_hbm_weight_bytes",
    "Bytes the loaded weights occupy per model runtime (int8 payloads + "
    "fp32 scales when --weights-dtype=int8 — the density lever's "
    "before/after)", labels=("model",))
HBM_KV_BYTES = REGISTRY.gauge(
    "ollamamq_hbm_kv_bytes",
    "Bytes the KV page pool occupies per model runtime (int8 pages + "
    "fp32 scale rows when --kv-dtype=int8; ~2x more concurrent requests "
    "fit the same budget)", labels=("model",))
QUANT_LOGIT_ERR = REGISTRY.gauge(
    "ollamamq_quant_logit_err",
    "Max absolute logit error of the int8-quantized weights vs their "
    "bf16 source on the guardrail probe (teacher-forced greedy rollout; "
    "set when the guardrail runs — tests, bench density scenario)",
    labels=("model",))

# -- fleet router (fleet/router.py; dispatcher-over-engines) ---------------
# Closed site vocabulary for ollamamq_router_overhead_ms{site}: every
# always-on nanosecond timer around the router hot path. "place" is the
# bounded one (the bench fleet-chaos gate fails when its p99 exceeds
# --router-overhead-budget-ms); the rest attribute where the router's
# own time goes per decision.
ROUTER_OVERHEAD_SITES = ("place", "journal", "wal_fsync",
                         "migrate_export", "migrate_ship",
                         "migrate_import")
ROUTER_OVERHEAD_MS = REGISTRY.histogram(
    "ollamamq_router_overhead_ms",
    "Router hot-path self-profiling: milliseconds the router itself "
    "spent per decision, by site (place = the placement decision, "
    "journal = one flight-recorder append, wal_fsync = the durable-"
    "admission gate, migrate_export/_ship/_import = the three legs of "
    "a KV handoff) — always-on perf_counter_ns timers, the measured "
    "and bounded 'router overhead' of the fleet-scale story",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             25.0, 50.0, 100.0, 250.0, 1000.0),
    labels=("site",))
FLEET_REPLICAS = REGISTRY.gauge(
    "ollamamq_fleet_replicas",
    "Engine replicas under the fleet router by state (healthy / ejected "
    "/ draining); absent when serving single-engine", labels=("state",))
FLEET_FAILOVERS_TOTAL = REGISTRY.counter(
    "ollamamq_fleet_failovers_total",
    "In-flight streams re-dispatched to another replica after their "
    "replica died or was ejected (each replays prompt + already-emitted "
    "tokens so the client sees one seamless stream)")
FLEET_AFFINITY_HITS_TOTAL = REGISTRY.counter(
    "ollamamq_fleet_placement_affinity_hits_total",
    "Placements routed to the replica whose prefix-cache radix tree "
    "already held the prompt's prefix (--placement=affinity); misses "
    "fall back to least-loaded")
FLEET_TIER_MEMBERS = REGISTRY.gauge(
    "ollamamq_fleet_tier_members",
    "Fleet members per replica tier by state (healthy / ejected / "
    "draining) under --tiers; a tier whose healthy count hits 0 is "
    "serving its traffic cross-tier (journaled tier_overflow) until a "
    "member heals or regroups in", labels=("tier", "state"))
FLEET_TIER_OVERFLOW_TOTAL = REGISTRY.counter(
    "ollamamq_fleet_tier_overflow_total",
    "Streams placed cross-tier, by (from, to) tier: per-tier SLO "
    "burn-rate overflow, an empty home tier, or a failover with no "
    "in-tier capacity — every one journaled as tier_overflow with its "
    "inputs", labels=("from", "to"))
FLEET_REGROUPS_TOTAL = REGISTRY.counter(
    "ollamamq_fleet_regroups_total",
    "Tier regroups (a member drained, live streams migrated off, "
    "hot-restarted at the target tier's TP width, rejoined the other "
    "tier) by outcome: 'done' or 'aborted' (crash/restart failure "
    "mid-retier; the member keeps its original tier)",
    labels=("outcome",))
FLEET_MIGRATIONS_TOTAL = REGISTRY.counter(
    "ollamamq_fleet_migrations_total",
    "KV page migrations between fleet members by outcome: 'migrated' "
    "(stream resumed from shipped state on the target), 'aborted' "
    "(transfer failed; the stream fell back to recompute replay), "
    "'prefix' (an affinity-miss shipped cached prefix pages to the "
    "chosen member)", labels=("outcome",))
FLEET_MIGRATE_BYTES_TOTAL = REGISTRY.counter(
    "ollamamq_fleet_migrate_bytes_total",
    "KV page payload bytes shipped between fleet members (migrations "
    "and prefix shipping; int8 pools move ~2x fewer bytes than bf16)")

# -- elastic fleet (fleet/autoscaler.py; --autoscale) ----------------------
FLEET_SCALE_EVENTS_TOTAL = REGISTRY.counter(
    "ollamamq_fleet_scale_events_total",
    "Autoscaler fleet-size changes by direction ('up' = member "
    "provisioned and joined, 'down' = member drained, migrated off, and "
    "retired) and outcome ('done' or 'aborted': a failed spawn, or an "
    "eject mid-retire) — every one journaled as scale_up/scale_down "
    "with the burn + backlog inputs that justified it",
    labels=("direction", "outcome"))
FLEET_MEMBER_HOURS_TOTAL = REGISTRY.counter(
    "ollamamq_fleet_member_hours_total",
    "Cumulative member-serving hours (fractional; accrued each scaler "
    "tick over every non-ejected member) — the resource-cost side of "
    "the elastic-fleet ledger the diurnal bench gates on")
FLEET_PREEMPTIONS_TOTAL = REGISTRY.counter(
    "ollamamq_fleet_preemptions_total",
    "Termination notices served to preemptible members (POST "
    "/admin/preempt/{replica} or the fault plan's preempt_notice site); "
    "each triggers migrate-off-then-retire within the notice window — "
    "spot reclamation with zero dropped streams")

# -- router HA (fleet/ha.py; --ha / --standby-of) --------------------------
HA_SYNC_LAG_RECORDS = REGISTRY.gauge(
    "ollamamq_ha_sync_lag_records",
    "Replication records the warm standby has not yet applied (primary "
    "head seq minus last acked seq); primary-side it tracks the "
    "connected standby's ack, standby-side its own apply position — "
    "what a takeover would have to recover without")
HA_SYNC_RECORDS_TOTAL = REGISTRY.counter(
    "ollamamq_ha_sync_records_total",
    "Replication records shipped over /admin/ha/sync by kind ('wal' = "
    "admission-WAL records into the standby's WAL replica, 'journal' = "
    "decision events into the standby's journal spill)",
    labels=("kind",))
HA_TAKEOVERS_TOTAL = REGISTRY.counter(
    "ollamamq_ha_takeovers_total",
    "Standby promotions to primary by why ('primary_dead' = heartbeat "
    "loss past the takeover grace, 'handover' = graceful SIGTERM on the "
    "primary handed the fleet over)", labels=("why",))
HA_TAKEOVER_DURATION_MS = REGISTRY.histogram(
    "ollamamq_ha_takeover_duration_ms",
    "Promotion wall time (ms): primary declared dead to the standby "
    "serving with every unfinished WAL stream re-admitted — the EMA of "
    "this feeds promotion-window Retry-After hints",
    buckets=(10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000, 30000))
HA_FENCED_CALLS_TOTAL = REGISTRY.counter(
    "ollamamq_ha_fenced_calls_total",
    "Stale-epoch router calls a member rejected after a takeover, by "
    "kind (placement / migrate / register) — each one is a zombie "
    "primary's write the epoch fence turned away, journaled epoch_fence",
    labels=("kind",))

# -- crash durability (durability/; --wal-dir) -----------------------------
WAL_FSYNC_MS = REGISTRY.histogram(
    "ollamamq_wal_fsync_ms",
    "Admission-WAL fsync latency (ms): how long the group-commit window "
    "plus the fsync itself held each durable write — the durability tax "
    "every ACKed enqueue pays under --wal-dir",
    buckets=(0.1, 0.5, 1, 2, 5, 10, 20, 50, 100, 250, 1000))
RECOVERED_STREAMS_TOTAL = REGISTRY.counter(
    "ollamamq_recovered_streams_total",
    "WAL'd requests handled by the cold-restart recovery pass, by "
    "outcome: 'replayed' (re-admitted token-exact with generated_ids "
    "pre-filled), 'finished' (budget already spent — only the terminal "
    "was surfaced), 'failed' (re-admission errored; the stream ends "
    "with an explicit error, never a silent drop)",
    labels=("outcome",))

# -- engine performance plane (telemetry/stepprof.py) ----------------------
# Closed site vocabulary for ollamamq_compile_total{site}: one per jit
# cache the engine fills (the compile ladder's rungs live in these).
COMPILE_SITES = ("ragged", "prefill", "chunk", "sp_prefill", "decode",
                 "embed")
STEP_PHASE_MS = REGISTRY.histogram(
    "ollamamq_step_phase_ms",
    "Engine dispatch self-profiling: milliseconds each step spent per "
    "phase (host_prep = python batch composition, dispatch = issuing "
    "the jit'd computation — XLA compile on a fresh cache key, "
    "collect = device wait + D2H materialization, detok = the host "
    "emit loop), by step mode (ragged / spec_verify / decode / embed "
    "/ fake) — the always-on stepprof ring's metric face",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             25.0, 50.0, 100.0, 250.0, 1000.0),
    labels=("phase", "mode"))
COMPILE_TOTAL = REGISTRY.counter(
    "ollamamq_compile_total",
    "XLA compiles the engine paid, by jit-cache site (ragged / prefill "
    "/ chunk / sp_prefill / decode / embed) — exactly one per compile-"
    "ladder rung in steady state; a climbing rate past warmup is a "
    "ladder bug (compile_storm alert)", labels=("site",))
COMPILE_MS = REGISTRY.histogram(
    "ollamamq_compile_ms",
    "Wall milliseconds one XLA compile held the dispatch path (the "
    "first call of a fresh jit cache entry traces + compiles "
    "synchronously; that call's wall IS the compile cost the step paid)",
    buckets=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000,
             30000, 60000, 120000))

# -- host / device ---------------------------------------------------------
HBM_USED_BYTES = REGISTRY.gauge(
    "ollamamq_hbm_used_bytes",
    "Per-chip HBM in use (chips without memory_stats are omitted, "
    "never reported as 0)", labels=("chip", "host"))
HBM_TOTAL_BYTES = REGISTRY.gauge(
    "ollamamq_hbm_total_bytes",
    "Per-chip HBM capacity", labels=("chip", "host"))
UPTIME_SECONDS = REGISTRY.gauge(
    "ollamamq_uptime_seconds", "Engine uptime")

_LATENCY_HISTOGRAMS = (TTFT_MS, TPOT_MS, STEP_LATENCY_MS, PREFILL_LATENCY_MS)


def configure_latency_buckets(bounds) -> None:
    """Apply the --metrics-buckets ladder to every latency histogram.
    Resets prior observations (boundaries don't translate); call at
    startup, before serving."""
    for h in _LATENCY_HISTOGRAMS:
        h.set_buckets(bounds)
