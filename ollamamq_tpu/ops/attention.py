"""Attention ops: causal prefill attention and paged decode attention.

The paged decode path is the TPU replacement for the reference's
one-request-per-backend model (/root/reference/src/dispatcher.rs:438):
many sequences share one forward step, each reading its own scattered KV
pages. The jnp implementations here are the semantic reference; the Pallas
ragged-paged-attention kernel (ollamamq_tpu/ops/pallas) is the fast path
and must match these numerically.

KV cache layout (flat token-slot pool, page-aligned):
    k_cache, v_cache: [num_layers, num_pages * page_size, kv_heads, head_dim]
A "page" is page_size contiguous slots; the host-side allocator
(engine/kv_cache.py) hands out page indices, and `flat_slot_indices`
translates (page_table, position) -> slot index.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ollamamq_tpu.ops.quant import QuantKV, kv_gather

NEG_INF = -1e30


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[.., L, kv_heads, hd] -> [.., L, kv_heads*n_rep, hd] (GQA head groups)."""
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=-2)


def causal_attention(
    q: jnp.ndarray,  # [B, T, H, hd]
    k: jnp.ndarray,  # [B, T, Hk, hd]
    v: jnp.ndarray,  # [B, T, Hk, hd]
    seq_lens: jnp.ndarray,  # [B] valid lengths (padding masked out)
) -> jnp.ndarray:
    """Causal self-attention over a padded prefill batch. f32 softmax."""
    B, T, H, hd = q.shape
    n_rep = H // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    logits = logits * scale
    pos = jnp.arange(T)
    causal = pos[None, :] <= pos[:, None]  # [q, k]
    valid = pos[None, None, :] < seq_lens[:, None, None]  # [B, 1, k]
    mask = causal[None, None, :, :] & valid[:, None, :, :]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def bidirectional_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, seq_lens: jnp.ndarray
) -> jnp.ndarray:
    """Full (non-causal) attention for encoder/embedding models."""
    B, T, H, hd = q.shape
    n_rep = H // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    logits = logits * scale
    pos = jnp.arange(T)
    valid = pos[None, None, None, :] < seq_lens[:, None, None, None]
    logits = jnp.where(valid, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def flat_slot_indices(
    page_table: jnp.ndarray,  # [B, max_pages] int32 page ids
    positions: jnp.ndarray,  # [B, L] int32 token positions within each seq
    page_size: int,
) -> jnp.ndarray:
    """Translate per-sequence token positions to flat cache slot indices."""
    page = jnp.take_along_axis(page_table, positions // page_size, axis=-1)
    return page * page_size + positions % page_size


def paged_chunk_attention(
    q: jnp.ndarray,  # [B, C, H, hd] — a chunk of new tokens per sequence
    k_cache: jnp.ndarray,  # [S, Hk, hd] flat slot pool for ONE layer
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    start: jnp.ndarray,  # [B] global position of the chunk's first token
    chunk_lens: jnp.ndarray,  # [B] valid tokens in this chunk (<= C)
    page_size: int,
) -> jnp.ndarray:
    """Chunked-prefill attention: the chunk's K/V are already scattered
    into the cache, so each query at global position start+i attends to
    cache positions <= start+i. Generalizes decode attention (C == 1).
    """
    B, C, H, hd = q.shape
    max_pages = page_table.shape[1]
    L = max_pages * page_size
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32), (B, L))
    slots = flat_slot_indices(page_table, positions, page_size)  # [B, L]
    k = kv_gather(k_cache, slots)  # [B, L, Hk, hd] (int8 pools dequantize)
    v = kv_gather(v_cache, slots)
    n_rep = H // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    logits = jnp.einsum(
        "bchd,blhd->bhcl", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale  # [B, H, C, L]
    q_pos = start[:, None] + jnp.arange(C)[None, :]  # [B, C] global positions
    causal = positions[:, None, :] <= q_pos[:, :, None]  # [B, C, L]
    in_seq = positions[:, None, :] < (start + chunk_lens)[:, None, None]
    mask = (causal & in_seq)[:, None, :, :]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhcl,blhd->bchd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_chunk_attention_blockwise(
    q: jnp.ndarray,  # [B, C, H, hd] — a chunk of new tokens per sequence
    k_cache: jnp.ndarray,  # [S, Hk, hd] flat slot pool for ONE layer
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    start: jnp.ndarray,  # [B] global position of the chunk's first token
    chunk_lens: jnp.ndarray,  # [B] valid tokens in this chunk (<= C)
    page_size: int,
    block_pages: int = 8,
) -> jnp.ndarray:
    """Non-materializing chunk attention: walks the context in blocks of
    `block_pages` pages with an online (flash-style) softmax, and the loop
    trip count is DYNAMIC — ceil(max_needed / block) for the batch — so HBM
    reads scale with the actual context length instead of gathering the
    full [B, max_pages*page_size] padded context like paged_chunk_attention
    (VERDICT r1 weak #4). Numerics match paged_chunk_attention (same f32
    online softmax, tested in test_model.py)."""
    B, C, H, hd = q.shape
    max_pages = page_table.shape[1]
    Hk = k_cache.shape[1]
    n_rep = H // Hk
    BLK = block_pages * page_size
    n_blocks = -(-max_pages // block_pages)  # static ceiling
    end = start + chunk_lens  # [B] tokens visible to the chunk's last query
    needed = jnp.max(-(-end // BLK))  # dynamic: blocks any sequence needs

    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    qf = q.astype(jnp.float32) * scale
    q_pos = start[:, None] + jnp.arange(C)[None, :]  # [B, C]

    def body(i, carry):
        m, l, acc = carry
        # Gather (not dynamic_slice: its clamping would silently relabel the
        # final partial block when max_pages % block_pages != 0). Clipped
        # rows carry positions >= max_pages*page_size, which the in_seq
        # mask below always rejects (end <= max_pages*page_size).
        pidx = jnp.clip(
            i * block_pages + jnp.arange(block_pages), 0, max_pages - 1
        )
        pages = page_table[:, pidx]  # [B, block_pages]
        pos = i * BLK + jnp.arange(BLK, dtype=jnp.int32)  # global positions
        slots = (pages[:, :, None] * page_size
                 + jnp.arange(page_size)[None, None, :]).reshape(B, BLK)
        k = repeat_kv(kv_gather(k_cache, slots).astype(jnp.float32),
                      n_rep)  # [B,BLK,H,hd]
        v = repeat_kv(kv_gather(v_cache, slots).astype(jnp.float32), n_rep)
        logits = jnp.einsum("bchd,blhd->bhcl", qf, k)  # [B, H, C, BLK]
        causal = pos[None, None, None, :] <= q_pos[:, None, :, None]
        in_seq = pos[None, None, None, :] < end[:, None, None, None]
        logits = jnp.where(causal & in_seq, logits, NEG_INF)
        blk_m = jnp.max(logits, axis=-1)  # [B, H, C]
        new_m = jnp.maximum(m, blk_m)
        # Keep exp arguments finite when a row has seen nothing yet.
        p = jnp.exp(logits - new_m[..., None])
        p = jnp.where(logits <= NEG_INF / 2, 0.0, p)
        corr = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - new_m))
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum("bhcl,blhd->bhcd", p, v)
        return new_m, l, acc

    m0 = jnp.full((B, H, C), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, C), jnp.float32)
    a0 = jnp.zeros((B, H, C, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(
        0, jnp.minimum(needed, n_blocks), body, (m0, l0, a0)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, H, C, hd]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, H, hd] one new token per sequence
    k_cache: jnp.ndarray,  # [S, Hk, hd] flat slot pool for ONE layer
    v_cache: jnp.ndarray,  # [S, Hk, hd]
    page_table: jnp.ndarray,  # [B, max_pages]
    seq_lens: jnp.ndarray,  # [B] context length INCLUDING the new token
    page_size: int,
) -> jnp.ndarray:
    """Decode attention: each query attends to its own paged context.

    The C == 1 case of paged_chunk_attention (the new token sits at
    position seq_len-1 and sees everything before it). jnp reference
    path — on TPU the Pallas kernel replaces it with per-page reads and
    no materialization.
    """
    out = paged_chunk_attention(
        q[:, None], k_cache, v_cache, page_table,
        start=seq_lens - 1, chunk_lens=jnp.ones_like(seq_lens),
        page_size=page_size,
    )
    return out[:, 0]


def ragged_paged_attention(
    q: jnp.ndarray,  # [T, H, hd] flattened mixed-batch query stream
    k_cache: jnp.ndarray,  # [S, Hk, hd] flat slot pool for ONE layer
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages] one row per sequence
    tok_seq: jnp.ndarray,  # [T] int32 sequence index of each token
    tok_pos: jnp.ndarray,  # [T] int32 kv position of each token (-1 = pad)
    kv_lens: jnp.ndarray,  # [B] context length incl. each seq's new tokens
    page_size: int,
) -> jnp.ndarray:
    """Ragged mixed-batch attention, materializing reference.

    One flattened token stream holds ANY mix of variable-length prefill
    spans and single decode tokens; each token attends causally over its
    own sequence's paged context (positions <= its kv position). The
    semantic twin of the Pallas ragged kernel
    (ops/pallas/ragged_attention.py) and the ground truth the blockwise
    serving path below is tested against. Padding tokens (tok_pos < 0)
    produce garbage rows the caller must ignore.
    """
    T, H, hd = q.shape
    B, max_pages = page_table.shape
    L = max_pages * page_size
    rows = page_table[jnp.clip(tok_seq, 0, B - 1)]  # [T, max_pages]
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32), (T, L))
    slots = flat_slot_indices(rows, positions, page_size)  # [T, L]
    k = kv_gather(k_cache, slots)  # [T, L, Hk, hd] (int8 pools dequantize)
    v = kv_gather(v_cache, slots)
    n_rep = H // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    logits = jnp.einsum(
        "thd,tlhd->thl", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale  # [T, H, L]
    causal = positions <= tok_pos[:, None]  # [T, L]
    in_seq = positions < kv_lens[jnp.clip(tok_seq, 0, B - 1)][:, None]
    mask = (causal & in_seq)[:, None, :]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("thl,tlhd->thd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ragged_paged_attention_blockwise(
    q: jnp.ndarray,  # [T, H, hd] flattened mixed-batch query stream
    k_cache: jnp.ndarray,  # [S, Hk, hd] flat slot pool for ONE layer
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    tok_seq: jnp.ndarray,  # [T] int32 sequence index of each token
    tok_pos: jnp.ndarray,  # [T] int32 kv position of each token (-1 = pad)
    kv_lens: jnp.ndarray,  # [B]
    page_size: int,
    block_pages: int = 8,
) -> jnp.ndarray:
    """Non-materializing ragged attention: the jnp serving path.

    Walks the paged context in blocks of `block_pages` pages with an
    online (flash-style) softmax; the loop trip count is DYNAMIC —
    bounded by the deepest causal frontier in the batch — so HBM reads
    scale with the actual context, not the padded maximum. Numerics
    match ragged_paged_attention (same f32 online softmax; pinned in
    tests/test_ragged_attention.py)."""
    T, H, hd = q.shape
    B, max_pages = page_table.shape
    Hk = k_cache.shape[1]
    n_rep = H // Hk
    BLK = block_pages * page_size
    n_blocks = -(-max_pages // block_pages)  # static ceiling
    rows = page_table[jnp.clip(tok_seq, 0, B - 1)]  # [T, max_pages]
    end = tok_pos + 1  # per-token causal frontier (0 for padding)
    needed = jnp.max(-(-jnp.maximum(end, 0) // BLK))

    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    qf = q.astype(jnp.float32) * scale  # [T, H, hd]

    def body(i, carry):
        m, l, acc = carry
        pidx = jnp.clip(
            i * block_pages + jnp.arange(block_pages), 0, max_pages - 1
        )
        pages = rows[:, pidx]  # [T, block_pages]
        pos = i * BLK + jnp.arange(BLK, dtype=jnp.int32)
        slots = (pages[:, :, None] * page_size
                 + jnp.arange(page_size)[None, None, :]).reshape(T, BLK)
        k = repeat_kv(kv_gather(k_cache, slots).astype(jnp.float32),
                      n_rep)  # [T,BLK,H,hd]
        v = repeat_kv(kv_gather(v_cache, slots).astype(jnp.float32), n_rep)
        logits = jnp.einsum("thd,tlhd->thl", qf, k)  # [T, H, BLK]
        keep = (pos[None, :] <= tok_pos[:, None]) \
            & (pos[None, :] < end[:, None])  # [T, BLK]
        logits = jnp.where(keep[:, None, :], logits, NEG_INF)
        blk_m = jnp.max(logits, axis=-1)  # [T, H]
        new_m = jnp.maximum(m, blk_m)
        p = jnp.exp(logits - new_m[..., None])
        p = jnp.where(logits <= NEG_INF / 2, 0.0, p)
        corr = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - new_m))
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum("thl,tlhd->thd", p, v)
        return new_m, l, acc

    m0 = jnp.full((T, H), NEG_INF, jnp.float32)
    l0 = jnp.zeros((T, H), jnp.float32)
    a0 = jnp.zeros((T, H, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(
        0, jnp.minimum(needed, n_blocks), body, (m0, l0, a0)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [T, H, hd]
    return out.astype(q.dtype)


def ragged_attention_any(
    attn_impl: str,
    q: jnp.ndarray,  # [T, H, hd]
    k_cache: jnp.ndarray,  # [S, Hk, hd] ONE layer's slot pool
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    tok_seq: jnp.ndarray,  # [T] (jnp path metadata)
    tok_pos: jnp.ndarray,  # [T]
    kv_lens: jnp.ndarray,  # [B]
    q_start: jnp.ndarray,  # [B] (pallas path metadata)
    q_lens: jnp.ndarray,  # [B]
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """The ONE pallas-vs-jnp ragged-attention dispatch (mirror of
    paged_decode_attention_any), shared by models/llama.forward_ragged so
    the two paths cannot drift. Both metadata encodings travel together:
    per-token (tok_seq/tok_pos) feeds the jnp gather path, per-sequence
    (q_start/q_lens) rides the Pallas kernel's scalar prefetch."""
    if attn_impl == "pallas":
        from ollamamq_tpu.ops.pallas.ragged_attention import (
            ragged_paged_attention_pallas,
        )

        if isinstance(k_cache, QuantKV):
            # Quantized pool: int8 payloads DMA as usual, the per-slot
            # scale rows ride along and dequantize in-kernel.
            return ragged_paged_attention_pallas(
                q, k_cache.q, v_cache.q, page_table, q_start, q_lens,
                kv_lens, page_size, interpret=interpret,
                k_scale=k_cache.s, v_scale=v_cache.s,
            )
        return ragged_paged_attention_pallas(
            q, k_cache, v_cache, page_table, q_start, q_lens, kv_lens,
            page_size, interpret=interpret,
        )
    return ragged_paged_attention_blockwise(
        q, k_cache, v_cache, page_table, tok_seq, tok_pos, kv_lens, page_size
    )


def paged_decode_attention_any(
    attn_impl: str,
    q: jnp.ndarray,  # [B, H, hd]
    k_cache: jnp.ndarray,  # [S, Hk, hd] ONE layer's slot pool
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    seq_lens: jnp.ndarray,  # [B]
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """The ONE pallas-vs-jnp decode-attention dispatch, shared by the
    single-mesh forward (models/llama.py) and the pipeline stage
    (parallel/pipeline.py) so the two paths cannot drift. The pallas
    import stays deferred: the kernel module only loads when selected."""
    if attn_impl == "pallas":
        from ollamamq_tpu.ops.pallas.paged_attention import (
            paged_decode_attention_pallas,
        )

        if isinstance(k_cache, QuantKV):
            return paged_decode_attention_pallas(
                q, k_cache.q, v_cache.q, page_table, seq_lens, page_size,
                interpret=interpret,
                k_scale=k_cache.s, v_scale=v_cache.s,
            )
        return paged_decode_attention_pallas(
            q, k_cache, v_cache, page_table, seq_lens, page_size,
            interpret=interpret,
        )
    return paged_decode_attention(
        q, k_cache, v_cache, page_table, seq_lens, page_size
    )
