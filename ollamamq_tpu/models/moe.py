"""Mixture-of-experts FFN (Mixtral family) with expert parallelism.

The reference serves MoE models only by proxying to an Ollama backend that
happens to run one (llama.cpp does the routing on CPU/GPU); it has no
expert-parallel story at all. Here MoE is a first-class layer family:

  - Routing is token-choice top-k (Mixtral semantics: softmax over all
    experts, take top-k, renormalize the kept probabilities).
  - Dispatch/combine use the GShard dense formulation — one-hot
    position-in-expert tensors contracted with einsum — because that is
    the shape-static, compiler-friendly layout: no gather/scatter with
    data-dependent sizes, everything tiles onto the MXU, and XLA's SPMD
    partitioner turns the [E, C, D] dispatch einsum into the expert
    all-to-all when `we_*` are sharded over the mesh "expert" axis.
  - Per-expert capacity C = ceil(N*k/E * capacity_factor) is STATIC.
    Tokens routed past an expert's capacity contribute nothing for that
    expert slot (their combine weight is zero) and fall through to the
    residual stream — the standard token-dropping trade, bounded by the
    capacity factor (config.moe_capacity_factor, default 2.0).

Expert weights are stacked [L, E, ...] so the layer scan carries them like
every other layer param; the "expert" dim shards over AXIS_EXPERT and the
per-expert FFN dim over AXIS_TENSOR (parallel/sharding.py), composing
EP x TP without any code change here — GSPMD propagates from the weight
shardings.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ollamamq_tpu.config import ModelConfig


def init_moe_layer_params(cfg: ModelConfig, key: jax.Array, dtype) -> dict:
    """Router + stacked expert weights for every layer: contributes the
    FFN entries of the `layers` tree when cfg.num_experts > 0."""
    d, f = cfg.hidden_size, cfg.intermediate_size
    L, E = cfg.num_layers, cfg.num_experts
    keys = jax.random.split(key, 4)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                / jnp.sqrt(fan_in)).astype(dtype)

    return {
        "w_router": w(keys[0], (L, d, E), d),
        "we_gate": w(keys[1], (L, E, d, f), d),
        "we_up": w(keys[2], (L, E, d, f), d),
        "we_down": w(keys[3], (L, E, f, d), f),
    }


def expert_capacity(n_tokens: int, cfg: ModelConfig) -> int:
    """Static per-expert token capacity for a batch of n_tokens."""
    ideal = n_tokens * cfg.num_experts_per_tok / cfg.num_experts
    return max(1, int(math.ceil(ideal * cfg.moe_capacity_factor)))


def moe_mlp(cfg: ModelConfig, lp: dict, h: jnp.ndarray,
            valid=None) -> jnp.ndarray:
    """Top-k routed expert FFN over [B, T, D] hiddens; returns [B, T, D].

    Same contract as llama._mlp (the residual add happens in the caller).
    `valid` ([B, T] bool, optional) marks real tokens: padding positions
    and inactive decode slots must not CLAIM expert capacity, or identical
    garbage rows (all routing alike) crowd real tokens out of their
    experts' queues and silently zero their FFN delta.
    """
    B, T, D = h.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    N = B * T
    C = expert_capacity(N, cfg)
    x = h.reshape(N, D)

    # Router in f32: the softmax is over a handful of experts and feeds
    # multiplicative gates — bf16 here costs real quality for no speed.
    logits = jnp.einsum(
        "nd,de->ne", x.astype(jnp.float32), lp["w_router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)  # [N, E]
    gate_vals, expert_idx = jax.lax.top_k(probs, K)  # [N, K]
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # Position of each (token, k-slot) in its expert's queue, token-major
    # (GShard "first C win"). sel: [N, K, E] one-hot on the routed expert;
    # invalid tokens select nothing (and so consume no capacity).
    sel = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # [N, K, E]
    if valid is not None:
        sel = sel * valid.reshape(N).astype(jnp.int32)[:, None, None]
    pos = jnp.cumsum(sel.reshape(N * K, E), axis=0).reshape(N, K, E) - sel
    keep = (pos < C) & (sel > 0)  # [N, K, E]

    # One-hot (token, k-slot) -> (expert, capacity-slot); dropped and
    # unrouted entries point at index C, whose one-hot row is all zeros.
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, C), C, dtype=h.dtype)
    dispatch = jnp.sum(pos_oh, axis=1)  # [N, E, C] 0/1 (k-slots disjoint)
    combine = jnp.einsum(
        "nkec,nk->nec", pos_oh, gate_vals.astype(h.dtype)
    )  # [N, E, C] gate weights

    # Expert compute on the dispatched [E, C, D] blocks — the einsums XLA
    # partitions over "expert"/"tensor" when we_* carry those shardings.
    xe = jnp.einsum("nec,nd->ecd", dispatch, x)
    gate = jnp.einsum("ecd,edf->ecf", xe, lp["we_gate"])
    up = jnp.einsum("ecd,edf->ecf", xe, lp["we_up"])
    out_e = jnp.einsum("ecf,efd->ecd", jax.nn.silu(gate) * up, lp["we_down"])

    y = jnp.einsum("nec,ecd->nd", combine, out_e)  # gates applied here
    return y.reshape(B, T, D)
