"""SPMD multi-host serving: 2 CPU processes, global mesh tp=2 spanning
both, primary serves a request while the worker replays its dispatches.
The generated tokens must equal a single-process run (same seed) — i.e.
cross-host tensor parallelism is numerically transparent."""

from testutil import run_two_process

_SCRIPT = r"""
import json, os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly 1 local device per process
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
assert jax.device_count() == 2

from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig
from ollamamq_tpu.parallel.mesh import make_mesh
import jax.numpy as jnp

mesh = make_mesh(dp=1, sp=1, tp=2)
ecfg = EngineConfig(model="test-tiny", max_slots=2, num_pages=32, page_size=8,
                    max_pages_per_seq=8, prefill_buckets=(16,),
                    decode_steps_per_iter=2)
mcfg = MODEL_CONFIGS["test-tiny"]

MODELS = {"test-tiny": None, "test-tiny-embed": None}

if pid == 0:
    from ollamamq_tpu.engine.spmd import SPMDEngine
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = SPMDEngine(ecfg, models=MODELS, blocklist_path=None,
                     mesh=mesh, dtype=jnp.float32)
    eng.start()
    import time

    def wait(req, budget=300):
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            item = req.stream.get(timeout=0.5)
            if item and item.kind in ("done", "error"):
                return item
        return None

    tok = eng.runtimes["test-tiny"].tokenizer
    req = eng.enqueue_request("u", "", "test-tiny",
                              prompt_tokens=tok.encode("spmd check"),
                              sampling=SamplingParams(max_tokens=6))
    wait(req)
    # Embedding request across both hosts (OP_ENCODE replay).
    etok = eng.runtimes["test-tiny-embed"].tokenizer
    ereq = eng.enqueue_request("u", "", "test-tiny-embed",
                               prompt_tokens=etok.encode("embed me"),
                               sampling=SamplingParams(), kind="embed")
    eitem = wait(ereq)
    eng.stop()  # also releases workers (single shutdown broadcast)
    print("RESULT " + json.dumps({
        "tokens": req.generated_ids,
        "embed_ok": bool(eitem and eitem.kind == "done"),
        "embed_dim": len(ereq.embedding or []),
        "embed_head": (ereq.embedding or [0.0, 0.0])[:2],
    }), flush=True)
else:
    from ollamamq_tpu.engine.spmd import run_worker

    steps = run_worker(MODELS, ecfg, mesh, dtype=jnp.float32)
    print("RESULT " + json.dumps({"steps": steps}), flush=True)
"""

def test_spmd_two_process_serving(tmp_path):
    primary, worker = run_two_process(_SCRIPT, tmp_path)
    assert worker["steps"] >= 3  # prefill + decode(s) + encode dispatch
    assert len(primary["tokens"]) >= 1
    assert primary["embed_ok"] and primary["embed_dim"] > 0

    # Single-process reference with the same seed/config must match exactly.
    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.engine import TPUEngine
    from ollamamq_tpu.ops.sampling import SamplingParams
    import jax.numpy as jnp
    import time

    eng = TPUEngine(
        EngineConfig(model="test-tiny", max_slots=2, num_pages=32, page_size=8,
                     max_pages_per_seq=8, prefill_buckets=(16,),
                     decode_steps_per_iter=2),
        models={"test-tiny": None, "test-tiny-embed": None},
        blocklist_path=None, dtype=jnp.float32,
    )
    eng.start()
    try:
        tok = eng.runtimes["test-tiny"].tokenizer

        def wait(req, budget=120):
            deadline = time.monotonic() + budget
            while time.monotonic() < deadline:
                item = req.stream.get(timeout=0.5)
                if item and item.kind in ("done", "error"):
                    return item

        req = eng.enqueue_request("u", "", "test-tiny",
                                  prompt_tokens=tok.encode("spmd check"),
                                  sampling=SamplingParams(max_tokens=6))
        wait(req)
        assert req.generated_ids == primary["tokens"]
        etok = eng.runtimes["test-tiny-embed"].tokenizer
        ereq = eng.enqueue_request("u", "", "test-tiny-embed",
                                   prompt_tokens=etok.encode("embed me"),
                                   sampling=SamplingParams(), kind="embed")
        wait(ereq)
        assert len(ereq.embedding) == primary["embed_dim"]
        import numpy as np

        np.testing.assert_allclose(
            ereq.embedding[:2], primary["embed_head"], rtol=1e-4, atol=1e-5
        )
    finally:
        eng.stop()
