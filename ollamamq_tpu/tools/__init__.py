"""Operator tools: offline consumers of engine artifacts (the decision
journal analyzer/replayer lives in tools/journal.py)."""
