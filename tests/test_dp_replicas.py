"""Data-parallel replica serving: dp=2 x tp=4 on the 8-virtual-device CPU
mesh (VERDICT r1 item 4). Each replica is an independent ModelRuntime
TP-sharded over its own slice of the mesh's data axis; placement is
least-loaded with round-robin rotation (dispatcher.rs:475-487 analogue)."""

import time

import jax
import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.engine import ReplicaSet, TPUEngine
from ollamamq_tpu.engine.request import FinishReason, Request
from ollamamq_tpu.ops.sampling import SamplingParams
from testutil import collect


def dp_cfg(**kw):
    defaults = dict(
        model="test-tiny-gqa", max_slots=2, num_pages=64, page_size=8,
        max_pages_per_seq=16, prefill_buckets=(16, 32, 64),
        max_new_tokens=8, decode_steps_per_iter=2, dp=2, tp=4,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


@pytest.fixture(scope="module")
def dp_engine():
    eng = TPUEngine(dp_cfg(), blocklist_path=None)
    eng.start()
    yield eng
    eng.stop()


def test_replicas_shard_over_disjoint_device_slices(dp_engine):
    """dp=2 builds two runtimes whose param shards live on DISJOINT 4-device
    subsets of the 8-device mesh (per-replica shards differ — this is
    replication of the model, not of the work)."""
    rs = dp_engine.runtimes["test-tiny-gqa"]
    assert isinstance(rs, ReplicaSet) and len(rs.replicas) == 2
    device_sets = []
    for rt in rs.replicas:
        leaf = jax.tree_util.tree_leaves(rt.params)[0]
        device_sets.append({d.id for d in leaf.sharding.device_set})
    assert device_sets[0] and device_sets[1]
    assert device_sets[0].isdisjoint(device_sets[1])
    # TP really sharded: each replica's tensor axis spans its 4 devices.
    assert all(len(s) == 4 for s in device_sets)


def test_two_requests_land_on_different_replicas(dp_engine):
    """Least-loaded placement spreads concurrent requests across replicas,
    and both generate correctly (greedy => identical outputs for identical
    prompts, which also pins replica weight equivalence)."""
    rs = dp_engine.runtimes["test-tiny-gqa"]
    tok = rs.tokenizer
    reqs = []
    for i, user in enumerate(("dp-a", "dp-b")):
        rid = dp_engine.core.enqueue(user, "", "test-tiny-gqa")
        req = Request(rid, user, "test-tiny-gqa", tok.encode("same prompt"),
                      SamplingParams(max_tokens=6))
        reqs.append(req)
    for r in reqs:
        dp_engine.submit(r)
    outs = [collect(r) for r in reqs]
    assert all(o[-1].kind == "done" for o in outs)
    # Both replicas were exercised.
    assert all(rt.tokens_generated > 0 for rt in rs.replicas), [
        rt.tokens_generated for rt in rs.replicas
    ]
    # Identical random-init seed + greedy => identical tokens on BOTH
    # replicas: per-replica param shards differ in placement, not values.
    assert reqs[0].generated_ids == reqs[1].generated_ids


def test_least_loaded_placement_and_rotation():
    """Placement picks the least-loaded replica; ties rotate (reference
    least-conn + rotate-after-last, dispatcher.rs:475-487)."""

    class FakeReplica:
        def __init__(self):
            self.pending_prefill = []
            self.chunking = []
            self.submitted = []
            self.capacity = True

        def has_capacity(self, kind=None):
            return self.capacity

        def active_count(self):
            return len(self.submitted)

        def submit(self, req):
            self.submitted.append(req.name)

        name = "fake"
        cfg = ecfg = None

    def _req(name):
        from types import SimpleNamespace

        return SimpleNamespace(name=name, kind="generate")

    a, b, c = FakeReplica(), FakeReplica(), FakeReplica()
    rs = ReplicaSet.__new__(ReplicaSet)
    rs.replicas = [a, b, c]
    rs._last_idx = 0
    # All empty: rotation starts after index 0 => b, then ties rotate c, a.
    rs.submit(_req("r1"))
    assert b.submitted == ["r1"]
    rs.submit(_req("r2"))
    assert c.submitted == ["r2"]
    rs.submit(_req("r3"))
    assert a.submitted == ["r3"]
    # Load-based: make b busiest, c without capacity => a wins.
    b.submitted += ["x", "y"]
    c.capacity = False
    rs.submit(_req("r4"))
    assert a.submitted == ["r3", "r4"]


def test_cancel_reaches_replica_held_request(dp_engine):
    """engine.cancel() finds requests held INSIDE a replica (client
    disconnects must cancel + reclaim under dp>1, not run to max_tokens)."""
    rs = dp_engine.runtimes["test-tiny-gqa"]
    for rt in rs.replicas:
        rt.tokenizer.eos_id = -1  # keep generating until cancelled
    free_before = [rt.alloc.free_pages for rt in rs.replicas]
    tok = rs.tokenizer
    rid = dp_engine.core.enqueue("dp-cancel", "", "test-tiny-gqa")
    req = Request(rid, "dp-cancel", "test-tiny-gqa", tok.encode("cancel me"),
                  SamplingParams(max_tokens=10_000))
    dp_engine.submit(req)
    deadline = time.monotonic() + 60
    while not req.stats.first_token_at and time.monotonic() < deadline:
        time.sleep(0.01)
    assert req.stats.first_token_at, "never started generating"
    dp_engine.cancel(rid)
    items = collect(req)
    assert items[-1].finish_reason == FinishReason.CANCELLED
    deadline = time.monotonic() + 10
    while ([rt.alloc.free_pages for rt in rs.replicas] != free_before
           and time.monotonic() < deadline):
        time.sleep(0.01)
    assert [rt.alloc.free_pages for rt in rs.replicas] == free_before
    for rt in rs.replicas:
        rt.tokenizer.eos_id = 2  # restore for other tests


def test_fairness_counters_shared_across_replicas(dp_engine):
    """Replicas share ONE scheduler core: processed counts accumulate per
    user regardless of which replica served them."""
    snap = dp_engine.core.snapshot()
    assert snap["users"]["dp-a"]["processed"] >= 1
    assert snap["users"]["dp-b"]["processed"] >= 1


def test_dp_decode_dispatches_overlap_before_any_collect():
    """The throughput point of dp (VERDICT r2 weak #1): the engine loop must
    dispatch EVERY replica's fused decode chunk before blocking on any —
    replicas on disjoint device sets then execute concurrently. Asserted
    structurally (dispatch/collect event order) rather than by wall-clock,
    which would be flaky on shared CPU cores."""
    from ollamamq_tpu.engine.engine import ModelRuntime

    eng = TPUEngine(dp_cfg(), blocklist_path=None)
    rs = eng.runtimes["test-tiny-gqa"]
    tok = rs.tokenizer
    events = []

    orig_dispatch = ModelRuntime.step_decode_dispatch
    orig_collect = ModelRuntime.step_decode_collect

    def rec_dispatch(self, core, k_steps=1):
        h = orig_dispatch(self, core, k_steps=k_steps)
        if h is not None:
            events.append(("dispatch", id(self)))
        return h

    def rec_collect(self, handle, core):
        events.append(("collect", id(self)))
        return orig_collect(self, handle, core)

    ModelRuntime.step_decode_dispatch = rec_dispatch
    ModelRuntime.step_decode_collect = rec_collect
    try:
        # One request per replica, installed via direct prefill (no loop
        # thread — we drive ticks by hand for deterministic ordering).
        for i, rep in enumerate(rs.replicas):
            req = Request(9000 + i, f"ovl{i}", "test-tiny-gqa",
                          tok.encode("overlap probe"),
                          SamplingParams(max_tokens=64))
            assert rep.submit(req)
            assert rep.step_prefill(eng.core)
        events.clear()
        eng._loop_once()
        decode_events = [e for e in events if e[0] in ("dispatch", "collect")]
        dispatches = [e for e in decode_events if e[0] == "dispatch"]
        assert len(dispatches) == 2, decode_events
        # Both dispatches precede the first collect.
        first_collect = next(
            i for i, e in enumerate(decode_events) if e[0] == "collect"
        )
        assert first_collect == 2, decode_events
    finally:
        ModelRuntime.step_decode_dispatch = orig_dispatch
        ModelRuntime.step_decode_collect = orig_collect
        for rep in rs.replicas:
            for s, r in enumerate(rep.slot_req):
                if r is not None:
                    rep._finish_slot(s, FinishReason.CANCELLED, eng.core)
