"""The driver contract: multi-chip dry run must compile+run on the
virtual CPU mesh (entry() uses the 1b model and is compile-checked by
the driver itself, not here)."""

import jax
import pytest


def test_dryrun_multichip_8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    import __graft_entry__ as g

    g.dryrun_multichip(8)
