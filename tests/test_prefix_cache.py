"""Automatic prefix caching (engine/prefix_cache.py): radix-tree KV
reuse with refcounted pages.

The load-bearing guarantees pinned here:
  - cache-on vs cache-off token streams are BYTE-IDENTICAL under greedy
    sampling (tiny llama on CPU and the fake backend), including
    repeat-penalty requests (the chunked tail seeds the penalty ring
    with the cached prefix) and a request cancelled mid-prefill whose
    pages were partially cached;
  - allocator exhaustion under a full cache triggers LRU eviction, not
    admission failure;
  - the tree + allocator invariants survive randomized
    insert/match/evict/cancel sequences (refcounts ≥ 0, no page both
    free and referenced, free + used + cached == num_pages - 1).
"""

import itertools
import random

import jax.numpy as jnp
import numpy as np
import pytest

from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig
from ollamamq_tpu.core import MQCore
from ollamamq_tpu.engine.engine import ModelRuntime, TPUEngine
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.engine.kv_cache import PageAllocator
from ollamamq_tpu.engine.prefix_cache import PrefixCache
from ollamamq_tpu.engine.request import FinishReason, Request
from ollamamq_tpu.ops.sampling import SamplingParams
from testutil import collect

_IDS = itertools.count(1)

PS = 8  # page size for every runtime-level test here


def make_rt(prefix_cache: bool, **kw) -> ModelRuntime:
    defaults = dict(
        model="test-tiny", max_slots=4, num_pages=96, page_size=PS,
        max_pages_per_seq=16, prefill_buckets=(16, 64), max_new_tokens=8,
        decode_steps_per_iter=2, prefix_cache=prefix_cache,
    )
    defaults.update(kw)
    ecfg = EngineConfig(**defaults)
    rt = ModelRuntime("test-tiny", MODEL_CONFIGS["test-tiny"], ecfg,
                      dtype=jnp.float32)
    rt.tokenizer.eos_id = -1  # deterministic full-length streams
    return rt


def run_request(rt: ModelRuntime, core: MQCore, prompt, max_tokens=6,
                repeat_penalty=1.0):
    """Drive one request synchronously to completion; returns its ids."""
    req = Request(next(_IDS), "u", "test-tiny", list(prompt),
                  SamplingParams(max_tokens=max_tokens,
                                 repeat_penalty=repeat_penalty))
    req._inc_decode = rt.tokenizer.make_incremental_decoder()
    rt.pending_prefill.append(req)
    for _ in range(200):
        if any(r is req for r in rt.slot_req):
            break
        progressed = rt.step_prefill(core)
        progressed = rt.step_chunk(core) or progressed
        assert progressed, "request stuck in admission"
    else:
        pytest.fail("request never installed")
    while any(r is req for r in rt.slot_req):
        rt.step_decode(core, k_steps=1)
    return list(req.generated_ids)


def pool_invariant(rt: ModelRuntime) -> None:
    a = rt.alloc
    assert a.free_pages + a.used_pages + a.cached_pages == a.num_pages - 1
    assert a.used_pages >= 0
    if rt.prefix_cache is not None:
        rt.prefix_cache.check()


# -- radix tree unit behavior ----------------------------------------------

def test_tree_match_insert_pin_evict():
    alloc = PageAllocator(num_pages=32, page_size=4, max_pages_per_seq=8)
    pc = PrefixCache(4, alloc, model="unit")
    tokens = list(range(12))  # 3 full blocks
    pages = alloc.alloc_n(3)
    assert pc.insert(tokens, pages) == 3
    assert alloc.cached_pages == 3 and pc.cached_pages == 3
    pc.check()

    # Full-prompt query caps the match so ≥ 1 token stays uncached.
    nodes, got = pc.match(tokens)
    assert len(nodes) == 2
    # One extra token exposes all 3 blocks.
    nodes, got = pc.match(tokens + [99])
    assert got == pages

    # Pinned paths survive eviction; unpinned leaves do not.
    pc.pin(nodes[:2])  # pin blocks 0-1; block 2 is an unpinned leaf
    assert pc.evictable_pages == 1
    assert pc.evict(5) == 1  # only the leaf
    assert alloc.cached_pages == 2
    assert pc.evict(5) == 0  # everything left is pinned
    pc.release(nodes[:2])
    pc.check()

    # Duplicate insert: the tree keeps its copy, ours is freed.
    free_before = alloc.free_pages
    dup = alloc.alloc_n(2)
    assert pc.insert(tokens[:8], dup) == 0
    assert alloc.free_pages == free_before  # both duplicates returned
    pc.check()

    # LRU: the least-recently-touched leaf goes first.
    b1 = [100] * 4 + [101] * 4
    b2 = [200] * 4 + [201] * 4
    pc.insert(b1, alloc.alloc_n(2))
    pc.insert(b2, alloc.alloc_n(2))
    pc.pin(pc.match(b2 + [0])[0])  # touch b2's path
    pc.release(pc.match(b2 + [0])[0])
    assert pc.evict(1) == 1
    assert len(pc.match(b2 + [0])[0]) == 2  # b2 untouched by the sweep
    assert len(pc.match(b1 + [0])[0]) == 2  # b1 untouched too
    # The stalest leaf was the original tokens-tree's deepest block
    # (touched last by the duplicate insert, before b1/b2 existed).
    assert len(pc.match(tokens + [99])[0]) == 1
    # Flush reclaims every unreferenced page.
    remaining = pc.cached_pages
    assert pc.flush() == remaining
    assert pc.cached_pages == 0
    pc.check()
    assert alloc.free_pages + alloc.cached_pages == alloc.num_pages - 1


# -- correctness gate: cache on/off byte-identical (tiny llama) -------------

def test_identical_streams_cache_on_vs_off():
    core = MQCore(None)
    rt_off = make_rt(False)
    rt_on = make_rt(True)  # identical weights: same seed, same config

    rng = np.random.RandomState(7)
    prefix = rng.randint(3, 500, size=4 * PS).tolist()  # 4 full pages
    tail_a = rng.randint(3, 500, size=7).tolist()
    tail_b = rng.randint(3, 500, size=9).tolist()
    long_tail = rng.randint(3, 500, size=80).tolist()  # > largest bucket

    prompts = [
        prefix + tail_a,          # miss (populates the tree on rt_on)
        prefix + tail_b,          # hit: shared 4-page prefix
        prefix + tail_a,          # hit: longest match incl. private page
        rng.randint(3, 500, size=5).tolist(),  # short, below any match
        prefix + long_tail,       # hit + chunked tail (> largest bucket)
    ]
    for i, prompt in enumerate(prompts):
        ids_off = run_request(rt_off, core, prompt)
        ids_on = run_request(rt_on, core, prompt)
        assert ids_off == ids_on, f"prompt {i}: {ids_off} != {ids_on}"
        pool_invariant(rt_on)
    assert rt_on.prefix_cache.hits >= 3
    assert rt_on.prefix_cache.tokens_saved >= 3 * 4 * PS
    assert rt_off.alloc.used_pages == 0  # everything reclaimed

    # Repeat-penalty streams must match too: the chunked tail seeds the
    # penalty ring with the cached prefix's last repeat_last_n tokens.
    pen_prompt = prefix + rng.randint(3, 500, size=6).tolist()
    ids_off = run_request(rt_off, core, pen_prompt, repeat_penalty=1.3)
    ids_on = run_request(rt_on, core, pen_prompt, repeat_penalty=1.3)
    assert ids_off == ids_on
    pool_invariant(rt_on)


def test_cancel_mid_prefill_with_partially_cached_pages():
    core = MQCore(None)
    # A single 16-token bucket so the 24-token tail needs TWO chunks —
    # the cancel really lands mid-prefill.
    rt_on = make_rt(True, prefill_buckets=(16,))
    rt_off = make_rt(False, prefill_buckets=(16,))
    rng = np.random.RandomState(13)
    base = rng.randint(3, 500, size=96).tolist()  # 12 full pages
    run_request(rt_on, core, base)  # populate the tree
    pool_invariant(rt_on)
    cached = rt_on.prefix_cache.cached_pages
    assert cached == 12

    # A longer prompt sharing the cached prefix: admission pins 12 pages
    # and routes the 24-token tail through the chunked path. Cancel it
    # after the first chunk — pages partially written, prefix pinned.
    victim = base + rng.randint(3, 500, size=24).tolist()
    req = Request(next(_IDS), "u", "test-tiny", victim,
                  SamplingParams(max_tokens=4))
    req._inc_decode = rt_on.tokenizer.make_incremental_decoder()
    rt_on.pending_prefill.append(req)
    assert rt_on.step_prefill(core)  # hit: parked in chunking
    assert rt_on.prefix_cache.hits >= 1
    assert req in rt_on.chunking
    assert rt_on.step_chunk(core)  # first tail chunk runs
    req.cancelled.set()
    assert rt_on.step_chunk(core)  # reaped: pins released, tail freed
    assert req not in rt_on.chunking
    assert not rt_on.reserved_slots
    pool_invariant(rt_on)
    assert rt_on.prefix_cache.cached_pages == cached  # nothing leaked in
    assert rt_on.prefix_cache.stats()["pinned_pages"] == 0

    # The same prompt run fresh still matches the cache-off stream.
    ids_on = run_request(rt_on, core, victim)
    ids_off = run_request(rt_off, core, base)  # warm rt_off compile path
    ids_off = run_request(rt_off, core, victim)
    assert ids_on == ids_off
    pool_invariant(rt_on)


# -- eviction under allocator pressure -------------------------------------

def test_full_cache_evicts_instead_of_failing_admission():
    core = MQCore(None)
    rt = make_rt(True, num_pages=20, max_pages_per_seq=8, max_new_tokens=4)
    rng = np.random.RandomState(3)
    # Two finished prompts leave 12 pages in the tree (6 full pages each);
    # the 19-page pool now has ≤ 7 free.
    for _ in range(2):
        run_request(rt, core, rng.randint(3, 500, size=48).tolist(),
                    max_tokens=2)
    pool_invariant(rt)
    assert rt.alloc.cached_pages == 12
    assert rt.alloc.free_pages < 8
    assert rt.has_capacity("generate")  # evictable pages count as capacity
    # A fresh 56-token prompt needs 8 pages: admission must evict, not
    # fail or wait forever.
    ids = run_request(rt, core, rng.randint(3, 500, size=56).tolist(),
                      max_tokens=2)
    assert len(ids) == 2
    assert rt.prefix_cache.evictions > 0
    pool_invariant(rt)


# -- property/fuzz: tree + allocator invariants ----------------------------

def test_fuzz_radix_tree_allocator_invariants():
    rng = random.Random(0)
    ps = 4
    num_pages = 48
    alloc = PageAllocator(num_pages=num_pages, page_size=ps,
                          max_pages_per_seq=10)
    pc = PrefixCache(ps, alloc, model="fuzz")
    live = []  # {tokens, nodes, pages, shared}

    def invariants():
        pc.check()
        used = sum(len(e["pages"]) - e["shared"] for e in live)
        assert alloc.free_pages + used + alloc.cached_pages == num_pages - 1
        tree_pages = pc.pages()
        free = set(alloc._free)
        assert not (free & tree_pages)
        private = []
        for e in live:
            private.extend(e["pages"][e["shared"]:])
        assert len(private) == len(set(private))  # no double ownership
        assert not (set(private) & tree_pages)
        assert not (set(private) & free)

    def admit():
        # Small alphabet of blocks => heavy prefix sharing.
        n_tokens = rng.randrange(ps, 9 * ps)
        tokens = []
        for _ in range(-(-n_tokens // ps)):
            tokens.extend([rng.randrange(3)] * ps)
        tokens = tokens[:n_tokens]
        nodes, shared_pages = pc.match(tokens)
        pc.pin(nodes)
        need = alloc.pages_needed(n_tokens + 1) - len(nodes)
        tail = alloc.alloc_n(need, held=len(nodes))
        if tail is None:
            short = need - alloc.free_pages
            if short > 0 and pc.evict(short) > 0:
                tail = alloc.alloc_n(need, held=len(nodes))
        if tail is None:
            pc.release(nodes)
            return
        live.append({"tokens": tokens, "nodes": nodes,
                     "pages": list(shared_pages) + tail,
                     "shared": len(nodes)})

    def retire(insert: bool):
        if not live:
            return
        e = live.pop(rng.randrange(len(live)))
        keep = e["shared"]
        if insert:  # finished request: engine's _release_slot_pages path
            full = min(len(e["tokens"]) // ps, len(e["pages"]))
            if full > keep:
                pc.insert(e["tokens"], e["pages"][:full])
                keep = full
        alloc.free(e["pages"][keep:])
        pc.release(e["nodes"])

    def extend():
        if not live:
            return
        e = rng.choice(live)
        alloc.extend(e["pages"], len(e["pages"]) * ps + rng.randrange(8))

    ops = [admit, lambda: retire(True), lambda: retire(False),
           lambda: pc.evict(rng.randrange(1, 4)), extend,
           lambda: pc.flush() if rng.random() < 0.2 else None]
    for i in range(600):
        rng.choice(ops)()
        invariants()
    while live:
        retire(True)
        invariants()
    pc.flush()
    invariants()
    assert alloc.free_pages + alloc.cached_pages == num_pages - 1


# -- engine-thread integration + fake backend ------------------------------

def engine_streams(prefix_cache: bool, prompts, fake=False):
    ecfg = EngineConfig(model="test-tiny", max_slots=4, num_pages=96,
                        page_size=PS, max_pages_per_seq=16,
                        prefill_buckets=(16, 64), max_new_tokens=6,
                        decode_steps_per_iter=2, prefix_cache=prefix_cache)
    if fake:
        eng = FakeEngine(ecfg, models={"test-tiny": None},
                         blocklist_path=None)
    else:
        eng = TPUEngine(ecfg, models={"test-tiny": None},
                        blocklist_path=None, dtype=jnp.float32)
    eng.start()
    out = []
    try:
        for prompt in prompts:
            rid = eng.core.enqueue("u", "127.0.0.1", "test-tiny")
            req = Request(rid, "u", "test-tiny", list(prompt),
                          SamplingParams(max_tokens=6))
            eng.submit(req)
            items = collect(req, timeout=120)
            assert items[-1].kind == "done", getattr(items[-1], "error", None)
            out.append(list(req.generated_ids))
    finally:
        eng.stop()
    return out, eng


def test_engine_loop_cache_on_off_identical_and_debug_api():
    rng = np.random.RandomState(23)
    prefix = rng.randint(3, 500, size=3 * PS).tolist()
    prompts = [prefix + [7, 8, 9], prefix + [11, 12], prefix + [7, 8, 9]]
    off, _ = engine_streams(False, prompts)
    on, eng = engine_streams(True, prompts)
    assert off == on
    stats = eng.prefix_cache_stats()
    assert stats["enabled"]
    ms = stats["models"]["test-tiny"]
    assert ms["hits"] >= 1 and ms["misses"] >= 1
    assert ms["cached_pages"] > 0
    # Flush on a stopped engine runs inline (call_on_loop fallback).
    freed = eng.prefix_cache_flush()
    assert freed == ms["cached_pages"]
    assert eng.prefix_cache_stats()["models"]["test-tiny"]["cached_pages"] == 0


def test_fake_backend_cache_flag_is_inert():
    prompts = [b"hello world", b"hello there"]
    prompts = [list(p) for p in prompts]
    off, _ = engine_streams(False, prompts, fake=True)
    on, eng = engine_streams(True, prompts, fake=True)
    assert off == on
    # Fake runtimes hold no KV: the cache surface reports disabled.
    assert eng.prefix_cache_stats() == {"enabled": False, "models": {}}
    assert eng.prefix_cache_flush() == 0


def test_debug_prefix_cache_http_endpoint():
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from ollamamq_tpu.server.app import Server

    async def main():
        eng = FakeEngine(EngineConfig(model="test-tiny", max_slots=4),
                         models={"test-tiny": None}, blocklist_path=None)
        eng.start()
        cl = TestClient(TestServer(Server(eng, timeout_s=10).build_app()))
        await cl.start_server()
        try:
            r = await cl.get("/debug/prefix_cache")
            assert r.status == 200
            body = await r.json()
            assert body == {"enabled": False, "models": {}}
            r = await cl.post("/debug/prefix_cache")
            assert r.status == 200
            assert (await r.json()) == {"status": "success",
                                        "freed_pages": 0}
        finally:
            await cl.close()
            eng.stop()

    asyncio.run(main())


def test_prefix_cache_metrics_exported():
    from ollamamq_tpu.telemetry import schema as tm

    core = MQCore(None)
    rt = make_rt(True)
    rng = np.random.RandomState(5)
    prompt = rng.randint(3, 500, size=3 * PS + 4).tolist()
    run_request(rt, core, prompt, max_tokens=2)
    run_request(rt, core, prompt, max_tokens=2)
    ratio = tm.PREFIX_CACHE_HIT_RATIO.labels(model="test-tiny").value
    assert 0.0 < ratio <= 1.0
    assert tm.PREFIX_CACHE_PAGES.labels(model="test-tiny").value >= 3
    assert tm.PREFIX_CACHE_TOKENS_SAVED.labels(model="test-tiny").value \
        >= 3 * PS
