"""Pallas ragged paged-attention kernel vs the jnp reference (interpret
mode on CPU; the compiled path runs on real TPU via the engine/bench)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollamamq_tpu.ops.attention import paged_decode_attention
from ollamamq_tpu.ops.pallas.paged_attention import paged_decode_attention_pallas


def _case(B, H, Hk, hd, PS_, MP, seq_lens, seed=0):
    rng = np.random.default_rng(seed)
    S = (MP * B + 2) * PS_
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(S, Hk, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(S, Hk, hd)), jnp.float32)
    pt = np.zeros((B, MP), np.int32)
    next_page = 1
    for b, L in enumerate(seq_lens):
        need = -(-L // PS_)
        pt[b, :need] = range(next_page, next_page + need)
        next_page += need
    return q, k, v, jnp.asarray(pt), jnp.asarray(seq_lens, jnp.int32)


@pytest.mark.parametrize("seq_lens", [[20, 9, 37], [1, 48, 16]])
def test_pallas_matches_reference(seq_lens):
    q, k, v, pt, sl = _case(3, 8, 4, 32, 8, 6, seq_lens)
    ref = paged_decode_attention(q, k, v, pt, sl, 8)
    out = paged_decode_attention_pallas(q, k, v, pt, sl, 8, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pallas_mqa_single_kv_head():
    q, k, v, pt, sl = _case(2, 4, 1, 16, 8, 4, [8, 25])
    ref = paged_decode_attention(q, k, v, pt, sl, 8)
    out = paged_decode_attention_pallas(q, k, v, pt, sl, 8, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_model_decode_with_pallas_impl(tiny_cfg, tiny_params):
    """forward_decode(attn_impl='pallas') == forward_decode('jnp') —
    but pallas_call's compiled path needs a TPU, so force interpret by
    monkeypatching the kernel wrapper."""
    import ollamamq_tpu.models.llama as llama_mod
    from ollamamq_tpu.engine import kv_cache as kvc
    import functools

    cfg, params = tiny_cfg, tiny_params
    PS_, MP = 8, 8
    shape = (cfg.num_layers, 32 * PS_, cfg.num_kv_heads, cfg.head_dim)
    import ollamamq_tpu.ops.pallas.paged_attention as pa

    orig = pa.paged_decode_attention_pallas
    # Force interpret even though the caller passes interpret=False
    # explicitly (a partial's keyword would be overridden).
    pa.paged_decode_attention_pallas = (
        lambda *a, **k: orig(*a, **{**k, "interpret": True})
    )
    try:
        a = kvc.PageAllocator(32, PS_, MP)
        pages = a.alloc(6)
        pt = jnp.asarray(np.stack([kvc.make_page_table_row(pages, MP)]))
        kc = jnp.zeros(shape, jnp.float32)
        vc = jnp.zeros(shape, jnp.float32)
        logits, kc, vc = llama_mod.forward_prefill(
            params, cfg, jnp.arange(1, 6, dtype=jnp.int32)[None], jnp.array([5]),
            kc, vc, pt, PS_,
        )
        out_jnp, kcj, vcj = llama_mod.forward_decode(
            params, cfg, jnp.array([7], jnp.int32), jnp.array([5], jnp.int32),
            kc, vc, pt, PS_, attn_impl="jnp",
        )
        out_pal, _, _ = llama_mod.forward_decode(
            params, cfg, jnp.array([7], jnp.int32), jnp.array([5], jnp.int32),
            kc, vc, pt, PS_, attn_impl="pallas",
        )
    finally:
        pa.paged_decode_attention_pallas = orig
    np.testing.assert_allclose(
        np.asarray(out_pal), np.asarray(out_jnp), rtol=5e-5, atol=5e-5
    )


def test_forward_prefill_sp_matches(tiny_cfg, tiny_params):
    """Sequence-parallel prefill (ring attention) == single-device prefill."""
    from jax.sharding import NamedSharding
    from ollamamq_tpu.engine import kv_cache as kvc
    from ollamamq_tpu.models import llama
    from ollamamq_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs virtual devices")
    cfg, params = tiny_cfg, tiny_params
    mesh = make_mesh(dp=1, sp=4, tp=1)
    PS_, MP = 8, 8
    T = 32
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, cfg.vocab_size, size=(1, T)),
        jnp.int32,
    )
    seq_lens = jnp.array([T])

    shape = (cfg.num_layers, 32 * PS_, cfg.num_kv_heads, cfg.head_dim)
    kc = jnp.zeros(shape, jnp.float32)
    vc = jnp.zeros(shape, jnp.float32)
    a = kvc.PageAllocator(32, PS_, MP)
    pages = a.alloc(T)
    pt = jnp.asarray(np.stack([kvc.make_page_table_row(pages, MP)]))
    ref_logits, ref_kc, _ = llama.forward_prefill(
        params, cfg, tokens, seq_lens, kc, vc, pt, PS_
    )

    with jax.set_mesh(mesh):
        sp_logits, k_stack, v_stack = llama.forward_prefill_sp(
            params, cfg, tokens, seq_lens, mesh
        )
    np.testing.assert_allclose(
        np.asarray(sp_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    # K stack matches what single-device prefill wrote into the pages.
    slots = np.asarray(
        [pages[t // PS_] * PS_ + t % PS_ for t in range(T)]
    )
    np.testing.assert_allclose(
        np.asarray(k_stack[:, 0]),  # [L,T,Hk,hd]
        np.asarray(ref_kc)[:, slots],
        rtol=2e-4, atol=2e-4,
    )
