"""Chat templating: messages -> prompt string.

The reference forwards chat bodies opaquely to Ollama, which applies each
model's template server-side; with inference in-tree, templating is ours.
Family-appropriate templates for Llama 3 and Qwen2 (ChatML), plus a plain
fallback for the byte-tokenizer test models.
"""

from __future__ import annotations

from typing import List, Optional

from ollamamq_tpu.config import ModelConfig


def chat_family(cfg: Optional[ModelConfig]) -> str:
    """'chatml' | 'llama3' | 'mistral' | 'plain' — the ONE place the
    template-family heuristics live. render_chat and template_owns_bos
    both read this, so the dispatch can't silently drift between them (a
    divergence doubles or drops the BOS on every chat prompt).

    Name prefix decides first (qwen3 has no attention bias and mixtral's
    vocab is small — architecture markers alone misroute both); the
    architecture heuristics remain for unregistered checkpoints."""
    if cfg is None:
        return "plain"
    name = cfg.name.lower()
    if name.startswith(("qwen",)):
        return "chatml"
    if name.startswith(("mixtral", "mistral")):
        return "mistral"
    if name.startswith(("llama3", "llama-3")):
        return "llama3"
    if cfg.attn_bias:  # Qwen2 family marker
        return "chatml"
    if not cfg.is_encoder and cfg.num_experts == 0 and cfg.vocab_size > 100_000:
        return "llama3"
    return "plain"


def template_owns_bos(cfg: Optional[ModelConfig]) -> bool:
    """True when the chat template emits its own begin-of-sequence text
    (Llama-3's <|begin_of_text|>) or the format defines none (ChatML).
    Plain-fallback and Mistral-[INST] models still need the tokenizer's
    BOS prepended — callers pass add_bos=not template_owns_bos(cfg)."""
    return chat_family(cfg) in ("chatml", "llama3")


def render_chat(messages: List[dict], cfg: Optional[ModelConfig]) -> str:
    """Render an Ollama/OpenAI-style messages list into a prompt."""
    msgs = []
    for m in messages:
        role = m.get("role", "user")
        content = m.get("content", "")
        if isinstance(content, list):  # OpenAI content-part arrays
            content = "".join(
                p.get("text", "") for p in content if isinstance(p, dict)
            )
        msgs.append((role, content))

    family = chat_family(cfg)
    if family == "chatml":
        out = []
        for role, content in msgs:
            out.append(f"<|im_start|>{role}\n{content}<|im_end|>\n")
        out.append("<|im_start|>assistant\n")
        return "".join(out)

    if family == "mistral":
        # Mixtral/Mistral instruct format: system text folds into the
        # first user turn; assistant turns close with </s>.
        out = []
        pending_sys = ""
        for role, content in msgs:
            if role == "system":
                pending_sys += content + "\n\n"
            elif role == "assistant":
                out.append(f"{content}</s>")
            else:
                out.append(f"[INST] {pending_sys}{content} [/INST]")
                pending_sys = ""
        if pending_sys:
            out.append(f"[INST] {pending_sys.strip()} [/INST]")
        return "".join(out)

    if family == "llama3":
        out = ["<|begin_of_text|>"]
        for role, content in msgs:
            out.append(
                f"<|start_header_id|>{role}<|end_header_id|>\n\n{content}<|eot_id|>"
            )
        out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        return "".join(out)

    out = []
    for role, content in msgs:
        out.append(f"{role}: {content}\n")
    out.append("assistant: ")
    return "".join(out)
