"""ThreadSanitizer run over the native scheduler core (cpp/mqcore.cpp):
concurrent enqueue/pop/cancel/admin/snapshot from 8 threads must produce
zero data-race reports."""

import os
import subprocess

import pytest

CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "cpp")


def test_mqcore_thread_sanitizer(tmp_path):
    exe = tmp_path / "mqcore_tsan"
    build = subprocess.run(
        ["g++", "-O1", "-g", "-std=c++17", "-fsanitize=thread",
         "mqcore.cpp", "test_mqcore_threads.cpp", "-o", str(exe), "-pthread"],
        cwd=CPP_DIR, capture_output=True, text=True,
    )
    if build.returncode != 0:
        pytest.skip(f"tsan build unavailable: {build.stderr[-300:]}")
    run = subprocess.run([str(exe)], capture_output=True, text=True, timeout=120)
    if "FATAL: ThreadSanitizer" in run.stderr:
        pytest.skip(f"tsan runtime unavailable: {run.stderr[-200:]}")
    assert run.returncode == 0, f"tsan reported races:\n{run.stderr[-3000:]}"
    assert "WARNING: ThreadSanitizer" not in run.stderr
    assert run.stdout.startswith("OK ")
