"""Native scheduler core: policy parity with the reference dispatcher.

Each test names the reference behavior it checks (file:line into
/root/reference/src/dispatcher.rs unless noted).
"""

import json
import os

import pytest

from ollamamq_tpu.core import MQCore, Family, Fairness
from ollamamq_tpu.core.mqcore import BlockedError, StuckQueue


@pytest.fixture
def core(tmp_path):
    c = MQCore(str(tmp_path / "blocked_items.json"))
    yield c
    c.close()


def drain_users(core, eligible=None, n=100):
    out = []
    for _ in range(n):
        try:
            item = core.next(eligible)
        except StuckQueue:
            out.append("<stuck>")
            continue
        if item is None:
            break
        out.append(item[1])
    return out


def test_fifo_per_user(core):
    ids = [core.enqueue("alice") for _ in range(3)]
    got = []
    while (item := core.next()) is not None:
        got.append(item[0])
    assert got == ids  # FIFO order preserved (queues push_back/pop_front)


def test_kind_aware_eligibility_gate(core):
    """Embed and generate tasks gate on SEPARATE eligibility lists: a
    saturated decode batch (model absent from the generate list) must not
    park embeds, and vice versa."""
    a = core.enqueue("alice", model="m1")  # generate kind
    b = core.enqueue("bob", model="m1", kind="embed")
    # Decode full: alice's generate pick is STUCK...
    with pytest.raises(StuckQueue):
        core.next(eligible_models=[], eligible_embed=["m1"])
    # ...but bob's embed pops through the embed list.
    rid, user, _ = core.next(eligible_models=[], eligible_embed=["m1"])
    assert rid == b and user == "bob"
    # Mirror image: embed backlog full, generates still flow.
    rid, user, _ = core.next(eligible_models=["m1"], eligible_embed=[])
    assert rid == a and user == "alice"
    # Requeued tasks keep their kind: a requeued embed still gates on the
    # embed list.
    e2 = core.requeue_front("bob", model="m1", kind="embed")
    with pytest.raises(StuckQueue):
        core.next(eligible_models=["m1"], eligible_embed=[])
    rid, _, _ = core.next(eligible_models=[], eligible_embed=["m1"])
    assert rid == e2


def test_requeue_front_preserves_fifo(core):
    """A popped-but-unplaceable task returns to the FRONT of its user's
    queue: the user's later request must never overtake it (the reference
    peeks and never pops until dispatchable, dispatcher.rs:427-431)."""
    a1 = core.enqueue("alice", model="m1")
    a2 = core.enqueue("alice", model="m2")
    rid, user, model = core.next()
    assert rid == a1 and model == "m1"
    back = core.requeue_front("alice", model="m1")
    assert back != a1  # fresh id
    rid2, _, model2 = core.next()
    assert rid2 == back and model2 == "m1"  # A again, NOT a2
    rid3, _, _ = core.next()
    assert rid3 == a2


def test_round_robin_cursor_persists(core):
    """dispatcher.rs:421-424: persistent cursor, not least-served-first."""
    for u in ("a", "b", "c"):
        for _ in range(3):
            core.enqueue(u)
    # Equal processed counts: sort is lexicographic; the persistent cursor
    # indexes into the CURRENT active list, so once 'a' drains (after pop 7)
    # the cursor lands on 'c', then wraps to 'b' — exactly what the
    # reference's current_idx does as active_users shrinks.
    assert drain_users(core) == ["a", "b", "c", "a", "b", "c", "a", "c", "b"]


def test_fairness_sort_by_processed(core):
    """dispatcher.rs:408-412: sort by lifetime processed asc, tie lexicographic."""
    core.mark_done("a", 10)
    core.mark_done("a", 10)
    core.mark_done("b", 10)
    core.enqueue("a")
    core.enqueue("b")
    core.enqueue("c")
    # Round 1: sorted [c(0), b(1), a(2)], cursor 0 -> c, cursor=1.
    # Round 2: sorted [b(1), a(2)], cursor 1 -> a (!), cursor=2.
    # Round 3: [b], cursor wraps -> b.
    # The persistent cursor means this is NOT strict least-served-first —
    # matching the reference exactly (dispatcher.rs:421-424).
    assert drain_users(core) == ["c", "a", "b"]


def test_vip_absolute_priority(core):
    """dispatcher.rs:415: VIP wins regardless of counts/cursor."""
    for u in ("a", "b", "v"):
        for _ in range(2):
            core.enqueue(u)
    core.mark_done("v", 0)  # worst fairness count — VIP still wins
    core.set_vip("v")
    assert drain_users(core)[:2] == ["v", "v"]


def test_boost_every_second(core):
    """dispatcher.rs:416-419: boost wins only when global_counter is even;
    counter increments on each pop."""
    for _ in range(4):
        core.enqueue("boosted")
        core.enqueue("other")
    core.set_boost("boosted")
    users = drain_users(core)
    # Even counter ticks go to boost; odd ticks go to the RR cursor (which
    # also reaches "boosted" on its own rotation since the boost path does
    # not advance the cursor — same as the reference, where boost roughly
    # doubles a user's share rather than strictly alternating).
    assert users == ["boosted", "boosted", "boosted", "other",
                     "boosted", "other", "other", "other"]


def test_vip_and_boost_coexist(core):
    """tui.rs:169-206: VIP and boost are independent slots — user A can be
    VIP while user B holds boost."""
    core.set_vip("a")
    core.set_boost("b")
    for u in ("a", "b", "c"):
        core.enqueue(u)
        core.enqueue(u)
    users = drain_users(core)
    # VIP drains fully first; then boost takes even ticks.
    assert users[:2] == ["a", "a"]
    assert users[2] == "b"  # counter=2, even -> boost


def test_stuck_queue_model_gate(core):
    """dispatcher.rs:444-473: policy pick's model unavailable => nothing
    popped; cursor advanced so the next round serves the next user."""
    core.enqueue("a", model="missing-model")
    core.enqueue("b", model="llama3:8b")
    with pytest.raises(StuckQueue):
        core.next(eligible_models=["llama3:8b"])
    # Next round: cursor moved past 'a', b gets served.
    rid, user, model = core.next(eligible_models=["llama3:8b"])
    assert user == "b" and model == "llama3:8b"


def test_smart_model_match_in_gate(core):
    """dispatcher.rs:231-252 semantics inside the eligibility gate."""
    core.enqueue("u", model="LLAMA3")
    rid, user, model = core.next(eligible_models=["llama3:latest"])
    assert user == "u"
    core.enqueue("u", model="qwen2.5:7b")
    with pytest.raises(StuckQueue):
        core.next(eligible_models=["llama3:latest"])


def test_no_model_passes_gate(core):
    """dispatcher.rs:453-461: no model requested => family check only
    (engine serves any family)."""
    core.enqueue("u", model=None, family=Family.OLLAMA)
    assert core.next(eligible_models=["whatever"]) is not None


def test_blocklist_and_403(core):
    """dispatcher.rs:602-610 ingress check; 184-228 persistence."""
    core.block_user("bad")
    with pytest.raises(BlockedError):
        core.enqueue("bad")
    core.block_ip("1.2.3.4")
    with pytest.raises(BlockedError):
        core.enqueue("ok-user", ip="1.2.3.4")
    core.enqueue("ok-user", ip="5.6.7.8")  # fine


def test_block_version_and_combined_check(core):
    """block_version bumps on every block mutation (the engine's late
    re-check sweep gate); is_user_or_ip_blocked covers both sets via the
    user's last recorded IP (dispatcher.rs:503-512)."""
    v0 = core.block_version()
    core.enqueue("ipuser", ip="6.6.6.6")
    assert not core.is_user_or_ip_blocked("ipuser")
    core.block_ip("6.6.6.6")
    assert core.block_version() == v0 + 1
    assert core.is_user_or_ip_blocked("ipuser")  # via IP
    assert not core.is_user_blocked("ipuser")
    core.block_user("directuser")
    assert core.block_version() == v0 + 2
    assert core.is_user_or_ip_blocked("directuser")
    core.unblock_ip("6.6.6.6")
    core.unblock_user("directuser")
    assert not core.is_user_or_ip_blocked("ipuser")


def test_queued_matching_scopes_by_model(core):
    """mq_queued_matching counts only tasks a model could serve (smart
    match or no model requested) — the decode-chunk policy's gate."""
    core.enqueue("qm1", model="llama3:8b")
    core.enqueue("qm2", model="LLAMA3")  # smart-matches llama3:8b
    core.enqueue("qm3", model="qwen2.5:7b")
    core.enqueue("qm4", model=None)  # servable by anyone
    assert core.queued_matching("llama3:8b") == 3
    assert core.queued_matching("qwen2.5:7b") == 2
    assert core.queued_matching("nomic-embed-text") == 1
    # Drain for other tests.
    while core.next(eligible_models=["llama3:8b", "qwen2.5:7b",
                                     "nomic-embed-text"]):
        pass


def test_blocklist_persistence(tmp_path):
    """blocked_items.json round-trip, reference-compatible schema
    (dispatcher.rs:19-25,165-182)."""
    path = str(tmp_path / "blocked_items.json")
    c1 = MQCore(path)
    c1.block_user("mallory")
    c1.block_ip("9.9.9.9")
    c1.close()

    data = json.loads(open(path).read())
    assert data["blocked_users"] == ["mallory"]
    assert data["blocked_ips"] == ["9.9.9.9"]

    c2 = MQCore(path)
    assert c2.is_user_blocked("mallory")
    assert c2.is_ip_blocked("9.9.9.9")
    assert c2.unblock_item("mallory")
    assert not c2.is_user_blocked("mallory")
    c2.close()
    assert json.loads(open(path).read())["blocked_users"] == []


def test_cancel_queued(core):
    """Client cancel before dispatch: request removed, counted dropped
    (dispatcher.rs:503-512 analogue)."""
    rid = core.enqueue("alice")
    assert core.cancel(rid)
    assert core.next() is None
    snap = core.snapshot()
    assert snap["users"]["alice"]["dropped"] == 1
    assert not core.cancel(rid)  # idempotent


def test_token_fairness_mode(core):
    """TPU-era fairness: sort by served tokens instead of request count."""
    core.set_fairness(Fairness.TOKENS)
    core.mark_done("a", tokens=1000)
    core.mark_done("b", tokens=10)
    core.mark_done("b", tokens=10)  # b: 2 requests but only 20 tokens
    core.enqueue("a")
    core.enqueue("b")
    assert drain_users(core) == ["b", "a"]


def test_snapshot_counters(core):
    core.enqueue("alice", ip="1.1.1.1")
    core.enqueue("alice")
    core.next()
    core.mark_started("alice")
    core.mark_done("alice", tokens=42)
    snap = core.snapshot()
    a = snap["users"]["alice"]
    assert a == {
        "queued": 1, "processing": 0, "processed": 1,
        "dropped": 0, "tokens": 42, "ip": "1.1.1.1",
    }
    assert snap["vip"] is None and snap["boost"] is None
    assert snap["global_counter"] == 1


def test_unicode_and_escaping(core):
    user = 'wéird"user\nname'
    core.enqueue(user, ip="::1")
    snap = core.snapshot()
    assert user in snap["users"]


def test_concurrent_enqueue_drain(core):
    """Thread-safety smoke: concurrent enqueues and drains lose nothing."""
    import threading

    N = 200
    def producer(u):
        for _ in range(N):
            core.enqueue(u)

    threads = [threading.Thread(target=producer, args=(f"u{i}",)) for i in range(4)]
    for t in threads:
        t.start()
    popped = []
    done = threading.Event()

    def consumer():
        while not done.is_set() or core.total_queued():
            item = core.next()
            if item:
                popped.append(item[0])

    ct = threading.Thread(target=consumer)
    ct.start()
    for t in threads:
        t.join()
    done.set()
    ct.join()
    assert len(popped) == 4 * N
    assert len(set(popped)) == 4 * N  # unique req ids, no double-pop
