"""Multi-model HBM pool on the REAL engine (BASELINE config 5): runtime
load (/api/pull), serving both models concurrently, evict (/api/delete),
stuck-in-queue for the evicted model, and re-load draining it."""

import time

import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.engine import TPUEngine
from ollamamq_tpu.engine.request import FinishReason, Request
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.server.registry import ModelRegistry
from testutil import collect


@pytest.fixture(scope="module")
def setup():
    eng = TPUEngine(
        EngineConfig(model="test-tiny", max_slots=4, num_pages=128,
                     page_size=8, max_pages_per_seq=16,
                     prefill_buckets=(16, 32, 64), max_new_tokens=8,
                     decode_steps_per_iter=2),
        blocklist_path=None,
    )
    eng.start()
    reg = ModelRegistry(eng)
    yield eng, reg
    eng.stop()


def run(eng, user, model, max_tokens=4):
    # Target model's tokenizer when loaded; any runtime's only for the
    # deliberately-evicted case (both test models use ByteTokenizer).
    rt = eng.runtimes.get(model) or next(iter(eng.runtimes.values()))
    tok = rt.tokenizer
    rid = eng.core.enqueue(user, "", model)
    req = Request(rid, user, model, tok.encode(f"for {model}"),
                  SamplingParams(max_tokens=max_tokens))
    eng.submit(req)
    return req


def test_pull_load_serve_evict_reload(setup):
    eng, reg = setup
    assert eng.loaded_models() == ["test-tiny"]

    # Runtime pull: second model loads into HBM and serves.
    reg.pull("test-tiny-gqa")
    assert set(eng.loaded_models()) == {"test-tiny", "test-tiny-gqa"}
    r1 = run(eng, "mmA", "test-tiny")
    r2 = run(eng, "mmB", "test-tiny-gqa")
    assert collect(r1)[-1].kind == "done"
    assert collect(r2)[-1].kind == "done"
    # HBM accounting covers both runtimes.
    stats = eng.stats()
    assert len(stats["runtimes"]) == 2
    assert all(s["param_bytes"] > 0 for s in stats["runtimes"])

    # Evict: requests for the gone model wait in queue (stuck semantics).
    assert reg.delete("test-tiny-gqa")
    assert eng.loaded_models() == ["test-tiny"]
    r3 = run(eng, "mmC", "test-tiny-gqa")
    time.sleep(0.5)
    assert r3.stream.get_nowait() is None  # not served, not errored
    snap = eng.core.snapshot()
    assert snap["users"]["mmC"]["queued"] == 1
    # Other model keeps serving during the outage.
    r4 = run(eng, "mmD", "test-tiny")
    assert collect(r4)[-1].kind == "done"

    # Re-pull: the parked request drains.
    reg.pull("test-tiny-gqa")
    assert collect(r3)[-1].kind == "done"


def test_evict_with_inflight_work_refuses(setup):
    eng, reg = setup
    if "test-tiny-gqa" not in eng.runtimes:  # independent of test order
        reg.pull("test-tiny-gqa")
    rt = eng.runtimes["test-tiny-gqa"]
    rt.tokenizer.eos_id = -1
    req = run(eng, "mmE", "test-tiny-gqa", max_tokens=10_000)
    deadline = time.monotonic() + 60
    while not req.stats.first_token_at and time.monotonic() < deadline:
        time.sleep(0.01)
    assert req.stats.first_token_at
    with pytest.raises(RuntimeError, match="in-flight"):
        eng.evict_model("test-tiny-gqa")
    eng.cancel(req.req_id)
    items = collect(req)
    assert items[-1].finish_reason == FinishReason.CANCELLED
    rt.tokenizer.eos_id = 2
