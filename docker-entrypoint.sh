#!/bin/sh
# Env -> CLI flag translation (the reference pattern: BACKEND_URLS/PORT/
# TIMEOUT envs feeding the binary; here MODELS replaces backend URLs).
# Args accumulate via `set --` so values with spaces survive quoting.
set -e

set -- --no-tui --host 0.0.0.0
[ -n "${MODELS:-}" ] && set -- "$@" --models "$MODELS"
[ -n "${CHECKPOINTS:-}" ] && set -- "$@" --checkpoints "$CHECKPOINTS"
[ -n "${PORT:-}" ] && set -- "$@" --port "$PORT"
[ -n "${TIMEOUT:-}" ] && set -- "$@" --timeout "$TIMEOUT"
[ -n "${TP:-}" ] && set -- "$@" --tp "$TP"
[ -n "${DP:-}" ] && set -- "$@" --dp "$DP"
[ -n "${SP:-}" ] && set -- "$@" --sp "$SP"
[ -n "${PP:-}" ] && set -- "$@" --pp "$PP"
[ -n "${EP:-}" ] && set -- "$@" --ep "$EP"
[ -n "${PAGE_SIZE:-}" ] && set -- "$@" --page-size "$PAGE_SIZE"
[ -n "${NUM_PAGES:-}" ] && set -- "$@" --num-pages "$NUM_PAGES"
[ "${SPMD:-}" = "true" ] && set -- "$@" --spmd
[ -n "${REPLICAS:-}" ] && set -- "$@" --replicas "$REPLICAS"
[ -n "${REPLICA_URLS:-}" ] && set -- "$@" --replica-urls "$REPLICA_URLS"
[ -n "${PLACEMENT:-}" ] && set -- "$@" --placement "$PLACEMENT"
[ -n "${SCHEDULER:-}" ] && set -- "$@" --scheduler "$SCHEDULER"
[ -n "${DRAIN_TIMEOUT_S:-}" ] && set -- "$@" --drain-timeout-s "$DRAIN_TIMEOUT_S"
[ "${MIGRATE:-}" = "false" ] && set -- "$@" --no-migrate
[ -n "${MIGRATE_TIMEOUT_S:-}" ] && set -- "$@" --migrate-timeout-s "$MIGRATE_TIMEOUT_S"
[ -n "${TIERS:-}" ] && set -- "$@" --tiers "$TIERS"
[ -n "${ROUTER_OVERHEAD_BUDGET_MS:-}" ] && set -- "$@" --router-overhead-budget-ms "$ROUTER_OVERHEAD_BUDGET_MS"
[ "${AUTOSCALE:-}" = "true" ] && set -- "$@" --autoscale
[ -n "${MIN_REPLICAS:-}" ] && set -- "$@" --min-replicas "$MIN_REPLICAS"
[ -n "${MAX_REPLICAS:-}" ] && set -- "$@" --max-replicas "$MAX_REPLICAS"
[ -n "${SCALE_COOLDOWN_S:-}" ] && set -- "$@" --scale-cooldown-s "$SCALE_COOLDOWN_S"
[ -n "${PREEMPTIBLE:-}" ] && set -- "$@" --preemptible "$PREEMPTIBLE"
[ "${FEDERATE_METRICS:-}" = "false" ] && set -- "$@" --no-federate-metrics
[ -n "${MAX_SLOTS:-}" ] && set -- "$@" --max-slots "$MAX_SLOTS"
[ "${HA:-}" = "true" ] && set -- "$@" --ha
[ -n "${STANDBY_OF:-}" ] && set -- "$@" --standby-of "$STANDBY_OF"
[ -n "${TAKEOVER_GRACE_S:-}" ] && set -- "$@" --takeover-grace-s "$TAKEOVER_GRACE_S"
[ -n "${WAL_DIR:-}" ] && set -- "$@" --wal-dir "$WAL_DIR"
[ -n "${WAL_FSYNC_MS:-}" ] && set -- "$@" --wal-fsync-ms "$WAL_FSYNC_MS"
[ -n "${JOURNAL_SAMPLE:-}" ] && set -- "$@" --journal-sample "$JOURNAL_SAMPLE"
[ -n "${STOP_GRACE_S:-}" ] && set -- "$@" --stop-grace-s "$STOP_GRACE_S"
[ -n "${BLOCKLIST:-}" ] && set -- "$@" --blocklist "$BLOCKLIST"
[ "${ALLOW_ALL_ROUTES:-}" = "true" ] && set -- "$@" --allow-all-routes
[ "${FAKE_ENGINE:-}" = "true" ] && set -- "$@" --fake-engine

cd /app
exec python -m ollamamq_tpu.cli "$@"
