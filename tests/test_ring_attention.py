"""Ring attention over the virtual seq-axis mesh must match single-device
causal attention exactly (long-context / context-parallel prefill path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollamamq_tpu.ops.attention import causal_attention
from ollamamq_tpu.parallel.mesh import make_mesh
from ollamamq_tpu.parallel.ring_attention import ring_attention


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_matches_causal(sp):
    if len(jax.devices()) < sp:
        pytest.skip("needs virtual devices")
    mesh = make_mesh(dp=1, sp=sp, tp=1)
    rng = np.random.default_rng(0)
    B, T, H, Hk, hd = 2, 32, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(B, T, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, Hk, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, Hk, hd)), jnp.float32)
    seq_lens = jnp.asarray([T, 19])  # one full, one ragged

    ref = causal_attention(q, k, v, seq_lens)
    with jax.set_mesh(mesh):
        out = ring_attention(q, k, v, seq_lens, mesh)

    # Positions beyond seq_len are padding — compare valid region only.
    for b, L in enumerate([T, 19]):
        np.testing.assert_allclose(
            np.asarray(out[b, :L]), np.asarray(ref[b, :L]), rtol=2e-5, atol=2e-5
        )


def test_ring_attention_jit_under_mesh():
    if len(jax.devices()) < 4:
        pytest.skip("needs virtual devices")
    mesh = make_mesh(dp=1, sp=4, tp=1)
    B, T, H, hd = 1, 16, 2, 8
    q = jnp.ones((B, T, H, hd), jnp.float32)
    with jax.set_mesh(mesh):
        fn = jax.jit(lambda q: ring_attention(q, q, q, jnp.array([T]), mesh))
        out = fn(q)
    assert out.shape == (B, T, H, hd)
    assert bool(jnp.all(jnp.isfinite(out)))
