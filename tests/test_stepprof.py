"""Engine performance plane (PR 20): the always-on step profiler,
compile-ladder observability, HBM timeline, and the bench regression
sentinel.

Contracts pinned here:

  - every ring (samples / shape table / compile events / HBM timeline)
    is bounded — always-on means O(1) memory forever;
  - a sample's phase milliseconds sum to its recorded step wall clock,
    and the instrumented wall covers >= 95% of the externally measured
    dispatch wall on a REAL tiny runtime;
  - compile events are exactly-once per (site, key) in steady state;
    an injected `compile`-site fault (jit cache eviction loop) turns
    the ladder into a storm and trips the health monitor's
    compile_storm alert past warmup;
  - the profiler survives injected dispatch faults: an abandoned step
    leaves NO partial sample and the decision journal stays clean;
  - profiler self-overhead stays under the 1% always-on budget;
  - the fleet router federates member `ollamamq_step_phase_ms` series
    with a replica label;
  - scripts/bench_compare.py classifies the checked-in wedged rounds
    as init-failed (exit 0) and exits non-zero on a synthetic >= 20%
    regression.
"""

import glob
import importlib.util
import json
import os
import re
import time

import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.telemetry import stepprof
from ollamamq_tpu.telemetry.journal import check_invariants
from ollamamq_tpu.telemetry.stepprof import (_COMPILE_RING, _HBM_RING,
                                             _RING, _SHAPE_KEYS, PHASES,
                                             PROFILER, StepProfiler)
from ollamamq_tpu.testing.faults import FaultPlan
from testutil import collect

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = dict(model="test-tiny", max_slots=2, num_pages=64, page_size=8,
            max_pages_per_seq=16, prefill_buckets=(16, 32, 64),
            decode_steps_per_iter=2)


@pytest.fixture(autouse=True)
def _fresh_profiler():
    PROFILER.reset()
    yield
    PROFILER.reset()


def _tpu_engine(plan=None, **over):
    import jax.numpy as jnp

    from ollamamq_tpu.engine.engine import TPUEngine

    cfg = dict(TINY)
    cfg.update(over)
    eng = TPUEngine(EngineConfig(fault_plan=plan, **cfg),
                    models={"test-tiny": None}, blocklist_path=None,
                    dtype=jnp.float32)
    eng.start()
    return eng


def _run(eng, user, prompt="the quick brown fox jumps", max_tokens=8):
    tok = eng.resolve_runtime("test-tiny").tokenizer
    return eng.enqueue_request(
        user, "", "test-tiny", prompt_tokens=tok.encode(prompt),
        sampling=SamplingParams(max_tokens=max_tokens))


def _phase_sum(sample):
    return sum(sample[ph + "_ms"] for ph in PHASES)


# ------------------------------------------------------------- boundedness
def test_every_ring_is_bounded():
    prof = StepProfiler()
    for i in range(_RING + 500):
        t = prof.start("ragged")
        t.mark("host_prep")
        t.finish(T_pad=(i % 100) * 8, k_cap=0, n_prefill=1, n_decode=0,
                 tokens=4, padded_tokens=8, compiled=False)
    for i in range(_COMPILE_RING + 50):
        prof.record_compile("ragged", ("ragged", i), 1.0, i)
    for i in range(_HBM_RING + 50):
        prof.hbm_record({"models": {}})
    assert len(prof.samples) == _RING
    assert prof.seq == _RING + 500          # seq keeps counting past evict
    assert len(prof._shapes) <= _SHAPE_KEYS
    assert len(prof.compiles) == _COMPILE_RING
    assert prof.compile_count() == _COMPILE_RING + 50
    assert len(prof.hbm) == _HBM_RING
    # Snapshot stays serializable and bounded too.
    snap = prof.snapshot(n=64)
    json.dumps(snap)
    assert len(snap["recent"]) == 64
    assert len(snap["shapes"]) <= _SHAPE_KEYS


# -------------------------------------------------- phase sum == wall clock
def test_phase_sum_matches_dispatch_wall_on_real_runtime():
    """ACCEPTANCE: per-sample phase ms sum EXACTLY to the sample's step
    wall (contiguous marks of one timer), and the instrumented wall
    covers >= 95% of the externally measured step_ragged wall."""
    eng = _tpu_engine()
    rt = eng.runtimes["test-tiny"]
    pairs = []  # (externally measured wall ms, the sample it produced)
    orig = rt.step_ragged

    def timed(core):
        seq0 = PROFILER.seq
        t0 = time.perf_counter()
        ran = orig(core)
        wall = (time.perf_counter() - t0) * 1e3
        if PROFILER.seq > seq0:  # this step recorded exactly one sample
            pairs.append((wall, PROFILER.tail(1)[0]))
        return ran

    rt.step_ragged = timed
    try:
        for i, u in enumerate(("alpha", "beta")):
            items = collect(_run(eng, u, prompt="count to ten " * (i + 1)))
            assert items[-1].kind == "done", items[-1].error
    finally:
        rt.step_ragged = orig
        eng.stop()

    assert pairs, "no ragged step samples were recorded"
    for wall, s in pairs:
        assert abs(_phase_sum(s) - s["total_ms"]) < 0.01, s
        assert s["mode"] in ("ragged", "spec_verify")
        assert s["tokens"] >= 0 and s["padded_tokens"] >= s["tokens"] >= 0
    measured = sum(w for w, _ in pairs)
    instrumented = sum(s["total_ms"] for _, s in pairs)
    assert instrumented >= 0.95 * measured, \
        f"instrumented {instrumented:.2f}ms < 95% of {measured:.2f}ms"
    # Decode-scan samples carry the same arithmetic identity.
    for s in PROFILER.tail():
        assert abs(_phase_sum(s) - s["total_ms"]) < 0.01, s


# ---------------------------------------------------------- compile ladder
def test_compile_events_exactly_once_per_rung_then_steady_state():
    eng = _tpu_engine()
    try:
        items = collect(_run(eng, "warm", prompt="short"))
        assert items[-1].kind == "done", items[-1].error
        items = collect(_run(eng, "warm2",
                             prompt="a much longer prompt " * 4))
        assert items[-1].kind == "done", items[-1].error
        n_warm = PROFILER.compile_count()
        assert n_warm > 0
        events = list(PROFILER.compiles)
        keys = [(e["site"], e["key"]) for e in events]
        assert len(keys) == len(set(keys)), f"duplicate compiles: {keys}"
        assert all(e["wall_ms"] > 0 for e in events)
        # Every compile journals once, with the same key vocabulary.
        jr = [r for r in eng.journal.tail(n=None) if r["kind"] == "compile"]
        assert len(jr) == n_warm
        assert {(r["site"], r["key"]) for r in jr} == set(keys)
        # At least one step paid a compile and said so.
        assert any(s.get("compiled") for s in PROFILER.tail())
        # Steady state: an identical re-run compiles NOTHING.
        items = collect(_run(eng, "steady", prompt="short"))
        assert items[-1].kind == "done", items[-1].error
        assert PROFILER.compile_count() == n_warm
    finally:
        eng.stop()


def test_injected_recompile_loop_trips_compile_storm(monkeypatch):
    """The faults.py `compile` site evicts cached jit entries, forcing a
    re-trace on every revisit — the recompile loop the compile_storm
    alert exists for. Warmup suppression, firing, and resolution all
    exercised through the real HealthMonitor rule."""
    from ollamamq_tpu.engine import health as health_mod
    from ollamamq_tpu.engine.health import HealthMonitor
    from ollamamq_tpu.telemetry import schema as tm

    plan = FaultPlan([{"site": "compile", "kind": "exception", "every": 1}])
    eng = _tpu_engine(plan=plan)
    hm = HealthMonitor(eng, period_s=999.0)  # never started: driven by hand
    try:
        collect(_run(eng, "w1", prompt="storm me"))
        n1 = PROFILER.compile_count()
        collect(_run(eng, "w2", prompt="storm me"))
        n2 = PROFILER.compile_count()
        assert n2 > n1, "eviction fault did not force recompiles"
        keys = [(e["site"], e["key"]) for e in PROFILER.compiles]
        assert len(keys) > len(set(keys)), "no duplicate (site, key) pairs"
        assert PROFILER.compile_rate_per_min() > 0

        # Inside the warmup window the rule stays quiet by design.
        monkeypatch.setattr(health_mod, "COMPILE_STORM_PER_MIN", 0.5)
        hm._check_compile_storm()
        assert "compile_storm" not in {a.name for a in eng.alerts.active()}

        # Past warmup the same rate fires, counted under kind=compile.
        monkeypatch.setattr(health_mod, "COMPILE_WARMUP_S", 0.0)
        before = tm.WATCHDOG_STALLS_TOTAL.labels(kind="compile").value
        hm._check_compile_storm()
        assert "compile_storm" in {a.name for a in eng.alerts.active()}
        assert tm.WATCHDOG_STALLS_TOTAL.labels(kind="compile").value \
            == before + 1

        # Storm over (events age out / ring reset) -> alert resolves.
        PROFILER.reset()
        hm._check_compile_storm()
        assert "compile_storm" not in {a.name for a in eng.alerts.active()}
    finally:
        eng.stop()


# ------------------------------------------------------- fault containment
def test_profiler_survives_dispatch_faults_with_clean_journal():
    """An injected ragged dispatch fault abandons that step's timer: no
    partial sample lands in the ring (every recorded sample still sums
    clean), the retried stream finishes, and the decision journal's
    invariants hold."""
    plan = FaultPlan([{"site": "ragged", "kind": "exception", "at": [1]}])
    eng = _tpu_engine(plan=plan)
    try:
        items = collect(_run(eng, "faulty"))
        assert items[-1].kind == "done", items[-1].error
        samples = PROFILER.tail()
        assert samples, "no samples after the retried dispatch"
        for s in samples:
            assert s["total_ms"] > 0
            assert abs(_phase_sum(s) - s["total_ms"]) < 0.01, s
        recs = eng.journal.tail(n=None)
        assert not check_invariants(recs)
    finally:
        eng.stop()


# ----------------------------------------------------------- self-overhead
def test_self_overhead_stays_under_one_percent():
    """ACCEPTANCE: always-on means the profiler's own clock reads and
    ring appends must cost < 1% of the step wall it measures."""
    eng = _tpu_engine()
    try:
        for u in ("o1", "o2"):
            items = collect(_run(eng, u, max_tokens=10))
            assert items[-1].kind == "done", items[-1].error
    finally:
        eng.stop()
    frac = PROFILER.overhead_fraction()
    assert PROFILER.seq > 0
    assert 0.0 <= frac < 0.01, f"profiler overhead {frac:.4f} >= 1%"


# -------------------------------------------------------------- federation
def test_federation_exposes_per_replica_step_series():
    """A fleet of real HTTP members federates their step-phase series
    into the router's /metrics exposition with a replica label."""
    from ollamamq_tpu.engine.fake import FakeEngine
    from ollamamq_tpu.fleet import FleetRouter, HttpMember
    from ollamamq_tpu.telemetry import REGISTRY
    from test_fleet import TINY as FLEET_TINY
    from test_fleet import _HttpBackend
    from test_fleet import _run as _fleet_run
    from test_fleet_obs import _wait

    member_cfg = EngineConfig(**FLEET_TINY)
    backends = [_HttpBackend(FakeEngine(member_cfg, blocklist_path=None))
                for _ in range(2)]
    for b in backends:
        b.engine.start()
    members = [HttpMember(f"h{i}", b.url, timeout_s=30, poll_period_s=0.1)
               for i, b in enumerate(backends)]
    router = FleetRouter(members, EngineConfig(**FLEET_TINY),
                         blocklist_path=None, probe_period_s=0.05,
                         eject_heartbeat_s=1.0, reprobe_backoff_s=0.1,
                         evac_grace_s=0.5)
    router.start()
    try:
        items = collect(_fleet_run(router, "fed-user"))
        assert items[-1].kind == "done", items[-1].error
        assert PROFILER.seq > 0, "fake member steps recorded no samples"

        def federated_step_series():
            fed = router.member_metric_federation()
            if {name for name, _ in fed} != {"h0", "h1"}:
                return False
            text = REGISTRY.render(federated=fed)
            return re.search(
                r'^ollamamq_step_phase_ms[^\n]*replica="h[01]"',
                text, re.M) is not None

        _wait(federated_step_series, msg="federated step-phase series")
    finally:
        router.stop()
        for b in backends:
            b.stop()


# ----------------------------------------------- capture-window cross-link
def test_window_slices_ring_by_capture_timestamps():
    """/debug/profile links its capture window to the stepprof ring by
    timestamp: samples inside [t0, t1] are returned, others are not."""
    t_before = time.time()
    t = PROFILER.start("fake")
    t.mark("dispatch")
    t.finish(T_pad=0, k_cap=0, n_prefill=0, n_decode=1, tokens=1,
             padded_tokens=1, compiled=False)
    t_after = time.time()
    inside = PROFILER.window(t_before, t_after)
    assert len(inside) == 1 and inside[0]["mode"] == "fake"
    assert PROFILER.window(t_after + 10, t_after + 20) == []
    assert PROFILER.window(t_before - 20, t_before - 10) == []


# -------------------------------------------------------- bench_compare CI
def _load_bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(_REPO, "scripts", "bench_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_compare_flags_wedged_history_as_init_failed():
    """ACCEPTANCE: the checked-in BENCH_r*.json trajectory (every round
    died at device init) classifies as init-failed — environment
    casualties, NOT regressions — and the sentinel exits 0."""
    mod = _load_bench_compare()
    files = sorted(glob.glob(os.path.join(_REPO, "BENCH_r*.json")))
    assert files, "checked-in bench history missing"
    for path in files:
        assert mod.classify(mod.load_round(path)) == "init-failed", path
    assert mod.main(files) == 0


def test_bench_compare_detects_synthetic_regressions(tmp_path):
    def write(n, value, p99):
        rec = {"n": n, "cmd": "bench", "rc": 0, "tail": "", "parsed": {
            "metric": "decode_tok_per_s_per_chip", "value": value,
            "step_profile": {"modes": {"decode": {
                "step": {"n": 10, "p50_ms": p99 / 2, "p99_ms": p99}}}}}}
        path = tmp_path / f"BENCH_r{n:02d}.json"
        path.write_text(json.dumps(rec))
        return str(path)

    mod = _load_bench_compare()
    # >= 20% tok/s drop => exit 2.
    a, b = write(1, 1000.0, 10.0), write(2, 750.0, 10.0)
    assert mod.main([a, b]) == 2
    # Step-p99 blowup with flat tok/s => still a regression.
    b2 = write(3, 990.0, 25.0)
    assert mod.main([a, b2]) == 2
    # Small drift under the threshold => clean exit.
    b3 = write(4, 950.0, 10.5)
    assert mod.main([a, b3]) == 0
    # A wedged round interleaved in the trajectory is skipped, and the
    # comparable neighbours still diff against each other.
    wedged = tmp_path / "BENCH_r05.json"
    wedged.write_text(json.dumps({
        "n": 5, "cmd": "bench", "rc": 3, "tail": "", "parsed": {
            "metric": "decode_tok_per_s_per_chip", "value": 0.0,
            "error": "device/runtime init exceeded 300s", "phase": "init"}}))
    assert mod.main([a, str(wedged)]) == 0
    assert mod.main([a, str(wedged), b]) == 2
