"""Attention ops: causal prefill attention and paged decode attention.

The paged decode path is the TPU replacement for the reference's
one-request-per-backend model (/root/reference/src/dispatcher.rs:438):
many sequences share one forward step, each reading its own scattered KV
pages. The jnp implementations here are the semantic reference; the Pallas
ragged-paged-attention kernel (ollamamq_tpu/ops/pallas) is the fast path
and must match these numerically.

KV cache layout (flat token-slot pool, page-aligned):
    k_cache, v_cache: [num_layers, num_pages * page_size, kv_heads, head_dim]
A "page" is page_size contiguous slots; the host-side allocator
(engine/kv_cache.py) hands out page indices, and `flat_slot_indices`
translates (page_table, position) -> slot index.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[.., L, kv_heads, hd] -> [.., L, kv_heads*n_rep, hd] (GQA head groups)."""
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=-2)


def causal_attention(
    q: jnp.ndarray,  # [B, T, H, hd]
    k: jnp.ndarray,  # [B, T, Hk, hd]
    v: jnp.ndarray,  # [B, T, Hk, hd]
    seq_lens: jnp.ndarray,  # [B] valid lengths (padding masked out)
) -> jnp.ndarray:
    """Causal self-attention over a padded prefill batch. f32 softmax."""
    B, T, H, hd = q.shape
    n_rep = H // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    logits = logits * scale
    pos = jnp.arange(T)
    causal = pos[None, :] <= pos[:, None]  # [q, k]
    valid = pos[None, None, :] < seq_lens[:, None, None]  # [B, 1, k]
    mask = causal[None, None, :, :] & valid[:, None, :, :]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def bidirectional_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, seq_lens: jnp.ndarray
) -> jnp.ndarray:
    """Full (non-causal) attention for encoder/embedding models."""
    B, T, H, hd = q.shape
    n_rep = H // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    logits = logits * scale
    pos = jnp.arange(T)
    valid = pos[None, None, None, :] < seq_lens[:, None, None, None]
    logits = jnp.where(valid, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def flat_slot_indices(
    page_table: jnp.ndarray,  # [B, max_pages] int32 page ids
    positions: jnp.ndarray,  # [B, L] int32 token positions within each seq
    page_size: int,
) -> jnp.ndarray:
    """Translate per-sequence token positions to flat cache slot indices."""
    page = jnp.take_along_axis(page_table, positions // page_size, axis=-1)
    return page * page_size + positions % page_size


def paged_chunk_attention(
    q: jnp.ndarray,  # [B, C, H, hd] — a chunk of new tokens per sequence
    k_cache: jnp.ndarray,  # [S, Hk, hd] flat slot pool for ONE layer
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    start: jnp.ndarray,  # [B] global position of the chunk's first token
    chunk_lens: jnp.ndarray,  # [B] valid tokens in this chunk (<= C)
    page_size: int,
) -> jnp.ndarray:
    """Chunked-prefill attention: the chunk's K/V are already scattered
    into the cache, so each query at global position start+i attends to
    cache positions <= start+i. Generalizes decode attention (C == 1).
    """
    B, C, H, hd = q.shape
    max_pages = page_table.shape[1]
    L = max_pages * page_size
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32), (B, L))
    slots = flat_slot_indices(page_table, positions, page_size)  # [B, L]
    k = k_cache[slots]  # [B, L, Hk, hd]
    v = v_cache[slots]
    n_rep = H // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    logits = jnp.einsum(
        "bchd,blhd->bhcl", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale  # [B, H, C, L]
    q_pos = start[:, None] + jnp.arange(C)[None, :]  # [B, C] global positions
    causal = positions[:, None, :] <= q_pos[:, :, None]  # [B, C, L]
    in_seq = positions[:, None, :] < (start + chunk_lens)[:, None, None]
    mask = (causal & in_seq)[:, None, :, :]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhcl,blhd->bchd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, H, hd] one new token per sequence
    k_cache: jnp.ndarray,  # [S, Hk, hd] flat slot pool for ONE layer
    v_cache: jnp.ndarray,  # [S, Hk, hd]
    page_table: jnp.ndarray,  # [B, max_pages]
    seq_lens: jnp.ndarray,  # [B] context length INCLUDING the new token
    page_size: int,
) -> jnp.ndarray:
    """Decode attention: each query attends to its own paged context.

    The C == 1 case of paged_chunk_attention (the new token sits at
    position seq_len-1 and sees everything before it). jnp reference
    path — on TPU the Pallas kernel replaces it with per-page reads and
    no materialization.
    """
    out = paged_chunk_attention(
        q[:, None], k_cache, v_cache, page_table,
        start=seq_lens - 1, chunk_lens=jnp.ones_like(seq_lens),
        page_size=page_size,
    )
    return out[:, 0]
