"""Durability manager: WAL wiring, cold-restart recovery, resumable
streams.

One manager per serving front-end (TPUEngine/FakeEngine when it owns
admission, FleetRouter in fleet mode — members never double-WAL, same as
the journal spill). It owns three pieces:

  RequestWAL       the durable admission log (durability/wal.py);
  StreamRegistry   per-stream frame log fed by a TokenStream tap: every
                   (token_id, text) item a client stream carried, plus
                   its terminal — what `GET /api/stream/{rid}?from=N`
                   replays byte-identical;
  recovery pass    at start(): read the previous generation's WAL,
                   re-admit every unfinished request token-exact through
                   the front-end's own enqueue path (`context` replay —
                   generated_ids pre-filled, max_tokens re-based so the
                   total budget is unchanged), journal `recover_replay`,
                   and compact the surviving state into a fresh WAL
                   generation.

Recovered streams have no client attached; a drainer thread consumes
their TokenStreams (the tap already captured every item) so generation
proceeds, and a reattaching client replays from the registry. Stream
identity is the rid the client saw on its NDJSON frames — recovery keys
the registry under the OLD rid (aliased to the new one), so the handle
printed before the crash still resolves after it.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ollamamq_tpu.durability.wal import RequestWAL
from ollamamq_tpu.telemetry import schema as tm

log = logging.getLogger("ollamamq.durability")

# Finished streams kept replayable for late resume; live streams are
# never evicted.
ARCHIVE_STREAMS = 512


class StreamEntry:
    """One stream's replayable history: (token_id, text) frames in emit
    order plus the terminal. Indexing for ?from=N counts frames whose
    token_id >= 0 (held-back/flush text rides id -1 frames)."""

    __slots__ = ("rid", "frames", "terminal", "lock", "recovered")

    def __init__(self, rid: int, recovered: bool = False):
        self.rid = rid
        self.frames: List[Tuple[int, str]] = []
        self.terminal: Optional[dict] = None
        self.lock = threading.Lock()
        self.recovered = recovered

    def append(self, token_id: int, text: str) -> None:
        with self.lock:
            if self.terminal is None:
                self.frames.append((int(token_id), text))

    def finish(self, reason: str, error: str = "") -> None:
        with self.lock:
            if self.terminal is None:
                self.terminal = {"reason": reason, "error": error}

    def snapshot(self, start: int) -> Tuple[List[Tuple[int, str]],
                                            Optional[dict]]:
        with self.lock:
            return self.frames[start:], self.terminal

    def token_count(self) -> int:
        with self.lock:
            return sum(1 for tid, _ in self.frames if tid >= 0)


class StreamRegistry:
    """rid -> StreamEntry, with aliasing (a recovered stream's new rid
    points at its original entry) and bounded archival of finished
    entries."""

    def __init__(self, max_entries: int = ARCHIVE_STREAMS):
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: Dict[int, StreamEntry] = {}
        self._order: List[int] = []  # insertion order, eviction candidates

    def create(self, rid: int, recovered: bool = False) -> StreamEntry:
        ent = StreamEntry(rid, recovered=recovered)
        with self._lock:
            self._entries[rid] = ent
            self._order.append(rid)
            self._evict_locked()
        return ent

    def alias(self, rid: int, entry: StreamEntry) -> None:
        with self._lock:
            self._entries[rid] = entry

    def find(self, rid: int) -> Optional[StreamEntry]:
        with self._lock:
            return self._entries.get(rid)

    def _evict_locked(self) -> None:
        # Evict oldest FINISHED entries past the cap; live streams stay.
        while len(self._order) > self.max_entries:
            for i, rid in enumerate(self._order):
                ent = self._entries.get(rid)
                if ent is None or ent.terminal is not None:
                    self._order.pop(i)
                    if ent is not None:
                        self._entries = {k: v for k, v
                                         in self._entries.items()
                                         if v is not ent}
                    break
            else:
                return  # everything live: let it grow (bounded by slots)


def _sampling_state(s) -> dict:
    return {
        "temperature": s.temperature, "top_k": s.top_k, "top_p": s.top_p,
        "repeat_penalty": s.repeat_penalty,
        "presence_penalty": s.presence_penalty,
        "frequency_penalty": s.frequency_penalty,
        "seed": s.seed, "max_tokens": s.max_tokens,
        "stop": list(s.stop), "deadline_ms": s.deadline_ms,
    }


def _sampling_from_state(state: dict, max_tokens: int):
    """Rebuild SamplingParams with fields set RAW (the stored seed is
    already folded — running __post_init__ on it would re-fold and fork
    the sampled stream; same convention as request_from_migration_state)."""
    from ollamamq_tpu.ops.sampling import SamplingParams

    sp = SamplingParams()
    for key, val in (state or {}).items():
        setattr(sp, key, val)
    sp.stop = tuple(sp.stop or ())
    sp.max_tokens = max_tokens
    return sp


class DurabilityManager:
    """See module docstring. Attached as `engine.durability` when
    EngineConfig.wal_dir is set; None otherwise (zero overhead)."""

    def __init__(self, ecfg, journal=None, alerts=None, fault_plan=None):
        self.ecfg = ecfg
        self.journal = journal
        self.alerts = alerts
        self.registry = StreamRegistry()
        self.wal = RequestWAL(ecfg.wal_dir, fsync_ms=ecfg.wal_fsync_ms,
                              fault_plan=fault_plan,
                              on_degrade=self._on_degrade)
        self.recovering = False
        self.recovered_streams = 0
        self._started = False
        self._recover_key: Optional[int] = None  # set around re-admission
        self._orphans: Dict[int, object] = {}    # entry-rid -> Request
        self._orphan_lock = threading.Lock()
        self._stop = threading.Event()
        self._drainer: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self, engine) -> None:
        """Recovery + WAL begin. Called from the front-end's start()
        AFTER its loop thread is up (re-admission needs a live engine).
        Idempotent across hot-restarts: recovery runs once per manager."""
        if self._started:
            if self.wal._fh is None and not self.wal.dead:
                self.wal.begin()  # re-opened after a close()
            self._ensure_drainer()
            return
        self._started = True
        self.recovering = True
        try:
            prev, torn = self.wal.read_existing()
            live = self._recover(engine, prev)
            if torn:
                log.warning("WAL recovery skipped %d torn line(s)", torn)
        finally:
            self.recovering = False
        self.wal.begin(initial=live)
        self._ensure_drainer()

    def _ensure_drainer(self) -> None:
        if self._drainer is None or not self._drainer.is_alive():
            self._stop.clear()
            self._drainer = threading.Thread(target=self._drain_loop,
                                             name="wal-drainer",
                                             daemon=True)
            self._drainer.start()

    def close(self) -> None:
        """Graceful shutdown: final flush + fsync of the WAL."""
        self._stop.set()
        t = self._drainer
        if t is not None:
            t.join(timeout=5.0)
            self._drainer = None
        self.wal.close()

    def wal_snapshot(self, mark=None) -> List[str]:
        """(HA) The current WAL generation's raw lines after a forced
        flush — the standby's cold catch-up payload. `mark` runs under
        the WAL lock at the snapshot edge (see RequestWAL.snapshot_lines)."""
        return self.wal.snapshot_lines(mark=mark)

    def _on_degrade(self, msg: str) -> None:
        if self.alerts is not None:
            try:
                self.alerts.fire("wal_degraded", "error",
                                 f"admission WAL degraded: {msg}",
                                 source="durability")
            except Exception:  # noqa: BLE001
                log.exception("wal_degraded alert failed")

    # -- admission ---------------------------------------------------------
    def admit(self, req, prompt_tokens=None) -> None:
        """Durably record one accepted generation request BEFORE the
        enqueue ACK returns, and start capturing its stream. `prompt_tokens`
        is the PRISTINE client prompt (before any context fold — the
        caller has it in hand; recovery re-folds explicitly)."""
        if req.kind != "generate":
            return  # embeds recompute cheaply and carry no stream
        key = self._recover_key
        if key is not None:
            # Recovery re-admission: the WAL entry (old rid, folded
            # state) is written by the compaction in begin(); here we
            # only rewire the live capture under the ORIGINAL identity.
            entry = self.registry.find(key)
            if entry is not None:
                self.registry.alias(req.req_id, entry)
                self._install_tap(req, entry, key)
                return
        rid = int(req.req_id)
        pristine = [int(t) for t in (prompt_tokens
                                     if prompt_tokens is not None
                                     else req.prompt_tokens)]
        rec = {
            "k": "admit", "rid": rid, "t": time.time(),
            "user": req.user, "model": req.model, "kind": req.kind,
            "raw_prompt": req.raw_prompt,
            "prompt": pristine,
            "ctx": [int(t) for t in req.generated_ids],
            "sampling": _sampling_state(req.sampling),
            "max_tokens_total": int(req.sampling.max_tokens),
        }
        entry = self.registry.create(rid)
        self._install_tap(req, entry, rid)
        fsync_ms = self.wal.admit(rec)
        if self.journal is not None:
            self.journal.record("wal_admit", req=req,
                                fsync_ms=round(fsync_ms, 3),
                                n_prompt=len(pristine))

    def _install_tap(self, req, entry: StreamEntry, wal_rid: int) -> None:
        wal = self.wal

        def tap(item) -> None:
            if item.kind == "token":
                entry.append(item.token_id, item.text)
                wal.append_tokens(
                    wal_rid, [[int(item.token_id), item.text]])
            else:
                reason = (item.finish_reason.value
                          if item.finish_reason is not None
                          else ("error" if item.kind == "error" else "stop"))
                entry.finish(reason, error=item.error)
                wal.finish(wal_rid, reason)

        req.stream.tap = tap

    # -- recovery ----------------------------------------------------------
    def _recover(self, engine, prev: Dict[int, dict]) -> Dict[int, dict]:
        """Re-admit every unfinished WAL'd request token-exact; returns
        the live state the fresh WAL generation is compacted from."""
        live: Dict[int, dict] = {}
        if prev:
            # Pre-crash clients still hold their old rids (the resume
            # handles their NDJSON frames carried): advance the id
            # counter past them so this generation's fresh requests can
            # never collide in the stream registry or on the wire.
            reserve = getattr(getattr(engine, "core", None),
                              "reserve_req_ids", None)
            if reserve is not None:
                reserve(max(prev) + 1)
        for rid in sorted(prev):
            ent = prev[rid]
            if ent["finished"] is not None:
                # Finished before the crash: nothing to re-admit, but a
                # client cut off mid-read can still replay the archive
                # through the resume endpoint.
                entry = self.registry.create(rid, recovered=True)
                for tid, text in ent["toks"]:
                    entry.append(tid, text)
                entry.finish(ent["finished"])
                continue
            admit = ent["admit"]
            toks = ent["toks"]
            gen = ([int(t) for t in admit.get("ctx") or []]
                   + [int(i) for i, _ in toks])
            total = int(admit.get("max_tokens_total") or 0)
            entry = self.registry.create(rid, recovered=True)
            for tid, text in toks:
                entry.append(tid, text)
            remaining = total - len(gen)
            if remaining <= 0:
                # The budget was already spent when the process died:
                # nothing to regenerate — surface the terminal the crash
                # swallowed so a resuming client gets its done frame.
                entry.finish("length")
                self.wal.finish(rid, "length")  # buffered until begin()
                self._note_recovered(rid, admit, len(gen),
                                     outcome="finished")
                live[rid] = ent
                continue
            sp = _sampling_from_state(admit.get("sampling"),
                                      max_tokens=remaining)
            self._recover_key = rid
            try:
                req = engine.enqueue_request(
                    admit.get("user", "anonymous"), "",
                    admit.get("model", ""),
                    prompt_tokens=[int(t) for t in admit.get("prompt", [])],
                    sampling=sp, kind="generate",
                    raw_prompt=admit.get("raw_prompt", ""),
                    context_ids=gen or None)
            except Exception as e:  # noqa: BLE001 — one bad entry must
                # not sink the rest of the recovery pass
                log.exception("WAL recovery of req %d failed", rid)
                entry.finish("error", error=f"recovery failed: {e}")
                self._note_recovered(rid, admit, len(gen),
                                     outcome="failed")
                continue
            finally:
                self._recover_key = None
            with self._orphan_lock:
                self._orphans[rid] = req
            self._note_recovered(req.req_id, admit, len(gen),
                                 outcome="replayed", wal_rid=rid)
            self.recovered_streams += 1
            live[rid] = ent
        return live

    def _note_recovered(self, rid: int, admit: dict, tokens: int,
                        outcome: str,
                        wal_rid: Optional[int] = None) -> None:
        tm.RECOVERED_STREAMS_TOTAL.labels(outcome=outcome).inc()
        if self.journal is not None:
            # req_id = the RE-ADMITTED id (the one this journal's later
            # finish record will carry), so the exactly-one-terminal
            # audit pairs them; wal_rid = the pre-crash client handle.
            self.journal.record(
                "recover_replay", req_id=rid,
                user=admit.get("user"), model=admit.get("model") or None,
                tokens=tokens, outcome=outcome,
                n_prompt=len(admit.get("prompt") or ()),
                wal_rid=wal_rid)
        log.warning("WAL recovery: req %d %s (%d token(s) restored)",
                    rid, outcome, tokens)

    def _drain_loop(self) -> None:
        """Consume recovered (client-less) streams so generation
        proceeds; the tap already captured every item, so drained items
        are discarded. A reattaching client replays from the registry."""
        while not self._stop.wait(0.02):
            with self._orphan_lock:
                items = list(self._orphans.items())
            for rid, req in items:
                done = False
                while (item := req.stream.get_nowait()) is not None:
                    if item.kind in ("done", "error"):
                        done = True
                if done:
                    with self._orphan_lock:
                        self._orphans.pop(rid, None)

    # -- introspection -----------------------------------------------------
    def status(self) -> dict:
        with self._orphan_lock:
            orphans = len(self._orphans)
        return {
            "enabled": True,
            "recovering": self.recovering,
            "recovered_streams": self.recovered_streams,
            "orphan_streams": orphans,
            "wal": self.wal.status(),
        }
