"""SPMD sequence-parallel prefill: 2 CPU processes, mesh seq axis spanning
both — a long prompt takes the OP_PREFILL_SP broadcast path and the
generated tokens equal a single-process run."""

from testutil import run_two_process

_SCRIPT = r"""
import json, os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly 1 local device per process
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
assert jax.device_count() == 2

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.parallel.mesh import make_mesh
import jax.numpy as jnp

mesh = make_mesh(dp=1, sp=2, tp=1)
ecfg = EngineConfig(model="test-tiny", max_slots=2, num_pages=64, page_size=8,
                    max_pages_per_seq=16, prefill_buckets=(16,),
                    decode_steps_per_iter=2, sp=2)

if pid == 0:
    from ollamamq_tpu.engine.spmd import SPMDEngine
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = SPMDEngine(ecfg, models={"test-tiny": None}, blocklist_path=None,
                     mesh=mesh, dtype=jnp.float32)
    eng.start()
    rt = eng.runtimes["test-tiny"]
    assert rt._sp, "seq axis not detected"
    tok = rt.tokenizer
    prompt = tok.encode("sequence parallel spmd " * 3)  # ~70 > bucket 16
    req = eng.enqueue_request("u", "", "test-tiny", prompt_tokens=prompt,
                              sampling=SamplingParams(max_tokens=5))
    import time
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        item = req.stream.get(timeout=0.5)
        if item and item.kind in ("done", "error"):
            break
    used_sp = any(isinstance(k, tuple) and k[0] == "sp"
                  for k in rt._prefill_jits)
    eng.stop()
    print("RESULT " + json.dumps({"tokens": req.generated_ids,
                                  "used_sp": used_sp}), flush=True)
else:
    from ollamamq_tpu.engine.spmd import run_worker

    steps = run_worker({"test-tiny": None}, ecfg, mesh, dtype=jnp.float32)
    print("RESULT " + json.dumps({"steps": steps}), flush=True)
"""

def test_spmd_sp_prefill_two_processes(tmp_path):
    primary, worker = run_two_process(_SCRIPT, tmp_path)
    assert primary["used_sp"], "long prompt did not take the SP path"
    assert worker["steps"] >= 2  # sp prefill + decode dispatches
    assert len(primary["tokens"]) >= 1

    # Single-process reference (same seed/config) must match exactly.
    import time

    import jax.numpy as jnp

    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.engine import TPUEngine
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = TPUEngine(
        EngineConfig(model="test-tiny", max_slots=2, num_pages=64,
                     page_size=8, max_pages_per_seq=16, prefill_buckets=(16,),
                     decode_steps_per_iter=2),
        models={"test-tiny": None}, blocklist_path=None, dtype=jnp.float32,
    )
    eng.start()
    try:
        tok = eng.runtimes["test-tiny"].tokenizer
        req = eng.enqueue_request(
            "u", "", "test-tiny",
            prompt_tokens=tok.encode("sequence parallel spmd " * 3),
            sampling=SamplingParams(max_tokens=5))
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            item = req.stream.get(timeout=0.5)
            if item and item.kind in ("done", "error"):
                break
        assert req.generated_ids == primary["tokens"]
    finally:
        eng.stop()
