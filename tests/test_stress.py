"""Randomized multi-user integration stress — the port of the reference's
only test (test_dispatcher.sh): 50 users x 1-12 randomized requests across
4 endpoints x 2 models, 10% early-cancel, 5% multimodal (base64 image)
payloads. Where the bash script's success criterion was "non-empty
response body" + visual TUI inspection, this asserts the accounting
invariants: every request either processed or dropped, queues drained,
KV/slots reclaimed, and no engine stall.
"""

import asyncio
import base64
import json
import random
import tempfile

from aiohttp.test_utils import TestClient, TestServer

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.server.app import Server

USERS = [f"user{i:02d}" for i in range(50)]
MODELS = ["test-tiny", "qwen-fake"]  # Ollama-style + LM-Studio-style naming
ENDPOINTS = ["/api/generate", "/api/chat", "/v1/chat/completions", "/v1/completions"]
TINY_PNG = base64.b64encode(bytes.fromhex(
    "89504e470d0a1a0a0000000d4948445200000001000000010802000000907753de"
)).decode()


def _body(endpoint: str, model: str, rng: random.Random) -> dict:
    n = rng.randint(1, 6)
    if endpoint == "/api/generate":
        body = {"model": model, "prompt": "stress prompt", "stream": rng.random() < 0.5,
                "options": {"num_predict": n}}
        if rng.random() < 0.05:  # multimodal injection (5%)
            body["images"] = [TINY_PNG]
        return body
    if endpoint == "/api/chat":
        return {"model": model, "stream": rng.random() < 0.5,
                "messages": [{"role": "user", "content": "hello"}],
                "options": {"num_predict": n}}
    if endpoint == "/v1/chat/completions":
        return {"model": model, "stream": rng.random() < 0.5, "max_tokens": n,
                "messages": [{"role": "user", "content": "hello"}]}
    return {"model": model, "prompt": "stress", "max_tokens": n,
            "stream": rng.random() < 0.3}


def test_stress_50_users():
    rng = random.Random(1234)

    async def main():
        with tempfile.TemporaryDirectory() as tmp:
            eng = FakeEngine(
                EngineConfig(model="test-tiny", max_slots=16),
                models={"test-tiny": None, "qwen-fake": None},
                blocklist_path=f"{tmp}/blocked_items.json",
            )
            # qwen-fake isn't a known architecture: register a FakeRuntime
            # directly (the fake layer doesn't need a ModelConfig).
            eng.start()
            server = Server(eng, timeout_s=60)
            # make the second model visible to the registry layer
            server.registry._entries["qwen-fake"] = next(
                iter(server.registry._entries.values())
            ).__class__("qwen-fake", server.registry._entries["test-tiny"].config)
            cl = TestClient(TestServer(server.build_app()))
            await cl.start_server()
            try:
                stats = {"ok": 0, "cancelled": 0, "errors": 0}

                async def one_request(user: str):
                    endpoint = rng.choice(ENDPOINTS)
                    model = rng.choice(MODELS)
                    body = _body(endpoint, model, rng)
                    cancel = rng.random() < 0.10  # 10% early-cancel
                    try:
                        if cancel:
                            try:
                                await asyncio.wait_for(
                                    cl.post(endpoint, json=body,
                                            headers={"X-User-ID": user}),
                                    timeout=0.05,
                                )
                                stats["ok"] += 1
                            except asyncio.TimeoutError:
                                stats["cancelled"] += 1
                            return
                        r = await cl.post(endpoint, json=body,
                                          headers={"X-User-ID": user})
                        text = await r.text()
                        assert r.status == 200, f"{endpoint}: {r.status} {text[:200]}"
                        assert text.strip(), "empty response body"
                        stats["ok"] += 1
                    except AssertionError:
                        raise
                    except Exception:
                        stats["errors"] += 1

                tasks = []
                for user in USERS:
                    for _ in range(rng.randint(1, 12)):
                        tasks.append(one_request(user))
                rng.shuffle(tasks)
                await asyncio.gather(*tasks)

                # Drain: engine must settle with empty queues.
                for _ in range(100):
                    if eng.core.total_queued() == 0 and not any(
                        rt.has_work() for rt in eng.runtimes.values()
                    ):
                        break
                    await asyncio.sleep(0.05)
                assert eng.core.total_queued() == 0

                snap = eng.core.snapshot()
                total_processed = sum(u["processed"] for u in snap["users"].values())
                total_dropped = sum(u["dropped"] for u in snap["users"].values())
                total_processing = sum(u["processing"] for u in snap["users"].values())
                assert total_processing == 0  # gauge back to zero
                assert stats["ok"] > 0 and stats["errors"] == 0
                # Everything accounted for: completions + drops >= successful
                # HTTP requests (cancelled ones may land either side).
                assert total_processed + total_dropped >= stats["ok"]
                # Fairness sanity: many distinct users actually got served.
                served_users = [u for u, v in snap["users"].items() if v["processed"] > 0]
                assert len(served_users) >= 40
            finally:
                await cl.close()
                eng.stop()

    asyncio.run(main())


def test_stress_with_vip_boost_and_blocks():
    """The 64-user VIP/Boost mix of BASELINE config 4 at the API level."""
    rng = random.Random(99)

    async def main():
        with tempfile.TemporaryDirectory() as tmp:
            eng = FakeEngine(
                EngineConfig(model="test-tiny", max_slots=8),
                models={"test-tiny": None},
                blocklist_path=f"{tmp}/blocked_items.json",
            )
            eng.start()
            eng.core.set_vip("vip-user")
            eng.core.set_boost("boost-user")
            eng.core.block_user("blocked-user")
            server = Server(eng, timeout_s=60)
            cl = TestClient(TestServer(server.build_app()))
            await cl.start_server()
            try:
                users = [f"u{i}" for i in range(61)] + ["vip-user", "boost-user", "blocked-user"]

                async def go(user):
                    r = await cl.post("/api/generate", json={
                        "model": "test-tiny", "prompt": "x", "stream": False,
                        "options": {"num_predict": 2}},
                        headers={"X-User-ID": user})
                    return user, r.status

                results = await asyncio.gather(*(go(u) for u in users))
                by_user = dict(results)
                assert by_user["blocked-user"] == 403
                assert by_user["vip-user"] == 200
                assert sum(1 for _, s in results if s == 200) == 63
                snap = eng.core.snapshot()
                assert snap["users"]["vip-user"]["processed"] == 1
                assert "blocked-user" not in snap["users"] or \
                    snap["users"]["blocked-user"]["processed"] == 0
            finally:
                await cl.close()
                eng.stop()

    asyncio.run(main())
