"""Fleet router: dispatcher-over-engines with health-driven failover,
journal-backed stream replay, and zero-drop draining.

The robustness contract under test: a replica dying (or being ejected,
or drained) mid-stream is INVISIBLE to the client beyond latency — the
stream continues byte-identically on another replica, nothing is
dropped, and the decision journal explains every eject/failover/drain
with the inputs that justified it.
"""

import asyncio
import dataclasses
import json
import threading
import time

import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.engine.request import FinishReason
from ollamamq_tpu.fleet import FleetRouter, HttpMember, LocalMember
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.telemetry import schema as tm
from ollamamq_tpu.testing.faults import FaultPlan
from ollamamq_tpu.tools.journal import check_no_dropped_streams
from testutil import collect, free_port

TINY = dict(model="test-tiny", max_slots=4, num_pages=64, page_size=8,
            max_pages_per_seq=8, prefill_buckets=(16, 32),
            decode_steps_per_iter=2)

FAST = dict(probe_period_s=0.05, eject_heartbeat_s=5.0,
            reprobe_backoff_s=0.1, evac_grace_s=1.0)


def _fake_fleet(n=2, token_latency_s=0.0, plan=None, router_kw=None,
                **ecfg_over):
    cfg = dict(TINY)
    cfg.update(ecfg_over)
    ecfg = EngineConfig(fault_plan=plan, **cfg)
    member_cfg = dataclasses.replace(ecfg, fault_plan=None, max_queued=0,
                                     max_queued_per_user=0)
    members = [
        LocalMember(f"r{i}", FakeEngine(member_cfg, blocklist_path=None,
                                        token_latency_s=token_latency_s))
        for i in range(n)
    ]
    kw = dict(FAST)
    kw.update(router_kw or {})
    router = FleetRouter(members, ecfg, blocklist_path=None, **kw)
    router.start()
    return router


def _tpu_fleet(n=2, plan=None, router_kw=None, **ecfg_over):
    import jax.numpy as jnp

    from ollamamq_tpu.engine.engine import TPUEngine

    cfg = dict(TINY)
    cfg.update(ecfg_over)
    ecfg = EngineConfig(fault_plan=plan, **cfg)
    member_cfg = dataclasses.replace(ecfg, fault_plan=None, max_queued=0,
                                     max_queued_per_user=0)
    members = [
        LocalMember(f"r{i}", TPUEngine(member_cfg,
                                       models={"test-tiny": None},
                                       blocklist_path=None,
                                       dtype=jnp.float32))
        for i in range(n)
    ]
    kw = dict(FAST)
    kw.update(router_kw or {})
    router = FleetRouter(members, ecfg, blocklist_path=None, **kw)
    router.start()
    return router


def _run(router, user, prompt="the quick brown fox jumps over", max_tokens=8,
         **sp_kw):
    rt = router.resolve_runtime("test-tiny")
    if rt is not None:
        tokens = rt.tokenizer.encode(prompt)
    else:
        from ollamamq_tpu.engine.tokenizer import ByteTokenizer

        tokens = ByteTokenizer().encode(prompt)
    return router.enqueue_request(
        user, "", "test-tiny", prompt_tokens=tokens,
        sampling=SamplingParams(max_tokens=max_tokens, **sp_kw),
        raw_prompt=prompt)


def _text(items):
    return "".join(i.text for i in items if i.kind == "token")


def _serving_member(router, req):
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        for f in list(router.flights):
            if f.req is req and f.member is not None:
                return f.member
        time.sleep(0.01)
    raise TimeoutError("request never placed")


# ------------------------------------------------------------ basic routing
def test_least_loaded_placement_spreads_across_members():
    router = _fake_fleet(n=2)
    try:
        reqs = [_run(router, f"u{i}") for i in range(8)]
        for r in reqs:
            items = collect(r)
            assert items[-1].kind == "done"
            assert _text(items).startswith("word0 ")
        placed = {rec["runtime"] for rec in router.journal.tail(
            None, kind="place")}
        assert placed == {"r0", "r1"}, placed
        assert check_no_dropped_streams(router.journal.tail(None)) == []
    finally:
        router.stop()


def test_bounded_admission_sheds_fleet_wide_with_aggregate_retry_after():
    from ollamamq_tpu.engine.engine import QueueFullError

    # 2 members x 1 slot, slow tokens: the 3rd+ request queues at the
    # ROUTER; the per-user cap sheds the 4th with a fleet-derived
    # Retry-After.
    router = _fake_fleet(n=2, token_latency_s=0.2, max_slots=1,
                         max_queued_per_user=1)
    try:
        reqs = []
        with pytest.raises(QueueFullError) as ei:
            for _ in range(11):  # the cap must hit while members serve
                reqs.append(_run(router, "greedy", max_tokens=4))
                time.sleep(0.02)  # let earlier ones place (cap is on the
                #                   ROUTER queue, not on in-flight work)
        assert ei.value.scope == "user_queue_full"
        assert 1 <= ei.value.retry_after_s <= 300
        sheds = router.journal.tail(None, kind="shed")
        assert sheds and sheds[-1]["reason"] == "user_queue_full"
        for r in reqs:
            collect(r)
        # Fleet-wide aggregation: the ROUTER tracer observed every
        # member's completions (this is what keeps Retry-After honest
        # when one replica is ejected — the rate is the fleet's, not one
        # member's share).
        assert len(router.tracer.finish_times) == len(reqs)
    finally:
        router.stop()


# ------------------------------------------------------- failover (local)
@pytest.mark.parametrize(
    "prefix_cache,spec",
    [(False, False), (True, False), (True, True)],
    ids=["plain", "cache", "cache+spec"])
def test_failover_byte_identity_fuzz(prefix_cache, spec):
    """Kill a replica mid-stream: every stream — failed-over ones
    included — matches the single-replica golden run byte for byte,
    across prefix cache on/off and speculative decoding on/off."""
    over = dict(prefix_cache=prefix_cache, spec=spec, spec_k=2)
    # Repetitive prompts give the n-gram proposer drafts to verify and
    # the prefix cache shared pages to pin.
    prompts = [
        "the cat sat on the mat the cat sat on the",
        "the cat sat on the mat the cat sat on a",
        "pack my box with five dozen jugs",
        "the cat sat on the mat the cat sat on my",
        "pack my box with five dozen mugs",
        "the cat sat on the mat the cat",
    ]
    golden = _tpu_fleet(n=1, **over)
    try:
        gtexts = [_text(collect(_run(golden, f"u{i % 3}", p,
                                     max_tokens=12)))
                  for i, p in enumerate(prompts)]
    finally:
        golden.stop()

    router = _tpu_fleet(n=2, **over)
    try:
        reqs = [_run(router, f"u{i % 3}", p, max_tokens=12)
                for i, p in enumerate(prompts)]
        # Wait for real mid-stream state (some tokens emitted), then
        # kill whichever member is serving the most streams.
        deadline = time.monotonic() + 120
        victim = None
        while time.monotonic() < deadline and victim is None:
            for f in list(router.flights):
                if f.attempt is not None \
                        and len(f.attempt.req.generated_ids) >= 2:
                    victim = f.member
                    break
            time.sleep(0.01)
        assert victim is not None, "no stream reached mid-generation"
        victim.crash()
        texts = [_text(collect(r)) for r in reqs]
        assert texts == gtexts
        recs = router.journal.tail(None)
        assert any(r["kind"] == "replica_eject" for r in recs)
        # Recovery is migration-first (zero recomputed tokens), with
        # recompute failover as the fallback — either way the victim
        # streams above continued byte-identically.
        assert router.migration_count + router.failover_count >= 1
        assert check_no_dropped_streams(recs) == []
        from ollamamq_tpu.telemetry.journal import check_invariants

        assert check_invariants(recs) == []
    finally:
        router.stop()


def test_affinity_placement_routes_to_cached_replica():
    router = _tpu_fleet(n=2, prefix_cache=True)
    try:
        prompt = "shared system preamble for affinity routing tests ok"
        collect(_run(router, "aff", prompt, max_tokens=4))
        first = router.journal.tail(None, kind="place")[-1]["runtime"]
        hits0 = tm.FLEET_AFFINITY_HITS_TOTAL.value
        collect(_run(router, "aff", prompt, max_tokens=4))
        second = router.journal.tail(None, kind="place")[-1]["runtime"]
        assert second == first  # the radix tree holds the prefix there
        assert tm.FLEET_AFFINITY_HITS_TOTAL.value > hits0
    finally:
        router.stop()


# ------------------------------------------------- eject / heal / rejoin
def test_ejected_replica_rejoins_after_heal():
    """faults.py site "replica" device_loss with heal_after_s: the member
    crashes, its stream fails over, the router's backoff re-probe keeps
    it ejected until the plan heals, then it rejoins — and the watchdog
    replica_stale alert fires while it is out and resolves after."""
    plan = FaultPlan([{"site": "replica", "kind": "device_loss",
                       "at": [1], "heal_after_s": 0.6}])
    router = _fake_fleet(n=2, token_latency_s=0.05, plan=plan)
    try:
        req = _run(router, "heal", max_tokens=16)
        deadline = time.monotonic() + 30
        while router.fleet_counts()["ejected"] == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert router.fleet_counts()["ejected"] == 1
        assert router.stale_replicas() == ["r0"]
        before = tm.WATCHDOG_STALLS_TOTAL.labels(kind="replica").value
        router.health.check_once()
        assert any(a.name == "replica_stale"
                   for a in router.alerts.active())
        assert tm.WATCHDOG_STALLS_TOTAL.labels(
            kind="replica").value == before + 1
        items = collect(req)
        assert items[-1].kind == "done"
        assert _text(items).startswith("word0 word1 ")
        deadline = time.monotonic() + 30
        while router.fleet_counts()["healthy"] < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.fleet_counts() == {"healthy": 2, "ejected": 0,
                                         "draining": 0}
        joins = [r for r in router.journal.tail(None, kind="replica_join")
                 if r.get("why") == "heal"]
        assert joins and joins[-1]["replica"] == "r0"
        router.health.check_once()
        assert not any(a.name == "replica_stale"
                       for a in router.alerts.active())
    finally:
        router.stop()


def test_slow_fault_forces_stale_heartbeat_eject_and_rejoin():
    plan = FaultPlan([{"site": "replica", "kind": "slow", "delay_s": 0.5,
                       "at": [2]}])  # call 2 = member r1, first sweep
    router = _fake_fleet(n=2, token_latency_s=0.02, plan=plan,
                         router_kw=dict(eject_heartbeat_s=0.2))
    try:
        reqs = [_run(router, f"s{i}", max_tokens=10) for i in range(4)]
        for r in reqs:
            assert collect(r)[-1].kind == "done"
        recs = router.journal.tail(None)
        ejected = [r for r in recs if r["kind"] == "replica_eject"]
        assert any(r["why"] == "stale_heartbeat" for r in ejected)
        deadline = time.monotonic() + 30
        while router.fleet_counts()["healthy"] < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.fleet_counts()["healthy"] == 2
        assert check_no_dropped_streams(router.journal.tail(None)) == []
    finally:
        router.stop()


# --------------------------------------------------------------- drain e2e
def test_drain_completes_all_streams_over_http():
    from aiohttp.test_utils import TestClient, TestServer

    from ollamamq_tpu.server.app import Server

    router = _fake_fleet(n=2, token_latency_s=0.05)

    async def main():
        cl = TestClient(TestServer(Server(router, timeout_s=60).build_app()))
        await cl.start_server()
        try:

            async def stream_one(i):
                texts = []
                async with cl.post("/api/generate", json={
                        "model": "test-tiny", "prompt": f"hello {i}",
                        "options": {"num_predict": 10}},
                        headers={"X-User-ID": f"d{i}"}) as resp:
                    assert resp.status == 200
                    async for line in resp.content:
                        if not line.strip():
                            continue
                        obj = json.loads(line)
                        texts.append(obj.get("response", ""))
                        if obj.get("done"):
                            assert obj["done_reason"] in ("length", "stop")
                return "".join(texts)

            tasks = [asyncio.ensure_future(stream_one(i)) for i in range(6)]
            await asyncio.sleep(0.15)  # streams are mid-flight
            resp = await cl.post("/admin/drain/r0")
            assert resp.status == 200
            body = await resp.json()
            assert body["state"] == "draining"
            texts = await asyncio.gather(*tasks)
            for t in texts:
                assert t.startswith("word0 word1 ")  # nothing dropped
            # The drained member hot-restarts and rejoins.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                fl = await (await cl.get("/admin/fleet")).json()
                if fl["counts"] == {"healthy": 2, "ejected": 0,
                                    "draining": 0}:
                    break
                await asyncio.sleep(0.05)
            assert fl["counts"]["healthy"] == 2
            assert fl["placement"] == "affinity"
            # Unknown replica 404s; a drain of an ejected member 409s.
            assert (await cl.post("/admin/drain/nope")).status == 404
            recs = router.journal.tail(None)
            kinds = [r["kind"] for r in recs]
            assert "replica_drain" in kinds
            assert any(r["kind"] == "replica_join"
                       and r.get("why") == "drain_complete" for r in recs)
            assert check_no_dropped_streams(recs) == []
        finally:
            await cl.close()

    asyncio.run(main())
    router.stop()


# ------------------------------------------------------------ HTTP members
class _HttpBackend:
    """A real-socket engine server for HttpMember tests."""

    def __init__(self, engine):
        self.engine = engine
        self.port = free_port()
        self._loop = None
        self._runner = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        assert self._started.wait(15), "backend server did not start"

    def _serve(self):
        from aiohttp import web

        from ollamamq_tpu.server.app import Server

        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        app = Server(self.engine, timeout_s=30).build_app()
        runner = web.AppRunner(app, shutdown_timeout=1.0)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", self.port)
        loop.run_until_complete(site.start())
        self._runner = runner
        self._started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())
        loop.close()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def stop(self):
        """HARD kill: abort every live connection (RST, not a graceful
        shutdown that would let in-flight handlers finish streaming),
        then stop the loop — the failure mode a crashed service
        actually presents."""
        loop = self._loop
        if loop is not None and loop.is_running():

            async def _abort():
                server = getattr(self._runner, "server", None)
                for conn in list(getattr(server, "connections", None)
                                 or []):
                    t = getattr(conn, "transport", None)
                    if t is not None:
                        t.abort()

            try:
                asyncio.run_coroutine_threadsafe(_abort(),
                                                 loop).result(timeout=5)
            except Exception:
                pass
            loop.call_soon_threadsafe(loop.stop)
        self._thread.join(timeout=15)
        self.engine.stop()


def test_http_members_serve_and_fail_over():
    """The docker-compose shape: a pure router over two engine services
    speaking the existing HTTP API. Killing a backend mid-stream fails
    the victim over (text-level replay) and drops nothing."""
    member_cfg = EngineConfig(**TINY)
    backends = [
        _HttpBackend(FakeEngine(member_cfg, blocklist_path=None,
                                token_latency_s=0.05))
        for _ in range(2)
    ]
    for b in backends:
        b.engine.start()
    ecfg = EngineConfig(**TINY)
    members = [HttpMember(f"h{i}", b.url, timeout_s=30, poll_period_s=0.1)
               for i, b in enumerate(backends)]
    router = FleetRouter(members, ecfg, blocklist_path=None,
                         probe_period_s=0.05, eject_heartbeat_s=1.0,
                         reprobe_backoff_s=0.2, evac_grace_s=0.5)
    router.start()
    try:
        warm = _run(router, "h-warm", "warmup prompt", max_tokens=4)
        items = collect(warm)
        assert items[-1].kind == "done"
        assert _text(items) == "word0 word1 word2 word3 "

        req = _run(router, "h-kill", "victim prompt", max_tokens=16)
        mem = _serving_member(router, req)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            f = next((f for f in list(router.flights) if f.req is req),
                     None)
            if f is not None and f.attempt is not None \
                    and f.attempt.n_items >= 2:
                break
            time.sleep(0.01)
        backends[int(mem.name[1])].stop()  # the service dies mid-stream
        items = collect(req, timeout=60)
        assert items[-1].kind == "done"
        # The NDJSON frames carry token_ids, so the resumed stream
        # replays in TOKEN space (Ollama `context`): the surviving
        # backend continues the word cursor where the dead one stopped —
        # byte-identical, verified token-identical, no gap.
        tokens = [i for i in items if i.kind == "token"]
        assert len(tokens) == 16
        assert _text(items) == "".join(f"word{i} " for i in range(16))
        assert [i.token_id for i in tokens] == list(range(1, 17))
        assert router.failover_count >= 1
        assert check_no_dropped_streams(router.journal.tail(None)) == []
    finally:
        router.stop()
        for b in backends:
            b.stop()


# ------------------------------------------------------- journal & surfaces
def test_fleet_journal_kinds_schema_and_explanations():
    from ollamamq_tpu.telemetry.journal import (Journal, JournalError,
                                                explain)

    j = Journal(capacity=64)
    j.record("replica_eject", replica="r1", why="stale_heartbeat",
             victims=3, heartbeat_age_s=4.2, backoff_s=0.5)
    j.record("replica_failover", req_id=7, user="u", replica="r1",
             to_replica="r0", replayed_tokens=5)
    j.record("replica_drain", replica="r0", inflight=2, timeout_s=30.0)
    j.record("replica_join", replica="r1", why="heal")
    j.record("migrate_export", req_id=7, user="u", replica="r1",
             tokens=5, kv_len=21, pages=3, bytes=4096)
    j.record("migrate_import", req_id=7, user="u", replica="r1",
             to_replica="r0", tokens=5, pages=3, bytes=4096)
    j.record("migrate_abort", req_id=8, user="u", replica="r1",
             why="timeout")
    texts = [explain(r) for r in j.tail(None)]
    assert "r1 ejected (stale_heartbeat)" in texts[0]
    assert "3 in-flight stream(s)" in texts[0]
    assert "failed over from replica r1 to r0" in texts[1]
    assert "replaying 5" in texts[1]
    assert "draining" in texts[2]
    assert "joined rotation (heal)" in texts[3]
    assert "exported for migration" in texts[4]
    assert "0 recomputed" in texts[5] and "r1 -> r0" in texts[5]
    assert "aborted (timeout)" in texts[6]
    assert "recompute" in texts[6]
    with pytest.raises(JournalError):
        j.record("replica_eject", why="missing-replica-field")
    with pytest.raises(JournalError):
        j.record("replica_failover", replica="r1", bogus=1)
    with pytest.raises(JournalError):
        j.record("migrate_export", replica="r1")  # missing tokens
    with pytest.raises(JournalError):
        j.record("migrate_abort", replica="r1")  # missing why


def test_no_dropped_streams_checker_flags_missing_terminal():
    clean = [
        {"kind": "replica_failover", "req_id": 4, "seq": 1},
        {"kind": "finish", "req_id": 4, "seq": 2, "reason": "length"},
    ]
    assert check_no_dropped_streams(clean) == []
    dropped = [
        {"kind": "replica_failover", "req_id": 4, "seq": 1},
        {"kind": "replica_failover", "req_id": 9, "seq": 3},
        {"kind": "deadline_drop", "req_id": 9, "seq": 4},
    ]
    bad = check_no_dropped_streams(dropped)
    assert len(bad) == 1 and "req 4" in bad[0] and "DROPPED" in bad[0]


def test_tui_brief_carries_replica_counts():
    from ollamamq_tpu.admin.tui import _engine_stats_brief

    router = _fake_fleet(n=2)
    try:
        brief = _engine_stats_brief(router)
        assert brief["replicas"] == {"healthy": 2, "ejected": 0,
                                     "draining": 0}
        assert len(brief["models"]) == 2  # one test-tiny row per member
    finally:
        router.stop()
    single = FakeEngine(EngineConfig(**TINY), blocklist_path=None)
    brief = _engine_stats_brief(single)
    assert "replicas" not in brief


def test_fleet_metrics_and_stats_surface():
    router = _fake_fleet(n=2)
    try:
        for i in range(3):
            collect(_run(router, f"m{i}"))
        snap = {}
        for label_values, child in tm.FLEET_REPLICAS.series():
            snap[label_values[0]] = child.value
        assert snap == {"healthy": 2, "ejected": 0, "draining": 0}
        stats = router.stats()
        assert stats["fleet"]["counts"]["healthy"] == 2
        assert len(stats["fleet"]["replicas"]) == 2
        assert stats["queue"] is not None
        assert len(stats["runtimes"]) == 2
        assert {r["replica"] for r in stats["runtimes"]} == {"r0", "r1"}
    finally:
        router.stop()


def test_cli_fleet_flag_validation():
    from ollamamq_tpu.cli import main

    assert main(["--replicas", "0", "--no-tui"]) == 2
    assert main(["--replicas", "-1", "--no-tui"]) == 2
    assert main(["--drain-timeout-s", "0", "--no-tui"]) == 2
    assert main(["--migrate-timeout-s", "0", "--no-tui"]) == 2
    assert main(["--migrate-timeout-s", "-1", "--no-tui"]) == 2
    assert main(["--replicas", "2", "--spmd", "--no-tui"]) == 2


# ------------------------------------------------------------- migration
def _alloc_conserved(router):
    """free + used + cached == pool on every member runtime."""
    for mem in router.local_members:
        for rt in mem.engine.runtimes.values():
            alloc = getattr(rt, "alloc", None)
            if alloc is None:
                continue
            assert (alloc.free_pages + alloc.used_pages
                    + alloc.cached_pages == alloc.num_pages - 1), (
                f"{mem.name}: free {alloc.free_pages} + used "
                f"{alloc.used_pages} + cached {alloc.cached_pages} "
                f"!= pool {alloc.num_pages - 1}")


def _member_journals_clean(router):
    from ollamamq_tpu.telemetry.journal import check_invariants

    for mem in router.local_members:
        assert check_invariants(mem.engine.journal.tail(None)) == [], \
            mem.name


@pytest.mark.parametrize(
    "prefix_cache,kv_dtype,spec,seed",
    [(False, "bfloat16", False, 0), (True, "bfloat16", False, 1),
     (False, "int8", False, 2), (True, "int8", True, 3)],
    ids=["plain", "cache", "int8", "cache+int8+spec"])
def test_migration_fuzz_byte_identity_and_page_conservation(
        prefix_cache, kv_dtype, spec, seed):
    """Kill a member at a randomized decode depth across the
    prefix-cache x int8-KV x spec matrix: victim streams MIGRATE (KV
    pages shipped, zero recomputed tokens), every stream matches the
    single-replica golden byte for byte, and page conservation
    (free+used+cached==pool) holds on BOTH members through the
    export/import/abort traffic."""
    import random

    over = dict(prefix_cache=prefix_cache, kv_dtype=kv_dtype, spec=spec,
                spec_k=2)
    prompts = [
        "the cat sat on the mat the cat sat on the",
        "the cat sat on the mat the cat sat on a",
        "pack my box with five dozen jugs",
        "the cat sat on the mat the cat sat on my",
        "pack my box with five dozen mugs",
        "the cat sat on the mat the cat",
    ]
    # Randomized decode depth for the kill, kept shallow enough that
    # the victim member still holds live streams when the eject's
    # migration pass runs (the dying loop finishes its current
    # iteration first). The budget is generous (48) for the same
    # reason: a 16-token stream could run out between the depth probe
    # below and the health sweep noticing the dead loop, leaving the
    # eject nothing to migrate.
    depth = random.Random(seed).randrange(1, 6)
    golden = _tpu_fleet(n=1, **over)
    try:
        gtexts = [_text(collect(_run(golden, f"mg{i % 2}", p,
                                     max_tokens=48)))
                  for i, p in enumerate(prompts)]
    finally:
        golden.stop()

    router = _tpu_fleet(n=2, **over)
    try:
        reqs = [_run(router, f"mg{i % 2}", p, max_tokens=48)
                for i, p in enumerate(prompts)]
        deadline = time.monotonic() + 120
        victim = None
        while time.monotonic() < deadline and victim is None:
            for f in list(router.flights):
                if f.attempt is not None \
                        and len(f.attempt.req.generated_ids) >= depth:
                    victim = f.member
                    break
            time.sleep(0.01)
        assert victim is not None, "no stream reached the kill depth"
        victim.crash()
        texts = [_text(collect(r)) for r in reqs]
        assert texts == gtexts
        recs = router.journal.tail(None)
        migrated = [r for r in recs if r["kind"] == "migrate_import"
                    and r.get("what") != "prefix"]
        assert migrated, "the crash should have migrated at least one " \
                         "stream (state was frozen, not lost)"
        assert router.migration_count >= 1
        assert tm.FLEET_MIGRATIONS_TOTAL.labels(
            outcome="migrated").value >= 1
        # Two-phase completeness + zero drops on the router journal,
        # page conservation + invariants on each member's own journal.
        assert check_no_dropped_streams(recs) == []
        from ollamamq_tpu.telemetry.journal import check_invariants

        assert check_invariants(recs) == []
        _member_journals_clean(router)
        # Let the healed member's restart settle before the allocator
        # sweep (pages of evacuated slots reclaim via cancellation).
        deadline = time.monotonic() + 30
        while router.fleet_counts()["healthy"] < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        _alloc_conserved(router)
    finally:
        router.stop()


def test_drain_migrates_streams_instead_of_running_them_out():
    """/admin/drain ships live streams to healthy members: the drain
    completes without waiting out long generations, the migrated word
    streams continue their numbering seamlessly, and nothing drops."""
    router = _fake_fleet(n=2, token_latency_s=0.05)
    try:
        reqs = [_run(router, f"dm{i}", max_tokens=16) for i in range(4)]
        # Wait until every stream is placed and mid-generation.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            placed = [f for f in list(router.flights)
                      if f.attempt is not None]
            if len(placed) == 4 and all(
                    f.attempt.req.generated_ids for f in placed):
                break
            time.sleep(0.01)
        router.drain_replica("r0")
        for r in reqs:
            items = collect(r)
            assert items[-1].kind == "done"
            text = _text(items)
            assert text.startswith("word0 word1 ")
            # Seamless continuation: the word cursor migrated with the
            # stream, so numbering never restarts.
            words = text.split()
            assert words == [f"word{i}" for i in range(len(words))]
        recs = router.journal.tail(None)
        assert any(r["kind"] == "migrate_export" for r in recs)
        assert any(r["kind"] == "migrate_import" for r in recs)
        assert router.migration_count >= 1
        assert check_no_dropped_streams(recs) == []
        deadline = time.monotonic() + 30
        while router.fleet_counts()["healthy"] < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.fleet_counts()["healthy"] == 2
    finally:
        router.stop()


def test_migration_mid_transfer_crash_falls_back_to_recompute():
    """faults.py site "migrate": the first transfer dies mid-flight
    (exception) and the second loses its SOURCE right after export
    (device_loss) — both abort into the recompute-replay fallback with
    zero dropped streams and a clean two-phase journal pairing."""
    plan = FaultPlan([
        {"site": "migrate", "kind": "exception", "at": [1]},
    ])
    router = _fake_fleet(n=2, token_latency_s=0.05, plan=plan)
    try:
        reqs = [_run(router, f"ab{i}", max_tokens=16) for i in range(3)]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            placed = [f for f in list(router.flights)
                      if f.member is not None
                      and f.member.name == "r0"
                      and f.attempt is not None
                      and f.attempt.req.generated_ids]
            if placed:
                break
            time.sleep(0.01)
        assert placed, "no stream mid-generation on r0"
        router.drain_replica("r0")
        for r in reqs:
            items = collect(r)
            assert items[-1].kind == "done"
            words = _text(items).split()
            assert words == [f"word{i}" for i in range(len(words))]
        recs = router.journal.tail(None)
        aborts = [r for r in recs if r["kind"] == "migrate_abort"]
        assert aborts and aborts[0]["why"] == "fault_injected"
        assert tm.FLEET_MIGRATIONS_TOTAL.labels(
            outcome="aborted").value >= 1
        # The aborted handoff is paired (export -> abort) and the stream
        # still reached its terminal: nothing dropped, nothing orphaned.
        assert check_no_dropped_streams(recs) == []
    finally:
        router.stop()


def test_migration_source_death_after_export_still_lands():
    """site "migrate" device_loss: the source member dies right after
    the export snapshot. The import still lands (the blob is already
    off the member), the commit resolves inline against the dead loop,
    and the ejected source heals back in later."""
    plan = FaultPlan([
        {"site": "migrate", "kind": "device_loss", "at": [1],
         "heal_after_s": 0.5},
    ])
    router = _fake_fleet(n=2, token_latency_s=0.05, plan=plan)
    try:
        reqs = [_run(router, f"dl{i}", max_tokens=16) for i in range(3)]
        deadline = time.monotonic() + 30
        placed = []
        while time.monotonic() < deadline:
            placed = [f for f in list(router.flights)
                      if f.member is not None
                      and f.member.name == "r0"
                      and f.attempt is not None
                      and f.attempt.req.generated_ids]
            if placed:
                break
            time.sleep(0.01)
        assert placed, "no stream mid-generation on r0"
        router.drain_replica("r0")
        for r in reqs:
            items = collect(r)
            assert items[-1].kind == "done"
            words = _text(items).split()
            assert words == [f"word{i}" for i in range(len(words))]
        recs = router.journal.tail(None)
        assert any(r["kind"] == "migrate_import" for r in recs)
        assert check_no_dropped_streams(recs) == []
    finally:
        router.stop()


def test_affinity_miss_ships_prefix_to_chosen_member():
    """When the cached member can't take the request, the prefix ships
    TO the chosen member instead of the router routing around it: the
    target's radix tree gains the pages and journals the shipment."""
    router = _tpu_fleet(n=2, prefix_cache=True)
    try:
        prompt = "shared system preamble for prefix shipping tests ok"
        collect(_run(router, "ps", prompt, max_tokens=4))
        holder = router.journal.tail(None, kind="place")[-1]["runtime"]
        src = next(m for m in router.members if m.name == holder)
        dst = next(m for m in router.members if m.name != holder)
        tokens = router.resolve_runtime("test-tiny").tokenizer.encode(
            prompt)
        assert src.affinity_pages("test-tiny", tokens) >= 1
        assert dst.affinity_pages("test-tiny", tokens) == 0
        flight = type("F", (), {"rid0": 999, "user": "ps", "model":
                      "test-tiny", "kind": "generate",
                      "prompt_tokens": tokens})()
        router._maybe_ship_prefix(flight, dst)
        assert dst.affinity_pages("test-tiny", tokens) >= 1
        ships = [r for r in router.journal.tail(None, kind="migrate_import")
                 if r.get("what") == "prefix"]
        assert ships and ships[-1]["replica"] == holder \
            and ships[-1]["to_replica"] == dst.name
        _alloc_conserved(router)
        _member_journals_clean(router)
    finally:
        router.stop()


def test_http_member_drain_migrates_over_admin_migrate_wire():
    """HTTP-member drain rides the /admin/migrate endpoints end to end:
    export (blob over the wire, keyed by the frames' req_id), import
    (2xx ack + NDJSON continuation), commit — the stream's word cursor
    migrates between two real socket services with zero recompute."""
    member_cfg = EngineConfig(**TINY)
    backends = [
        _HttpBackend(FakeEngine(member_cfg, blocklist_path=None,
                                token_latency_s=0.05))
        for _ in range(2)
    ]
    for b in backends:
        b.engine.start()
    ecfg = EngineConfig(**TINY)
    members = [HttpMember(f"h{i}", b.url, timeout_s=30, poll_period_s=0.1)
               for i, b in enumerate(backends)]
    router = FleetRouter(members, ecfg, blocklist_path=None,
                         probe_period_s=0.05, eject_heartbeat_s=2.0,
                         reprobe_backoff_s=0.2, evac_grace_s=0.5,
                         migrate_timeout_s=10.0)
    router.start()
    try:
        req = _run(router, "hm", "migrate me over http", max_tokens=16)
        mem = _serving_member(router, req)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            f = next((f for f in list(router.flights) if f.req is req),
                     None)
            if f is not None and f.attempt is not None \
                    and f.attempt.member_rid is not None \
                    and f.attempt.n_items >= 2:
                break
            time.sleep(0.01)
        router.drain_replica(mem.name)
        items = collect(req, timeout=60)
        assert items[-1].kind == "done"
        words = _text(items).split()
        assert words == [f"word{i}" for i in range(16)]
        recs = router.journal.tail(None)
        migrated = [r for r in recs if r["kind"] == "migrate_import"
                    and r.get("what") != "prefix"]
        assert migrated and migrated[-1]["replica"] == mem.name
        assert migrated[-1]["tokens"] >= 2  # resumed mid-stream, not fresh
        assert router.migration_count >= 1
        assert check_no_dropped_streams(recs) == []
    finally:
        router.stop()
        for b in backends:
            b.stop()


def test_migration_blob_wire_roundtrip():
    import numpy as np

    from ollamamq_tpu.engine import kv_cache as kvc

    blob = {"version": 1, "kind": "stream", "kv_len": 9,
            "request": {"user": "u", "generated_ids": [1, 2, 3]},
            "k_pages": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
            "recent": np.full((8,), -1, np.int32),
            "_inc_decode": object()}  # in-process only: dropped on pack
    raw = kvc.pack_migration_blob(blob)
    out = kvc.unpack_migration_blob(raw)
    assert out["kv_len"] == 9 and out["request"]["generated_ids"] == [1, 2, 3]
    assert np.array_equal(out["k_pages"], blob["k_pages"])
    assert np.array_equal(out["recent"], blob["recent"])
    assert "_inc_decode" not in out
    with pytest.raises(ValueError):
        kvc.unpack_migration_blob(b"not a blob")
    # bfloat16 pools (ml_dtypes, not npz-serializable natively) survive
    # the wire as byte views with the dtype recorded in the header.
    import ml_dtypes

    bf = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16)
    out = kvc.unpack_migration_blob(kvc.pack_migration_blob(
        {"kind": "stream", "k_pages": bf.reshape(2, 4)}))
    assert out["k_pages"].dtype == bf.dtype
    assert np.array_equal(out["k_pages"], bf.reshape(2, 4))


def test_no_dropped_streams_checker_pairs_migrations():
    # Committed handoff: export -> import -> terminal = clean.
    clean = [
        {"kind": "migrate_export", "req_id": 4, "seq": 1, "tokens": 2},
        {"kind": "migrate_import", "req_id": 4, "seq": 2},
        {"kind": "finish", "req_id": 4, "seq": 3, "reason": "length"},
    ]
    assert check_no_dropped_streams(clean) == []
    # Aborted handoff that fell back and finished = clean.
    aborted = [
        {"kind": "migrate_export", "req_id": 5, "seq": 1, "tokens": 2},
        {"kind": "migrate_abort", "req_id": 5, "seq": 2, "why": "t"},
        {"kind": "replica_failover", "req_id": 5, "seq": 3},
        {"kind": "finish", "req_id": 5, "seq": 4, "reason": "stop"},
    ]
    assert check_no_dropped_streams(aborted) == []
    # Export with no resolution AND no terminal: dropped + orphaned.
    orphan = [
        {"kind": "migrate_export", "req_id": 6, "seq": 1, "tokens": 2},
    ]
    bad = check_no_dropped_streams(orphan)
    assert len(bad) == 2
    assert any("DROPPED" in b for b in bad)
    assert any("ORPHANED" in b for b in bad)
    # Imported but never finished: dropped.
    undone = [
        {"kind": "migrate_export", "req_id": 7, "seq": 1, "tokens": 2},
        {"kind": "migrate_import", "req_id": 7, "seq": 2},
    ]
    bad = check_no_dropped_streams(undone)
    assert len(bad) == 1 and "DROPPED" in bad[0]


def test_cancel_mid_stream_releases_fleet_state():
    router = _fake_fleet(n=2, token_latency_s=0.05)
    try:
        req = _run(router, "cx", max_tokens=64)
        _serving_member(router, req)
        router.cancel(req.req_id)
        items = collect(req)
        assert items[-1].finish_reason == FinishReason.CANCELLED
        deadline = time.monotonic() + 10
        while router.flights and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not router.flights
    finally:
        router.stop()
