"""Mixture-of-experts layer + expert parallelism.

Covers: routing math against a plain per-token numpy-style reference,
capacity-overflow fallthrough, EP-sharded == unsharded execution on the
8-virtual-device mesh, and the engine serving a MoE model end-to-end.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig
from ollamamq_tpu.models import llama
from ollamamq_tpu.models.moe import expert_capacity, moe_mlp
from ollamamq_tpu.parallel.mesh import make_mesh
from ollamamq_tpu.parallel.sharding import shard_params

CFG = MODEL_CONFIGS["test-tiny-moe"]


def _layer_params(cfg, seed=0):
    params = llama.init_params(cfg, jax.random.PRNGKey(seed), dtype=jnp.float32)
    # Layer 0's slice of the stacked tree.
    return {k: v[0] for k, v in params["layers"].items()}, params


def _reference_moe(cfg, lp, h):
    """Per-token loop: softmax -> top-k -> renormalize -> sum of expert
    FFNs. No capacity limit (the dense path must match when capacity is
    generous)."""
    B, T, D = h.shape
    x = np.asarray(h, np.float32).reshape(-1, D)
    out = np.zeros_like(x)
    wr = np.asarray(lp["w_router"], np.float32)
    for n in range(x.shape[0]):
        logits = x[n] @ wr
        p = np.exp(logits - logits.max())
        p = p / p.sum()
        top = np.argsort(-p)[: cfg.num_experts_per_tok]
        gates = p[top] / p[top].sum()
        for g, e in zip(gates, top):
            gate = x[n] @ np.asarray(lp["we_gate"], np.float32)[e]
            up = x[n] @ np.asarray(lp["we_up"], np.float32)[e]
            silu = gate / (1.0 + np.exp(-gate))
            out[n] += g * ((silu * up) @ np.asarray(lp["we_down"], np.float32)[e])
    return out.reshape(B, T, D)


def test_moe_matches_per_token_reference():
    lp, _ = _layer_params(CFG)
    h = jax.random.normal(jax.random.PRNGKey(1), (2, 5, CFG.hidden_size),
                          jnp.float32)
    got = moe_mlp(CFG, lp, h)
    want = _reference_moe(CFG, lp, h)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_capacity_overflow_drops_to_residual():
    # Force capacity 1: route many identical tokens -> all want the same
    # experts, only the first per expert is served, the rest contribute 0.
    cfg = dataclasses.replace(CFG, moe_capacity_factor=1e-9)
    lp, _ = _layer_params(cfg)
    h = jnp.ones((1, 6, cfg.hidden_size), jnp.float32)
    assert expert_capacity(6, cfg) == 1
    out = np.asarray(moe_mlp(cfg, lp, h))
    ref_one = _reference_moe(cfg, lp, h[:, :1])
    # Token 0 got both its experts; identical later tokens were dropped by
    # at least one expert, so their output is smaller in norm (or zero).
    np.testing.assert_allclose(out[0, 0], ref_one[0, 0], rtol=2e-4, atol=2e-4)
    assert np.linalg.norm(out[0, -1]) < np.linalg.norm(out[0, 0]) + 1e-6
    assert np.isfinite(out).all()


def test_invalid_tokens_do_not_claim_capacity():
    """Garbage rows (inactive decode slots / prefill padding) routing
    identically must not evict real tokens from their experts' queues."""
    cfg = dataclasses.replace(CFG, moe_capacity_factor=1.0)
    lp, _ = _layer_params(cfg, seed=5)
    real = jax.random.normal(jax.random.PRNGKey(6), (1, 2, cfg.hidden_size),
                             jnp.float32)
    # 14 identical garbage rows ahead of the 2 real tokens (token-major
    # "first C win" would hand them every expert slot), then the real rows.
    garbage = jnp.ones((1, 14, cfg.hidden_size), jnp.float32)
    h = jnp.concatenate([garbage, real], axis=1)
    valid = jnp.concatenate(
        [jnp.zeros((1, 14), bool), jnp.ones((1, 2), bool)], axis=1
    )
    out = moe_mlp(cfg, lp, h, valid=valid)
    # With the mask, the real tokens see no capacity pressure (C=8 for 16
    # tokens, demand 2x2): their outputs match the capacity-free reference.
    want = _reference_moe(cfg, lp, real)
    np.testing.assert_allclose(out[:, 14:], want, rtol=2e-4, atol=2e-4)
    # And WITHOUT the mask, the identical garbage rows (routing alike,
    # ahead in token-major order) really do evict at least one real
    # token's expert assignment — the bug the mask exists to prevent.
    out_nomask = moe_mlp(cfg, lp, h)
    assert not np.allclose(np.asarray(out_nomask[:, 14:]), want,
                           rtol=2e-4, atol=2e-4)


def test_ep_sharded_matches_unsharded():
    cfg = CFG
    _, params = _layer_params(cfg, seed=3)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 16), 1,
                                cfg.vocab_size, jnp.int32)
    seq_lens = jnp.asarray([16, 12, 16, 9], jnp.int32)

    ref = llama.forward_embed(params, cfg, tokens, seq_lens)

    mesh = make_mesh(dp=1, ep=4, tp=2)  # EP x TP over all 8 devices
    sharded = shard_params(params, mesh)
    got = jax.jit(
        lambda p, t, l: llama.forward_embed(p, cfg, t, l)
    )(sharded, tokens, seq_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_grouped_routing_matches_reference_across_groups():
    """N > group cap: per-group capacity must not change results when
    capacity is generous (routing is per-token; groups only bound C)."""
    import ollamamq_tpu.models.moe as moe_mod

    lp, _ = _layer_params(CFG, seed=7)
    # 2 groups of 8 via a tiny cap — compare against one flat group.
    h = jax.random.normal(jax.random.PRNGKey(8), (1, 16, CFG.hidden_size),
                          jnp.float32)
    want = _reference_moe(CFG, lp, h)
    orig = moe_mod.group_size
    try:
        moe_mod.group_size = lambda n, cap=8: orig(n, cap=8)
        got = moe_mlp(CFG, lp, h)
    finally:
        moe_mod.group_size = orig
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    assert moe_mod.group_size(16, cap=8) == 8  # really 2 groups


def test_dense_model_allowed_on_ep_mesh():
    """A dense model on an --ep mesh replicates over the expert axis —
    building its runtime must not raise (multi-model pools mix families)."""
    from ollamamq_tpu.engine.engine import ModelRuntime

    ecfg = EngineConfig(
        model="test-tiny", max_slots=2, num_pages=32, page_size=8,
        max_pages_per_seq=8, prefill_buckets=(16,), dtype="float32",
    )
    mesh = make_mesh(dp=1, ep=2, tp=2)
    import jax.numpy as jnp

    rt = ModelRuntime("test-tiny", MODEL_CONFIGS["test-tiny"], ecfg,
                      mesh=mesh, dtype=jnp.float32)
    assert rt is not None


def test_engine_serves_moe_end_to_end():
    from ollamamq_tpu.engine.engine import TPUEngine
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.ops.sampling import SamplingParams
    from testutil import collect

    ecfg = EngineConfig(
        model="test-tiny-moe", max_slots=4, num_pages=64, page_size=8,
        max_pages_per_seq=16, prefill_buckets=(16, 32), max_new_tokens=8,
        decode_steps_per_iter=2, ep=4, tp=2, dtype="float32",
    )
    eng = TPUEngine(ecfg, blocklist_path=None)
    eng.start()
    try:
        tok = eng.runtimes["test-tiny-moe"].tokenizer
        texts = []
        for _ in range(2):  # determinism across runs (greedy)
            rid = eng.core.enqueue("u", "127.0.0.1", "test-tiny-moe")
            req = Request(rid, "u", "test-tiny-moe", tok.encode("route me"),
                          SamplingParams(max_tokens=6))
            eng.submit(req)
            items = collect(req, timeout=120)
            assert items[-1].kind == "done", items[-1].error
            texts.append("".join(i.text for i in items if i.kind == "token"))
        assert texts[0] == texts[1] and len(texts[0]) > 0
    finally:
        eng.stop()
