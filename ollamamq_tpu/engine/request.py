"""Request objects and token streams.

A Request is the engine-side unit of work — the analogue of the reference's
`Task` (/root/reference/src/dispatcher.rs:33-40), but carrying tokenized
prompts and sampling params instead of opaque HTTP bodies. The TokenStream
replaces the 32-deep mpsc responder channel (dispatcher.rs:617): the engine
thread pushes items into a thread-safe queue; an optional callback lets the
asyncio server mirror items into its event loop without the engine knowing
about asyncio.
"""

from __future__ import annotations

import dataclasses
import enum
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

from ollamamq_tpu.ops.sampling import SamplingParams


class FinishReason(str, enum.Enum):
    STOP = "stop"          # EOS token or stop string
    LENGTH = "length"      # max_tokens or context budget hit
    CANCELLED = "cancelled"  # client disconnected / admin drop
    ERROR = "error"
    # Degradation-specific terminals: the client must be able to tell an
    # honest resource/deadline failure from a generic engine error, so
    # these surface as their own API done_reason (never folded into
    # "length" or a bare "error").
    KV_EXHAUSTED = "kv_exhausted"  # decode-time page-pool exhaustion
    DEADLINE = "deadline"          # per-request deadline expired


# Terminal reasons delivered to the client as an "error" stream item
# (with finish_reason carrying the specific done_reason).
ERROR_REASONS = (FinishReason.ERROR, FinishReason.KV_EXHAUSTED,
                 FinishReason.DEADLINE)


@dataclasses.dataclass
class StreamItem:
    kind: str  # "token" | "done" | "error"
    text: str = ""
    token_id: int = -1
    finish_reason: Optional[FinishReason] = None
    error: str = ""


class TokenStream:
    """Thread-safe token channel, engine thread -> consumer.

    Backpressure: bounded queue (default 1024 items — generous vs the
    reference's 32 because items are single tokens, not HTTP chunks).
    `on_item` (if set) fires after each push, from the engine thread; the
    server uses it to wake the asyncio loop.
    """

    def __init__(self, maxsize: int = 1024):
        self._q: "queue.Queue[StreamItem]" = queue.Queue(maxsize=maxsize)
        self.on_item: Optional[Callable[[], None]] = None
        # Durability tap (durability/manager.py): observes every pushed
        # item — the WAL's emitted-token log and the resumable-stream
        # frame registry read here, WITHOUT consuming the queue (the
        # client stream stays the sole consumer). Fires even when the
        # queue overflows: the durable record must be complete.
        self.tap: Optional[Callable[[StreamItem], None]] = None
        # Consumer-not-draining threshold: the engine marks the request's
        # trace with a stream_stall span when the backlog crosses this
        # (latency attribution's "stream" phase) — well before the hard
        # overflow below turns it into a disconnect.
        self.high_water = max(1, maxsize // 2)
        self._closed = False
        # Set when the consumer stops reading and the queue fills: the engine
        # treats it as a client disconnect (the reference likewise interprets
        # a failed channel send as client-gone, dispatcher.rs:537-551). The
        # engine thread must NEVER block on a slow consumer.
        self.overflowed = False

    def push(self, item: StreamItem) -> None:
        if self._closed:
            return
        tap = self.tap
        if tap is not None:
            try:
                tap(item)
            except Exception:  # noqa: BLE001 — a broken tap must never
                self.tap = None  # take the engine thread down with it
        try:
            self._q.put_nowait(item)
        except queue.Full:
            if item.kind in ("done", "error"):
                # Terminal items must reach the consumer: shed one token.
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    pass
                try:
                    self._q.put_nowait(item)
                except queue.Full:
                    pass
                self._closed = True
            else:
                self.overflowed = True
            return
        if item.kind in ("done", "error"):
            self._closed = True
        cb = self.on_item
        if cb is not None:
            cb()

    def depth(self) -> int:
        return self._q.qsize()

    def get(self, timeout: Optional[float] = None) -> Optional[StreamItem]:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def get_nowait(self) -> Optional[StreamItem]:
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def drain(self) -> List[StreamItem]:
        out = []
        while (item := self.get_nowait()) is not None:
            out.append(item)
        return out


@dataclasses.dataclass
class RequestStats:
    enqueued_at: float = dataclasses.field(default_factory=time.monotonic)
    prefill_started_at: float = 0.0
    first_token_at: float = 0.0
    finished_at: float = 0.0
    prompt_tokens: int = 0
    completion_tokens: int = 0

    @property
    def ttft_ms(self) -> float:
        if self.first_token_at:
            return (self.first_token_at - self.enqueued_at) * 1e3
        return 0.0

    @property
    def total_duration_s(self) -> float:
        end = self.finished_at or time.monotonic()
        return end - self.enqueued_at


class Request:
    """One generation (or embedding) request flowing through the engine."""

    def __init__(
        self,
        req_id: int,
        user: str,
        model: str,
        prompt_tokens: Sequence[int],
        sampling: Optional[SamplingParams] = None,
        kind: str = "generate",  # "generate" | "embed"
        raw_prompt: str = "",
    ):
        self.req_id = req_id
        self.user = user
        self.model = model
        self.prompt_tokens = list(prompt_tokens)
        self.sampling = sampling or SamplingParams()
        self.kind = kind
        self.raw_prompt = raw_prompt
        self.stream = TokenStream()
        self.stats = RequestStats(prompt_tokens=len(self.prompt_tokens))
        self.cancelled = threading.Event()
        # Per-request deadline (monotonic instant), from the sampling
        # params' deadline_ms budget (header or options). None = none.
        dm = float(getattr(self.sampling, "deadline_ms", 0.0) or 0.0)
        self.deadline = (self.stats.enqueued_at + dm / 1e3) if dm > 0 else None
        # Scheduler-accounting flag: True once mark_started ran for this
        # request — a preempted/retried requeue must not double-count it.
        self.started = False
        # Graceful-degradation state (engine-owned): preemption count
        # (anti-livelock budget), fault-retry count (poisoning budget),
        # earliest next retry attempt, and how many generated ids are
        # already folded into prompt_tokens for recompute replay.
        self.preemptions = 0
        self.retries = 0
        self._retry_at = 0.0
        self._replay_gen = 0
        # Incremental detokenizer: attached at first runtime submit and
        # PRESERVED across preemption/retry requeues — the replay prompt
        # carries already-generated ids, so the decoder must not re-see
        # them (stream continuity).
        self._inc_decode = None
        # Lifecycle trace (telemetry.tracing.Trace), attached by the
        # engine's enqueue path; None for directly-constructed Requests
        # (bench, unit tests) — every trace hook below no-ops then.
        self.trace = None
        # Stream-stall attribution state (engine-owned): True while the
        # consumer's backlog sits above the TokenStream high-water mark.
        self._stream_stalled = False
        # Generation state (engine-owned):
        self.generated_ids: List[int] = []
        self.emitted_len = 0  # chars of detok text already pushed
        self._detok_text = ""
        self.embedding: Optional[list] = None

    # -- stop-string handling ---------------------------------------------
    def emit_text(self, new_text: str) -> Optional[str]:
        """Accumulate detokenized text, honoring stop strings with hold-back.

        Returns the safe-to-emit chunk (may be ""), or None if a stop string
        fired (caller should finish the request with reason=STOP).
        """
        self._detok_text += new_text
        stops = self.sampling.stop
        if stops:
            for s in stops:
                idx = self._detok_text.find(s)
                if idx != -1:
                    chunk = self._detok_text[self.emitted_len:idx]
                    self.emitted_len = idx
                    if chunk:
                        self.stream.push(StreamItem("token", text=chunk))
                    return None
            holdback = max(len(s) for s in stops) - 1
        else:
            holdback = 0
        safe_end = len(self._detok_text) - holdback
        if safe_end > self.emitted_len:
            chunk = self._detok_text[self.emitted_len:safe_end]
            self.emitted_len = safe_end
            return chunk
        return ""

    def flush_text(self) -> str:
        """Emit any held-back text (at finish, when no stop matched)."""
        chunk = self._detok_text[self.emitted_len:]
        self.emitted_len = len(self._detok_text)
        return chunk

    @property
    def full_text(self) -> str:
        return self._detok_text[: self.emitted_len]

    def expired(self, now: Optional[float] = None) -> bool:
        """True when the request's deadline has passed."""
        if self.deadline is None:
            return False
        return (now if now is not None else time.monotonic()) >= self.deadline

    def trace_event(self, name: str, **args) -> None:
        """Record a lifecycle span event; no-op for untraced requests."""
        tr = self.trace
        if tr is not None:
            tr.event(name, **args)

    def finish(self, reason: FinishReason, error: str = "") -> None:
        self.stats.finished_at = time.monotonic()
        kind = "error" if reason in ERROR_REASONS else "done"
        self.stream.push(StreamItem(kind, finish_reason=reason, error=error))
        tr = self.trace
        if tr is not None:
            tr.finish(reason.value)
