"""Engine flight recorder: the scheduler decision journal.

Every scheduler-visible decision the engine makes — admit, shed, batch
compose, preempt, requeue, retry, poison, deadline drop, page
alloc/free/evict, runtime rebuild — lands here as ONE typed record
carrying the decision's *inputs* (queue depths, free/cached page counts,
fair-share standing, deadline slack), so a bad scheduling episode
observed in production is explainable after the fact and, for
harness-driven runs, replayable (tools/journal replay).

Design constraints, in order:

  1. bounded — a deque ring of `capacity` records; memory is O(capacity)
     no matter how long the engine runs. An optional JSONL spill
     (--journal-file) keeps the full history on disk with size-based
     rotation so soak runs can't fill the volume.
  2. low overhead — nothing is recorded per decoded token; the hottest
     sites are one record per prefill batch / chunk / page-table growth.
     Schema validation is two frozenset subset checks.
  3. typed — EVENTS is a CLOSED vocabulary and every kind declares its
     required/optional fields (EVENT_FIELDS). An event kind added to the
     engine without a README table row fails the doc gate
     (scripts/check_metrics_docs.py), exactly like an undocumented
     metric.

Stdlib-only, like the rest of telemetry: the doc checker and the offline
analyzer (tools/journal) import this module without jax or an engine.
"""

from __future__ import annotations

import collections
import json
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ollamamq_tpu.telemetry import schema as tm

# Closed event vocabulary, lifecycle order. The README "Flight recorder"
# table (between <!-- journal-events:begin/end --> markers) documents
# every kind; the doc gate pins the two together.
EVENTS = (
    "enqueue",        # arrival accepted into the fair-share queue
    "admit",          # scheduler popped the request for placement
    "sched",          # scheduling policy applied an ordering decision
    #                   (admission window / preemption victim) + inputs
    "place",          # placed onto a runtime (replica chosen)
    "shed",           # refused/dropped instead of served, by reason
    "batch",          # prefill batch composed (slots/bucket/occupancy)
    "chunk",          # one chunked-prefill piece dispatched
    "install",        # slot activated: request entered the decode batch
    "speculate",      # drafts composed into a verify span for a slot
    "spec_verify",    # verify outcome: drafts proposed vs accepted
    "spec_rollback",  # rejected drafts' KV page claim released
    "preempt",        # victim evicted for recompute under KV pressure
    "kv_stall",       # page growth failed; slot holds a reservation
    "requeue",        # returned to the FRONT of its user's queue
    "retry",          # fault-implicated request re-dispatched
    "poison",         # retry budget spent; request errored on purpose
    "deadline_drop",  # per-request deadline expired before completion
    "finish",         # slot/stream finished, by reason
    "page_alloc",     # KV pages allocated (admission or decode growth)
    "page_free",      # KV pages returned to the free list
    "page_evict",     # cached prefix pages reclaimed under pressure
    "broadcast",      # SPMD primary shipped a step plan to worker hosts
    "rebuild",        # failed runtime replaced (weights reloaded)
    # Fleet router (fleet/router.py): dispatcher-over-engines decisions.
    "replica_eject",     # replica removed from rotation (health-driven)
    "replica_failover",  # victim stream re-dispatched to another replica
    "replica_drain",     # replica quiesced: no new placements, in-flight
    #                      streams run to completion
    "replica_join",      # replica (re)entered rotation, by reason
    # Tiered fleet (fleet/tiering.py): SLO-aware replica tiers with
    # adaptive regrouping.
    "tier_place",        # placement matched a request class to a tier
    "tier_overflow",     # a stream placed cross-tier (per-tier SLO burn,
    #                      an empty tier, or a failover with no in-tier
    #                      capacity) — never silently
    "tier_regroup",      # a member changed tiers (drain -> migrate ->
    #                      restart at the tier's TP width -> rejoin),
    #                      by phase: start / done / aborted
    # KV page migration (two-phase handoff; fleet/router.py + engine):
    "migrate_export",    # source snapshot taken, slot detached/parked
    "migrate_import",    # target installed the shipped state (the ack)
    "migrate_abort",     # transfer failed; source state released and the
    #                      stream falls back to recompute replay
    # Crash durability (durability/): the admission WAL + cold-restart
    # recovery.
    "wal_admit",         # request durably logged (fsynced) pre-ACK
    "recover_replay",    # WAL'd unfinished request re-admitted at start
    # Elastic fleet (fleet/autoscaler.py): SLO-burn-driven fleet sizing.
    "scale_up",          # scaler grew a tier, by phase: start (decision
    #                      made, provisioning began) / done (member
    #                      joined rotation) / aborted (spawn failed)
    "scale_down",        # a member retired (drain -> migrate-off ->
    #                      stop), by phase: start / done / aborted; also
    #                      records preemption-notice retires (why:
    #                      "preempt") and manual ones (why: "manual")
    "preempt_notice",    # a preemptible member was served a termination
    #                      notice; resolved by a scale_down for the same
    #                      member within the notice window
    # Router HA (fleet/ha.py): warm-standby sync, takeover, fencing.
    "standby_sync",      # standby (re)synced against the primary: cold
    #                      catch-up, snapshot reload after ring overrun,
    #                      or reconnect — NOT one record per batch
    "router_takeover",   # standby promoted to primary, by phase: begin
    #                      (primary declared dead / handover received) /
    #                      done (serving, streams re-admitted) / aborted
    "epoch_fence",       # a stale-epoch router call was rejected — the
    #                      zombie-primary split-brain guard firing
    # Engine performance plane (telemetry/stepprof.py).
    "compile",           # a jit cache filled and the first call paid an
    #                      XLA compile: which site/shape key, the wall
    #                      ms the dispatch path stalled, the cache size
    #                      after — exactly-once per ladder rung unless
    #                      something is thrashing (compile_storm)
)

# kind -> (required fields, optional fields) beyond the common header
# (seq, t, tick, kind, req_id, user, model). Validation is loud: an
# instrumentation site that forgets a decision input fails its test, not
# an operator's incident review.
EVENT_FIELDS: Dict[str, Tuple[tuple, tuple]] = {
    "enqueue": (("n_prompt", "queued"),
                ("kind_req", "max_tokens", "deadline_ms")),
    "admit": (("queued",), ()),
    # Policy ordering decisions carry their score inputs: which policy
    # chose, at which decision point ("admit" window / "victim" pick),
    # over how many candidates, and the chosen request's predicted
    # output length + effective (aged) score — the explainability
    # contract for "why did THIS request go first / lose its slot".
    "sched": (("policy", "point"), ("candidates", "score", "predicted")),
    # `overhead_ms` (fleet router only) = the router's own placement-
    # decision cost for THIS place, measured by the always-on
    # perf_counter_ns timer that feeds ollamamq_router_overhead_ms.
    "place": (("runtime",), ("overhead_ms",)),
    "shed": (("reason",),
             ("queued", "limit", "retry_after_s", "n_prompt", "max_tokens")),
    # `mode` tells the two batch shapes apart: "bucketed" records carry
    # the bucket they padded to; "ragged" records carry the granule-
    # padded stream total plus its prefill/decode row split. Both carry
    # real vs padded token counts, which batch_stats() below turns into
    # the padding-waste scoreboard.
    "batch": (("slots", "batch_size", "tokens", "occupancy"),
              ("reqs", "pending", "free_pages", "bucket", "mode",
               "padded_tokens", "n_prefill", "n_decode", "n_spec",
               "spec_tokens", "spec_accepted")),
    "chunk": (("slot", "pos"), ("tokens", "cached")),
    "install": (("slot",), ("n_prompt",)),
    # Speculation decisions carry their inputs/outcomes: `k` drafts from
    # `source` were composed (speculate); `accepted` of `proposed` drafts
    # survived greedy verification (spec_verify — accepted <= proposed is
    # a checked invariant); the rollback releases the rejected tail's
    # page claim with the allocator post-state, so page conservation
    # (free+used+cached==pool) stays checkable through speculation.
    "speculate": (("slot", "k"), ("source",)),
    "spec_verify": (("slot", "proposed", "accepted"), ("rolled_back",)),
    "spec_rollback": (("slot", "kv_before", "kv_after", "freed",
                       "free", "used", "cached", "pool"), ()),
    "preempt": (("slot", "why"),
                ("n", "free_pages", "victim_served", "vip")),
    "kv_stall": (("slot",), ("free_pages", "need")),
    "requeue": ((), ("why",)),
    "retry": (("n",), ("error",)),
    "poison": (("retries",), ("error",)),
    "deadline_drop": (("slack_ms",), ()),
    # `predicted_tokens` pairs the scheduler's output-length prediction
    # with the actual outcome (`tokens`) — per-policy predictor accuracy
    # is auditable straight off the journal.
    "finish": (("reason",), ("slot", "tokens", "predicted_tokens")),
    "page_alloc": (("n", "free", "used", "cached", "pool"), ("slot",)),
    "page_free": (("n", "free", "used", "cached", "pool"), ("slot",)),
    "page_evict": (("n", "free", "used", "cached", "pool"), ()),
    "broadcast": (("op",), ("wire_seq",)),
    "rebuild": ((), ()),
    # Fleet records carry the replica name plus the inputs that justified
    # the decision: why a member was ejected (and how stale its heartbeat
    # was), where a victim stream went and how many tokens its replay
    # carried, how much in-flight work a drain waited out.
    "replica_eject": (("replica", "why"),
                      ("victims", "heartbeat_age_s", "backoff_s")),
    "replica_failover": (("replica",), ("to_replica", "replayed_tokens")),
    "replica_drain": (("replica",), ("inflight", "timeout_s")),
    "replica_join": (("replica",), ("why",)),
    # Tier records carry the classification inputs: which request class
    # (vip/boost/deadline/default) mapped to which tier and which
    # replica won (tier_place); why a stream crossed tiers and how hot
    # the burn was (tier_overflow); a regroup's phase with the class-mix
    # EMA and TP widths that justified it (tier_regroup).
    "tier_place": (("tier", "cls"), ("replica", "overflow")),
    "tier_overflow": (("from_tier", "to_tier", "why"),
                      ("burn", "queued", "replica")),
    "tier_regroup": (("replica", "phase"),
                     ("from_tier", "to_tier", "why", "mix",
                      "tp_from", "tp_to")),
    # Migration records carry the shipped state's size (tokens already
    # generated = what recompute would have re-derived; pages/bytes =
    # what actually moved) and, router-side, the members involved.
    # `what` tells a stream handoff from a shipped prefix.
    # `overhead_ms` (router-side records) = the router's measured cost
    # of that handoff leg (export / import), per decision.
    "migrate_export": (("tokens",),
                       ("replica", "kv_len", "pages", "bytes",
                        "overhead_ms")),
    "migrate_import": ((),
                       ("replica", "to_replica", "tokens", "pages",
                        "bytes", "what", "overhead_ms")),
    "migrate_abort": (("why",), ("replica", "to_replica")),
    # WAL records carry the durability cost (how long the admission
    # waited on its covering fsync) and the recovery inputs (how many
    # already-emitted tokens the replay restored without recompute).
    "wal_admit": (("fsync_ms",), ("n_prompt",)),
    # `req_id` is the RE-ADMITTED id (what the rest of this journal's
    # records use); `wal_rid` is the pre-crash id the client still
    # holds — the resume endpoint aliases the two.
    "recover_replay": (("tokens",), ("outcome", "n_prompt", "wal_rid")),
    # Scale records carry the control-loop inputs that justified the
    # decision: which tier moved, the burn rate and queue backlog at
    # decision time, and the fleet size it moved toward. scale_up's
    # done-phase records the measured spawn cost (what a scaled-to-zero
    # tier's Retry-After must account for); scale_down's start-phase
    # records the in-flight work the drain must migrate off first.
    "scale_up": (("replica", "phase"),
                 ("tier", "why", "burn", "queued", "fleet", "spawn_ms")),
    "scale_down": (("replica", "phase"),
                   ("tier", "why", "burn", "queued", "fleet", "inflight")),
    "preempt_notice": (("replica",),
                       ("tier", "notice_s", "why", "inflight")),
    # HA records carry the replication position (seq = last applied
    # replication record, lag = primary head minus that) and, for
    # takeovers, the epochs involved plus the promotion outcome counts
    # (streams re-admitted, how many migrated vs recompute-replayed) —
    # the inputs tools/journal's takeover-pairing and epoch-monotonicity
    # audits check across spills.
    "standby_sync": (("seq", "lag"), ("records", "epoch", "why")),
    "router_takeover": (("phase", "why"),
                        ("epoch", "from_epoch", "streams", "migrated",
                         "replayed", "takeover_ms", "lag",
                         "members_claimed")),
    "epoch_fence": (("epoch", "stale_epoch"), ("path", "caller")),
    # Compile events carry the shape key that missed, the wall ms the
    # first call stalled compiling, and the cache size after the fill —
    # enough to reconstruct the whole ladder from a journal tail.
    "compile": (("site", "key", "wall_ms"), ("cache_size",)),
}
assert set(EVENT_FIELDS) == set(EVENTS)

_FIELD_SETS = {k: (frozenset(req), frozenset(req) | frozenset(opt))
               for k, (req, opt) in EVENT_FIELDS.items()}

# Kinds whose (kind, req_id, user, salient-fields) sequence defines THE
# decision stream for deterministic replay. Page events and dispatch
# bookkeeping (chunk/broadcast) carry device/layout detail that replay
# harnesses without real KV pools can't reproduce; everything
# scheduler-visible is in.
DECISION_KINDS = ("enqueue", "admit", "sched", "place", "shed", "batch",
                  "install", "preempt", "requeue", "retry", "poison",
                  "deadline_drop", "finish", "replica_eject",
                  "replica_failover", "replica_drain", "replica_join",
                  "tier_place", "tier_overflow", "tier_regroup",
                  "migrate_export", "migrate_import", "migrate_abort",
                  "recover_replay", "scale_up", "scale_down",
                  "preempt_notice", "standby_sync", "router_takeover",
                  "epoch_fence")

# High-rate bookkeeping kinds eligible for probabilistic sampling
# (--journal-sample < 1): each record is self-contained (page events
# carry their full post-state), so a sampled trace stays checkable —
# only the batch-ordinal starvation count loses meaning (tools/journal
# check skips it on sampled traces). Decision-critical kinds are never
# sampled out.
SAMPLED_KINDS = frozenset({"batch", "chunk", "page_alloc", "page_free",
                           "page_evict", "broadcast"})

# Per-kind fields folded into the replay signature (deterministic given
# the same arrivals; excludes timestamps, latencies, and page ids).
_SIG_FIELDS = {
    "enqueue": ("n_prompt", "queued"),
    "sched": ("policy", "point", "candidates"),
    "shed": ("reason",),
    "place": ("runtime",),
    "retry": ("n",),
    "poison": ("retries",),
    "finish": ("reason",),
    "preempt": ("why",),
    "tier_place": ("tier", "cls"),
    "tier_overflow": ("from_tier", "to_tier", "why"),
    "tier_regroup": ("replica", "phase", "from_tier", "to_tier"),
    "scale_up": ("replica", "phase", "tier"),
    "scale_down": ("replica", "phase", "why"),
    "preempt_notice": ("replica",),
}


class JournalError(ValueError):
    """A record violated the event schema (unknown kind / bad fields)."""


class Journal:
    """Bounded append-only decision journal with optional JSONL spill.

    Thread-safe: the engine loop appends while HTTP readers tail. The
    ring holds plain dicts (JSON-able as-is); `seq` is a monotonically
    increasing record index so consumers can detect ring evictions
    (size < seq means the oldest records fell off).
    """

    def __init__(self, capacity: int = 2048, path: Optional[str] = None,
                 rotate_bytes: int = 64_000_000, keep: int = 3,
                 meta: Optional[dict] = None, sample: float = 1.0):
        self.capacity = max(1, int(capacity))
        # Probabilistic sampling of SAMPLED_KINDS (--journal-sample):
        # seeded so two runs of the same trace sample identically.
        self.sample = min(1.0, max(0.0, float(sample)))
        self._sample_rng = random.Random(0)
        self.sampled_out = 0
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()
        self.seq = 0
        # Engine-loop iteration counter; the synchronous replay driver
        # sets it explicitly so recorded arrivals carry a deterministic
        # virtual tick.
        self.tick = 0
        self.path = path or None
        self.rotate_bytes = max(0, int(rotate_bytes))
        self.keep = max(1, int(keep))
        self.meta = dict(meta or {})
        if self.sample < 1.0:
            # The spill must say it is sampled: the offline checker
            # reads this to skip batch-ordinal-dependent invariants.
            self.meta.setdefault("sample", self.sample)
        self._fh = None
        self._bytes = 0
        self._last_decision: Optional[dict] = None
        # Optional replication tap (fleet/ha.py): called with each
        # validated record AFTER it lands in the ring/spill, outside the
        # journal lock. Exceptions are contained — replication trouble
        # must not take recording (or serving) down.
        self.tap = None
        self._tm = {k: tm.JOURNAL_EVENTS_TOTAL.labels(kind=k)
                    for k in EVENTS}
        if self.path:
            self._open_file()

    # -- file spill --------------------------------------------------------
    def _open_file(self) -> None:
        # Line-buffered: each record reaches the OS as it is written, so
        # the spill is tail-able mid-incident and survives a crash — a
        # flight recorder that only flushes on clean shutdown records
        # nothing about the flights that matter.
        self._fh = open(self.path, "a", encoding="utf-8", buffering=1)
        self._bytes = self._fh.tell()
        if self._bytes == 0:
            head = {"journal_meta": {
                "version": 1, "opened_at": time.time(), **self.meta}}
            line = json.dumps(head, default=str) + "\n"
            self._fh.write(line)
            self._bytes += len(line)

    def _rotate(self) -> None:
        """path -> path.1 -> ... -> path.keep (oldest dropped): bounded
        disk no matter how long the soak runs."""
        self._fh.close()
        for i in range(self.keep - 1, 0, -1):
            src, dst = f"{self.path}.{i}", f"{self.path}.{i + 1}"
            if os.path.exists(src):
                os.replace(src, dst)
        os.replace(self.path, f"{self.path}.1")
        self._fh = None
        self._open_file()

    def _spill(self, rec: dict) -> None:
        line = json.dumps(rec, default=str) + "\n"
        self._fh.write(line)
        self._bytes += len(line)
        if self.rotate_bytes and self._bytes >= self.rotate_bytes:
            self._rotate()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None

    # -- recording ---------------------------------------------------------
    def record(self, kind: str, req=None, req_id: Optional[int] = None,
               user: Optional[str] = None, model: Optional[str] = None,
               **fields) -> dict:
        """Append one validated record. `req` (duck-typed Request) fills
        req_id/user/model unless given explicitly."""
        sets = _FIELD_SETS.get(kind)
        if sets is None:
            raise JournalError(f"unknown journal event kind {kind!r} "
                               f"(vocabulary: {EVENTS})")
        if self.sample < 1.0 and kind in SAMPLED_KINDS:
            # Sampled journaling: high-rate bookkeeping kinds keep the
            # ring and spill alive at 100x event rates; the metric still
            # counts every event so rates stay readable off /metrics.
            with self._lock:
                keep = self._sample_rng.random() < self.sample
            if not keep:
                self.sampled_out += 1
                self._tm[kind].inc()
                return {}
        required, allowed = sets
        got = frozenset(fields)
        if not required <= got:
            raise JournalError(
                f"journal event {kind!r} missing required field(s) "
                f"{sorted(required - got)}")
        if not got <= allowed:
            raise JournalError(
                f"journal event {kind!r} got unknown field(s) "
                f"{sorted(got - allowed)} (allowed: {sorted(allowed)})")
        if req is not None:
            if req_id is None:
                req_id = getattr(req, "req_id", None)
            if user is None:
                user = getattr(req, "user", None)
            if model is None:
                model = getattr(req, "model", None)
        rec = {"seq": 0, "t": time.monotonic(), "tick": self.tick,
               "kind": kind}
        if req_id is not None:
            rec["req_id"] = int(req_id)
        if user is not None:
            rec["user"] = user
        if model:
            rec["model"] = model
        rec.update(fields)
        with self._lock:
            rec["seq"] = self.seq
            self.seq += 1
            self._ring.append(rec)
            if kind in DECISION_KINDS:
                self._last_decision = rec
            if self._fh is not None:
                try:
                    self._spill(rec)
                except OSError:
                    # Disk trouble must not take serving down; the ring
                    # keeps recording.
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
        self._tm[kind].inc()
        tap = self.tap
        if tap is not None:
            try:
                tap(rec)
            except Exception:  # noqa: BLE001
                pass
        return rec

    # -- reading -----------------------------------------------------------
    def tail(self, n: Optional[int] = 200, req_id: Optional[int] = None,
             user: Optional[str] = None,
             kind: Optional[str] = None) -> List[dict]:
        """Newest-last slice of the ring, optionally filtered. n=None (or
        <= 0) returns every retained record passing the filters."""
        with self._lock:
            recs = list(self._ring)
        if req_id is not None:
            recs = [r for r in recs if r.get("req_id") == req_id]
        if user is not None:
            recs = [r for r in recs if r.get("user") == user]
        if kind is not None:
            recs = [r for r in recs if r["kind"] == kind]
        if n is not None and n > 0:
            recs = recs[-n:]
        return recs

    def snapshot(self) -> dict:
        with self._lock:
            size = len(self._ring)
        out = {"capacity": self.capacity, "size": size, "seq": self.seq,
               "evicted": max(0, self.seq - size),
               "file": self.path, "tick": self.tick}
        if self.sample < 1.0:
            out["sample"] = self.sample
            out["sampled_out"] = self.sampled_out
        return out

    def last_summary(self) -> str:
        """One-line text of the most recent scheduler decision (the TUI
        last-decision line); "" before the first decision."""
        rec = self._last_decision
        return explain(rec) if rec is not None else ""


# ---------------------------------------------------------------------------
# Explanations: per-decision human text built from the recorded inputs.
# ---------------------------------------------------------------------------

def explain(rec: dict) -> str:
    """Human one-liner for one record: WHAT was decided and the inputs
    that justify it."""
    kind = rec.get("kind", "?")
    rid = rec.get("req_id")
    who = f"req {rid}" if rid is not None else rec.get("user", "?")
    if rec.get("user") and rid is not None:
        who += f" ({rec['user']})"
    if kind == "enqueue":
        return (f"{who} enqueued: {rec.get('n_prompt', '?')} prompt tokens, "
                f"queue depth {rec.get('queued', '?')}")
    if kind == "admit":
        return f"{who} admitted (queue depth {rec.get('queued', '?')})"
    if kind == "sched":
        verb = ("picked as preemption victim"
                if rec.get("point") == "victim" else "ordered first")
        s = f"{who} {verb} by policy {rec.get('policy', '?')}"
        if rec.get("candidates") is not None:
            s += f" among {rec['candidates']} candidate(s)"
        if rec.get("predicted") is not None:
            s += f" (predicted {rec['predicted']} token(s)"
            if rec.get("score") is not None:
                s += f", score {rec['score']}"
            s += ")"
        return s
    if kind == "place":
        return f"{who} placed on runtime {rec.get('runtime', '?')}"
    if kind == "shed":
        parts = [f"{who} shed ({rec.get('reason', '?')})"]
        if "queued" in rec and "limit" in rec:
            parts.append(f"queued {rec['queued']} >= cap {rec['limit']}")
        if "retry_after_s" in rec:
            parts.append(f"retry after ~{rec['retry_after_s']:.0f}s")
        return ": ".join([parts[0], ", ".join(parts[1:])]) if parts[1:] \
            else parts[0]
    if kind == "batch":
        if rec.get("mode") == "ragged" or "bucket" not in rec:
            return (f"ragged batch on {rec.get('model', '?')}: "
                    f"{rec.get('n_prefill', '?')} prefill span(s) + "
                    f"{rec.get('n_decode', '?')} decode row(s), "
                    f"{rec.get('tokens', '?')}/"
                    f"{rec.get('padded_tokens', '?')} real/padded tokens, "
                    f"occupancy {rec.get('occupancy', 0):.2f}")
        return (f"prefill batch on {rec.get('model', '?')}: "
                f"{len(rec.get('slots', []))} req(s) in bucket "
                f"{rec.get('bucket', '?')} (B={rec.get('batch_size', '?')}, "
                f"{rec.get('tokens', '?')} real tokens, occupancy "
                f"{rec.get('occupancy', 0):.2f})")
    if kind == "chunk":
        return (f"{who} prefill chunk at pos {rec.get('pos', '?')} "
                f"({rec.get('tokens', '?')} tokens, slot {rec.get('slot')})")
    if kind == "install":
        return f"{who} installed in slot {rec.get('slot', '?')}"
    if kind == "speculate":
        return (f"{who} speculating {rec.get('k', '?')} draft token(s) in "
                f"slot {rec.get('slot', '?')} "
                f"(source {rec.get('source', 'ngram')})")
    if kind == "spec_verify":
        return (f"{who} verified speculation in slot {rec.get('slot', '?')}"
                f": accepted {rec.get('accepted', '?')}/"
                f"{rec.get('proposed', '?')} draft(s)"
                + (f", rolled back {rec['rolled_back']}"
                   if rec.get("rolled_back") else ""))
    if kind == "spec_rollback":
        return (f"{who} speculative rollback in slot {rec.get('slot', '?')}"
                f": kv {rec.get('kv_before', '?')} -> "
                f"{rec.get('kv_after', '?')}, {rec.get('freed', '?')} "
                f"page(s) freed (free={rec.get('free')}, "
                f"used={rec.get('used')}, cached={rec.get('cached')}, "
                f"pool={rec.get('pool')})")
    if kind == "preempt":
        s = (f"{who} preempted from slot {rec.get('slot', '?')} "
             f"({rec.get('why', '?')}, n={rec.get('n', '?')})")
        if "free_pages" in rec:
            s += f": free_pages={rec['free_pages']}"
        if "victim_served" in rec:
            s += f", victim served {rec['victim_served']} lifetime requests"
        return s
    if kind == "kv_stall":
        return (f"{who} stalled holding slot {rec.get('slot', '?')} "
                f"(free_pages={rec.get('free_pages', '?')})")
    if kind == "requeue":
        return f"{who} requeued to queue front"
    if kind == "retry":
        return (f"{who} retry #{rec.get('n', '?')}"
                + (f": {rec['error']}" if rec.get("error") else ""))
    if kind == "poison":
        return (f"{who} poisoned after {rec.get('retries', '?')} retr"
                f"{'y' if rec.get('retries') == 1 else 'ies'}")
    if kind == "deadline_drop":
        return (f"{who} dropped: deadline expired "
                f"{rec.get('slack_ms', 0):.0f}ms ago")
    if kind == "finish":
        return (f"{who} finished ({rec.get('reason', '?')}"
                + (f", {rec['tokens']} tokens" if "tokens" in rec else "")
                + ")")
    if kind in ("page_alloc", "page_free", "page_evict"):
        verb = {"page_alloc": "allocated", "page_free": "freed",
                "page_evict": "evicted"}[kind]
        return (f"{rec.get('model', '?')}: {rec.get('n', '?')} page(s) "
                f"{verb} (free={rec.get('free')}, used={rec.get('used')}, "
                f"cached={rec.get('cached')}, pool={rec.get('pool')})")
    if kind == "broadcast":
        return (f"SPMD plan broadcast: {rec.get('op', '?')} "
                f"(wire seq {rec.get('wire_seq', '?')})")
    if kind == "rebuild":
        return f"runtime {rec.get('model', '?')} rebuilt (weights reloaded)"
    if kind == "replica_eject":
        s = (f"replica {rec.get('replica', '?')} ejected "
             f"({rec.get('why', '?')})")
        if rec.get("victims"):
            s += f", {rec['victims']} in-flight stream(s) to fail over"
        if rec.get("heartbeat_age_s") is not None:
            s += f", heartbeat {rec['heartbeat_age_s']:.1f}s stale"
        return s
    if kind == "replica_failover":
        return (f"{who} failed over from replica {rec.get('replica', '?')}"
                f" to {rec.get('to_replica', '?')}, replaying "
                f"{rec.get('replayed_tokens', 0)} already-emitted token(s)")
    if kind == "replica_drain":
        return (f"replica {rec.get('replica', '?')} draining: "
                f"{rec.get('inflight', 0)} in-flight stream(s) running to "
                "completion, no new placements")
    if kind == "replica_join":
        return (f"replica {rec.get('replica', '?')} joined rotation "
                f"({rec.get('why', 'start')})")
    if kind == "tier_place":
        s = (f"{who} (class {rec.get('cls', '?')}) placed in tier "
             f"{rec.get('tier', '?')}")
        if rec.get("replica"):
            s += f" on replica {rec['replica']}"
        if rec.get("overflow"):
            s += " via cross-tier overflow"
        return s
    if kind == "tier_overflow":
        s = (f"{who} overflowed {rec.get('from_tier', '?')} -> "
             f"{rec.get('to_tier', '?')} ({rec.get('why', '?')})")
        if rec.get("burn") is not None:
            s += f", burn {rec['burn']:.1f}x budget"
        if rec.get("replica"):
            s += f", landed on {rec['replica']}"
        return s
    if kind == "tier_regroup":
        phase = rec.get("phase", "?")
        s = (f"replica {rec.get('replica', '?')} regroup "
             f"{rec.get('from_tier', '?')} -> {rec.get('to_tier', '?')} "
             f"{phase}")
        if rec.get("why"):
            s += f" ({rec['why']})"
        if rec.get("mix") is not None:
            s += f", interactive mix EMA {rec['mix']:.2f}"
        if rec.get("tp_to") is not None:
            s += (f", tp {rec.get('tp_from', '?')} -> {rec['tp_to']}")
        if phase == "aborted":
            s += "; member keeps its ORIGINAL tier"
        return s
    if kind == "migrate_export":
        s = (f"{who} KV state exported for migration "
             f"({rec.get('tokens', '?')} generated token(s)")
        if rec.get("pages") is not None:
            s += f", {rec['pages']} page(s)"
        if rec.get("replica"):
            s += f", from replica {rec['replica']}"
        return s + ")"
    if kind == "migrate_import":
        if rec.get("what") == "prefix":
            return (f"cached prefix shipped "
                    f"{rec.get('replica', '?')} -> "
                    f"{rec.get('to_replica', '?')} "
                    f"({rec.get('pages', '?')} page(s), "
                    f"{rec.get('bytes', '?')} bytes)")
        s = f"{who} migrated"
        if rec.get("replica") or rec.get("to_replica"):
            s += (f" {rec.get('replica', '?')} -> "
                  f"{rec.get('to_replica', '?')}")
        s += f": resumed from shipped state at {rec.get('tokens', '?')} "
        s += "token(s), 0 recomputed"
        if rec.get("bytes") is not None:
            s += f" ({rec['bytes']} bytes moved)"
        return s
    if kind == "migrate_abort":
        s = f"{who} migration aborted ({rec.get('why', '?')})"
        if rec.get("replica"):
            s += f" on replica {rec['replica']}"
        return s + "; falling back to recompute replay"
    if kind == "wal_admit":
        return (f"{who} durably WAL'd pre-ACK "
                f"(fsync wait {rec.get('fsync_ms', '?')}ms, "
                f"{rec.get('n_prompt', '?')} prompt tokens)")
    if kind == "recover_replay":
        return (f"{who} recovered from the WAL at restart "
                f"({rec.get('outcome', 'replayed')}: "
                f"{rec.get('tokens', '?')} already-emitted token(s) "
                "restored without recompute)")
    if kind == "scale_up":
        phase = rec.get("phase", "?")
        s = (f"scaler growing tier {rec.get('tier', 'fleet')}: "
             f"member {rec.get('replica', '?')} {phase}")
        if rec.get("why"):
            s += f" ({rec['why']}"
            if rec.get("burn") is not None:
                s += f", burn {rec['burn']:.1f}x budget"
            if rec.get("queued") is not None:
                s += f", {rec['queued']} queued"
            s += ")"
        if phase == "done" and rec.get("spawn_ms") is not None:
            s += f", spawned in {rec['spawn_ms']:.0f}ms"
        if rec.get("fleet") is not None:
            s += f"; fleet -> {rec['fleet']}"
        return s
    if kind == "scale_down":
        phase = rec.get("phase", "?")
        s = (f"scaler retiring member {rec.get('replica', '?')} "
             f"from tier {rec.get('tier', 'fleet')} {phase}")
        if rec.get("why"):
            s += f" ({rec['why']})"
        if phase == "start" and rec.get("inflight") is not None:
            s += (f", {rec['inflight']} in-flight stream(s) migrating "
                  "off first")
        if phase == "aborted":
            s += "; member stays in rotation"
        if rec.get("fleet") is not None:
            s += f"; fleet -> {rec['fleet']}"
        return s
    if kind == "preempt_notice":
        s = (f"preemptible member {rec.get('replica', '?')} served a "
             f"termination notice")
        if rec.get("notice_s") is not None:
            s += f" ({rec['notice_s']:g}s window)"
        if rec.get("inflight") is not None:
            s += f", {rec['inflight']} in-flight stream(s) to migrate off"
        return s
    if kind == "standby_sync":
        s = (f"standby synced to replication seq {rec.get('seq', '?')} "
             f"(lag {rec.get('lag', '?')} record(s)")
        if rec.get("why"):
            s += f", {rec['why']}"
        if rec.get("epoch") is not None:
            s += f", primary epoch {rec['epoch']}"
        return s + ")"
    if kind == "router_takeover":
        phase = rec.get("phase", "?")
        s = f"router takeover {phase} ({rec.get('why', '?')})"
        if rec.get("from_epoch") is not None or rec.get("epoch") is not None:
            s += (f": epoch {rec.get('from_epoch', '?')} -> "
                  f"{rec.get('epoch', '?')}")
        if phase == "done":
            if rec.get("streams") is not None:
                s += (f", {rec['streams']} unfinished stream(s) re-admitted"
                      f" ({rec.get('migrated', 0)} migrated, "
                      f"{rec.get('replayed', 0)} replayed)")
            if rec.get("takeover_ms") is not None:
                s += f", took {rec['takeover_ms']:.0f}ms"
        return s
    if kind == "epoch_fence":
        s = (f"stale-epoch router call fenced: caller epoch "
             f"{rec.get('stale_epoch', '?')} < current "
             f"{rec.get('epoch', '?')}")
        if rec.get("path"):
            s += f" ({rec['path']})"
        return s
    return f"{kind} {who}"


# ---------------------------------------------------------------------------
# Replay signature: the normalized decision stream two runs must agree on.
# ---------------------------------------------------------------------------

def decision_signature(records: List[dict]) -> List[tuple]:
    out = []
    for r in records:
        kind = r.get("kind")
        if kind not in DECISION_KINDS:
            continue
        salient = tuple(r.get(f) for f in _SIG_FIELDS.get(kind, ()))
        out.append((kind, r.get("req_id"), r.get("user"), salient))
    return out


# ---------------------------------------------------------------------------
# Invariant checker: turns any journal (live ring tail, JSONL file, chaos
# run artifact) into a checked artifact. Tolerant of partial windows: a
# ring that evicted its head must not fabricate violations.
# ---------------------------------------------------------------------------

# An admitted request must reach a slot (install) or a terminal decision
# within this many subsequent prefill batches, or it is starving.
STARVATION_BATCHES = 50


def check_invariants(records: List[dict],
                     starve_after: Optional[int] = STARVATION_BATCHES
                     ) -> List[str]:
    """Returns violation strings (empty = clean). Checked invariants:

      1. pages conserved — every page event's post-state satisfies
         free + used + cached == pool (speculative rollbacks included:
         rejected-draft page releases must balance too);
      2. no slot double-assignment — an install on a slot whose observed
         holder never finished/preempted is a scheduler bug;
      3. preempt victim is never the VIP;
      4. shed only when bounds exceeded — a queue_full/user_queue_full
         shed whose recorded depth is below the recorded cap lied;
      5. no admitted request starves past `starve_after` prefill batches
         without progress (install/finish/requeue/retry/shed/preempt);
      6. speculation never accepts more than it proposed — a spec_verify
         with accepted > proposed fabricated tokens;
      7. tier decisions are well-formed — a tier_overflow whose from and
         to tiers are the same lied about crossing tiers, and a
         tier_regroup outside the start/done/aborted phase vocabulary is
         an instrumentation bug (tools/journal check additionally pairs
         every regroup start with its done/aborted, end-of-run).

    `starve_after=None` skips check 5 — sampled journals
    (--journal-sample < 1) drop a fraction of `batch` records, so the
    batch-ordinal starvation clock under-counts and cannot be trusted;
    every other check reads self-contained records and stays valid.
    """
    bad: List[str] = []
    # (model, slot) -> req_id currently observed holding it.
    held: Dict[tuple, int] = {}
    # req_id -> batch ordinal at admit time (starvation tracking).
    admitted: Dict[int, int] = {}
    batches = 0
    progress = ("install", "finish", "requeue", "retry", "shed",
                "preempt", "deadline_drop", "poison")
    for r in records:
        kind = r.get("kind")
        seq = r.get("seq", "?")
        rid = r.get("req_id")
        if kind in ("page_alloc", "page_free", "page_evict",
                    "spec_rollback"):
            free, used = r.get("free"), r.get("used")
            cached, pool = r.get("cached"), r.get("pool")
            if None not in (free, used, cached, pool) \
                    and free + used + cached != pool:
                bad.append(
                    f"seq {seq}: pages not conserved after {kind}: "
                    f"free {free} + used {used} + cached {cached} "
                    f"!= pool {pool}")
        elif kind == "spec_verify":
            prop, acc = r.get("proposed"), r.get("accepted")
            if None not in (prop, acc) and acc > prop:
                bad.append(
                    f"seq {seq}: speculation accepted {acc} > proposed "
                    f"{prop} draft(s) for req {rid}")
        elif kind == "install" and (r.get("slot") or 0) >= 0:
            # slot -1 = an unslotted runtime (FakeRuntime): nothing to
            # double-assign.
            key = (r.get("model"), r.get("slot"))
            holder = held.get(key)
            if holder is not None and holder != rid:
                bad.append(
                    f"seq {seq}: slot double-assignment: slot {key[1]} of "
                    f"{key[0]} installed for req {rid} while held by "
                    f"req {holder}")
            held[key] = rid
        elif kind in ("finish", "preempt"):
            slot = r.get("slot")
            if slot is not None and slot >= 0:
                held.pop((r.get("model"), slot), None)
        if kind == "preempt":
            vip = r.get("vip")
            if vip is not None and r.get("user") is not None \
                    and r.get("user") == vip:
                bad.append(
                    f"seq {seq}: preempt victim req {rid} IS the VIP "
                    f"({vip})")
        if kind == "tier_overflow":
            ft, tt = r.get("from_tier"), r.get("to_tier")
            if ft is not None and ft == tt:
                bad.append(
                    f"seq {seq}: tier_overflow from and to the same tier "
                    f"({ft}) for req {rid}")
        if kind == "tier_regroup" \
                and r.get("phase") not in ("start", "done", "aborted"):
            bad.append(
                f"seq {seq}: tier_regroup phase {r.get('phase')!r} not in "
                "start/done/aborted")
        if kind == "shed" and r.get("reason") in ("queue_full",
                                                  "user_queue_full"):
            queued, limit = r.get("queued"), r.get("limit")
            if queued is not None and limit is not None and queued < limit:
                bad.append(
                    f"seq {seq}: shed ({r['reason']}) below bound: "
                    f"queued {queued} < cap {limit}")
        if kind == "batch":
            batches += 1
        if kind == "admit" and rid is not None:
            admitted[rid] = batches
        elif kind in progress and rid is not None:
            admitted.pop(rid, None)
    if starve_after is None:
        return bad
    for rid, at_batch in admitted.items():
        if batches - at_batch >= starve_after:
            bad.append(
                f"req {rid} starved: admitted at batch {at_batch} with no "
                f"progress through batch {batches} "
                f"(>= {starve_after} cycles)")
    return bad


# ---------------------------------------------------------------------------
# Batch stats: occupancy / padding-waste from the composed-batch records
# (bench.py folds this into the BENCH JSON line).
# ---------------------------------------------------------------------------

def _padded_of(rec: dict) -> int:
    """Dispatched token positions of one batch record: the explicit
    padded total (ragged + new bucketed records) or bucket x rows
    (records spilled before the field existed)."""
    if rec.get("padded_tokens") is not None:
        return int(rec["padded_tokens"])
    return int(rec.get("bucket", 0)) * int(rec.get("batch_size", 0))


def batch_stats(records: List[dict]) -> dict:
    """Occupancy and padding-waste summary over `batch` records.

    padding_waste = fraction of dispatched token positions that were
    padding — power-of-two bucket rows on the bucketed path, the granule
    tail on the ragged path: the compute burned for shape stability.
    Per-mode rows break the two shapes apart when a journal holds both."""
    batches = [r for r in records if r.get("kind") == "batch"]
    if not batches:
        return {"batches": 0, "mean_occupancy": 0.0,
                "padding_waste": 0.0, "real_tokens": 0, "padded_tokens": 0}
    occ = sum(r.get("occupancy", 0.0) for r in batches) / len(batches)
    real = sum(int(r.get("tokens", 0)) for r in batches)
    padded = sum(_padded_of(r) for r in batches)
    out = {
        "batches": len(batches),
        "mean_occupancy": round(occ, 4),
        "padding_waste": round(1.0 - real / padded, 4) if padded else 0.0,
        "real_tokens": real,
        "padded_tokens": padded,
    }
    modes = sorted({r.get("mode", "bucketed") for r in batches})
    if len(modes) > 1:
        out["modes"] = {}
        for mode in modes:
            ms = [r for r in batches if r.get("mode", "bucketed") == mode]
            mreal = sum(int(r.get("tokens", 0)) for r in ms)
            mpad = sum(_padded_of(r) for r in ms)
            out["modes"][mode] = {
                "batches": len(ms),
                "padding_waste": (round(1.0 - mreal / mpad, 4)
                                  if mpad else 0.0),
            }
    return out


def fair_share_audit(records: List[dict]) -> dict:
    """Per-user decision accounting: enqueued/admitted/shed/preempted/
    finished counts — the offline answer to "who was the scheduler
    actually serving, and at whose expense"."""
    users: Dict[str, Dict[str, int]] = {}
    for r in records:
        u = r.get("user")
        if u is None:
            continue
        row = users.setdefault(u, {"enqueued": 0, "admitted": 0, "shed": 0,
                                   "preempted": 0, "finished": 0,
                                   "deadline_dropped": 0})
        k = r["kind"]
        if k == "enqueue":
            row["enqueued"] += 1
        elif k == "admit":
            row["admitted"] += 1
        elif k == "shed":
            row["shed"] += 1
        elif k == "preempt":
            row["preempted"] += 1
        elif k == "finish":
            row["finished"] += 1
        elif k == "deadline_drop":
            row["deadline_dropped"] += 1
    return users


def load_jsonl(path: str) -> Tuple[dict, List[dict]]:
    """Read a spilled journal file: (meta, records). Lines without a
    "kind" key (the header) feed meta; malformed lines are skipped with
    a count in meta["parse_errors"]."""
    meta: dict = {}
    records: List[dict] = []
    errors = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                errors += 1
                continue
            if "kind" in obj:
                records.append(obj)
            elif "journal_meta" in obj:
                meta = obj["journal_meta"]
    if errors:
        meta["parse_errors"] = errors
    return meta, records
