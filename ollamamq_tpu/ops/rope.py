"""Rotary position embeddings (HF-Llama rotate-half convention)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_freqs(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies [head_dim//2], float32."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponents)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Apply RoPE.

    x: [..., T, H, head_dim] (positions broadcast over leading dims)
    positions: [..., T] int32
    """
    head_dim = x.shape[-1]
    inv_freq = rope_freqs(head_dim, theta)  # [hd/2]
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., T, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., T, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
