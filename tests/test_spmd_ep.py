"""Expert parallelism ACROSS hosts: 2 CPU processes, global mesh ep=2 —
each process owns half the experts of the MoE model; GSPMD inserts the
expert all-to-all across the process boundary. Greedy tokens must equal a
plain single-device run (EP is layout-only)."""

from testutil import run_two_process, single_device_greedy_tokens

_SCRIPT = r"""
import json, os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly 1 local device per process
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
assert jax.device_count() == 2

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.parallel.mesh import make_mesh
import jax.numpy as jnp

mesh = make_mesh(dp=1, sp=1, tp=1, ep=2)  # half the experts per host
ecfg = EngineConfig(model="test-tiny-moe", max_slots=2, num_pages=32,
                    page_size=8, max_pages_per_seq=8, prefill_buckets=(16,),
                    decode_steps_per_iter=2, ep=2)
MODELS = {"test-tiny-moe": None}

if pid == 0:
    from ollamamq_tpu.engine.spmd import SPMDEngine
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = SPMDEngine(ecfg, models=MODELS, blocklist_path=None,
                     mesh=mesh, dtype=jnp.float32)
    eng.start()
    import time

    tok = eng.runtimes["test-tiny-moe"].tokenizer
    req = eng.enqueue_request("u", "", "test-tiny-moe",
                              prompt_tokens=tok.encode("experts apart"),
                              sampling=SamplingParams(max_tokens=6))
    deadline = time.monotonic() + 300
    item = None
    while time.monotonic() < deadline:
        item = req.stream.get(timeout=0.5)
        if item and item.kind in ("done", "error"):
            break
    eng.stop()
    print("RESULT " + json.dumps({
        "kind": item.kind if item else "timeout",
        "error": getattr(item, "error", "") if item else "",
        "tokens": req.generated_ids,
    }), flush=True)
else:
    from ollamamq_tpu.engine.spmd import run_worker

    steps = run_worker(MODELS, ecfg, mesh, dtype=jnp.float32)
    print("RESULT " + json.dumps({"steps": steps}), flush=True)
"""


def test_spmd_expert_parallel_across_processes(tmp_path):
    primary, worker = run_two_process(_SCRIPT, tmp_path)
    assert primary["kind"] == "done", primary
    assert worker["steps"] >= 2  # prefill + decode dispatches replayed
    assert len(primary["tokens"]) >= 1
    # EP across hosts must be numerically transparent.
    assert single_device_greedy_tokens(
        "test-tiny-moe", "experts apart") == primary["tokens"]
