"""Concurrency chaos: admin mutations racing live traffic.

The reference's thread-safety story is Rust's compiler (SURVEY.md §5
"race detection: none beyond what the compiler enforces"); here the
equivalent assurance is exercised empirically: concurrent generate /
cancel / block / unblock / VIP-boost flips / model pull+delete / metrics
polls against one engine, then assert the system settled consistently —
no deadlock, queues drained, gauges zeroed, no thread deaths.
"""

import asyncio
import random
import tempfile

from aiohttp.test_utils import TestClient, TestServer

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.server.app import Server


def test_admin_mutations_race_traffic():
    rng = random.Random(7)

    async def main():
        with tempfile.TemporaryDirectory() as tmp:
            eng = FakeEngine(
                EngineConfig(model="test-tiny", max_slots=8),
                models={"test-tiny": None},
                blocklist_path=f"{tmp}/blocked_items.json",
                token_latency_s=0.002,
            )
            eng.start()
            server = Server(eng, timeout_s=60)
            cl = TestClient(TestServer(server.build_app()))
            await cl.start_server()
            try:
                stop = asyncio.Event()

                async def traffic(user):
                    while not stop.is_set():
                        try:
                            async with cl.post("/api/generate", json={
                                "model": "test-tiny", "prompt": "x",
                                "stream": rng.random() < 0.5,
                                "options": {"num_predict": rng.randint(1, 6)},
                            }, headers={"X-User-ID": user}) as r:
                                await r.read()  # drive streams to completion
                        except Exception:
                            pass
                        await asyncio.sleep(0)

                async def admin():
                    core = eng.core
                    for _ in range(200):
                        action = rng.randint(0, 6)
                        user = f"chaos{rng.randint(0, 4)}"
                        if action == 0:
                            core.block_user(user)
                        elif action == 1:
                            core.unblock_user(user)
                        elif action == 2:
                            core.set_vip(user if rng.random() < 0.8 else None)
                        elif action == 3:
                            core.set_boost(user if rng.random() < 0.8 else None)
                        elif action == 4:
                            try:
                                await cl.post("/api/pull", json={
                                    "model": "test-tiny-qwen", "stream": False})
                            except Exception:
                                pass
                        elif action == 5:
                            try:
                                await cl.post("/api/delete", json={
                                    "model": "test-tiny-qwen"})
                            except Exception:
                                pass
                        else:
                            try:
                                async with cl.get("/metrics") as r:
                                    await r.read()
                            except Exception:
                                pass
                        await asyncio.sleep(0.002)
                    stop.set()

                users = [f"chaos{i}" for i in range(5)]
                await asyncio.gather(admin(), *(traffic(u) for u in users))

                # Unblock everyone, then the system must settle.
                for u in users:
                    eng.core.unblock_user(u)
                for _ in range(200):
                    if eng.core.total_queued() == 0 and not any(
                        rt.has_work() for rt in eng.runtimes.values()
                    ):
                        break
                    await asyncio.sleep(0.05)
                assert eng.core.total_queued() == 0
                snap = eng.core.snapshot()
                assert sum(u["processing"] for u in snap["users"].values()) == 0
                total = sum(u["processed"] + u["dropped"]
                            for u in snap["users"].values())
                assert total > 0
                # Engine thread is alive and still serves.
                r = await cl.post("/api/generate", json={
                    "model": "test-tiny", "prompt": "after-chaos",
                    "stream": False, "options": {"num_predict": 2}})
                assert r.status == 200
                assert (await r.json())["done"] is True
            finally:
                await cl.close()
                eng.stop()

    asyncio.run(main())
