"""SPMD peer liveness (VERDICT r3 weak #3 / next #3).

A host that DIES (process kill, host loss) never fails an op — it just
stops arriving at status syncs. Without liveness the primary would block
at the KV-store rendezvous for the full OLLAMAMQ_SPMD_STATUS_TIMEOUT
(900s default). With heartbeats, the primary treats a stale peer
(~OLLAMAMQ_SPMD_HB_STALE, default 10s — the reference's dead-backend
detection cadence, dispatcher.rs:385) as dead and fails in-flight work
loudly within seconds.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from testutil import free_port

from ollamamq_tpu.engine.spmd import _HeartbeatMonitor


def test_heartbeat_monitor_staleness_logic(monkeypatch):
    monkeypatch.setenv("OLLAMAMQ_SPMD_HB_STALE", "5")
    m = _HeartbeatMonitor()
    # Never-written peers are alive (liveness is opt-in per host).
    assert m.observe(1, None, now=0.0) is False
    assert m.observe(1, None, now=100.0) is False
    # A changing value is alive, however long between observations.
    assert m.observe(1, "0", now=0.0) is False
    assert m.observe(1, "1", now=50.0) is False
    # Unchanged value within the stale window: still alive.
    assert m.observe(1, "1", now=54.0) is False
    # Unchanged beyond the window (since FIRST seen at 50): stale.
    assert m.observe(1, "1", now=56.0) is True
    # Recovery: the value moves again => alive again.
    assert m.observe(1, "2", now=57.0) is False


_DEATH_SCRIPT = r"""
import json, os, sys, time
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly 1 local device per process
os.environ["OLLAMAMQ_SPMD_HB_EVERY"] = "0.5"
os.environ["OLLAMAMQ_SPMD_HB_STALE"] = "3"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
assert jax.device_count() == 2

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.parallel.mesh import make_mesh
import jax.numpy as jnp

mesh = make_mesh(dp=1, sp=1, tp=2)
ecfg = EngineConfig(model="test-tiny", max_slots=2, num_pages=64, page_size=8,
                    max_pages_per_seq=8, prefill_buckets=(16,),
                    decode_steps_per_iter=2)
MODELS = {"test-tiny": None}

if pid == 0:
    from ollamamq_tpu.engine.spmd import SPMDEngine
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = SPMDEngine(ecfg, models=MODELS, blocklist_path=None,
                     mesh=mesh, dtype=jnp.float32)
    eng.start()

    def wait(req, budget):
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            item = req.stream.get(timeout=0.5)
            if item and item.kind in ("done", "error"):
                return item
        return None

    tok = eng.runtimes["test-tiny"].tokenizer
    # A long generation: the worker kills itself (os._exit) partway
    # through the decode stream — no failed op, no shutdown, just gone.
    req = eng.enqueue_request("u", "", "test-tiny",
                              prompt_tokens=tok.encode("long request"),
                              sampling=SamplingParams(max_tokens=64))
    t0 = time.monotonic()
    item = wait(req, budget=240)
    elapsed = time.monotonic() - t0
    eng.stop()
    print("RESULT " + json.dumps({
        "kind": item.kind if item else "timeout",
        "error": (item.error or "") if item else "",
        "elapsed": elapsed,
    }), flush=True)
else:
    from ollamamq_tpu.engine import spmd

    orig = spmd._replay
    state = {"decodes": 0}

    def die_midstream(rt, op, a, b, payload):
        if op == spmd.OP_DECODE:
            state["decodes"] += 1
            if state["decodes"] >= 2:
                os._exit(7)  # hard death: no cleanup, no status write
        return orig(rt, op, a, b, payload)

    spmd._replay = die_midstream
    spmd.run_worker(MODELS, ecfg, mesh, dtype=jnp.float32)
"""



def test_spmd_dead_worker_fails_requests_fast(tmp_path):
    port = free_port()
    script = tmp_path / "hb_child.py"
    script.write_text(_DEATH_SCRIPT)
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen([sys.executable, str(script), str(pid), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, env=env)
        for pid in (0, 1)
    ]
    out0, err0 = "", ""
    try:
        out0, err0 = procs[0].communicate(timeout=420)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        pytest.fail("primary hung waiting on the dead worker")
    finally:
        procs[1].kill()
    # The primary prints RESULT after failing the request, then exits —
    # possibly nonzero: jaxlib's coordination client fatally terminates
    # the process at shutdown when a peer task died (its own heartbeat
    # timeout). The engine-level behavior under test is the RESULT line.
    lines = [l for l in out0.splitlines() if l.startswith("RESULT ")]
    assert lines, (f"primary produced no RESULT (rc={procs[0].returncode}):"
                   f"\n{err0[-3000:]}")
    res = json.loads(lines[0][7:])
    # Loud: the in-flight request errors rather than hanging/serving.
    assert res["kind"] == "error", res
    # Fast: worker dies ~2 decode ops in; detection must be heartbeat-
    # scale (stale=3s) plus transport noise — nowhere near the 900s
    # barrier timeout. CPU-gloo's own send timeouts can add ~a minute.
    assert res["elapsed"] < 180, res
