from ollamamq_tpu.parallel.mesh import (make_mesh, AXIS_DATA, AXIS_EXPERT,
                                        AXIS_PIPE, AXIS_SEQ, AXIS_TENSOR)
from ollamamq_tpu.parallel.sharding import (
    param_partition_specs,
    pipeline_param_specs,
    kv_cache_spec,
    shard_params,
)

__all__ = [
    "make_mesh", "AXIS_DATA", "AXIS_EXPERT", "AXIS_PIPE", "AXIS_SEQ",
    "AXIS_TENSOR", "param_partition_specs", "pipeline_param_specs",
    "kv_cache_spec", "shard_params",
]
