"""ctypes binding to the native mqcore serving core (cpp/mqcore.cpp).

The shared library is built on demand with `make` the first time it's
imported (the native toolchain is a hard dependency of the framework, like
the reference's cargo build). All policy logic lives in C++; this wrapper
only marshals strings and exposes a pythonic facade.
"""

from __future__ import annotations

import ctypes
import enum
import json
import os
import subprocess
import threading
from typing import Iterable, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CPP_DIR = os.path.join(_REPO_ROOT, "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "libmqcore.so")
_BUILD_LOCK = threading.Lock()


class Family(enum.IntEnum):
    UNKNOWN = 0
    OLLAMA = 1
    OPENAI = 2


class Fairness(enum.IntEnum):
    REQUESTS = 0
    TOKENS = 1


def _ensure_built() -> str:
    with _BUILD_LOCK:
        if not os.path.isdir(_CPP_DIR):
            # A plain `pip install .` copies only the python package to
            # site-packages; the native core's sources stay in the repo.
            raise RuntimeError(
                "native scheduler core sources not found at "
                f"{_CPP_DIR}: ollamamq-tpu must run from a checkout "
                "(`pip install -e .`) or the Docker image, which builds "
                "cpp/libmqcore.so in stage 1"
            )
        sources = [
            os.path.join(_CPP_DIR, f)
            for f in os.listdir(_CPP_DIR)
            if f.endswith((".cpp", ".h"))
        ]
        stale = not os.path.exists(_LIB_PATH) or any(
            os.path.getmtime(s) > os.path.getmtime(_LIB_PATH) for s in sources
        )
        if stale:
            subprocess.run(
                ["make", "-C", _CPP_DIR], check=True, capture_output=True, text=True
            )
    return _LIB_PATH


def _load() -> ctypes.CDLL:
    lib = ctypes.CDLL(_ensure_built())
    lib.mq_new.restype = ctypes.c_void_p
    lib.mq_new.argtypes = [ctypes.c_char_p]
    lib.mq_destroy.argtypes = [ctypes.c_void_p]
    lib.mq_enqueue.restype = ctypes.c_int64
    lib.mq_enqueue.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                               ctypes.c_char_p, ctypes.c_int]
    lib.mq_enqueue_kind.restype = ctypes.c_int64
    lib.mq_enqueue_kind.argtypes = lib.mq_enqueue.argtypes + [ctypes.c_int]
    lib.mq_requeue_front.restype = ctypes.c_int64
    lib.mq_requeue_front.argtypes = lib.mq_enqueue_kind.argtypes
    lib.mq_next2.restype = ctypes.c_int64
    lib.mq_next2.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_char_p,
                             ctypes.c_char_p, ctypes.c_int,
                             ctypes.c_char_p, ctypes.c_int]
    lib.mq_next.restype = ctypes.c_int64
    lib.mq_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_char_p, ctypes.c_int,
                            ctypes.c_char_p, ctypes.c_int]
    lib.mq_cancel.restype = ctypes.c_int
    lib.mq_cancel.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mq_reserve_req_ids.restype = None
    lib.mq_reserve_req_ids.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    for name in ("mq_mark_started", "mq_block_user",
                 "mq_unblock_user", "mq_block_ip", "mq_unblock_ip",
                 "mq_set_vip", "mq_set_boost"):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.mq_mark_dropped.restype = None
    lib.mq_mark_dropped.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.mq_mark_done.restype = None
    lib.mq_mark_done.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.mq_is_user_blocked.restype = ctypes.c_int
    lib.mq_is_user_blocked.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.mq_is_ip_blocked.restype = ctypes.c_int
    lib.mq_is_ip_blocked.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.mq_unblock_item.restype = ctypes.c_int
    lib.mq_unblock_item.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.mq_block_version.restype = ctypes.c_int64
    lib.mq_block_version.argtypes = [ctypes.c_void_p]
    lib.mq_is_user_or_ip_blocked.restype = ctypes.c_int
    lib.mq_is_user_or_ip_blocked.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.mq_set_fairness_mode.restype = None
    lib.mq_set_fairness_mode.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mq_queue_len.restype = ctypes.c_int64
    lib.mq_queue_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.mq_total_queued.restype = ctypes.c_int64
    lib.mq_total_queued.argtypes = [ctypes.c_void_p]
    lib.mq_queued_matching.restype = ctypes.c_int64
    lib.mq_queued_matching.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.mq_snapshot_json.restype = ctypes.c_int64
    lib.mq_snapshot_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    return lib


_lib: Optional[ctypes.CDLL] = None


def _get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load()
    return _lib


EMPTY = 0
STUCK = -1
BLOCKED_USER = -1
BLOCKED_IP = -2


class MQCore:
    """Per-user fair-share queue core (native)."""

    def __init__(self, blocklist_path: Optional[str] = None):
        self._lib = _get_lib()
        self._h = ctypes.c_void_p(
            self._lib.mq_new(blocklist_path.encode() if blocklist_path else None)
        )

    def close(self) -> None:
        if self._h:
            self._lib.mq_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- queue ops ---------------------------------------------------------
    def enqueue(
        self,
        user: str,
        ip: str = "",
        model: Optional[str] = None,
        family: Family = Family.UNKNOWN,
        kind: str = "generate",
    ) -> int:
        """Returns req_id > 0, or raises BlockedError. `kind` selects the
        capacity pool the scheduler gate checks for this task (embed vs
        generate are independent engine resources)."""
        rid = self._lib.mq_enqueue_kind(
            self._h, user.encode(), ip.encode(),
            model.encode() if model else None, int(family),
            1 if kind == "embed" else 0,
        )
        if rid == BLOCKED_USER:
            raise BlockedError("user", user)
        if rid == BLOCKED_IP:
            raise BlockedError("ip", ip)
        return rid

    def requeue_front(
        self,
        user: str,
        ip: str = "",
        model: Optional[str] = None,
        family: Family = Family.UNKNOWN,
        kind: str = "generate",
    ) -> int:
        """Undo a pop whose placement raced away: the task returns to the
        FRONT of its user's queue (per-user FIFO preserved — the reference
        peeks and never pops until dispatchable, dispatcher.rs:427-431).
        Returns the fresh req_id, or raises BlockedError."""
        rid = self._lib.mq_requeue_front(
            self._h, user.encode(), ip.encode(),
            model.encode() if model else None, int(family),
            1 if kind == "embed" else 0,
        )
        if rid == BLOCKED_USER:
            raise BlockedError("user", user)
        if rid == BLOCKED_IP:
            raise BlockedError("ip", ip)
        return rid

    def next(
        self, eligible_models: Optional[Iterable[str]] = None,
        eligible_embed: Optional[Iterable[str]] = None,
    ) -> Optional[Tuple[int, str, str]]:
        """Pop per policy. Returns (req_id, user, model) or None (empty).
        Raises StuckQueue if the policy pick's model isn't servable.
        `eligible_embed`, when given, gates embed-kind tasks instead of
        `eligible_models` — the two capacity pools are independent (a
        full decode batch must not park embeds and vice versa); None
        keeps the kind-blind single-list behavior."""
        ubuf = ctypes.create_string_buffer(512)
        mbuf = ctypes.create_string_buffer(512)
        em = None
        if eligible_models is not None:
            em = "\n".join(eligible_models).encode()
        ee = None
        if eligible_embed is not None:
            ee = "\n".join(eligible_embed).encode()
        rid = self._lib.mq_next2(self._h, em, ee, ubuf, len(ubuf), mbuf,
                                 len(mbuf))
        if rid == EMPTY:
            return None
        if rid == STUCK:
            raise StuckQueue()
        return rid, ubuf.value.decode(), mbuf.value.decode()

    def next_window(
        self, k: int,
        eligible_models: Optional[Iterable[str]] = None,
        eligible_embed: Optional[Iterable[str]] = None,
    ) -> Tuple[list, bool]:
        """Pop up to k dispatchable tasks in fair-share order — the
        candidate window a SchedulerPolicy (engine/scheduler.py) may
        reorder before placement. The native core still decides WHICH
        tasks are released (per-user fair share, VIP/boost, blocklist,
        model eligibility); a policy only reorders within the released
        window, so k=1 is exactly the legacy pop-and-place flow.

        Returns (items, stuck): items is a list of (req_id, user, model)
        tuples, stuck=True means a later pop hit a policy-selected-but-
        unservable front (StuckQueue) AFTER the returned items — they
        were already dequeued and must still be placed."""
        eligible_models = (list(eligible_models)
                          if eligible_models is not None else None)
        eligible_embed = (list(eligible_embed)
                          if eligible_embed is not None else None)
        items: list = []
        stuck = False
        for _ in range(max(1, int(k))):
            try:
                item = self.next(eligible_models, eligible_embed)
            except StuckQueue:
                stuck = True
                break
            if item is None:
                break
            items.append(item)
        return items, stuck

    def cancel(self, req_id: int) -> bool:
        return bool(self._lib.mq_cancel(self._h, req_id))

    def reserve_req_ids(self, min_next: int) -> None:
        """Advance the request-id counter to at least `min_next` — crash
        recovery calls this with (max WAL rid + 1) BEFORE re-admitting,
        so a restarted process's fresh ids never collide with the ids
        pre-crash clients still hold (their resume handles)."""
        self._lib.mq_reserve_req_ids(self._h, int(min_next))

    # -- accounting --------------------------------------------------------
    def mark_started(self, user: str) -> None:
        self._lib.mq_mark_started(self._h, user.encode())

    def mark_done(self, user: str, tokens: int = 0) -> None:
        self._lib.mq_mark_done(self._h, user.encode(), tokens)

    def mark_dropped(self, user: str, started: bool = True) -> None:
        self._lib.mq_mark_dropped(self._h, user.encode(), int(started))

    # -- admin -------------------------------------------------------------
    def block_user(self, user: str) -> None:
        self._lib.mq_block_user(self._h, user.encode())

    def unblock_user(self, user: str) -> None:
        self._lib.mq_unblock_user(self._h, user.encode())

    def block_ip(self, ip: str) -> None:
        self._lib.mq_block_ip(self._h, ip.encode())

    def unblock_ip(self, ip: str) -> None:
        self._lib.mq_unblock_ip(self._h, ip.encode())

    def unblock_item(self, item: str) -> bool:
        return bool(self._lib.mq_unblock_item(self._h, item.encode()))

    def is_user_blocked(self, user: str) -> bool:
        return bool(self._lib.mq_is_user_blocked(self._h, user.encode()))

    def block_version(self) -> int:
        return int(self._lib.mq_block_version(self._h))

    def is_user_or_ip_blocked(self, user: str) -> bool:
        """Blocked directly or via the user's last recorded IP."""
        return bool(self._lib.mq_is_user_or_ip_blocked(self._h, user.encode()))

    def is_ip_blocked(self, ip: str) -> bool:
        return bool(self._lib.mq_is_ip_blocked(self._h, ip.encode()))

    def set_vip(self, user: Optional[str]) -> None:
        self._lib.mq_set_vip(self._h, user.encode() if user else None)

    def set_boost(self, user: Optional[str]) -> None:
        self._lib.mq_set_boost(self._h, user.encode() if user else None)

    def set_fairness(self, mode: Fairness) -> None:
        self._lib.mq_set_fairness_mode(self._h, int(mode))

    # -- introspection -----------------------------------------------------
    def queue_len(self, user: str) -> int:
        return self._lib.mq_queue_len(self._h, user.encode())

    def total_queued(self) -> int:
        return self._lib.mq_total_queued(self._h)

    def queued_matching(self, model: str) -> int:
        """Queued tasks `model` could serve (empty-model tasks count)."""
        return int(self._lib.mq_queued_matching(self._h, model.encode()))

    def snapshot(self) -> dict:
        need = self._lib.mq_snapshot_json(self._h, None, 0)
        buf = ctypes.create_string_buffer(need + 16)
        self._lib.mq_snapshot_json(self._h, buf, len(buf))
        return json.loads(buf.value.decode())


class BlockedError(Exception):
    def __init__(self, kind: str, item: str):
        self.kind = kind
        self.item = item
        super().__init__(f"blocked {kind}: {item}")


class StuckQueue(Exception):
    """Policy-selected user's front request can't be served right now
    (model not loaded / no capacity) — reference's 'stuck in queue'."""
