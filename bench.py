"""Benchmark: decode throughput (tok/s/chip) + prefill TTFT through the
real engine runtime on whatever accelerator jax.devices() provides.

Workload = BASELINE.json config 4's shape: a full decode batch of
concurrent sequences sharing every step (the reference's ceiling is one
request per backend; the TPU engine's is `--slots` per chip). Prints ONE
JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
vs_baseline is against the 2000 tok/s/chip north-star target
(BASELINE.md — the reference itself publishes no numbers).

Usage: python bench.py [--model llama3.2:1b] [--slots 64] [--steps 256]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import threading
import time


def _emit_error(msg: str, **extras) -> None:
    """Structured failure line: same shape as the success line so the
    driver's JSON parse always gets a record (round 1 produced nothing
    when TPU backend init died — VERDICT.md 'What's weak' #1)."""
    rec = {
        "metric": "decode_tok_per_s_per_chip",
        "value": 0.0,
        "unit": "tok/s/chip",
        "vs_baseline": 0.0,
        "error": msg,
        **extras,
    }
    # Error lines carry whatever the step profiler saw before the
    # failure — a round that died mid-ladder still shows its compile
    # walls and partial phase timings to the regression sentinel.
    try:
        from ollamamq_tpu.telemetry import stepprof
        rec["step_profile"] = stepprof.PROFILER.summary()
    except Exception:
        pass
    print(json.dumps(rec), flush=True)


def _fallback_argv(model: str, dtypes=("bfloat16", "bfloat16"),
                   cpu: bool = True) -> list:
    """argv for a fallback run: a fresh subprocess (the wedged tunnel has
    this process's backend thread stuck forever) with a smoke workload —
    small enough that a 1B model finishes on CPU in seconds, real enough
    that TTFT/step/MFU plumbing all execute. The partial-pod leg reuses
    the same workload without --cpu (the child env restricts the TPU
    topology instead)."""
    return [sys.executable, os.path.abspath(__file__)] \
        + (["--cpu"] if cpu else []) \
        + ["--model", model, "--slots", "4", "--prompt-len", "32",
           "--steps", "16", "--warmup-steps", "4", "--chunk", "4",
           "--ttft-samples", "2", "--sweep-chunks", "",
           "--weights-dtype", dtypes[0], "--kv-dtype", dtypes[1],
           "--speculative", "3",
           "--shared-prefix", "2", "--shared-prefix-len", "64",
           "--shared-prefix-tail", "16",
           "--slo-burst", "2", "--slo-burst-size", "4",
           "--overload", "16", "--density", "8", "--scheduling", "16",
           "--tiering", "16", "--diurnal", "8",
           "--init-timeout", "300"]


def _run_fallback(argv: list, env: dict, timeout: float, tag: dict,
                  label: str) -> bool:
    """Run one fallback subprocess and re-emit its BENCH line with the
    fallback provenance tagged. Returns True if a line was emitted."""
    import subprocess

    try:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout, env=env)
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("{")][-1]
        rec = json.loads(line)
        if rec.get("error"):
            raise RuntimeError(rec["error"])
    except Exception as e:
        print(f"# {label} fallback failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return False
    rec.update(tag)
    print(json.dumps(rec), flush=True)
    return True


def _partial_pod_fallback(model: str, reason: str,
                          dtypes=("bfloat16", "bfloat16")) -> bool:
    """Single-host TPU fallback for a wedged POD init: re-run the smoke
    workload in a child whose env restricts the topology to this host's
    chips (no cross-host tunnel to wedge). A partial-pod number beats a
    CPU number when the chips themselves are healthy. Disabled off-TPU
    or when OLLAMAMQ_BENCH_NO_FALLBACK is set."""
    if os.environ.get("OLLAMAMQ_BENCH_NO_FALLBACK"):
        return False
    if not (os.environ.get("TPU_WORKER_HOSTNAMES")
            or os.environ.get("TPU_PROCESS_BOUNDS")
            or os.environ.get("JAX_COORDINATOR_ADDRESS")):
        return False  # not a multi-host pod: nothing partial to fall to
    env = dict(os.environ, OLLAMAMQ_BENCH_NO_FALLBACK="1",
               TPU_PROCESS_BOUNDS="1,1,1",
               TPU_CHIPS_PER_PROCESS_BOUNDS="1,1,1",
               TPU_VISIBLE_DEVICES="0")
    for k in ("TPU_WORKER_HOSTNAMES", "TPU_WORKER_ID",
              "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        env.pop(k, None)
    return _run_fallback(
        _fallback_argv(model, dtypes, cpu=False), env, 1800,
        {"partial_pod": True, "fallback": True, "fallback_reason": reason},
        "partial-pod")


def _cpu_fallback(model: str, reason: str,
                  dtypes=("bfloat16", "bfloat16")) -> bool:
    """Run the CPU-mesh fallback and emit ITS measurement, clearly tagged
    platform=cpu + fallback_reason, so a wedged TPU tunnel still yields a
    non-empty scoreboard line. Returns True if a line was emitted."""
    if os.environ.get("OLLAMAMQ_BENCH_NO_FALLBACK"):
        return False
    env = dict(os.environ, OLLAMAMQ_BENCH_NO_FALLBACK="1",
               JAX_PLATFORMS="cpu")
    return _run_fallback(
        _fallback_argv(model, dtypes, cpu=True), env, 1200,
        {"platform": "cpu", "fallback": True, "fallback_reason": reason},
        "cpu")


def _any_fallback(model: str, reason: str,
                  dtypes=("bfloat16", "bfloat16")) -> bool:
    """Fallback ladder for a dead/wedged pod init: single-host TPU first
    (real accelerator numbers), CPU smoke last."""
    return (_partial_pod_fallback(model, reason, dtypes)
            or _cpu_fallback(model, reason, dtypes))


def _init_devices(retries: int = 3, backoff_s: float = 2.0):
    """jax.devices() with retry + exponential backoff: transient TPU
    tunnel/driver races (the 'wedged TPU tunnel' that scrubbed five
    straight official rounds) often succeed on a second attempt a few
    seconds later. Raises the last error once the budget is spent."""
    import jax

    last = None
    delay = backoff_s
    for attempt in range(max(1, retries)):
        try:
            return jax.devices()
        except Exception as e:
            last = e
            if attempt + 1 < max(1, retries):
                print(f"# device init failed (attempt {attempt + 1}/"
                      f"{retries}): {type(e).__name__}: {e}; retrying in "
                      f"{delay:.0f}s", file=sys.stderr)
                time.sleep(delay)
                delay *= 2
    raise last


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="llama3.2:1b")
    p.add_argument("--slots", type=int, default=64)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--steps", type=int, default=256, help="decode steps to time")
    p.add_argument("--chunk", type=int, default=16, help="decode steps per dispatch")
    p.add_argument("--warmup-steps", type=int, default=32)
    p.add_argument("--ttft-samples", type=int, default=8)
    p.add_argument("--page-size", type=int, default=32,
                   help="KV page size (tokens per page); 32 measured "
                        "faster than 16 on v5e (r3: 1762 vs <1700 tok/s)")
    p.add_argument("--weights-dtype", choices=("bfloat16", "int8"),
                   default="bfloat16",
                   help="weight storage dtype under test (int8 = "
                        "per-channel symmetric, dequant-fused matmuls); "
                        "every BENCH record carries this field next to "
                        "'attention'/'spec' so A/B rounds are "
                        "attributable")
    p.add_argument("--kv-dtype", choices=("bfloat16", "int8"),
                   default="bfloat16",
                   help="KV page dtype under test (int8 = ~2x pages per "
                        "HBM byte); carried in every BENCH record")
    p.add_argument("--density", type=int, default=16,
                   help="requests per leg of the density scenario: the "
                        "SAME arrival trace against a bf16-KV pool and "
                        "an int8-KV pool sized to the SAME HBM byte "
                        "budget — reports concurrent-requests-at-equal-"
                        "HBM, preemptions/sheds per leg, and the int8-"
                        "vs-bf16 quality guardrail; 0 disables")
    p.add_argument("--max-batch-tokens", type=int, default=512,
                   help="ragged dispatch token budget")
    p.add_argument("--token-granule", type=int, default=16,
                   help="ragged stream-total padding granule")
    p.add_argument("--spec", action="store_true",
                   help="enable speculative decoding in the engine config "
                        "under test (n-gram drafts + ragged verify); every "
                        "BENCH record carries this field next to "
                        "'attention' so A/B rounds are attributable")
    p.add_argument("--spec-k", type=int, default=4,
                   help="max draft tokens per decode slot per dispatch")
    p.add_argument("--speculative", type=int, default=4,
                   help="requests in the speculative scenario (spec-off vs "
                        "spec-on decode throughput on a repetitive "
                        "generation regime + accept-rate/throttle readout "
                        "on a non-repetitive one; reports byte-identity "
                        "and rollback counts); 0 disables")
    p.add_argument("--scheduler", choices=("fcfs", "srpt", "edf"),
                   default="fcfs",
                   help="scheduling policy of the engine config under "
                        "test (fcfs = legacy FIFO-within-fair-share; "
                        "srpt = shortest-predicted-remaining-first; edf "
                        "= earliest-deadline-first); every BENCH record "
                        "carries this field next to 'attention'/'spec'/"
                        "'*_dtype' so A/B rounds are attributable")
    p.add_argument("--scheduling", type=int, default=32,
                   help="requests in the scheduling scenario: a bimodal "
                        "trace (a few long batch requests parked ahead "
                        "of many short interactive ones) run at the "
                        "same seed under fcfs and srpt, reporting "
                        "p50/p99 TTFT per leg with a pass gate (srpt "
                        "p99 TTFT <= fcfs) and the journal invariant + "
                        "zero-silent-truncation checks in-band; "
                        "0 disables")
    p.add_argument("--sampled", action="store_true",
                   help="use Ollama-default sampling (temp 0.8, repeat 1.1) "
                        "instead of greedy — exercises the full sampler")
    p.add_argument("--long-prompt", type=int, default=0,
                   help="if >0, also time chunked prefill of a prompt this "
                        "long (should exceed the largest bucket)")
    p.add_argument("--sweep-chunks", default="32,64",
                   help="comma-separated extra decode-chunk sizes to sweep "
                        "(same runtime; batch reset between legs); the "
                        "headline number is the best leg. Defaults on so "
                        "the driver's plain run self-tunes the dispatch "
                        "amortization (tunnel RTT dominates small chunks); "
                        "pass '' for a single-chunk run")
    p.add_argument("--embed-model", default="",
                   help="if set, also measure embedding batch throughput "
                        "on this encoder model (BASELINE config 3)")
    p.add_argument("--shared-prefix", type=int, default=4,
                   help="users in the shared_prefix scenario (N requests "
                        "behind one common system prompt, TTFT measured "
                        "with the prefix cache off vs on); 0 disables")
    p.add_argument("--shared-prefix-len", type=int, default=512,
                   help="common system-prompt length in tokens (should be "
                        "a multiple of --page-size)")
    p.add_argument("--shared-prefix-tail", type=int, default=32,
                   help="per-user unique prompt tail in tokens")
    p.add_argument("--slo-burst", type=int, default=4,
                   help="bursts in the slo_burst scenario (bursty arrivals "
                        "measured against a TTFT SLO, with latency "
                        "attribution and burn rate reported); 0 disables")
    p.add_argument("--slo-burst-size", type=int, default=8,
                   help="requests arriving at once per burst")
    p.add_argument("--slo-ttft-ms", type=float, default=250.0,
                   help="TTFT objective for the slo_burst scenario (ms)")
    p.add_argument("--overload", type=int, default=24,
                   help="requests in the overload scenario (arrival rate "
                        "> capacity over a bounded queue, with fault "
                        "injection driving KV-pressure preemption and a "
                        "prefill fault; reports shed rate, preemptions, "
                        "recompute overhead, p99 TTFT); 0 disables")
    p.add_argument("--overload-queue-cap", type=int, default=0,
                   help="queued-request cap for the overload scenario "
                        "(0 = 2x slots)")
    p.add_argument("--fleet", type=int, default=240,
                   help="requests in the fleet scenario (kill-and-drain "
                        "chaos through the dispatcher-over-engines "
                        "router at ~10x the overload scenario's count; "
                        "0 disables). Runs on tiny members so the chaos "
                        "is cheap — the readout is robustness counters "
                        "(dropped_streams, failovers, affinity hits, "
                        "byte-identical resumed streams), not tok/s")
    p.add_argument("--fleet-replicas", type=int, default=2,
                   help="engine replicas behind the router in the fleet "
                        "scenario's chaos leg (the golden leg always "
                        "runs one)")
    p.add_argument("--tiering", type=int, default=32,
                   help="interactive requests in the tiering scenario "
                        "(0 disables): a seeded bimodal VIP/bulk trace "
                        "through a 2-tier fleet vs homogeneous fleets "
                        "at equal member count — per-tier p50/p99 TTFT, "
                        "aggregate tok/s, overflow/regroup counts, 0 "
                        "dropped streams, and a clean multi-spill "
                        "journal audit; pass gate: tiered <= the "
                        "latency-viable homogeneous fleet on p99 "
                        "interactive TTFT AND >= on aggregate tok/s")
    p.add_argument("--diurnal", type=int, default=24,
                   help="interactive requests across the diurnal "
                        "scenario's compressed day (0 disables): a "
                        "night-day-night sinusoidal + bursty trace "
                        "through an ELASTIC tiered fleet (--autoscale: "
                        "burn/backlog scale-up, drain-based scale-down, "
                        "a mid-day preemption notice, and a bulk "
                        "scale-to-zero + wake cycle) and through a "
                        "FIXED fleet at the elastic leg's peak size — "
                        "p99 interactive TTFT, member-hours, scale "
                        "events, 0 drops / 0 silent truncations, and "
                        "the multi-spill journal audit incl. scale "
                        "pairing; pass gate: elastic within tolerance "
                        "of fixed on p99 TTFT at strictly fewer "
                        "member-hours")
    p.add_argument("--crash-restart", type=int, default=8,
                   help="streams in the crash_restart scenario: real "
                        "server subprocesses (router + two HTTP member "
                        "services, admission WAL on) with a mid-run "
                        "kill -9 of a MEMBER (failover) and then of the "
                        "ROUTER itself; the router restarts, recovers "
                        "from the WAL, and clients reconnect via GET "
                        "/api/stream/{req_id}?from=N — gated on 0 "
                        "dropped streams, 0 silent truncations, "
                        "recovered_streams > 0, every resumed stream "
                        "byte-identical to the golden run, and the "
                        "fleet-wide journal audit clean across router + "
                        "member spills; 0 disables")
    p.add_argument("--router-ha", type=int, default=6,
                   help="streams in the router_ha scenario: real server "
                        "subprocesses — an HA primary router (--ha, WAL "
                        "on) + a warm standby (--standby-of) over two "
                        "HTTP member services; mid-decode kill -9 of the "
                        "PRIMARY, the standby replays the shipped "
                        "WAL/journal into a promotion (epoch bump, "
                        "member re-registration, WAL re-admission) and "
                        "clients reconnect to the STANDBY via GET "
                        "/api/stream/{req_id}?from=N; the dead primary "
                        "is then revived and must be FENCED (members "
                        "409 its stale epoch) — gated on 0 dropped "
                        "streams, 0 silent truncations, byte-identical "
                        "resumed streams vs the golden run, >=1 fenced "
                        "call, and the multi-spill journal audit "
                        "(takeover pairing + epoch monotonicity) clean; "
                        "0 disables")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU platform (smoke-testing the harness)")
    p.add_argument("--init-timeout", type=float, default=300.0,
                   help="seconds to wait for device/backend init before "
                        "emitting a structured error and exiting")
    args = p.parse_args()

    # Everything that can fail on operator error must fail BEFORE the first
    # device touch: a wedged TPU tunnel makes jax.devices() hang, and an
    # argument typo must not spend (or wedge) the one chip claim.
    if (min(args.slots, args.prompt_len, args.steps, args.chunk,
            args.ttft_samples) < 1 or args.warmup_steps < 0
            or args.long_prompt < 0):
        _emit_error("invalid arguments: counts must be positive")
        return 2
    try:
        sweep_extra = [int(c) for c in args.sweep_chunks.split(",")
                       if c.strip()]
    except ValueError:
        _emit_error(f"invalid --sweep-chunks '{args.sweep_chunks}'")
        return 2
    if any(c < 1 for c in sweep_extra):
        _emit_error("sweep chunks must be positive")
        return 2

    from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig, get_model_config

    model_cfg = get_model_config(args.model)
    if model_cfg is None:
        _emit_error(f"unknown model '{args.model}'", known=sorted(MODEL_CONFIGS))
        return 2
    emodel_cfg = None
    if args.embed_model:
        emodel_cfg = get_model_config(args.embed_model)
        if emodel_cfg is None or not emodel_cfg.is_encoder:
            _emit_error(f"--embed-model '{args.embed_model}' is not an "
                        "encoder architecture")
            return 2

    if args.cpu:
        from ollamamq_tpu.platform_force import force_cpu

        force_cpu(1)

    import jax

    import numpy as np

    from ollamamq_tpu.engine.engine import ModelRuntime
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.core import MQCore
    from ollamamq_tpu.ops.sampling import SamplingParams

    # Backend init can hang forever on a wedged tunnel (jax.devices() blocks
    # in make_c_api_client), and so can the weight upload inside
    # ModelRuntime init. A daemon watchdog spanning both phases turns a hang
    # into a structured error line instead of a silent driver timeout.
    # --init-timeout <= 0 disables the watchdog.
    def arm_watchdog(done: threading.Event, budget: float, phase: str,
                     exit_code: int, msg: str, fallback: bool = False,
                     **extras) -> None:
        """One definition for every hang-to-structured-error conversion
        (init, run, embed): if `done` isn't set within `budget`, emit and
        exit. `fallback` additionally attempts the CPU-mesh measurement
        first, so a wedged tunnel still scores a tagged line instead of
        value 0.0. Disabled when --init-timeout <= 0."""
        if args.init_timeout <= 0:
            return

        def w():
            if not done.wait(budget):
                if fallback and _any_fallback(args.model, msg, _dtypes):
                    os._exit(exit_code)
                _emit_error(msg, phase=phase, attention="ragged",
                            weights_dtype=args.weights_dtype,
                            kv_dtype=args.kv_dtype,
                            spec=args.spec, scheduler=args.scheduler,
                            **extras)
                os._exit(exit_code)

        threading.Thread(target=w, daemon=True).start()

    _dtypes = (args.weights_dtype, args.kv_dtype)
    init_done = threading.Event()
    arm_watchdog(init_done, args.init_timeout, "init", 3,
                 f"device/runtime init exceeded {args.init_timeout:.0f}s "
                 "(wedged TPU tunnel?)", fallback=True)
    try:
        dev = _init_devices()[0]
    except Exception as e:
        init_done.set()
        msg = f"backend init failed: {type(e).__name__}: {e}"
        if _any_fallback(args.model, msg, _dtypes):
            return 3
        _emit_error(msg, phase="init", attention="ragged",
                    weights_dtype=args.weights_dtype,
                    kv_dtype=args.kv_dtype, spec=args.spec,
                    scheduler=args.scheduler)
        return 3
    # Pages: prompt + generated headroom for every slot. A leg consumes,
    # beyond prompt + steps: one compile dispatch (chunk), timed_decode's
    # unconditional first dispatch (chunk), warmup rounded UP to a chunk
    # multiple (chunk - 1 over), and the final timed dispatch overshooting
    # `steps` by up to chunk - 1 — so 4 chunks of slack on top of
    # warmup + steps covers the worst case for the largest sweep leg.
    max_chunk = max([args.chunk] + sweep_extra)
    tokens_per_seq = max(
        args.prompt_len + args.warmup_steps + args.steps + 4 * max_chunk,
        args.long_prompt + max_chunk,
    )
    page_size = args.page_size
    pages_per_seq = -(-tokens_per_seq // page_size) + 1
    ecfg = EngineConfig(
        model=args.model,
        max_slots=args.slots,
        num_pages=args.slots * pages_per_seq + 2,
        page_size=page_size,
        max_pages_per_seq=pages_per_seq,
        prefill_buckets=(args.prompt_len,),
        max_new_tokens=10**9,
        decode_steps_per_iter=args.chunk,
        max_batch_tokens=args.max_batch_tokens,
        token_granule=args.token_granule,
        spec=args.spec,
        spec_k=args.spec_k,
        scheduler=args.scheduler,
        weights_dtype=args.weights_dtype,
        kv_dtype=args.kv_dtype,
    )
    core = MQCore(None)
    t0 = time.monotonic()
    try:
        rt = ModelRuntime(args.model, model_cfg, ecfg)
        from ollamamq_tpu.engine.scheduler import make_policy

        # Scheduling-policy seam, attached like the engine does in
        # _attach_hooks (bench drives the runtime directly).
        rt.policy = make_policy(ecfg)
    except Exception as e:
        msg = f"runtime init failed: {type(e).__name__}: {e}"
        if _any_fallback(args.model, msg, _dtypes):
            return 4
        _emit_error(msg, phase="runtime_init", device=str(dev),
                    attention="ragged", weights_dtype=args.weights_dtype,
                    kv_dtype=args.kv_dtype, spec=args.spec,
                    scheduler=args.scheduler)
        return 4
    finally:
        init_done.set()  # watchdog covers device + runtime init, not the run
    init_s = time.monotonic() - t0

    # Run-phase watchdog: a tunnel that answers init and then wedges
    # mid-run would otherwise hang the whole bench with nothing emitted —
    # and the official run may get exactly one shot at a live chip.
    # INACTIVITY-based so long honest runs (many sweep legs, long prompts)
    # never trip it: the run touches the deadman after every dispatch, and
    # only `run_budget` seconds with NO completed dispatch counts as a
    # wedge. A single decode chunk or prefill taking that long is one.
    run_done = threading.Event()
    run_budget = max(600.0, args.init_timeout)
    deadman = {"t": time.monotonic(), "phase": "ttft"}

    def touch(phase: str) -> None:
        deadman["t"] = time.monotonic()
        deadman["phase"] = phase

    if args.init_timeout > 0:
        def _run_watchdog():
            while not run_done.wait(15.0):
                idle = time.monotonic() - deadman["t"]
                if idle > run_budget:
                    _emit_error(
                        f"no progress for {idle:.0f}s in phase "
                        f"'{deadman['phase']}' after successful init "
                        "(device wedged mid-run?)", phase=deadman["phase"],
                        init_s=round(init_s, 1))
                    os._exit(6)

        threading.Thread(target=_run_watchdog, daemon=True).start()

    rng = np.random.default_rng(0)

    def make_req(i):
        prompt = rng.integers(3, min(model_cfg.vocab_size, 30000),
                              size=args.prompt_len).tolist()
        sp = (SamplingParams(max_tokens=10**9, temperature=0.8,
                             repeat_penalty=1.1, seed=i + 1)
              if args.sampled else SamplingParams(max_tokens=10**9))
        req = Request(i + 1, f"user{i}", args.model, prompt, sp)
        req._inc_decode = rt.tokenizer.make_incremental_decoder()
        return req

    # TTFT: sequential prefills on the otherwise-empty engine (compile first).
    ttfts = []
    for i in range(args.ttft_samples):
        req = make_req(1000 + i)
        rt.pending_prefill.append(req)
        t0 = time.monotonic()
        for _ in range(10_000):
            _pump(rt, core, touch, "ttft")
            if req.stats.first_token_at:
                break
        else:
            raise RuntimeError("ttft request never produced a token")
        ttfts.append((time.monotonic() - t0) * 1e3)
        # Clear the slot again so the throughput phase starts clean.
        for s, r in enumerate(rt.slot_req):
            if r is not None:
                from ollamamq_tpu.engine.request import FinishReason
                rt._finish_slot(s, FinishReason.CANCELLED, core)
    ttft_compile_ms = ttfts[0]
    ttft_p50_ms = statistics.median(ttfts[1:]) if len(ttfts) > 1 else ttfts[0]

    rt.tokenizer.eos_id = -1  # keep sequences alive (incl. long-prompt runs)

    # Long-prompt prefill: a prompt 4x the largest bucket streams through
    # the chunked path (block-wise paged attention) — tracks the HBM-gap
    # work on long-context prefill. Timed after a compile pass.
    long_ms = None
    if args.long_prompt:
        from ollamamq_tpu.engine.request import FinishReason

        def run_long(i):
            prompt = rng.integers(3, min(model_cfg.vocab_size, 30000),
                                  size=args.long_prompt).tolist()
            req = Request(5000 + i, f"lpuser{i}", args.model, prompt,
                          SamplingParams(max_tokens=10**9))
            req._inc_decode = rt.tokenizer.make_incremental_decoder()
            rt.pending_prefill.append(req)
            t0 = time.monotonic()
            while rt.pending_prefill or rt.chunking:
                progressed = _pump(rt, core, touch, "long_prefill")
                if not progressed and not rt.chunking:
                    # step_prefill returned False with the request still
                    # pending (page allocation failed): no iteration will
                    # ever succeed — surface the structured error instead
                    # of spinning forever.
                    break
            ms = (time.monotonic() - t0) * 1e3
            installed = any(r is req for r in rt.slot_req)
            for s, r in enumerate(rt.slot_req):
                if r is not None:
                    rt._finish_slot(s, FinishReason.CANCELLED, core)
            if not installed:
                raise RuntimeError("long prompt rejected (pages too small?)")
            return ms

        run_long(0)  # compile
        long_ms = statistics.median(run_long(i) for i in range(1, 4))

    from ollamamq_tpu.engine.request import FinishReason

    def reset_batch():
        """Finish every slot and re-prefill a fresh full batch, so each
        sweep leg starts from the same context length / page budget."""
        for s, r in enumerate(rt.slot_req):
            if r is not None:
                rt._finish_slot(s, FinishReason.CANCELLED, core)
        for i in range(args.slots):
            rt.pending_prefill.append(make_req(i))
            _pump(rt, core, touch, "batch_prefill")
        # Ragged spans may still be mid-flight: drain the admission queue
        # so every leg starts with the full batch installed.
        for _ in range(10_000):
            if not (rt.pending_prefill or rt.chunking):
                break
            if not _pump(rt, core, touch, "batch_prefill"):
                break
        return rt.active_count()

    def timed_decode(chunk):
        """Warmup (compiles this chunk size) + timed run; returns
        (steps_done, elapsed_s)."""
        rt.step_decode(core, k_steps=chunk)
        touch("decode_warmup")
        warm_remaining = max(0, args.warmup_steps - chunk)
        while warm_remaining > 0:
            rt.step_decode(core, k_steps=chunk)
            touch("decode_warmup")
            warm_remaining -= chunk
        done = 0
        t0 = time.monotonic()
        while done < args.steps:
            if rt.step_decode(core, k_steps=chunk) == 0:
                break
            touch("decode")
            done += chunk
        return done, time.monotonic() - t0

    active = reset_batch()

    # First dispatch compiles the decode chunk. If the Pallas kernel fails
    # to compile on this hardware, fall back to the jnp attention path
    # rather than losing the benchmark run.
    attn_fallback = False
    try:
        rt.step_decode(core, k_steps=args.chunk)
    except Exception as e:
        if rt.attn_impl == "pallas":
            print(f"# pallas path failed ({type(e).__name__}); falling back to jnp",
                  file=sys.stderr)
            attn_fallback = True
            rt.attn_impl = "jnp"
            rt._decode_jits.clear()
            rt.step_decode(core, k_steps=args.chunk)
        else:
            raise

    sweep = []
    chunks = [args.chunk] + [c for c in sweep_extra if c != args.chunk]
    for leg_chunk in chunks:
        # Each leg is error-contained: with the sweep on by default, a
        # compile/device failure on a later chunk size must not discard
        # the legs already measured (this may be a one-shot live-chip run).
        try:
            if leg_chunk != chunks[0]:
                active = reset_batch()
            done, el = timed_decode(leg_chunk)
        except Exception as e:
            print(f"# sweep leg chunk={leg_chunk} failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            sweep.append({"chunk": leg_chunk, "tok_per_s": 0.0, "steps": 0,
                          "elapsed_s": 0.0, "step_ms": None,
                          "error": f"{type(e).__name__}: {e}"})
            continue
        leg_tok_s = active * done / el if el > 0 else 0.0
        sweep.append({"chunk": leg_chunk, "tok_per_s": round(leg_tok_s, 1),
                      "steps": done, "elapsed_s": el,
                      "step_ms": round(el / done * 1e3, 3) if done else None})
    best = max(sweep, key=lambda s: s["tok_per_s"])
    if best["steps"] == 0:
        _emit_error("decode made no progress on any sweep leg (page "
                    "budget too small, or every leg failed?)",
                    device=str(dev), sweep=sweep)
        return 5
    done_steps, elapsed = best["steps"], best.pop("elapsed_s")
    for leg in sweep:
        leg.pop("elapsed_s", None)
    tok_per_s = best["tok_per_s"]
    best_chunk = best["chunk"]

    # Embedding throughput (BASELINE config 3: /api/embed batches). A
    # failure here (second model's weights may not fit next to the decode
    # model) must not discard the decode numbers already measured — report
    # it in-band instead. A watchdog covers the second weight upload, which
    # can hang the same way initial init can.
    embed_tok_per_s = None
    embed_error = None
    if emodel_cfg is not None:
        embed_done = threading.Event()
        arm_watchdog(embed_done, args.init_timeout, "embed_init", 3,
                     f"embed-model init exceeded {args.init_timeout:.0f}s "
                     "(wedged device?)")
        try:
            from ollamamq_tpu.engine.engine import EncoderRuntime

            ert = EncoderRuntime(args.embed_model, emodel_cfg, ecfg)

            def embed_batch(i0):
                for i in range(8):
                    prompt = rng.integers(
                        3, min(emodel_cfg.vocab_size, 30000), size=64).tolist()
                    ereq = Request(9000 + i0 + i, "embuser", args.embed_model,
                                   prompt, SamplingParams(), kind="embed")
                    ert.pending.append(ereq)
                ert.step(core)
                touch("embed")

            embed_batch(0)  # compile
            n_batches = 8
            t0 = time.monotonic()
            for b in range(1, n_batches + 1):
                embed_batch(b * 10)
            embed_elapsed = time.monotonic() - t0
            embed_tok_per_s = n_batches * 8 * 64 / embed_elapsed
        except Exception as e:
            embed_error = f"{type(e).__name__}: {e}"
            print(f"# embed phase failed: {embed_error}", file=sys.stderr)
        finally:
            embed_done.set()

    # Hardware-relative efficiency: decode is HBM-bandwidth-bound, so
    # report achieved weight+KV streaming rate and MFU against v5e peak
    # (819 GB/s, 394 bf16 TFLOP/s) — "fast" judged against the chip, not
    # only the 2000 tok/s target. KV read per step ~ active x mean
    # context x Hk x hd x 2 (K+V) x bytes x layers.
    step_s = elapsed / max(1, done_steps)
    mean_ctx = args.prompt_len + (args.warmup_steps + done_steps / 2)
    kv_read = (active * mean_ctx * rt.cfg.num_kv_heads * rt.cfg.head_dim
               * 2 * 2 * rt.cfg.num_layers)
    hbm_gbps = (rt.param_bytes + kv_read) / step_s / 1e9
    flops_per_step = 2 * (rt.param_bytes / 2) * active  # 2*params*tokens
    mfu_pct = flops_per_step / step_s / 394e12 * 100

    # Serving-path telemetry readback: the same registry /metrics exposes,
    # populated by the runtime steps this bench just drove — the bench's
    # external timers and the engine's own accounting must agree.
    from ollamamq_tpu.telemetry import schema as tm

    telemetry = {
        "ttft_p50_ms": round(tm.TTFT_MS.labels(model=args.model)
                             .quantile(0.5), 1),
        "tpot_p50_ms": round(tm.TPOT_MS.labels(model=args.model)
                             .quantile(0.5), 3),
        "step_p99_ms": round(tm.STEP_LATENCY_MS.labels(model=args.model)
                             .quantile(0.99), 3),
        "mfu": round(tm.MFU.labels(model=args.model).value, 4),
    }

    # Shared-prefix scenario: N users behind one common system prompt,
    # TTFT measured with the prefix cache OFF then ON against the same
    # runtime (the cache is attached between legs). Reports the hit
    # ratio and the TTFT delta the radix-tree KV reuse buys.
    shared_prefix = None
    if args.shared_prefix > 0:
        try:
            shared_prefix = _shared_prefix_scenario(rt, core, args, rng, touch)
        except Exception as e:  # never discard the decode numbers
            shared_prefix = {"error": f"{type(e).__name__}: {e}"}
            print(f"# shared_prefix scenario failed: {shared_prefix['error']}",
                  file=sys.stderr)
        finally:
            rt.prefix_cache = None  # detach: rt state stays cache-free

    # overload scenario: arrival rate > capacity over a bounded queue,
    # with deterministic fault injection supplying KV-pressure (preempt +
    # recompute) and one contained prefill fault — the chaos acceptance
    # run: zero crashes, zero silent truncations, every request either
    # completes or terminates with an explicit shed/deadline/error.
    overload = None
    if args.overload > 0:
        try:
            overload = _overload_scenario(rt, core, args, rng, touch)
        except Exception as e:  # never discard the decode numbers
            overload = {"error": f"{type(e).__name__}: {e}"}
            print(f"# overload scenario failed: {overload['error']}",
                  file=sys.stderr)
        finally:
            rt.fault_plan = None
            rt.on_preempt = None

    # density scenario: the SAME arrival trace against a bf16-KV pool
    # and an int8-KV pool sized to the SAME HBM byte budget — the
    # quantization PR's acceptance line: ~2x concurrent requests at
    # equal HBM, fewer preemptions/sheds at the same arrival rate, with
    # the int8-vs-bf16 quality guardrail and journal invariants in-band.
    density = None
    if args.density > 0:
        try:
            density = _density_scenario(rt, model_cfg, args, rng, touch)
        except Exception as e:  # never discard the decode numbers
            density = {"error": f"{type(e).__name__}: {e}"}
            print(f"# density scenario failed: {density['error']}",
                  file=sys.stderr)

    # speculative scenario: spec-off vs spec-on decode throughput on a
    # repetitive generation regime (where n-gram drafts verify), plus an
    # accept-rate/auto-throttle readout on the chaotic regime — with the
    # byte-identity of the two legs' streams checked in-band.
    speculative = None
    if args.speculative > 0:
        try:
            speculative = _speculative_scenario(rt, core, args, rng, touch)
        except Exception as e:  # never discard the decode numbers
            speculative = {"error": f"{type(e).__name__}: {e}"}
            print(f"# speculative scenario failed: {speculative['error']}",
                  file=sys.stderr)

    # slo_burst scenario: bursty arrivals against a TTFT objective —
    # where does the burst's latency actually go (queue vs prefill), and
    # how fast does it burn the error budget? Anchors the SLO/attribution
    # observability stack with real numbers.
    slo_burst = None
    if args.slo_burst > 0:
        try:
            slo_burst = _slo_burst_scenario(rt, core, args, rng, touch)
        except Exception as e:  # never discard the decode numbers
            slo_burst = {"error": f"{type(e).__name__}: {e}"}
            print(f"# slo_burst scenario failed: {slo_burst['error']}",
                  file=sys.stderr)

    # scheduling scenario: the SAME bimodal arrival trace (long batch
    # requests parked ahead of a burst of short interactive ones) under
    # --scheduler=fcfs and --scheduler=srpt on identically shaped tiny
    # runtimes — p50/p99 TTFT per leg, the srpt-must-not-lose pass gate,
    # and journal invariants (incl. the anti-starvation bound) +
    # zero-silent-truncation checks in-band.
    scheduling = None
    if args.scheduling > 0:
        try:
            scheduling = _scheduling_scenario(args, touch)
        except Exception as e:  # never discard the decode numbers
            scheduling = {"error": f"{type(e).__name__}: {e}"}
            print(f"# scheduling scenario failed: {scheduling['error']}",
                  file=sys.stderr)

    # fleet scenario: kill-and-drain chaos through the fleet router at
    # ~10x the overload request count — a seeded replica-kill fault plan
    # plus a mid-run POST /admin/drain, with the zero-drop contract
    # checked in-band: dropped_streams == 0, silent_truncations == 0,
    # journal invariants clean, and every failed-over stream
    # byte-identical to the unkilled golden run.
    fleet = None
    if args.fleet > 0:
        try:
            fleet = _fleet_scenario(args, rng, touch)
        except Exception as e:  # never discard the decode numbers
            fleet = {"error": f"{type(e).__name__}: {e}"}
            print(f"# fleet scenario failed: {fleet['error']}",
                  file=sys.stderr)

    # tiering scenario: the same seeded bimodal VIP/bulk trace through a
    # 2-tier fleet (latency-grade interactive member + throughput-grade
    # bulk member) and through homogeneous fleets at equal member count;
    # gate: tiered <= the latency-viable homogeneous fleet on p99
    # interactive TTFT AND >= on aggregate tok/s, zero dropped streams,
    # clean multi-spill journal audit — plus a balancer regroup
    # exercise (class-mix shift -> drain -> migrate -> rejoin).
    tiering = None
    if args.tiering > 0:
        try:
            tiering = _tiering_scenario(args, rng, touch)
        except Exception as e:  # never discard the decode numbers
            tiering = {"error": f"{type(e).__name__}: {e}"}
            print(f"# tiering scenario failed: {tiering['error']}",
                  file=sys.stderr)

    # diurnal scenario: a compressed day of sinusoidal + bursty load
    # through an elastic fleet (--autoscale, with a mid-day preemption
    # notice and a bulk scale-to-zero + wake cycle) vs a fixed fleet at
    # the elastic leg's peak size; gate: elastic within tolerance of
    # fixed on p99 interactive TTFT at STRICTLY fewer member-hours,
    # zero drops, clean multi-spill journal audit incl. scale pairing.
    diurnal = None
    if args.diurnal > 0:
        try:
            diurnal = _diurnal_scenario(args, rng, touch)
        except Exception as e:  # never discard the decode numbers
            diurnal = {"error": f"{type(e).__name__}: {e}"}
            print(f"# diurnal scenario failed: {diurnal['error']}",
                  file=sys.stderr)

    # crash_restart scenario: real subprocess servers (router + two HTTP
    # members, WAL on), kill -9 of a member mid-run (failover) and then
    # of the router itself; restart, WAL recovery, clients reconnect via
    # the resume endpoint — the durability acceptance run, gated on zero
    # drops, zero silent truncations, recovered_streams > 0, and
    # byte-identical resumed streams vs the unkilled golden leg.
    crash_restart = None
    if args.crash_restart > 0:
        try:
            crash_restart = _crash_restart_scenario(args, touch)
        except Exception as e:  # never discard the decode numbers
            crash_restart = {"error": f"{type(e).__name__}: {e}"}
            print(f"# crash_restart scenario failed: "
                  f"{crash_restart['error']}", file=sys.stderr)

    # router_ha scenario: real subprocess servers again — an HA primary
    # (replication stream on) with a warm standby tailing it; kill -9
    # the primary mid-decode, the standby promotes (epoch bump + member
    # re-registration + WAL re-admission), clients resume against the
    # standby byte-identically, and the revived zombie primary is fenced
    # by every member. The ROADMAP item-3 closer.
    router_ha = None
    if args.router_ha > 0:
        try:
            router_ha = _router_ha_scenario(args, touch)
        except Exception as e:  # never discard the decode numbers
            router_ha = {"error": f"{type(e).__name__}: {e}"}
            print(f"# router_ha scenario failed: {router_ha['error']}",
                  file=sys.stderr)

    result = {
        "metric": "decode_tok_per_s_per_chip",
        "value": round(tok_per_s, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_per_s / 2000.0, 3),
        "model": args.model,
        "device": str(dev),
        "platform": jax.default_backend(),
        # The A/B matrix cell this record measured: platform above +
        # batch composition + storage dtypes here ride EVERY record
        # (incl. error and fallback lines), so official rounds are
        # attributable. attention is constant since the bucketed oracle
        # was removed (PR 8) — kept so round-over-round tooling keys on
        # a stable field set.
        "attention": "ragged",
        "weights_dtype": args.weights_dtype,
        "kv_dtype": args.kv_dtype,
        # Speculative decoding on/off in the engine config under test;
        # the `speculative` scenario below reports its own A/B legs.
        "spec": bool(args.spec),
        # Scheduling policy of the config under test; the `scheduling`
        # scenario below reports its own fcfs-vs-srpt legs.
        "scheduler": args.scheduler,
        "telemetry": telemetry,
        "hbm_gbps_est": round(hbm_gbps, 1),
        "mfu_pct_est": round(mfu_pct, 2),
        "page_size": page_size,
        "sampled": args.sampled,
        "slots": active,
        "prompt_len": args.prompt_len,
        "decode_steps": done_steps,
        "chunk": best_chunk,
        "step_ms": round(step_s * 1e3, 3),
        "ttft_p50_ms": round(ttft_p50_ms, 1),
        "ttft_compile_ms": round(ttft_compile_ms, 1),
        "init_s": round(init_s, 1),
        "attn_impl": rt.attn_impl,
        "attn_fallback": attn_fallback,
    }
    if len(sweep) > 1:
        result["sweep"] = sweep
    if long_ms is not None:
        result["long_prompt_len"] = args.long_prompt
        result["long_prefill_ms"] = round(long_ms, 1)
    if args.embed_model:
        result["embed_model"] = args.embed_model
        if embed_tok_per_s is not None:
            result["embed_tok_per_s"] = round(embed_tok_per_s, 1)
        if embed_error is not None:
            result["embed_error"] = embed_error
    if shared_prefix is not None:
        result["shared_prefix"] = shared_prefix
    if speculative is not None:
        result["speculative"] = speculative
    if slo_burst is not None:
        result["slo_burst"] = slo_burst
    if overload is not None:
        result["overload"] = overload
    if density is not None:
        result["density"] = density
    if scheduling is not None:
        result["scheduling"] = scheduling
    if fleet is not None:
        result["fleet"] = fleet
    if tiering is not None:
        result["tiering"] = tiering
    if diurnal is not None:
        result["diurnal"] = diurnal
    if crash_restart is not None:
        result["crash_restart"] = crash_restart
    if router_ha is not None:
        result["router_ha"] = router_ha
    # Step-profiler summary (per-mode phase p50/p99, compile count,
    # padding waste) rides EVERY official record so the regression
    # sentinel (scripts/bench_compare.py) can diff phase-level timings
    # round-over-round, not just the headline tok/s.
    try:
        from ollamamq_tpu.telemetry import stepprof
        result["step_profile"] = stepprof.PROFILER.summary()
    except Exception:
        pass
    run_done.set()
    print(json.dumps(result), flush=True)
    return 0


def _pump(rt, core, touch, phase):
    """One admission/prefill tick: the ragged mixed token-budget dispatch
    (decode rows advance inside it) — the ONE seam every scenario
    drives. The bucketed-oracle branch this used to carry was removed
    with --attention=bucketed (single-mesh runtimes are always ragged)."""
    progressed = rt.step_ragged(core)
    touch(phase)
    return progressed


def _scheduling_scenario(args, touch):
    """Size-aware scheduling A/B: the SAME bimodal trace — a few long
    batch requests enqueued ahead of many short interactive ones, over a
    2-slot runtime — runs under fcfs and srpt on identically shaped
    test-tiny runtimes (same prompt seed, eos disabled so every stream
    runs exactly max_tokens). The readout is p50/p99 TTFT per leg; the
    pass gate is srpt p99 TTFT <= fcfs with 0 journal invariant
    violations (the anti-starvation bound included) and 0 silent
    truncations — ordering must only ever change timing, never tokens."""
    import time

    import numpy as np

    import jax.numpy as jnp

    from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig
    from ollamamq_tpu.core.mqcore import MQCore
    from ollamamq_tpu.engine.engine import ModelRuntime, drop_expired
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.engine.scheduler import make_policy
    from ollamamq_tpu.ops.sampling import SamplingParams
    from ollamamq_tpu.telemetry.journal import Journal, check_invariants

    n_total = max(8, args.scheduling)
    n_long = max(1, n_total // 8)
    long_new, short_new = 48, 4
    long_prompt, short_prompt = 48, 8
    # Longs FIRST: the regime ROADMAP item 4 names — one long output
    # parked ahead of a burst of short interactive requests.
    arrivals = [(f"batch{i}", long_prompt, long_new) for i in range(n_long)]
    arrivals += [(f"chat{i % 8}", short_prompt, short_new)
                 for i in range(n_total - n_long)]

    def leg(policy_name):
        ecfg = EngineConfig(
            model="test-tiny", max_slots=2, num_pages=256, page_size=8,
            max_pages_per_seq=16, decode_steps_per_iter=2,
            max_batch_tokens=128, token_granule=8,
            scheduler=policy_name)
        rt = ModelRuntime("test-tiny", MODEL_CONFIGS["test-tiny"], ecfg,
                          dtype=jnp.float32)
        rt.tokenizer.eos_id = -1  # deterministic full-length streams
        policy = make_policy(ecfg)
        rt.policy = policy
        journal = Journal(capacity=65536)
        rt.journal = journal
        core = MQCore(None)

        def requeue(req):
            if req.expired():
                drop_expired(req, core, rt.name)
                return False
            rt.pending_prefill.appendleft(req)
            return True

        rt.on_preempt = requeue
        prompt_rng = np.random.default_rng(1234)  # SAME prompts per leg
        reqs = []
        for i, (user, plen, mnew) in enumerate(arrivals):
            prompt = prompt_rng.integers(
                3, rt.cfg.vocab_size - 1, size=plen).tolist()
            req = Request(60000 + i, user, rt.name, prompt,
                          SamplingParams(max_tokens=mnew))
            req._inc_decode = rt.tokenizer.make_incremental_decoder()
            reqs.append(req)
            rt.pending_prefill.append(req)
        guard = 0
        while any(not r.stats.finished_at for r in reqs):
            policy.on_admit_tick()  # the aging clock, as the engine loop
            progressed = _pump(rt, core, touch, "scheduling")
            if any(r is not None for r in rt.slot_req):
                progressed = (rt.step_decode(core, k_steps=2) > 0) \
                    or progressed
            guard += 1
            if guard > 2000 * n_total:
                raise RuntimeError("scheduling leg wedged")
            if not progressed:
                time.sleep(0.001)
        ttfts = sorted(r.stats.ttft_ms for r in reqs)
        # Ordering must never change tokens: every stream runs exactly
        # its max_tokens (eos disabled), or something truncated silently.
        silent = sum(1 for r in reqs
                     if len(r.generated_ids) != r.sampling.max_tokens)
        recs = journal.tail(None)
        rt.journal = None
        return {
            "scheduler": policy_name,
            "served": len(ttfts),
            "ttft_p50_ms": round(ttfts[len(ttfts) // 2], 1),
            "ttft_p99_ms": round(
                ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))], 1),
            "ttft_max_ms": round(ttfts[-1], 1),
            "invariant_violations": len(check_invariants(recs)),
            "silent_truncations": silent,
            "sched_decisions": policy.decisions,
            "pred_observed": policy.predictor.observed,
        }

    legs = {name: leg(name) for name in ("fcfs", "srpt")}
    delta = legs["fcfs"]["ttft_p99_ms"] - legs["srpt"]["ttft_p99_ms"]
    return {
        "requests": n_total,
        "long_requests": n_long,
        "long_tokens": long_new,
        "short_tokens": short_new,
        "legs": legs,
        "p99_ttft_delta_ms": round(delta, 1),
        "pass": bool(
            legs["srpt"]["ttft_p99_ms"] <= legs["fcfs"]["ttft_p99_ms"]
            and all(leg_["invariant_violations"] == 0
                    and leg_["silent_truncations"] == 0
                    for leg_ in legs.values())),
    }


def _fleet_scenario(args, rng, touch):
    """Fleet robustness acceptance: the SAME seeded arrival trace runs
    (a) through a single-replica fleet untouched (the golden leg),
    (b) through an N-replica fleet under kill-and-drain chaos — a seeded
    `replica` fault plan crashes a member mid-serving and a mid-run
    drain_replica exercises the zero-drop rolling-restart path — with
    KV migration ON (recovery resumes from shipped state), and
    (c) the same chaos trace with migration OFF (every recovery is a
    recompute replay). The contract checked in-band: dropped_streams ==
    0, silent_truncations == 0, journal invariants (incl.
    no-dropped-streams and migration handoff pairing) clean, every
    stream — failed-over ones included — byte-identical to the golden
    leg, and the migration gate: leg (b) recomputes >= 5x fewer tokens
    than leg (c). Members are tiny real engines (test-tiny, prefix
    cache on so affinity placement has a radix signal); the readout is
    robustness counters, not throughput."""
    import dataclasses
    import time

    import jax.numpy as jnp

    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.engine import TPUEngine
    from ollamamq_tpu.fleet import FleetRouter, LocalMember
    from ollamamq_tpu.ops.sampling import SamplingParams
    from ollamamq_tpu.telemetry import schema as tm
    from ollamamq_tpu.telemetry.journal import check_invariants
    from ollamamq_tpu.testing.faults import FaultPlan
    from ollamamq_tpu.tools.journal import check_no_dropped_streams

    n_total = args.fleet
    n_members = max(2, args.fleet_replicas)
    max_new = 8
    member_kw = dict(model="test-tiny", max_slots=8, num_pages=128,
                     page_size=8, max_pages_per_seq=8,
                     decode_steps_per_iter=2, prefill_buckets=(32, 64),
                     prefix_cache=True)
    # Per-user shared prompt prefixes: repeat traffic from the same user
    # hits that user's cached prefix, giving --placement=affinity a
    # radix-tree signal to route on.
    n_users = 8
    prefixes = [rng.integers(3, 500, size=17).tolist()
                for _ in range(n_users)]
    arrivals = [(f"fl{i % n_users}",
                 prefixes[i % n_users]
                 + rng.integers(3, 500, size=6).tolist())
                for i in range(n_total)]

    def run_leg(replicas, plan, drain, migrate=True, late_kill=False):
        ecfg = EngineConfig(fault_plan=plan, **member_kw)
        member_cfg = dataclasses.replace(ecfg, fault_plan=None)
        members = [
            LocalMember(f"r{i}", TPUEngine(
                member_cfg, models={"test-tiny": None},
                blocklist_path=None, dtype=jnp.float32))
            for i in range(replicas)
        ]
        # Heartbeat threshold generous enough that a multi-second jit
        # compile inside one engine iteration doesn't read as a hung
        # loop; the injected kill is detected via thread death, not
        # staleness, so it still ejects immediately.
        # migrate_timeout bounds how long an export may wait on a
        # wedged (e.g. mid-compile) member before recompute takes over.
        router = FleetRouter(
            members, ecfg, blocklist_path=None, probe_period_s=0.1,
            eject_heartbeat_s=5.0, reprobe_backoff_s=0.2,
            evac_grace_s=1.0, drain_timeout_s=8.0, migrate=migrate,
            migrate_timeout_s=2.0)
        router.start()
        reqs, rids, items = [], [], []
        issued, drained = 0, not drain
        killed_late = not late_kill
        t0 = time.monotonic()
        deadline = t0 + 600.0
        try:
            while True:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        "fleet leg wedged: "
                        f"{sum(1 for r in reqs if not r.stats.finished_at)}"
                        " unresolved")
                done = sum(1 for r in reqs if r.stats.finished_at)
                # Progress-triggered mid-serving kill (identical in both
                # chaos legs): lands deterministically once the engines
                # are warm and streams are mid-decode — the regime where
                # migrating shipped state vs recomputing it actually
                # differs. The plan's sweep-counted kill stays for the
                # mid-compile (0-token) edge.
                if not killed_late and done >= n_total // 2:
                    router._member(f"r{replicas - 1}").crash()
                    killed_late = True
                # Bounded in-flight issuance with slot HEADROOM (3/2 x
                # one member's slots across N members): the trace
                # stretches across the whole serving window so the chaos
                # lands mid-stream, while the surviving member keeps
                # free slots for migrated/replayed victims to land in —
                # this is a robustness readout, not a saturation one.
                while issued < n_total and issued - done < 3 * member_kw[
                        "max_slots"] // 2:
                    user, prompt = arrivals[issued]
                    req = router.enqueue_request(
                        user, "", "test-tiny", prompt_tokens=prompt,
                        sampling=SamplingParams(max_tokens=max_new))
                    reqs.append(req)
                    rids.append(req.req_id)  # rid0: stable journal id
                    items.append([])
                    issued += 1
                for i, r in enumerate(reqs):
                    items[i].extend(r.stream.drain())
                if not drained and done >= n_total // 3 \
                        and issued > n_total // 2:
                    router.drain_replica("r0")
                    drained = True
                touch("fleet")
                if issued >= n_total and done >= n_total:
                    for i, r in enumerate(reqs):
                        items[i].extend(r.stream.drain())
                    break
                time.sleep(0.01)
            jrecs = router.journal.tail(None)
            return {
                "texts": ["".join(it.text for it in seq
                                  if it.kind == "token") for seq in items],
                "terminals": [next((it for it in reversed(seq)
                                    if it.kind in ("done", "error")), None)
                              for seq in items],
                "rids": rids,
                "journal": jrecs,
                "failovers": router.failover_count,
                "migrations": router.migration_count,
                "migrate_aborts": router.migrate_abort_count,
                # Router-overhead self-profiling: this leg's windowed
                # placement p99 (the per-instance window, NOT the
                # process-cumulative histogram, so legs don't bleed
                # into each other's gate).
                "router_overhead_p99_ms": router.router_overhead_p99_ms(),
                "router_overhead_budget_ms":
                    ecfg.router_overhead_budget_ms,
                "elapsed_s": round(time.monotonic() - t0, 3),
            }
        finally:
            router.stop()

    golden = run_leg(1, None, drain=False)
    affinity0 = tm.FLEET_AFFINITY_HITS_TOTAL.value
    # Seeded replica-kill plan: members are probed in order each health
    # sweep (n_members "replica"-site calls per sweep), so call
    # s * n_members crashes the LAST member on sweep s. One kill lands
    # early (sweep 10, ~1s — often mid-compile, exercising 0-token
    # failovers) and one mid-serving (sweep 45, ~4.5s) if the run lasts
    # that long. A FRESH plan per leg: the per-site call counters are
    # stateful, and the migration A/B below must see the same kills.
    def kill_plan():
        return FaultPlan([{"site": "replica", "kind": "exception",
                           "at": [10 * n_members, 45 * n_members],
                           "times": 2}], seed=7)

    chaos = run_leg(n_members, kill_plan(), drain=True, late_kill=True)
    # Affinity delta bounds to the chaos leg only (the recompute leg
    # below increments the same process-global counter).
    chaos_affinity = int(tm.FLEET_AFFINITY_HITS_TOTAL.value - affinity0)
    # Migration A/B: the SAME kill-and-drain chaos trace with migration
    # disabled — every recovery recomputes. The gate: migration
    # recomputes >= 5x fewer tokens (journal replayed_tokens), still
    # with zero drops and clean invariants on both legs.
    recompute = run_leg(n_members, kill_plan(), drain=True, migrate=False,
                        late_kill=True)

    mismatches = [i for i, (a, b) in enumerate(zip(golden["texts"],
                                                   chaos["texts"]))
                  if a != b]
    # A chaos stream that is a strict PREFIX of its golden twin AND ended
    # with a normal done was silently truncated — the exact bug the
    # zero-drop contract kills. (An explicit error terminal is loud, not
    # silent — it still counts as a mismatch above.)
    silent = sum(
        1 for i in mismatches
        if golden["texts"][i].startswith(chaos["texts"][i])
        and chaos["terminals"][i] is not None
        and chaos["terminals"][i].kind == "done")
    dropped = sum(1 for t in chaos["terminals"] if t is None)
    jrecs = chaos["journal"]
    violations = check_invariants(jrecs) + check_no_dropped_streams(jrecs)
    # Victim streams = everything a recovery touched, whether it rode a
    # migration (migrate_import, prefix shipments excluded) or the
    # recompute replay (replica_failover).
    failover_rids = {r.get("req_id") for r in jrecs
                     if r["kind"] == "replica_failover"
                     or (r["kind"] == "migrate_import"
                         and r.get("what") != "prefix")}
    failover_idx = [i for i, rid in enumerate(chaos["rids"])
                    if rid in failover_rids]
    outcomes: dict = {}
    for t in chaos["terminals"]:
        reason = (t.finish_reason.value
                  if t is not None and t.finish_reason else "none")
        outcomes[reason] = outcomes.get(reason, 0) + 1
    placements = sum(1 for r in jrecs if r["kind"] == "place")
    affinity_hits = chaos_affinity

    # Migration leg readout: recomputed tokens = what each leg's
    # recoveries replayed (replica_failover.replayed_tokens); the
    # migration leg's shipped tokens rode migrate_import instead.
    def recomputed_tokens(recs):
        return sum(int(r.get("replayed_tokens") or 0) for r in recs
                   if r["kind"] == "replica_failover")

    recomputed_off = recomputed_tokens(recompute["journal"])
    recomputed_on = recomputed_tokens(jrecs)
    shipped = sum(int(r.get("tokens") or 0) for r in jrecs
                  if r["kind"] == "migrate_import"
                  and r.get("what") != "prefix")
    rec_mismatch = [i for i, (a, b) in enumerate(zip(golden["texts"],
                                                     recompute["texts"]))
                    if a != b]
    rec_violations = (check_invariants(recompute["journal"])
                      + check_no_dropped_streams(recompute["journal"]))
    rec_dropped = sum(1 for t in recompute["terminals"] if t is None)
    migration = {
        "migrations": chaos["migrations"],
        "migrate_aborts": chaos["migrate_aborts"],
        "shipped_tokens": shipped,
        "recomputed_tokens_migrate_on": recomputed_on,
        "recomputed_tokens_migrate_off": recomputed_off,
        "recompute_leg_mismatches": len(rec_mismatch),
        "recompute_leg_dropped": rec_dropped,
        "recompute_leg_invariant_violations": len(rec_violations),
        "elapsed_s_migrate_off": recompute["elapsed_s"],
        # Gate: resuming from shipped state must recompute >= 5x fewer
        # tokens than recompute-only recovery on the same chaos trace,
        # with zero drops and clean invariants on both legs.
        "pass": bool(
            recomputed_on * 5 <= recomputed_off
            and (recomputed_off > 0 or chaos["migrations"] > 0)
            and dropped == 0 and rec_dropped == 0
            and not violations and not rec_violations),
    }
    # Router-overhead gate (ROADMAP: "router overhead (placement +
    # journal) measured and bounded"): the CHAOS leg's windowed
    # placement p99 must come in under the configured budget — chaos is
    # exactly when an unbounded router hot path would hide behind the
    # failover noise.
    overhead_p99 = chaos["router_overhead_p99_ms"]
    overhead_budget = chaos["router_overhead_budget_ms"]
    overhead_pass = bool(overhead_p99 is not None
                         and (not overhead_budget
                              or overhead_p99 <= overhead_budget))
    return {
        "requests": n_total,
        "replicas": n_members,
        "max_new_tokens": max_new,
        "router_overhead_p99_ms": (round(overhead_p99, 4)
                                   if overhead_p99 is not None else None),
        "router_overhead_budget_ms": overhead_budget,
        "router_overhead_pass": overhead_pass,
        "ejects": sum(1 for r in jrecs if r["kind"] == "replica_eject"),
        "failovers": chaos["failovers"],
        "drains": sum(1 for r in jrecs if r["kind"] == "replica_drain"),
        "rejoins": sum(1 for r in jrecs if r["kind"] == "replica_join"
                       and r.get("why") != "start"),
        "dropped_streams": dropped,
        "silent_truncations": silent,
        "stream_mismatches": len(mismatches),
        "failover_streams": len(failover_idx),
        "failover_streams_byte_identical": bool(failover_idx) and not any(
            i in mismatches for i in failover_idx),
        "placements": placements,
        "affinity_hits": affinity_hits,
        "affinity_hit_ratio": round(affinity_hits / max(1, placements), 4),
        "invariant_violations": len(violations),
        "outcomes": outcomes,
        "migration": migration,
        "elapsed_s_golden": golden["elapsed_s"],
        "elapsed_s_chaos": chaos["elapsed_s"],
    }


def _tiering_scenario(args, rng, touch):
    """Tiered-fleet acceptance (Nitsum): the SAME seeded bimodal trace —
    deadlined interactive shorts paced through a window, a bulk backlog
    of long generations — runs through

      (a) the TIERED fleet: one latency-grade member (few slots, fast
          steps) serving `interactive`, one throughput-grade member
          (many slots, slower steps — the big-batch config) serving
          `bulk`, with per-tier burn-rate overflow ON so bulk backlog
          may spill into interactive headroom;
      (b) the latency-viable HOMOGENEOUS fleet at equal member count:
          both members latency-grade — what an operator bound by the
          interactive SLO must deploy without tiers (Nitsum's
          comparator); and
      (c) the throughput-grade homogeneous fleet, reported for the full
          tradeoff picture (it wins raw tok/s but blows the interactive
          p99 — the tradeoff tiering escapes).

    Readout: per-tier p50/p99 TTFT, aggregate tok/s, overflow/regroup
    counts, dropped streams, invariant violations, and the multi-spill
    journal audit (router + both members' spills through tools/journal
    check_files). Gate: tiered <= leg (b) on p99 interactive TTFT AND
    >= on aggregate tok/s, zero drops, clean audit. A separate
    3-member regroup exercise shifts the class mix and lets the
    TierBalancer retier a member (drain -> migrate -> rejoin),
    journaled tier_regroup start -> done."""
    import dataclasses
    import os
    import tempfile
    import time

    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.fake import FakeEngine
    from ollamamq_tpu.fleet import FleetRouter, LocalMember
    from ollamamq_tpu.ops.sampling import SamplingParams
    from ollamamq_tpu.telemetry.journal import check_invariants
    from ollamamq_tpu.tools.journal import check_files

    n_short = args.tiering
    # Bulk sized to outlast the interactive window: the backlog's tail
    # drains after the shorts stop, which is exactly when burn-driven
    # overflow finds idle interactive headroom to spill into.
    n_bulk = max(6, (n_short * 5) // 4)
    short_toks, bulk_toks = 2, 16
    window_s = 1.0  # interactive pacing window
    # Member grades: the real big-batch tradeoff modeled on the fake —
    # throughput-grade runs many slots at a slower step (higher
    # aggregate tok/s, worse latency), latency-grade few slots fast.
    lat_grade = dict(max_slots=2, latency=0.01)
    thr_grade = dict(max_slots=12, latency=0.03)
    base_kw = dict(model="test-tiny", num_pages=64, page_size=8,
                   max_pages_per_seq=8, decode_steps_per_iter=2)
    tmp = tempfile.mkdtemp(prefix="ollamamq-tiering-")

    def run_leg(tag, grades, tiers_spec):
        ecfg = EngineConfig(
            max_slots=max(g["max_slots"] for g in grades),
            journal_file=os.path.join(tmp, f"{tag}-router.jsonl"),
            tiers=tiers_spec, **base_kw)
        members = []
        spills = [ecfg.journal_file]
        for i, grade in enumerate(grades):
            mcfg = dataclasses.replace(
                ecfg, max_slots=grade["max_slots"], tiers=None,
                journal_file=os.path.join(tmp, f"{tag}-r{i}.jsonl"))
            spills.append(mcfg.journal_file)
            members.append(LocalMember(
                f"r{i}", FakeEngine(mcfg, blocklist_path=None,
                                    token_latency_s=grade["latency"])))
        router = FleetRouter(
            members, ecfg, blocklist_path=None, probe_period_s=0.05,
            eject_heartbeat_s=5.0, reprobe_backoff_s=0.2,
            evac_grace_s=1.0,
            # Overflow windows shrunk to the smoke's timescale so bulk
            # backlog (bulk-tier TTFT burn) can spill into interactive
            # headroom within the run; untiered legs ignore this.
            tiering_kw=dict(windows=(("fast", 5.0, 1.0, 1.0, "warn"),),
                            bulk_ttft_ms=150.0, balance=False))
        router.start()
        reqs, kinds = [], []
        t0 = time.monotonic()
        deadline = t0 + 300.0
        issued_shorts = issued_bulk = 0
        try:
            while True:
                if time.monotonic() > deadline:
                    raise RuntimeError(f"tiering leg {tag} wedged")
                now = time.monotonic() - t0
                # Bulk backlog lands up front; interactive shorts pace
                # through the window (deadline_ms classifies them —
                # generous enough that none can expire: the zero-drop
                # gate stays meaningful).
                while issued_bulk < n_bulk:
                    sp = SamplingParams(max_tokens=bulk_toks)
                    reqs.append(router.enqueue_request(
                        f"bulk{issued_bulk % 4}", "", "test-tiny",
                        prompt_tokens=[1] * 8, sampling=sp))
                    kinds.append("bulk")
                    issued_bulk += 1
                want = min(n_short, int(now / window_s * n_short) + 1)
                while issued_shorts < want:
                    sp = SamplingParams(max_tokens=short_toks)
                    sp.deadline_ms = 60_000.0
                    reqs.append(router.enqueue_request(
                        f"int{issued_shorts % 8}", "", "test-tiny",
                        prompt_tokens=[1] * 4, sampling=sp))
                    kinds.append("interactive")
                    issued_shorts += 1
                for r in reqs:
                    r.stream.drain()
                done = sum(1 for r in reqs if r.stats.finished_at)
                touch("tiering")
                if issued_shorts >= n_short and done >= len(reqs):
                    break
                time.sleep(0.005)
            elapsed = time.monotonic() - t0
            tokens = sum(r.stats.completion_tokens for r in reqs)
            dropped = sum(1 for r in reqs if not r.stats.finished_at)

            def pctl(xs, q):
                xs = sorted(xs)
                return (round(xs[min(len(xs) - 1, int(q * len(xs)))], 1)
                        if xs else None)

            out = {"tok_per_s": round(tokens / max(1e-9, elapsed), 1),
                   "elapsed_s": round(elapsed, 3),
                   "tokens": tokens, "dropped_streams": dropped}
            for cls in ("interactive", "bulk"):
                ttfts = [r.stats.ttft_ms for r, k in zip(reqs, kinds)
                         if k == cls and r.stats.first_token_at]
                out[f"{cls}_ttft_p50_ms"] = pctl(ttfts, 0.5)
                out[f"{cls}_ttft_p99_ms"] = pctl(ttfts, 0.99)
            # Counter, not a ring scan: the admission churn of a parked
            # bulk backlog can rotate early records out of the ring (the
            # spill files below keep everything for the audit).
            out["overflows"] = (router.tiers.overflow_count
                                if router.tiers is not None else 0)
            p99 = router.router_overhead_p99_ms()
            out["router_overhead_p99_ms"] = (round(p99, 4)
                                             if p99 is not None else None)
            out["invariant_violations"] = len(
                check_invariants(router.journal.tail(None)))
            return out, spills
        finally:
            router.stop()

    tiered, tiered_spills = run_leg(
        "tiered", [lat_grade, thr_grade], "interactive=r0;bulk=r1")
    homo_lat, lat_spills = run_leg("homo-lat", [lat_grade, lat_grade],
                                   None)
    homo_thr, _ = run_leg("homo-thr", [thr_grade, thr_grade], None)

    # Multi-spill audit: the tiered leg's router + member journals
    # checked as ONE run (invariants, zero-drop, regroup pairing).
    audit_bad, audit_records = check_files(
        [p for p in tiered_spills if os.path.exists(p)])

    # Regroup exercise: a 3-member tiered mini-fleet under a class-mix
    # shift — the balancer must retier a bulk member into interactive
    # (drain -> migrate live streams -> rejoin), journaled start->done.
    regroup = {"regroups_done": 0, "regroups_aborted": 0}
    ecfg = EngineConfig(max_slots=4, **base_kw)
    members = [LocalMember(f"r{i}",
                           FakeEngine(dataclasses.replace(ecfg),
                                      blocklist_path=None,
                                      token_latency_s=0.02))
               for i in range(3)]
    router = FleetRouter(
        members, ecfg, blocklist_path=None, probe_period_s=0.05,
        eject_heartbeat_s=5.0, reprobe_backoff_s=0.2, evac_grace_s=1.0,
        tiers="interactive=r0;bulk=r1,r2",
        tiering_kw=dict(ema_alpha=0.3, deadband=0.1, cooldown_s=0.2,
                        min_samples=8))
    router.start()
    try:
        deadline = time.monotonic() + 60.0
        i = 0
        while time.monotonic() < deadline:
            sp = SamplingParams(max_tokens=4)
            sp.deadline_ms = 60_000.0  # all-interactive mix shift
            req = router.enqueue_request(f"mix{i % 4}", "", "test-tiny",
                                         prompt_tokens=[1] * 4,
                                         sampling=sp)
            i += 1
            t1 = time.monotonic() + 5.0
            while not req.stats.finished_at and time.monotonic() < t1:
                req.stream.drain()
                time.sleep(0.005)
            touch("tiering")
            recs = router.journal.tail(None, kind="tier_regroup")
            regroup["regroups_done"] = sum(
                1 for r in recs if r.get("phase") == "done")
            regroup["regroups_aborted"] = sum(
                1 for r in recs if r.get("phase") == "aborted")
            if regroup["regroups_done"] >= 1:
                break
        regroup["interactive_members"] = len(
            router.tiers._tier_members("interactive"))
        regroup["mix_ema"] = (round(router.tiers.mix_ema, 3)
                              if router.tiers.mix_ema is not None
                              else None)
    finally:
        router.stop()

    gate = bool(
        tiered["interactive_ttft_p99_ms"] is not None
        and homo_lat["interactive_ttft_p99_ms"] is not None
        and tiered["interactive_ttft_p99_ms"]
        <= homo_lat["interactive_ttft_p99_ms"]
        and tiered["tok_per_s"] >= homo_lat["tok_per_s"]
        and tiered["dropped_streams"] == 0
        and tiered["invariant_violations"] == 0
        and regroup["regroups_done"] >= 1
        and not audit_bad)
    return {
        "interactive_requests": n_short,
        "bulk_requests": n_bulk,
        "router_overhead_p99_ms": tiered.get("router_overhead_p99_ms"),
        "tiered": tiered,
        "homogeneous_latency_grade": homo_lat,
        "homogeneous_throughput_grade": homo_thr,
        "regroup_exercise": regroup,
        "journal_audit_records": audit_records,
        "journal_audit_violations": len(audit_bad),
        "pass": gate,
    }


def _diurnal_scenario(args, rng, touch):
    """Elastic-fleet acceptance: a compressed day of load — a quiet
    night, a bursty sinusoidal day with a bulk backlog, a quiet night —
    runs through

      (a) the ELASTIC tiered fleet (--autoscale): starts at interactive
          r0 + bulk r1, sleeps the idle bulk tier to ZERO overnight,
          wakes it when the day's backlog arrives (parked work is the
          wake signal), grows interactive under the burst pressure, and
          survives a mid-day PREEMPTION NOTICE on a spot member — every
          size change the drain -> migrate-off -> retire ladder or a
          journaled spawn; and
      (b) the FIXED fleet at the elastic leg's observed PEAK size —
          what an operator without elasticity must keep running all
          day to hold the same burst.

    Readout per leg: p99/p50 interactive TTFT, member-hours (the
    resource-cost denominator), scale events by direction/why,
    preemptions, drops, silent truncations. Gate: elastic holds the
    fixed leg's p99 interactive TTFT within tolerance at STRICTLY
    fewer member-hours, zero drops and zero silent truncations through
    every scale event (incl. the preemption notice and the zero/wake
    cycle), at least one wake and one idle scale-down, and the
    multi-spill journal audit (router + seed + provisioned member
    spills through tools/journal check_files, scale pairing included)
    comes back clean."""
    import dataclasses
    import itertools
    import os
    import tempfile
    import time

    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.fake import FakeEngine
    from ollamamq_tpu.fleet import FleetRouter, LocalMember
    from ollamamq_tpu.ops.sampling import SamplingParams
    from ollamamq_tpu.telemetry.journal import check_invariants
    from ollamamq_tpu.tools.journal import check_files

    n_day = args.diurnal
    n_bulk = max(6, n_day // 2)
    short_toks, bulk_toks = 2, 10
    # Elastic p99 tolerance vs fixed: the elastic leg pays a bounded
    # queueing premium while a scale-up spawns; it must not pay an
    # unbounded one.
    tol_mult, tol_abs_ms = 2.0, 150.0
    base_kw = dict(model="test-tiny", max_slots=4, num_pages=64,
                   page_size=8, max_pages_per_seq=8,
                   decode_steps_per_iter=2)
    tmp = tempfile.mkdtemp(prefix="ollamamq-diurnal-")
    # Day-phase burst sizes, a one-humped "sinusoid" scaled by n_day —
    # the midday hump must overflow one interactive member's slots so
    # the backlog-pressure scale-up path fires, not just the wake.
    shape = [1, 2, 6, 6, 2, 1]
    bursts = [max(1, round(n_day * s / sum(shape))) for s in shape]

    def run_leg(tag, elastic, tiers_spec, n_members):
        ecfg = EngineConfig(
            journal_file=os.path.join(tmp, f"{tag}-router.jsonl"),
            tiers=tiers_spec, autoscale=elastic, min_replicas=1,
            max_replicas=4, scale_cooldown_s=0.3,
            preemptible="r1" if elastic else None, **base_kw)
        member_cfg = dataclasses.replace(
            ecfg, fault_plan=None, max_queued=0, max_queued_per_user=0,
            tiers=None, autoscale=False, preemptible=None,
            journal_file=None)
        spills = [ecfg.journal_file]
        prov_seq = itertools.count()

        def mkfactory(seed_name=None):
            def build(tp=None):
                jf = os.path.join(
                    tmp, f"{tag}-{seed_name or f'prov{next(prov_seq)}'}"
                         ".jsonl")
                spills.append(jf)
                mcfg = dataclasses.replace(member_cfg, journal_file=jf)
                return FakeEngine(mcfg, blocklist_path=None,
                                  token_latency_s=0.02)
            return build

        members = []
        for i in range(n_members):
            f = mkfactory(seed_name=f"r{i}")
            members.append(LocalMember(f"r{i}", f(), engine_factory=f))
        router = FleetRouter(
            members, ecfg, blocklist_path=None, probe_period_s=0.05,
            eject_heartbeat_s=5.0, reprobe_backoff_s=0.2,
            evac_grace_s=1.0,
            tiering_kw=dict(balance=False,
                            windows=(("fast", 5.0, 1.0, 1.0, "warn"),),
                            bulk_ttft_ms=150.0),
            # Hysteresis shrunk to the smoke's timescale; backlog_high
            # lowered so the midday hump's queue depth reads as
            # pressure on these tiny members; provisioned members join
            # as preemptible SPOT capacity — what the mid-day
            # termination notice reclaims.
            autoscale_kw=dict(tick_period_s=0.02, cooldown_s=0.3,
                              sustain_s=0.1, idle_sustain_s=0.25,
                              backlog_high=2,
                              provision_preemptible=True))
        router.start()
        reqs, kinds, want = [], [], []
        peak = {"interactive": 0, "bulk": 0, "total": 0}
        seen = {"zero": False, "preempted": False}

        def issue(user, cls, toks, deadline_ms=None):
            sp = SamplingParams(max_tokens=toks)
            if deadline_ms is not None:
                sp.deadline_ms = deadline_ms
            reqs.append(router.enqueue_request(
                user, "", "test-tiny", prompt_tokens=[1] * 4,
                sampling=sp))
            kinds.append(cls)
            want.append(toks)

        def pulse():
            for r in reqs:
                r.stream.drain()
            counts = {"interactive": 0, "bulk": 0}
            for m in router.members:
                t = getattr(m, "tier", None)
                if t in counts and m.state != "ejected":
                    counts[t] += 1
            for t in counts:
                peak[t] = max(peak[t], counts[t])
            peak["total"] = max(peak["total"], len(router.members))
            if (router.tiers is not None
                    and "bulk" in router.tiers.scaled_to_zero):
                seen["zero"] = True
            touch("diurnal")

        t0 = time.monotonic()
        try:
            # --- night 0: an interactive trickle, nothing for bulk.
            # The elastic leg's idle bulk member drains off; the tier
            # sleeps at zero. Phase timings are IDENTICAL across legs —
            # the member-hours comparison depends on it.
            i_seq = itertools.count()
            end = time.monotonic() + 1.2
            while time.monotonic() < end:
                issue(f"n{next(i_seq) % 4}", "interactive", short_toks,
                      deadline_ms=60_000.0)
                for _ in range(5):
                    pulse()
                    time.sleep(0.05)
            # --- day: the bulk backlog lands (the elastic leg's WAKE
            # signal) and interactive arrives in sinusoidal bursts.
            b_seq = itertools.count()
            bulk_per_step = -(-n_bulk // len(bursts))  # ceil
            for step, size in enumerate(bursts):
                for _ in range(bulk_per_step):
                    if next(b_seq) < n_bulk:
                        issue(f"b{step % 4}", "bulk", bulk_toks)
                for _ in range(size):
                    issue(f"d{next(i_seq) % 8}", "interactive",
                          short_toks, deadline_ms=60_000.0)
                # Mid-day spot reclamation: serve a termination notice
                # on a preemptible member (elastic leg only).
                if elastic and step == len(bursts) // 2 \
                        and not seen["preempted"]:
                    victim = next(
                        (m for m in router.members
                         if getattr(m, "preemptible", False)
                         and m.state == "healthy"
                         and not getattr(m, "retiring", False)), None)
                    serving = sum(
                        1 for m in router.members
                        if m.state != "ejected"
                        and not getattr(m, "retiring", False))
                    if victim is not None and serving > 1:
                        router.preempt_replica(victim.name,
                                               notice_s=5.0)
                        seen["preempted"] = True
                for _ in range(6):
                    pulse()
                    time.sleep(0.05)
            # --- night 1: arrivals stop; everything drains, then an
            # evening beat (same length both legs) in which the
            # elastic fleet shrinks back toward the floor and the
            # fixed one just keeps burning member-hours.
            deadline = time.monotonic() + 300.0
            while any(not r.stats.finished_at for r in reqs):
                if time.monotonic() > deadline:
                    raise RuntimeError(f"diurnal leg {tag} wedged")
                pulse()
                time.sleep(0.01)
            end = time.monotonic() + 2.5
            while time.monotonic() < end:
                pulse()
                time.sleep(0.05)
            elapsed = time.monotonic() - t0
            pulse()

            def pctl(xs, q):
                xs = sorted(xs)
                return (round(xs[min(len(xs) - 1, int(q * len(xs)))], 1)
                        if xs else None)

            ttfts = [r.stats.ttft_ms for r, k in zip(reqs, kinds)
                     if k == "interactive" and r.stats.first_token_at]
            dropped = sum(1 for r in reqs if not r.stats.finished_at)
            # A stream that finished "normally" with fewer tokens than
            # it asked for was silently truncated somewhere in a scale
            # event — the exact bug the drain ladder exists to prevent.
            silent = sum(
                1 for r, w in zip(reqs, want)
                if r.stats.finished_at and r.stats.completion_tokens < w)
            jrecs = router.journal.tail(None)
            hours = (router.autoscaler.member_hours() if elastic
                     else n_members * elapsed / 3600.0)
            scale = {"up_done": 0, "up_aborted": 0, "down_done": 0,
                     "down_aborted": 0, "wakes": 0, "idle_downs": 0}
            for r in jrecs:
                if r["kind"] == "scale_up" and r.get("phase") == "start" \
                        and r.get("why") == "wake":
                    scale["wakes"] += 1
                if r["kind"] == "scale_down" \
                        and r.get("phase") == "start" \
                        and r.get("why") == "idle":
                    scale["idle_downs"] += 1
                for kind, key in (("scale_up", "up"), ("scale_down",
                                                       "down")):
                    if r["kind"] == kind:
                        if r.get("phase") == "done":
                            scale[f"{key}_done"] += 1
                        elif r.get("phase") == "aborted":
                            scale[f"{key}_aborted"] += 1
            out = {
                "elapsed_s": round(elapsed, 3),
                "requests": len(reqs),
                "interactive_ttft_p50_ms": pctl(ttfts, 0.5),
                "interactive_ttft_p99_ms": pctl(ttfts, 0.99),
                "member_hours": round(hours, 5),
                "dropped_streams": dropped,
                "silent_truncations": silent,
                "scale_events": scale,
                "preempt_notices": sum(1 for r in jrecs
                                       if r["kind"] == "preempt_notice"),
                "slept_to_zero": seen["zero"],
                "preempted": seen["preempted"],
                "peak_members": dict(peak),
                "final_members": len(router.members),
                "invariant_violations": len(check_invariants(jrecs)),
            }
            return out, spills
        finally:
            router.stop()

    elastic, elastic_spills = run_leg(
        "elastic", True, "interactive=r0;bulk=r1", 2)
    # The fixed comparator runs all day at the elastic leg's peak —
    # tier spec rebuilt at the observed per-tier peak counts.
    n_int = max(1, elastic["peak_members"]["interactive"])
    n_blk = max(1, elastic["peak_members"]["bulk"])
    spec = ("interactive=" + ",".join(f"r{i}" for i in range(n_int))
            + ";bulk=" + ",".join(f"r{i}"
                                  for i in range(n_int, n_int + n_blk)))
    fixed, _ = run_leg("fixed", False, spec, n_int + n_blk)

    # Multi-spill audit of the elastic leg: router + seed + provisioned
    # member journals as ONE run — invariants, zero-drop, regroup AND
    # scale pairing (a hanging scale_up/scale_down or a lapsed
    # preemption notice fails here).
    audit_bad, audit_records = check_files(
        [p for p in elastic_spills if os.path.exists(p)])

    p99_e = elastic["interactive_ttft_p99_ms"]
    p99_f = fixed["interactive_ttft_p99_ms"]
    gate = bool(
        p99_e is not None and p99_f is not None
        and p99_e <= p99_f * tol_mult + tol_abs_ms
        and elastic["member_hours"] < fixed["member_hours"]
        and elastic["dropped_streams"] == 0
        and fixed["dropped_streams"] == 0
        and elastic["silent_truncations"] == 0
        and fixed["silent_truncations"] == 0
        and elastic["invariant_violations"] == 0
        and elastic["slept_to_zero"]
        and elastic["preempted"]
        and elastic["preempt_notices"] >= 1
        and elastic["scale_events"]["wakes"] >= 1
        and elastic["scale_events"]["up_done"] >= 1
        and elastic["scale_events"]["down_done"] >= 1
        and not audit_bad)
    return {
        "interactive_requests_day": n_day,
        "bulk_requests": n_bulk,
        "ttft_tolerance": {"mult": tol_mult, "abs_ms": tol_abs_ms},
        "elastic": elastic,
        "fixed": fixed,
        "member_hours_saved_pct": round(
            100.0 * (1.0 - elastic["member_hours"]
                     / max(1e-12, fixed["member_hours"])), 1),
        "journal_audit_records": audit_records,
        "journal_audit_violations": len(audit_bad),
        "pass": gate,
    }


def _crash_restart_scenario(args, touch):
    """Durability acceptance at the PROCESS level: everything runs as
    real server subprocesses (fake engines — the machinery under test
    is the WAL/recovery/resume plumbing, not kernels). Topology: a
    fleet router (admission WAL on, journal spilled) over two HTTP
    member services. One seeded trace, two legs:

      golden leg  N streams served untouched; texts recorded.
      chaos leg   the same N streams; mid-run, `kill -9` a MEMBER
                  process (PR-9/11 failover covers it, clients see one
                  seamless stream), then `kill -9` the ROUTER itself —
                  every client connection dies. The router restarts on
                  the same --wal-dir, the recovery pass re-admits the
                  unfinished streams token-exact across the surviving
                  members, and each client reconnects with
                  GET /api/stream/{rid}?from=N to collect the remainder.

    Gates, all in-band: dropped_streams == 0, silent_truncations == 0,
    recovered_streams > 0, every resumed stream byte-identical to its
    golden twin, and the fleet-wide journal audit clean across the
    union of router (pre- and post-crash) + member spills."""
    import json as _json
    import shutil
    import socket
    import subprocess
    import tempfile
    import urllib.request

    from ollamamq_tpu.tools.journal import check_files

    n = args.crash_restart
    max_new = 14  # under the fake runtime's 16-token ceiling
    golden_text = "".join(f"word{i} " for i in range(max_new))
    tmp = tempfile.mkdtemp(prefix="ollamamq-crash-")
    wal_dir = os.path.join(tmp, "wal")
    procs = []

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def spawn(argv, log_name):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["FAKE_TOKEN_LATENCY_S"] = "0.05"
        logf = open(os.path.join(tmp, log_name), "wb")
        p = subprocess.Popen(
            [sys.executable, "-m", "ollamamq_tpu.cli"] + argv,
            stdout=logf, stderr=subprocess.STDOUT, env=env)
        p._logf = logf
        procs.append(p)
        return p

    def wait_health(port, budget=90.0, want_ready=True):
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/health",
                        timeout=2.0) as r:
                    body = _json.loads(r.read())
                if not want_ready or body.get("status") != "recovering":
                    return body
            except Exception:  # noqa: BLE001
                pass
            touch("crash_restart")
            time.sleep(0.2)
        raise RuntimeError(f"server on :{port} never became healthy")

    class Client:
        """One NDJSON stream through the router: records every frame's
        text + token ids, notes its req_id, and survives the router
        dying mid-read (the resume endpoint picks up from there)."""

        def __init__(self, port, user, prompt):
            self.port = port
            self.user = user
            self.prompt = prompt
            self.rid = None
            self.text = ""
            self.ids = []
            self.done_reason = None
            self.thread = threading.Thread(target=self._run, daemon=True)
            self.thread.start()

        def _consume(self, resp):
            for raw in resp:
                obj = _json.loads(raw)
                if obj.get("req_id") is not None:
                    self.rid = int(obj["req_id"])
                self.ids.extend(int(t) for t in obj.get("token_ids") or ())
                self.text += obj.get("response", "")
                if obj.get("done"):
                    self.done_reason = obj.get("done_reason", "stop")
                    return

        def _run(self):
            body = _json.dumps({
                "model": "test-tiny", "prompt": self.prompt,
                "stream": True, "options": {"num_predict": max_new}})
            req = urllib.request.Request(
                f"http://127.0.0.1:{self.port}/api/generate",
                data=body.encode(),
                headers={"Content-Type": "application/json",
                         "X-User-ID": self.user}, method="POST")
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    self._consume(resp)
            except Exception:  # noqa: BLE001 — the router died under us
                pass

        def resume(self):
            """Reattach after the router restart: frames from the token
            index this client already holds, byte-identical remainder."""
            req = urllib.request.Request(
                f"http://127.0.0.1:{self.port}/api/stream/{self.rid}"
                f"?from={len(self.ids)}",
                headers={"X-User-ID": self.user}, method="GET")
            with urllib.request.urlopen(req, timeout=120) as resp:
                self._consume(resp)

    def run_leg(port, chaos):
        clients = [Client(port, f"cr{i % 4}", f"crash restart {i}")
                   for i in range(n)]
        member_killed = not chaos
        router_killed = not chaos
        deadline = time.monotonic() + 300.0
        while time.monotonic() < deadline:
            touch("crash_restart")
            tokens = sum(len(c.ids) for c in clients)
            if not member_killed and tokens >= 2 * n:
                procs[0].kill()  # member A: SIGKILL, failover territory
                member_killed = True
            if member_killed and not router_killed and tokens >= 6 * n \
                    and all(c.rid is not None for c in clients):
                # Every client holds its resume handle (the req_id its
                # frames carried) before the router goes down.
                router.kill()  # the router itself: the WAL's moment
                router_killed = True
                break
            if all(c.done_reason is not None for c in clients):
                break
            time.sleep(0.05)
        if not chaos:
            for c in clients:
                c.thread.join(timeout=120)
            return clients, 0
        for c in clients:
            c.thread.join(timeout=30)  # reader dies with the router
        # Restart the router on the same WAL; readiness gates on the
        # recovery pass (status "recovering" until re-admission done).
        restarted = spawn(router_argv(journal_tag="2"), "router2.log")
        health = wait_health(port)
        recovered = (health.get("wal") or {}).get("recovered_streams", 0)
        for c in clients:
            if c.done_reason is None and c.rid is not None:
                c.resume()
        return clients, recovered, restarted

    # -- topology ----------------------------------------------------------
    ports = {"a": free_port(), "b": free_port(), "router": free_port()}
    member_argv = ["--fake-engine", "--no-tui", "--models", "test-tiny",
                   "--blocklist", os.path.join(tmp, "bl.json")]
    spawn(member_argv + ["--port", str(ports["a"]),
                         "--journal-file", os.path.join(tmp, "ma.jsonl")],
          "member_a.log")
    spawn(member_argv + ["--port", str(ports["b"]),
                         "--journal-file", os.path.join(tmp, "mb.jsonl")],
          "member_b.log")

    def router_argv(journal_tag=""):
        return ["--fake-engine", "--no-tui", "--models", "test-tiny",
                "--port", str(ports["router"]),
                "--replicas", "0",
                "--replica-urls",
                f"http://127.0.0.1:{ports['a']},"
                f"http://127.0.0.1:{ports['b']}",
                "--wal-dir", wal_dir, "--wal-fsync-ms", "5",
                "--journal-file",
                os.path.join(tmp, f"router{journal_tag}.jsonl"),
                "--blocklist", os.path.join(tmp, "bl.json")]

    try:
        wait_health(ports["a"])
        wait_health(ports["b"])
        router = spawn(router_argv(), "router.log")
        wait_health(ports["router"])

        golden_clients, _ = run_leg(ports["router"], chaos=False)
        chaos_clients, recovered, router2 = run_leg(ports["router"],
                                                    chaos=True)

        dropped = sum(1 for c in chaos_clients if c.done_reason is None)
        mismatches = [i for i, c in enumerate(chaos_clients)
                      if c.text != golden_text]
        silent = sum(1 for i in mismatches
                     if golden_text.startswith(chaos_clients[i].text)
                     and chaos_clients[i].done_reason
                     in ("stop", "length"))
        golden_ok = all(c.text == golden_text for c in golden_clients)
        id_exact = all(c.ids == list(range(1, max_new + 1))
                       for c in chaos_clients if c.done_reason)
        # Router-overhead readout off the RESTARTED router's own stats
        # surface (/metrics.json → fleet.router_overhead): the crash
        # leg's recovery placements are the router hot path under the
        # worst realistic conditions.
        overhead_p99 = None
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{ports['router']}/metrics.json",
                    timeout=10) as r:
                stats = _json.loads(r.read())
            overhead_p99 = ((stats.get("fleet") or {})
                            .get("router_overhead") or {}).get(
                                "place_p99_ms")
        except Exception:  # noqa: BLE001 — readout only, never the gate
            pass
        # Graceful close of the restarted router flushes its spill, so
        # the audit reads a complete journal.
        router2.send_signal(15)
        try:
            router2.wait(timeout=60)
        except subprocess.TimeoutExpired:
            router2.kill()
        spills = [os.path.join(tmp, f) for f in
                  ("router.jsonl", "router2.jsonl", "ma.jsonl",
                   "mb.jsonl")
                  if os.path.exists(os.path.join(tmp, f))]
        violations, audited = check_files(spills)
        return {
            "requests": n,
            "max_new_tokens": max_new,
            "router_overhead_p99_ms": overhead_p99,
            "recovered_streams": recovered,
            "dropped_streams": dropped,
            "silent_truncations": silent,
            "stream_mismatches": len(mismatches),
            "resumed_streams": sum(1 for c in chaos_clients
                                   if c.rid is not None
                                   and c.done_reason is not None),
            "token_exact": id_exact,
            "golden_leg_ok": golden_ok,
            "journal_spills_audited": len(spills),
            "journal_records_audited": audited,
            "invariant_violations": len(violations),
            "violations_sample": violations[:5],
            "pass": bool(golden_ok and dropped == 0 and silent == 0
                         and not mismatches and recovered > 0
                         and id_exact and not violations),
        }
    finally:
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass
            try:
                p._logf.close()
            except Exception:  # noqa: BLE001
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _router_ha_scenario(args, touch):
    """Router-HA acceptance at the PROCESS level: an HA primary router
    (admission WAL + journal tap replicated over /admin/ha/sync) and a
    warm standby tailing it, over two HTTP member services. One seeded
    trace, two legs:

      golden leg  N streams through the primary, untouched.
      chaos leg   the same N streams; mid-decode, `kill -9` the
                  PRIMARY. The standby detects heartbeat loss past the
                  takeover grace, promotes — epoch bump, member
                  re-registration, WAL-replica re-admission — and each
                  client reconnects TO THE STANDBY with
                  GET /api/stream/{rid}?from=N for the remainder.

    Then the dead primary is REVIVED on its old WAL dir: its recovery
    replays the same streams at the stale epoch and every member must
    fence it (409 + epoch_fence journaled) — zero stale-epoch
    placements accepted, while a fresh stream through the promoted
    standby still completes. Gates: dropped_streams == 0,
    silent_truncations == 0, every resumed stream byte-identical to
    its golden twin, the standby 503s (with Retry-After) before
    promotion, >= 1 fenced call after revival, and the multi-spill
    journal audit — primary spill, standby spill (takeover pairing +
    epoch monotonicity bind here), the standby's primary-journal
    replica, and both member spills — clean."""
    import json as _json
    import shutil
    import socket
    import subprocess
    import tempfile
    import urllib.error
    import urllib.request

    from ollamamq_tpu.tools.journal import check_files
    from ollamamq_tpu.telemetry.journal import load_jsonl

    n = args.router_ha
    max_new = 14  # under the fake runtime's 16-token ceiling
    golden_text = "".join(f"word{i} " for i in range(max_new))
    tmp = tempfile.mkdtemp(prefix="ollamamq-ha-")
    wal_p = os.path.join(tmp, "wal-primary")
    wal_s = os.path.join(tmp, "wal-standby")
    procs = []

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def spawn(argv, log_name):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["FAKE_TOKEN_LATENCY_S"] = "0.05"
        logf = open(os.path.join(tmp, log_name), "wb")
        p = subprocess.Popen(
            [sys.executable, "-m", "ollamamq_tpu.cli"] + argv,
            stdout=logf, stderr=subprocess.STDOUT, env=env)
        p._logf = logf
        procs.append(p)
        return p

    def get_health(port, timeout=2.0):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=timeout) as r:
            return _json.loads(r.read())

    def wait_health(port, budget=90.0, ok=None):
        """Poll /health until `ok(body)` (default: not recovering)."""
        if ok is None:
            ok = lambda b: b.get("status") != "recovering"  # noqa: E731
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            try:
                body = get_health(port)
                if ok(body):
                    return body
            except Exception:  # noqa: BLE001
                pass
            touch("router_ha")
            time.sleep(0.2)
        raise RuntimeError(f"server on :{port} never became healthy")

    def prom_counter(port, name):
        """Sum a counter across its label rows off /metrics; None if
        the metric never fired (no rows exported)."""
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        total, found = 0.0, False
        for line in text.splitlines():
            if line.startswith(name) and " " in line:
                try:
                    total += float(line.rsplit(" ", 1)[1])
                    found = True
                except ValueError:
                    pass
        return total if found else None

    class Client:
        """One NDJSON stream: records frames + token ids, notes its
        req_id, survives the router dying mid-read (resume() collects
        the remainder — possibly from a DIFFERENT router port)."""

        def __init__(self, port, user, prompt):
            self.port = port
            self.user = user
            self.prompt = prompt
            self.rid = None
            self.text = ""
            self.ids = []
            self.done_reason = None
            self.thread = threading.Thread(target=self._run, daemon=True)
            self.thread.start()

        def _consume(self, resp):
            for raw in resp:
                obj = _json.loads(raw)
                if obj.get("req_id") is not None:
                    self.rid = int(obj["req_id"])
                self.ids.extend(int(t) for t in obj.get("token_ids") or ())
                self.text += obj.get("response", "")
                if obj.get("done"):
                    self.done_reason = obj.get("done_reason", "stop")
                    return

        def _run(self):
            body = _json.dumps({
                "model": "test-tiny", "prompt": self.prompt,
                "stream": True, "options": {"num_predict": max_new}})
            req = urllib.request.Request(
                f"http://127.0.0.1:{self.port}/api/generate",
                data=body.encode(),
                headers={"Content-Type": "application/json",
                         "X-User-ID": self.user}, method="POST")
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    self._consume(resp)
            except Exception:  # noqa: BLE001 — the primary died under us
                pass

        def resume(self):
            req = urllib.request.Request(
                f"http://127.0.0.1:{self.port}/api/stream/{self.rid}"
                f"?from={len(self.ids)}",
                headers={"X-User-ID": self.user}, method="GET")
            with urllib.request.urlopen(req, timeout=120) as resp:
                self._consume(resp)

    # -- topology ----------------------------------------------------------
    ports = {"a": free_port(), "b": free_port(),
             "primary": free_port(), "standby": free_port()}
    member_argv = ["--fake-engine", "--no-tui", "--models", "test-tiny",
                   "--blocklist", os.path.join(tmp, "bl.json")]
    spawn(member_argv + ["--port", str(ports["a"]),
                         "--journal-file", os.path.join(tmp, "ma.jsonl")],
          "member_a.log")
    spawn(member_argv + ["--port", str(ports["b"]),
                         "--journal-file", os.path.join(tmp, "mb.jsonl")],
          "member_b.log")
    replica_urls = (f"http://127.0.0.1:{ports['a']},"
                    f"http://127.0.0.1:{ports['b']}")

    def primary_argv(journal_tag=""):
        return ["--fake-engine", "--no-tui", "--models", "test-tiny",
                "--port", str(ports["primary"]),
                "--replicas", "0", "--replica-urls", replica_urls,
                "--ha", "--takeover-grace-s", "1.0",
                "--wal-dir", wal_p, "--wal-fsync-ms", "5",
                "--journal-file",
                os.path.join(tmp, f"router-primary{journal_tag}.jsonl"),
                "--blocklist", os.path.join(tmp, "bl.json")]

    standby_argv = [
        "--fake-engine", "--no-tui", "--models", "test-tiny",
        "--port", str(ports["standby"]),
        "--replicas", "0", "--replica-urls", replica_urls,
        "--standby-of", f"http://127.0.0.1:{ports['primary']}",
        "--takeover-grace-s", "1.0",
        "--wal-dir", wal_s, "--wal-fsync-ms", "5",
        "--journal-file", os.path.join(tmp, "standby.jsonl"),
        "--blocklist", os.path.join(tmp, "bl.json")]

    try:
        wait_health(ports["a"])
        wait_health(ports["b"])
        primary = spawn(primary_argv(), "primary.log")
        wait_health(ports["primary"])
        standby = spawn(standby_argv, "standby.log")
        # Standby is healthy once it reports its role AND has applied
        # the cold snapshot (lag 0 against an idle primary).
        wait_health(ports["standby"],
                    ok=lambda b: b.get("role") == "standby"
                    and b.get("sync_lag_records") == 0)

        # -- golden leg (through the primary, untouched) -------------------
        golden = [Client(ports["primary"], f"ha{i % 4}", f"router ha {i}")
                  for i in range(n)]
        for c in golden:
            c.thread.join(timeout=120)
        golden_ok = all(c.text == golden_text for c in golden)

        # -- standby never serves pre-promotion ----------------------------
        standby_503 = False
        retry_after = None
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{ports['standby']}/api/generate",
                data=_json.dumps({"model": "test-tiny", "prompt": "x",
                                  "stream": False}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST"), timeout=10)
        except urllib.error.HTTPError as e:
            standby_503 = e.code in (429, 503)
            retry_after = e.headers.get("Retry-After")

        # -- chaos leg: kill -9 the primary mid-decode ---------------------
        clients = [Client(ports["primary"], f"ha{i % 4}", f"router ha {i}")
                   for i in range(n)]
        deadline = time.monotonic() + 120.0
        killed_at = None
        pre_kill_lag = None
        while time.monotonic() < deadline:
            touch("router_ha")
            tokens = sum(len(c.ids) for c in clients)
            if tokens >= 4 * n and all(c.rid is not None for c in clients):
                try:  # standby's replication position just before the cut
                    pre_kill_lag = get_health(
                        ports["standby"]).get("sync_lag_records")
                except Exception:  # noqa: BLE001
                    pass
                primary.kill()  # SIGKILL: no drain, no handover
                killed_at = time.monotonic()
                break
            if all(c.done_reason is not None for c in clients):
                break
            time.sleep(0.05)
        if killed_at is None:
            raise RuntimeError("streams finished before the kill point")
        for c in clients:
            c.thread.join(timeout=30)  # readers die with the primary

        # Promotion: role flips standby -> (promoting) -> primary, and
        # the WAL replay must be done before clients resume.
        wait_health(ports["standby"], budget=60.0,
                    ok=lambda b: b.get("role") == "primary"
                    and b.get("status") != "recovering")
        takeover_observed_ms = round((time.monotonic() - killed_at) * 1e3)
        for c in clients:
            if c.done_reason is None and c.rid is not None:
                c.port = ports["standby"]
                c.resume()

        # -- revive the zombie primary: every member must fence it --------
        zombie = spawn(primary_argv(journal_tag="-zombie"), "zombie.log")
        time.sleep(3.0)  # register + WAL recovery placements, all fenced
        touch("router_ha")
        fenced = sum(
            prom_counter(ports[m], "ollamamq_ha_fenced_calls_total") or 0
            for m in ("a", "b"))
        # The promoted router must still place fresh work while the
        # zombie is being turned away.
        probe = Client(ports["standby"], "ha-probe", "post takeover")
        probe.thread.join(timeout=60)
        post_ok = probe.text == golden_text

        # -- scoring -------------------------------------------------------
        dropped = sum(1 for c in clients if c.done_reason is None)
        mismatches = [i for i, c in enumerate(clients)
                      if c.text != golden_text]
        silent = sum(1 for i in mismatches
                     if golden_text.startswith(clients[i].text)
                     and clients[i].done_reason in ("stop", "length"))
        id_exact = all(c.ids == list(range(1, max_new + 1))
                       for c in clients if c.done_reason)

        # Graceful close of the promoted standby flushes its spill (its
        # handover attempt no-ops: nobody tails it). The zombie is
        # killed hard — its spill stays out of the audit below.
        zombie.kill()
        standby.send_signal(15)
        try:
            standby.wait(timeout=60)
        except subprocess.TimeoutExpired:
            standby.kill()
        # Multi-spill audit as ONE run: the dead primary's spill, the
        # standby's spill (router_takeover pairing + epoch monotonicity
        # bind here), the standby's primary-journal replica (byte copy,
        # journal_meta replica_of excludes it from the cross-spill
        # duplicate-epoch check), and both member spills (epoch_fence
        # sanity binds there). The ZOMBIE's spill is excluded by
        # design: its recovery replays streams other spills already
        # resolved, at an epoch the fleet fenced — it is not part of
        # the surviving run.
        spills = [p for p in
                  (os.path.join(tmp, "router-primary.jsonl"),
                   os.path.join(tmp, "standby.jsonl"),
                   os.path.join(wal_s, "primary-journal.jsonl"),
                   os.path.join(tmp, "ma.jsonl"),
                   os.path.join(tmp, "mb.jsonl"))
                  if os.path.exists(p)]
        violations, audited = check_files(spills)
        takeover_ms = None
        new_epoch = None
        try:
            _, srecs = load_jsonl(os.path.join(tmp, "standby.jsonl"))
            for r in srecs:
                if r.get("kind") == "router_takeover" \
                        and r.get("phase") == "done":
                    takeover_ms = r.get("takeover_ms")
                    new_epoch = r.get("epoch")
        except Exception:  # noqa: BLE001 — readout only, never the gate
            pass
        return {
            "requests": n,
            "max_new_tokens": max_new,
            "takeover_ms": takeover_ms,
            "takeover_observed_ms": takeover_observed_ms,
            "epoch_after_takeover": new_epoch,
            "pre_kill_sync_lag_records": pre_kill_lag,
            "standby_shed_pre_promotion": standby_503,
            "standby_retry_after_s": retry_after,
            "fenced_calls": fenced,
            "post_takeover_stream_ok": post_ok,
            "dropped_streams": dropped,
            "silent_truncations": silent,
            "stream_mismatches": len(mismatches),
            "resumed_streams": sum(1 for c in clients
                                   if c.rid is not None
                                   and c.done_reason is not None),
            "token_exact": id_exact,
            "golden_leg_ok": golden_ok,
            "journal_spills_audited": len(spills),
            "journal_records_audited": audited,
            "invariant_violations": len(violations),
            "violations_sample": violations[:5],
            "pass": bool(golden_ok and dropped == 0 and silent == 0
                         and not mismatches and id_exact
                         and standby_503 and retry_after is not None
                         and fenced >= 1 and post_ok
                         and takeover_observed_ms < 60_000
                         and not violations),
        }
    finally:
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass
            try:
                p._logf.close()
            except Exception:  # noqa: BLE001
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _overload_scenario(rt, core, args, rng, touch):
    """Graceful-degradation acceptance: N requests arrive faster than the
    engine drains them, over a bounded queue, with a seeded fault plan
    supplying KV-allocation pressure (every few decode-time page growths
    fail => preemption with recompute) and one injected prefill fault
    (=> contained retry). Reports shed rate, preemption count, recompute
    token overhead, deadline drops, p99 TTFT — and `silent_truncations`,
    which the chaos acceptance criterion requires to be ZERO: every
    request either completes or carries an explicit shed/deadline/error
    reason."""
    import statistics
    import time

    from ollamamq_tpu.engine.engine import drop_expired
    from ollamamq_tpu.engine.request import FinishReason, Request
    from ollamamq_tpu.ops.sampling import SamplingParams
    from ollamamq_tpu.telemetry import schema as tm
    from ollamamq_tpu.testing.faults import FaultPlan

    n_total = args.overload
    qcap = args.overload_queue_cap or max(2, 2 * args.slots)
    max_ctx = rt.ecfg.max_pages_per_seq * rt.ecfg.page_size
    prompt_len = min(args.prompt_len, 64)
    max_new = 16
    hi = min(rt.cfg.vocab_size, 30000)

    def drain():
        for s, r in enumerate(rt.slot_req):
            if r is not None:
                rt._finish_slot(s, FinishReason.CANCELLED, core)

    drain()
    # The prefill path IS the ragged mixed dispatch.
    prefill_site = "ragged"
    plan = FaultPlan([
        # KV pressure: every 5th decode-time page growth "fails",
        # driving the preempt-with-recompute path repeatedly.
        {"site": "extend", "kind": "alloc_fail", "every": 5},
        # One transient prefill fault: its batch must retry and survive.
        {"site": prefill_site, "kind": "exception", "at": [4]},
    ], seed=7)
    rt.fault_plan = plan
    # Flight recorder on: the chaos run becomes a checked artifact —
    # batch occupancy / padding waste read off the journal, and the
    # invariant checker must stay clean under injected pressure.
    from ollamamq_tpu.telemetry.journal import (Journal, batch_stats,
                                                check_invariants)
    journal = Journal(capacity=65536)
    rt.journal = journal

    recompute = {"tokens": 0}
    preempt0, retries0 = rt.preempt_count, rt.retry_count

    def requeue(req):
        # The engine's on_preempt hook, bench-local: front of the queue,
        # deadline honored, recompute overhead tallied.
        if req.expired():
            drop_expired(req, core, rt.name)
            return False
        recompute["tokens"] += len(req.prompt_tokens)
        rt.pending_prefill.appendleft(req)
        return True

    rt.on_preempt = requeue

    def shed_count():
        return sum(c.value for _, c in tm.SHED_TOTAL.series())

    def deadline_count():
        return sum(c.value for _, c in tm.DEADLINE_DROPS_TOTAL.series())

    shed0, dl0 = shed_count(), deadline_count()
    reqs, shed_at_admission, issued = [], 0, 0
    peak_active = 0
    t_start = time.monotonic()
    guard = 0
    while True:
        # Arrivals: a burst of 4 per engine tick — strictly faster than
        # the batch drains, so the bounded queue must shed.
        burst = 0
        while issued < n_total and burst < 4:
            burst += 1
            if len(rt.pending_prefill) + len(rt.chunking) >= qcap:
                # Bounded admission (the server's 503/429 path): count
                # the shed, never construct engine-side state for it.
                tm.SHED_TOTAL.labels(reason="queue_full").inc()
                shed_at_admission += 1
                issued += 1
                continue
            prompt = rng.integers(3, hi, size=prompt_len).tolist()
            sp = SamplingParams(max_tokens=max_new)
            if issued % 5 == 4:
                # Every 5th request carries a tight deadline; under the
                # backlog some expire in queue and must drop BEFORE
                # prefill, with the explicit deadline reason.
                sp = SamplingParams(max_tokens=max_new, deadline_ms=30.0)
            req = Request(40000 + issued, f"ovl{issued % 8}", rt.name,
                          prompt, sp)
            req._inc_decode = rt.tokenizer.make_incremental_decoder()
            reqs.append(req)
            rt.pending_prefill.append(req)
            issued += 1
        # One engine tick: admission + chunk/mixed dispatch + decode.
        progressed = False
        try:
            progressed = _pump(rt, core, touch, "overload")
            if any(r is not None for r in rt.slot_req):
                progressed = (rt.step_decode(core, k_steps=2) > 0) \
                    or progressed
        except Exception as e:
            # The acceptance criterion is ZERO engine crashes: any
            # escape from the contained paths fails the scenario.
            raise RuntimeError(f"engine step escaped containment: "
                               f"{type(e).__name__}: {e}")
        touch("overload")
        peak_active = max(peak_active,
                          sum(1 for r in rt.slot_req if r is not None)
                          + len(rt.chunking))
        unresolved = [r for r in reqs if not r.stats.finished_at]
        if issued >= n_total and not unresolved:
            break
        guard += 1
        if guard > 2000 * n_total:
            raise RuntimeError(
                f"overload scenario wedged: {len(unresolved)} unresolved")
        if not progressed:
            if not unresolved:
                break
            time.sleep(0.001)  # head-of-queue backoff: don't spin hot
    elapsed_s = time.monotonic() - t_start

    outcomes: dict = {}
    silent_truncations = 0
    ttfts = []
    for r in reqs:
        item = None
        for it in r.stream.drain():
            if it.kind in ("done", "error"):
                item = it
        reason = (item.finish_reason.value
                  if item is not None and item.finish_reason else "none")
        outcomes[reason] = outcomes.get(reason, 0) + 1
        if r.stats.first_token_at:
            ttfts.append(r.stats.ttft_ms)
        if (item is not None and item.finish_reason == FinishReason.LENGTH
                and len(r.generated_ids) < r.sampling.max_tokens
                and len(r.prompt_tokens) + len(r.generated_ids) + 1 < max_ctx):
            silent_truncations += 1  # MUST stay 0: the bug this PR kills

    ttfts.sort()
    served = len(ttfts)
    rt.journal = None  # detach before later scenarios reuse this runtime
    jrecs = journal.tail(None)
    # Density readout: how many of THIS workload's requests the pool
    # could hold concurrently at the configured HBM (pages per request =
    # prompt + generation headroom), next to the observed peak — the
    # quantized-vs-bf16 A/B line reads straight off these when two
    # rounds differ only in --kv-dtype.
    pages_per_req = rt.alloc.pages_needed(prompt_len + max_new)
    return {
        "requests": n_total,
        "queue_cap": qcap,
        "kv_dtype": rt.kv_dtype,
        "weights_dtype": rt.weights_dtype,
        "peak_active": peak_active,
        "concurrent_capacity_at_hbm": (rt.alloc.num_pages - 1)
        // max(1, pages_per_req),
        "journal": batch_stats(jrecs),
        "invariant_violations": len(check_invariants(jrecs)),
        "elapsed_s": round(elapsed_s, 3),
        "shed": int(shed_count() - shed0),
        "shed_at_admission": shed_at_admission,
        "shed_rate": round((shed_count() - shed0) / max(1, n_total), 4),
        "deadline_drops": int(deadline_count() - dl0),
        "preemptions": rt.preempt_count - preempt0,
        "retries": rt.retry_count - retries0,
        "recompute_tokens": recompute["tokens"],
        "injected_faults": plan.injected,
        "outcomes": outcomes,
        "served": served,
        "ttft_p50_ms": round(ttfts[served // 2], 1) if served else None,
        "ttft_p99_ms": (round(ttfts[min(served - 1,
                                        int(0.99 * served))], 1)
                        if served else None),
        "silent_truncations": silent_truncations,
    }


def _density_scenario(rt, model_cfg, args, rng, touch):
    """Serving-density A/B at EQUAL HBM: size a bf16-KV pool to hold
    only ~half the offered concurrency, compute its byte budget, size an
    int8-KV pool to the SAME budget (more pages per byte), and drive the
    identical arrival trace through both. The int8 leg must hold ~2x the
    concurrent requests (2*hd/(hd+4) exactly — fp32 scale rows are the
    overhead) and therefore preempt/shed less at the same arrival rate.
    The int8-vs-bf16 weight-quality guardrail (teacher-forced greedy
    token match + max logit error) and the journal invariant checker run
    in-band; `gate` summarizes pass/fail for the density regression."""
    import time

    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.core import MQCore
    from ollamamq_tpu.engine import kv_cache as kvc
    from ollamamq_tpu.engine.engine import ModelRuntime, drop_expired
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.models import weights as weights_mod
    from ollamamq_tpu.ops.sampling import SamplingParams
    from ollamamq_tpu.telemetry.journal import Journal, check_invariants

    n_total = args.density
    slots = min(args.slots, 4)
    prompt_len = min(args.prompt_len, 32)
    max_new = 8
    ps = rt.ecfg.page_size
    pages_per_req = -(-(prompt_len + max_new) // ps) + 1
    # bf16 pool: room for ~half the decode batch -> the trace MUST hit
    # the ceiling, so preemptions register on the scoreboard.
    pages_bf16 = max(2, (slots * pages_per_req) // 2) + 1
    budget = pages_bf16 * kvc.kv_page_bytes(model_cfg, ps,
                                            kv_dtype="bfloat16")
    pages_int8 = budget // kvc.kv_page_bytes(model_cfg, ps,
                                             kv_dtype="int8")
    hd = model_cfg.head_dim
    expected_ratio = 2 * hd / (hd + 4)

    def run_leg(kv_dtype, num_pages):
        ecfg = EngineConfig(
            model=args.model, max_slots=slots, num_pages=num_pages + 1,
            page_size=ps, max_pages_per_seq=pages_per_req + 2,
            prefill_buckets=(max(32, prompt_len),), max_new_tokens=max_new,
            decode_steps_per_iter=2,
            max_batch_tokens=max(64, slots * 16), token_granule=16,
            weights_dtype=args.weights_dtype, kv_dtype=kv_dtype,
            preempt=True, preempt_max=2, seed=rt.ecfg.seed,
        )
        leg = ModelRuntime(args.model, model_cfg, ecfg,
                           preloaded_params=rt.params)
        leg.tokenizer.eos_id = -1  # full-length streams: equal pressure
        journal = Journal(capacity=65536)
        leg.journal = journal
        core = MQCore(None)
        recompute = {"tokens": 0}

        def requeue(req):
            if req.expired():
                drop_expired(req, core, leg.name)
                return False
            recompute["tokens"] += len(req.prompt_tokens)
            leg.pending_prefill.appendleft(req)
            return True

        leg.on_preempt = requeue
        trace = __import__("numpy").random.default_rng(1234)
        hi = min(model_cfg.vocab_size, 30000)
        reqs, issued, peak_active, guard = [], 0, 0, 0
        t0 = time.monotonic()
        while True:
            while issued < n_total and len(leg.pending_prefill) < 4:
                prompt = trace.integers(3, hi, size=prompt_len).tolist()
                req = Request(60000 + issued, f"dn{issued % 4}", leg.name,
                              prompt, SamplingParams(max_tokens=max_new))
                req._inc_decode = leg.tokenizer.make_incremental_decoder()
                reqs.append(req)
                leg.pending_prefill.append(req)
                issued += 1
            progressed = leg.step_ragged(core)
            if any(r is not None for r in leg.slot_req):
                progressed = (leg.step_decode(core, k_steps=2) > 0) \
                    or progressed
            touch("density")
            peak_active = max(peak_active,
                              sum(1 for r in leg.slot_req if r is not None)
                              + len(leg.chunking))
            unresolved = [r for r in reqs if not r.stats.finished_at]
            if issued >= n_total and not unresolved:
                break
            guard += 1
            if guard > 3000 * n_total:
                raise RuntimeError(
                    f"density leg {kv_dtype} wedged: "
                    f"{len(unresolved)} unresolved")
            if not progressed and unresolved:
                time.sleep(0.001)
        outcomes = {}
        for r in reqs:
            item = None
            for it in r.stream.drain():
                if it.kind in ("done", "error"):
                    item = it
            reason = (item.finish_reason.value
                      if item is not None and item.finish_reason else "none")
            outcomes[reason] = outcomes.get(reason, 0) + 1
        jrecs = journal.tail(None)
        leg.journal = None
        return {
            "kv_dtype": kv_dtype,
            "pages": num_pages,
            "kv_pool_bytes": leg.kv_bytes,
            "concurrent_capacity_at_hbm": num_pages // pages_per_req,
            "peak_active": peak_active,
            "preemptions": leg.preempt_count,
            "kv_exhausted": outcomes.get("kv_exhausted", 0),
            "recompute_tokens": recompute["tokens"],
            "outcomes": outcomes,
            "elapsed_s": round(time.monotonic() - t0, 3),
            "invariant_violations": len(check_invariants(jrecs)),
        }

    bf16 = run_leg("bfloat16", pages_bf16)
    int8 = run_leg("int8", pages_int8)

    # Weight-quality guardrail: int8 tree vs its bf16 source. Reuses the
    # runtime-under-test's params for whichever side it already is.
    guardrail = None
    try:
        if args.weights_dtype == "int8":
            base = weights_mod.load_params(model_cfg, None,
                                           seed=rt.ecfg.seed)
            qp = rt.params
        else:
            base = rt.params
            qp = weights_mod.quantize_params_int8(rt.params, model_cfg)
        guardrail = weights_mod.quant_guardrail(
            model_cfg, base_params=base, q_params=qp,
            seed=rt.ecfg.seed, prompt_len=8, steps=4)
        touch("density")
    except Exception as e:
        guardrail = {"error": f"{type(e).__name__}: {e}"}

    ratio = int8["concurrent_capacity_at_hbm"] / max(
        1, bf16["concurrent_capacity_at_hbm"])
    reasons = []
    if ratio < 0.85 * expected_ratio:
        reasons.append(f"capacity ratio {ratio:.2f} under "
                       f"{0.85 * expected_ratio:.2f}")
    if int8["preemptions"] > bf16["preemptions"]:
        reasons.append("int8 leg preempted MORE than bf16 at equal HBM")
    if int8["invariant_violations"] or bf16["invariant_violations"]:
        reasons.append("journal invariant violations")
    if (isinstance(guardrail, dict)
            and guardrail.get("token_match_rate", 1.0) < 0.8):
        reasons.append("quality guardrail under 0.8 token match")
    return {
        "requests": n_total,
        "hbm_budget_bytes": budget,
        "page_bytes_bf16": kvc.kv_page_bytes(model_cfg, ps,
                                             kv_dtype="bfloat16"),
        "page_bytes_int8": kvc.kv_page_bytes(model_cfg, ps,
                                             kv_dtype="int8"),
        "capacity_ratio": round(ratio, 3),
        "expected_ratio": round(expected_ratio, 3),
        "bf16": bf16,
        "int8": int8,
        "guardrail": guardrail,
        "gate": "pass" if not reasons else "fail",
        "gate_reasons": reasons,
    }


def _speculative_scenario(rt, core, args, rng, touch):
    """Speculative-decoding acceptance: the same prompt mix driven
    spec-off then spec-on at the same seed, on the serving-path tick
    shape (one mixed/decode dispatch per tick — the regime the ISSUE
    targets, where decode tok/s is bounded by dispatch rate).

    Two generation regimes, because draft accept rate is a property of
    what the model GENERATES, not of the engine: random weights produce
    chaotic streams no lookup can predict, so the "repetitive" leg
    rebuilds the same architecture as a deterministic copy map (residual
    output projections zeroed => next token is a pure function of the
    last => generation enters a cycle, exactly the regime real LMs hit
    on repetitive text) and measures spec-on vs spec-off tok/s there;
    the "non_repetitive" leg keeps the real random weights and reports
    the accept rate and whether the per-user auto-throttle engaged.
    Both legs assert byte-identical streams — `identical` and
    `silent_truncations` land in the record."""
    import time

    import jax.numpy as jnp

    from ollamamq_tpu.engine.request import FinishReason, Request
    from ollamamq_tpu.ops.sampling import SamplingParams

    if not getattr(rt, "ragged", False):
        return {"skipped": "speculation needs the ragged path (pp=1)"}
    n_req = min(args.speculative, args.slots)
    # Floor high enough that the spec-on leg sees several STEADY verify
    # dispatches after its compile ticks are excluded — a 2-tick sample
    # is noise, not a measurement.
    max_new = max(24, min(48, args.steps))
    prompt_len = min(args.prompt_len, 48)
    hi = min(rt.cfg.vocab_size, 30000)

    def drain():
        for s, r in enumerate(rt.slot_req):
            if r is not None:
                rt._finish_slot(s, FinishReason.CANCELLED, core)

    def make_prompts(repetitive):
        out = []
        for i in range(n_req):
            if repetitive and i % 2 == 0:
                pat = rng.integers(3, hi, size=6).tolist()
                out.append((pat * ((prompt_len // 6) + 1))[:prompt_len])
            else:
                out.append(rng.integers(3, hi, size=prompt_len).tolist())
        return out

    def copy_map_cycle(start, budget=128):
        """The copy model's next-token map is context-free (next =
        argmax(logits(embed[last]))), so its cycle is computable
        off-engine: iterate the map until a token repeats. Prompts tiled
        from the cycle make generation predictable from the FIRST decode
        tick — the repetitive regime at full strength even on a short
        smoke run. One probe step is a full-vocab logit row (heavy on a
        big CPU-smoke model), so the walk is budgeted and probed ONCE;
        an unclosed walk degrades to its tail (lower accept, reported
        honestly)."""
        from ollamamq_tpu.models import llama as llm

        seen, seq, t = {}, [], int(start)
        for _ in range(budget):
            if t in seen:
                return seq[seen[t]:]
            seen[t] = len(seq)
            seq.append(t)
            x = rt.params["embed"][t][None, None, :]
            t = int(jnp.argmax(llm._logits(rt.params, rt.cfg, x)[0, 0]))
        return seq[-16:]

    def cycle_prompts():
        # One probe, rotated per request: any rotation of a cycle is
        # still map-consecutive, so every prompt stays predictable.
        cyc = copy_map_cycle(int(rng.integers(3, hi)))
        out = []
        for i in range(n_req):
            rot = cyc[i % len(cyc):] + cyc[:i % len(cyc)]
            out.append((rot * (prompt_len // len(rot) + 2))[:prompt_len])
        return out

    def run_leg(prompts, spec_on, idx0, new_tokens=None):
        """Drive one A/B leg on the serving-path tick shape. Throughput
        is computed over STEADY-STATE ticks only: a tick that grew the
        jit cache paid a compile, and counting it would bill one leg
        for one-time cost the other never sees — this is also what
        makes the scenario affordable on slow backends (no separate
        full-length warmup leg per mode)."""
        drain()
        rt.spec = spec_on
        rt._spec_user.clear()
        rt._spec_throttled.clear()
        p0, a0, r0 = rt.spec_proposed, rt.spec_accepted, rt.spec_rollbacks
        reqs = []
        for i, p in enumerate(prompts):
            req = Request(50000 + idx0 + i, f"spec{i}", rt.name, list(p),
                          SamplingParams(max_tokens=new_tokens or max_new))
            req._inc_decode = rt.tokenizer.make_incremental_decoder()
            rt.pending_prefill.append(req)
            reqs.append(req)
        ticks = 0
        steady_s, steady_tokens, gen_prev = 0.0, 0, 0
        while not all(r.stats.finished_at for r in reqs):
            jits0 = len(rt._prefill_jits) + len(rt._decode_jits)
            t0 = time.monotonic()
            progressed = rt.step_ragged(core)
            if not progressed and any(r is not None for r in rt.slot_req):
                progressed = rt.step_decode(core, k_steps=1) > 0
            dt = time.monotonic() - t0
            touch("speculative")
            ticks += 1
            gen_now = sum(len(r.generated_ids) for r in reqs)
            if len(rt._prefill_jits) + len(rt._decode_jits) == jits0:
                steady_s += dt
                steady_tokens += gen_now - gen_prev
            gen_prev = gen_now
            if ticks > 4000 * max(1, n_req):
                raise RuntimeError("speculative leg wedged")
        return {
            "streams": [list(r.generated_ids) for r in reqs],
            "tok_s": (round(steady_tokens / steady_s, 1)
                      if steady_s > 0 else 0.0),
            "ticks": ticks,
            "proposed": rt.spec_proposed - p0,
            "accepted": rt.spec_accepted - a0,
            "rollbacks": rt.spec_rollbacks - r0,
            "throttled_users": len(rt._spec_throttled),
        }

    spec0, k0, min0 = rt.spec, rt.ecfg.spec_k, rt.ecfg.spec_min_accept
    eos0 = rt.tokenizer.eos_id
    layers = rt.params["layers"]
    orig_wo, orig_wd = layers["wo"], layers["w_down"]
    rt.ecfg.spec_k = args.spec_k
    rt.tokenizer.eos_id = -1  # full-length streams: compare whole outputs
    silent_truncations = 0
    try:
        # Repetitive regime: deterministic copy map (see docstring),
        # prompts tiled from the map's own cycle so drafts verify from
        # the first decode tick. One untimed warmup leg per mode first:
        # each leg's jit variants must be compiled before the A/B is
        # timed, or the first leg pays compile time the second doesn't.
        layers["wo"] = jnp.zeros_like(orig_wo)
        layers["w_down"] = jnp.zeros_like(orig_wd)
        rt.ecfg.spec_min_accept = 0.0  # measuring, not throttling
        rep_prompts = cycle_prompts()
        rep_off = run_leg(rep_prompts, spec_on=False, idx0=0)
        rep_on = run_leg(rep_prompts, spec_on=True, idx0=1000)
        rep_identical = rep_off["streams"] == rep_on["streams"]
        for leg in (rep_off, rep_on):
            silent_truncations += sum(
                1 for s in leg.pop("streams") if len(s) < max_new)
        # Chaotic regime: real weights, default throttle — what accept
        # rate does prompt-lookup actually get, and does the throttle
        # stop paying for hopeless users? (Accept-rate readout only;
        # spec-on/off byte-identity across regimes is pinned by tier-1
        # tests/test_spec_decoding.py, so no off-baseline leg is spent
        # here — the CPU-smoke budget is tight on a 1B model.)
        layers["wo"], layers["w_down"] = orig_wo, orig_wd
        rt.ecfg.spec_min_accept = 0.1
        chaos_new = max(8, max_new // 2)  # readout leg: keep it cheap
        chaos_on = run_leg(make_prompts(repetitive=False), spec_on=True,
                           idx0=3000, new_tokens=chaos_new)
        silent_truncations += sum(
            1 for s in chaos_on.pop("streams") if len(s) < chaos_new)
    finally:
        layers["wo"], layers["w_down"] = orig_wo, orig_wd
        rt.spec = spec0
        rt.ecfg.spec_k = k0
        rt.ecfg.spec_min_accept = min0
        rt.tokenizer.eos_id = eos0
        rt._spec_user.clear()
        rt._spec_throttled.clear()
        drain()
    prop = max(1, rep_on["proposed"])
    cprop = max(1, chaos_on["proposed"])
    return {
        "requests": n_req,
        "max_new": max_new,
        "spec_k": args.spec_k,
        "repetitive": {
            "tok_s_spec_off": rep_off["tok_s"],
            "tok_s_spec_on": rep_on["tok_s"],
            "speedup": round(rep_on["tok_s"] / max(0.001,
                                                   rep_off["tok_s"]), 2),
            "ticks_off": rep_off["ticks"],
            "ticks_on": rep_on["ticks"],
            "proposed": rep_on["proposed"],
            "accepted": rep_on["accepted"],
            "accept_rate": round(rep_on["accepted"] / prop, 4),
            "rollbacks": rep_on["rollbacks"],
            "identical": rep_identical,
        },
        "non_repetitive": {
            "proposed": chaos_on["proposed"],
            "accepted": chaos_on["accepted"],
            "accept_rate": round(chaos_on["accepted"] / cprop, 4),
            "rollbacks": chaos_on["rollbacks"],
            "throttled_users": chaos_on["throttled_users"],
        },
        "silent_truncations": silent_truncations,
    }


def _slo_burst_scenario(rt, core, args, rng, touch):
    """Bursty arrivals against a TTFT SLO on a drained runtime: each of
    B bursts drops `--slo-burst-size` requests into the prefill queue at
    once, then steps the engine until every request has its first token.
    Requests carry real traces, so the report includes the latency
    attribution breakdown (mean ms per phase — under a burst, queueing
    behind batch-mates dominates) plus the burn rate against
    --slo-ttft-ms at a 0.99 target. One warmup burst (compiles the
    batched-prefill jit) is excluded from the recorded stats."""
    import statistics
    import time

    from ollamamq_tpu.engine.request import FinishReason, Request
    from ollamamq_tpu.ops.sampling import SamplingParams
    from ollamamq_tpu.telemetry import attribution
    from ollamamq_tpu.telemetry.slo import AlertManager, SLOEngine
    from ollamamq_tpu.telemetry.tracing import Tracer

    from ollamamq_tpu.telemetry.journal import Journal, batch_stats

    target = 0.99
    tracer = Tracer(capacity=args.slo_burst * args.slo_burst_size + 8)
    slo = SLOEngine(AlertManager(), ttft_ms=args.slo_ttft_ms, target=target)
    hi = min(rt.cfg.vocab_size, 30000)
    # Journal the bursts: batch occupancy and padding waste per burst
    # land in the BENCH record (how much of each padded prefill forward
    # was real work).
    journal = Journal(capacity=16384)

    def drain():
        for s, r in enumerate(rt.slot_req):
            if r is not None:
                rt._finish_slot(s, FinishReason.CANCELLED, core)

    def run_burst(idx0, record):
        reqs = []
        for i in range(args.slo_burst_size):
            prompt = rng.integers(3, hi, size=args.prompt_len).tolist()
            req = Request(30000 + idx0 + i, f"burst{i}", rt.name, prompt,
                          SamplingParams(max_tokens=10**9))
            req._inc_decode = rt.tokenizer.make_incremental_decoder()
            req.trace = tracer.begin(req.req_id, req.user, rt.name)
            reqs.append(req)
        # The burst lands at once; admission order is queue order.
        for req in reqs:
            req.trace_event("admit")
            rt.pending_prefill.append(req)
        while any(not r.stats.first_token_at for r in reqs):
            progressed = _pump(rt, core, touch, "slo_burst")
            if not progressed and not rt.chunking:
                raise RuntimeError("slo_burst request never admitted "
                                   "(slots/pages too small for the burst?)")
        if record:
            for req in reqs:
                slo.record("ttft", req.stats.ttft_ms)
        drain()  # finishes the traces (outcome: cancelled)
        return [r.stats.ttft_ms for r in reqs]

    drain()
    run_burst(0, record=False)  # warmup: compiles the B=MAX batch jit
    rt.journal = journal  # after warmup: stats cover recorded bursts only
    ttfts = []
    t0 = time.monotonic()
    for b in range(args.slo_burst):
        ttfts.extend(run_burst((b + 1) * 1000, record=True))
    elapsed_s = time.monotonic() - t0
    rt.journal = None

    # Attribution breakdown: mean per-phase ms over the recorded bursts'
    # finished traces (warmup requests excluded by req_id).
    phase_sums, n_traces = {}, 0
    for tr in tracer.traces():
        if not tr.finished or tr.req_id < 31000:
            continue
        n_traces += 1
        for phase, ms in attribution.phase_totals(list(tr.events)).items():
            phase_sums[phase] = phase_sums.get(phase, 0.0) + ms
    violations = sum(1 for t in ttfts if t > args.slo_ttft_ms)
    obj = slo.objectives["ttft"]
    return {
        "bursts": args.slo_burst,
        "burst_size": args.slo_burst_size,
        "slo_ttft_ms": args.slo_ttft_ms,
        "target": target,
        "elapsed_s": round(elapsed_s, 3),
        "ttft_p50_ms": round(statistics.median(ttfts), 1),
        "ttft_max_ms": round(max(ttfts), 1),
        "violations": violations,
        "violation_ratio": round(violations / max(1, len(ttfts)), 4),
        # Burn over a window covering the whole run: ratio_bad / budget.
        "burn_rate": round(obj.burn_rate(max(60.0, elapsed_s + 5)), 2),
        "journal": batch_stats(journal.tail(None)),
        "attribution_ms": {
            p: round(phase_sums[p] / max(1, n_traces), 2)
            for p in attribution.PHASES if p in phase_sums
        },
    }


def _shared_prefix_scenario(rt, core, args, rng, touch):
    """TTFT for N same-prefix users, cache off vs on, on a drained
    runtime. One warmup (compile) request per leg is excluded from the
    means; the on-leg warmup also seeds the tree, so every timed on-leg
    request is a hit."""
    import statistics
    import time

    import numpy as np

    from ollamamq_tpu.engine.prefix_cache import PrefixCache
    from ollamamq_tpu.engine.request import FinishReason, Request
    from ollamamq_tpu.ops.sampling import SamplingParams

    ps = rt.ecfg.page_size
    prefix_len = max(ps, (args.shared_prefix_len // ps) * ps)
    tail_len = max(1, args.shared_prefix_tail)
    n = prefix_len + tail_len
    if rt.alloc.pages_needed(n + 1) > rt.ecfg.max_pages_per_seq:
        return {"skipped": f"prompt of {n} tokens exceeds the page budget "
                           f"({rt.ecfg.max_pages_per_seq} pages/seq)"}
    hi = min(rt.cfg.vocab_size, 30000)
    prefix = rng.integers(3, hi, size=prefix_len).tolist()

    def drain():
        for s, r in enumerate(rt.slot_req):
            if r is not None:
                rt._finish_slot(s, FinishReason.CANCELLED, core)

    def run_one(i):
        prompt = prefix + rng.integers(3, hi, size=tail_len).tolist()
        req = Request(20000 + i, f"spuser{i}", rt.name, prompt,
                      SamplingParams(max_tokens=10**9))
        req._inc_decode = rt.tokenizer.make_incremental_decoder()
        rt.pending_prefill.append(req)
        t0 = time.monotonic()
        while not req.stats.first_token_at:
            progressed = _pump(rt, core, touch, "shared_prefix")
            if not progressed and not rt.chunking:
                raise RuntimeError("shared_prefix request never admitted "
                                   "(page budget?)")
        ms = (time.monotonic() - t0) * 1e3
        drain()  # finish-on-install: the on-leg insert populates the tree
        return ms

    drain()
    legs = {}
    for leg, idx0 in (("off", 0), ("on", 1000)):
        if leg == "on":
            rt.prefix_cache = PrefixCache(ps, rt.alloc, model=rt.name)
        run_one(idx0)  # warmup: compiles (off) / seeds the tree (on)
        legs[leg] = statistics.mean(
            run_one(idx0 + 1 + i) for i in range(args.shared_prefix))
    stats = rt.prefix_cache.stats()
    return {
        "users": args.shared_prefix,
        "prefix_tokens": prefix_len,
        "tail_tokens": tail_len,
        "hit_ratio": stats["hit_ratio"],
        "tokens_saved": stats["tokens_saved"],
        "ttft_cache_off_ms": round(legs["off"], 1),
        "ttft_cache_on_ms": round(legs["on"], 1),
        "ttft_delta_ms": round(legs["off"] - legs["on"], 1),
    }


if __name__ == "__main__":
    sys.exit(main())
