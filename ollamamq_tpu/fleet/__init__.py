from ollamamq_tpu.fleet.members import HttpMember, LocalMember
from ollamamq_tpu.fleet.router import FleetRouter

__all__ = ["FleetRouter", "LocalMember", "HttpMember"]
