"""Latency attribution + SLO burn-rate alerting + stall watchdog +
diagnostics bundle (the flight-recorder stack on telemetry/).

Pins the acceptance contract: /debug/requests/{id} returns a phase
timeline whose phases sum to wall-clock e2e within 5% (streamed AND
cancelled requests); an injected engine-step stall on the fake backend
flips /health to degraded, fires a watchdog alert visible in /metrics,
and surfaces through the TUI's alert feed; SLO violations burn the
budget and fire/resolve multi-window alerts.
"""

import asyncio
import tempfile
import threading
import time

from aiohttp.test_utils import TestClient, TestServer

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.engine.health import HealthMonitor
from ollamamq_tpu.server.app import Server, _redact
from ollamamq_tpu.telemetry import attribution
from ollamamq_tpu.telemetry.slo import AlertManager, Objective, SLOEngine
from ollamamq_tpu.telemetry.tracing import Tracer


# ------------------------------------------------------------ attribution
def test_phase_totals_sum_to_e2e_exactly():
    tracer = Tracer(capacity=4)
    tr = tracer.begin(1, "u", "m")
    time.sleep(0.005)
    tr.event("admit")
    tr.event("place")
    time.sleep(0.005)
    tr.event("prefill")
    time.sleep(0.01)
    tr.event("first_token")
    time.sleep(0.005)
    tr.finish("stop")
    tl = attribution.timeline(tr)
    assert tl["state"] == "stop"
    total = sum(tl["phases_ms"].values())
    # Contiguous spans: the tolerance only absorbs rounding.
    assert abs(total - tl["e2e_ms"]) < 0.05, tl
    assert set(tl["phases_ms"]) <= set(attribution.PHASES)
    assert tl["phases_ms"]["prefill"] >= 9.0
    # Events are relative to enqueue and monotonic.
    ts = [e["t_ms"] for e in tl["events"]]
    assert ts == sorted(ts) and ts[0] == 0.0


def test_unknown_event_lands_in_other_and_inflight_has_current_phase():
    tracer = Tracer(capacity=4)
    tr = tracer.begin(2, "u", "m")
    tr.event("admit")
    tr.event("totally_new_event")
    time.sleep(0.005)
    tl = attribution.timeline(tr)
    assert tl["state"] == "inflight"
    assert tl["current_phase"] == "other"
    assert tl["phase_age_ms"] >= 4.0
    assert "other" in tl["phases_ms"]
    # In-flight too: phases (up to now) sum to e2e-so-far.
    assert abs(sum(tl["phases_ms"].values()) - tl["e2e_ms"]) < 0.05
    tr.finish("cancelled")


def test_every_engine_event_is_mapped():
    """The attribution table knows every event name the engine emits —
    grep the engine sources for trace_event calls and check coverage."""
    import os
    import re

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    names = set()
    for fname in ("engine/engine.py", "engine/fake.py", "engine/spmd.py"):
        with open(os.path.join(repo, "ollamamq_tpu", fname)) as f:
            names |= set(re.findall(r'trace_event\(\s*"([a-z_]+)"', f.read()))
    # Tracer-internal events:
    names |= {"enqueue"}
    unmapped = {n for n in names if attribution.phase_of(n) == "other"}
    assert not unmapped, f"events not in attribution.EVENT_PHASE: {unmapped}"


def test_request_phase_histogram_observed_on_finish():
    from ollamamq_tpu.telemetry import schema as tm

    child = tm.REQUEST_PHASE_MS.labels(model="attr-test", phase="decode")
    before = child.count
    tracer = Tracer(capacity=4)
    tr = tracer.begin(3, "u", "attr-test")
    tr.event("first_token")
    time.sleep(0.002)
    tr.finish("stop")
    assert child.count == before + 1


# ------------------------------------------------------------------- slo
def test_burn_rate_math():
    obj = Objective("ttft", threshold_ms=100.0, target=0.99)
    now = 1000.0
    for _ in range(90):
        obj.record(50.0, now=now)   # good
    for _ in range(10):
        obj.record(500.0, now=now)  # bad
    # 10% bad over a 1% budget = burn 10x.
    assert abs(obj.burn_rate(60.0, now=now + 1) - 10.0) < 1e-6
    # Outside the window: no data, burn 0.
    assert obj.burn_rate(60.0, now=now + 3000) == 0.0


def test_slo_multiwindow_fire_and_resolve():
    am = AlertManager()
    slo = SLOEngine(am, ttft_ms=10.0, target=0.9,
                    windows=(("fast", 10.0, 3.0, 2.0, "page"),))
    now = 5000.0
    for _ in range(10):
        slo.record("ttft", 100.0)  # all bad -> burn 10x budget
    slo.evaluate(now=time.monotonic())
    names = [a.name for a in am.active()]
    assert "slo_ttft_burn_fast" in names
    assert am.degraded()
    # Recovery: the short window goes clean -> resolve even though the
    # long window still remembers the burn.
    obj = slo.objectives["ttft"]
    obj.counts.record(good=1000, now=time.monotonic())
    time.sleep(0)
    slo.evaluate(now=time.monotonic())
    assert not am.degraded(), [a.to_dict() for a in am.active()]
    # The resolved alert moved to history.
    assert any(h["name"] == "slo_ttft_burn_fast" for h in am.history())


def test_alert_manager_transitions():
    am = AlertManager()
    assert am.fire("x", "page", "first") is True
    assert am.fire("x", "page", "updated") is False  # refresh, no re-fire
    assert am.active()[0].message == "updated"
    assert am.resolve("x") is True
    assert am.resolve("x") is False
    assert not am.degraded()


# ----------------------------------------------------------------- redact
def test_bundle_redaction():
    out = _redact({
        "hf_token": "secret123",
        "nested": {"api_key": "k", "ok_value": 5},
        "list": [{"password": "p"}],
        "checkpoint": "/data/model.safetensors",
    })
    assert out["hf_token"] == "[REDACTED]"
    assert out["nested"]["api_key"] == "[REDACTED]"
    assert out["nested"]["ok_value"] == 5
    assert out["list"][0]["password"] == "[REDACTED]"
    assert out["checkpoint"] == "/data/model.safetensors"


# ------------------------------------------------------------------- e2e
def _serve(fn, *, token_latency_s=0.0, ecfg=None):
    async def main():
        with tempfile.TemporaryDirectory() as tmp:
            eng = FakeEngine(
                ecfg or EngineConfig(model="test-tiny", max_slots=8),
                models={"test-tiny": None},
                blocklist_path=f"{tmp}/blocked_items.json",
                token_latency_s=token_latency_s,
            )
            eng.start()
            server = Server(eng, timeout_s=30)
            cl = TestClient(TestServer(server.build_app()))
            cl.engine = eng
            await cl.start_server()
            try:
                await fn(cl)
            finally:
                await cl.close()
                eng.stop()

    asyncio.run(main())


async def _drain_http(resp):
    async for _ in resp.content:
        pass


def test_debug_requests_timeline_sums_streamed():
    """Acceptance: a streamed request's phases sum to wall-clock e2e
    within 5% on /debug/requests/{id}."""
    async def run(cl):
        r = await cl.post("/api/generate", json={
            "model": "test-tiny", "prompt": "hello world", "stream": True,
            "options": {"num_predict": 8},
        }, headers={"X-User-ID": "alice"})
        assert r.status == 200
        await _drain_http(r)
        r = await cl.get("/debug/requests")
        assert r.status == 200
        body = await r.json()
        assert body["inflight"] == []
        row = next(rw for rw in body["recent"] if rw["user"] == "alice")
        r = await cl.get(f"/debug/requests/{row['req_id']}")
        assert r.status == 200
        tl = await r.json()
        assert tl["state"] in ("length", "stop")
        total = sum(tl["phases_ms"].values())
        assert abs(total - tl["e2e_ms"]) <= max(0.05 * tl["e2e_ms"], 0.5), tl
        # The lifecycle chain is present and decode got the bulk.
        names = [e["name"] for e in tl["events"]]
        for must in ("enqueue", "admit", "place", "prefill", "first_token"):
            assert must in names, names
        assert "decode" in tl["phases_ms"]

    _serve(run)


def test_debug_requests_timeline_sums_cancelled():
    """Acceptance: a cancelled (client-gone mid-stream) request's
    timeline also closes cleanly and sums within tolerance."""
    async def run(cl):
        resp = await cl.post("/api/generate", json={
            "model": "test-tiny", "prompt": "hello", "stream": True,
            "options": {"num_predict": 10_000},
        }, headers={"X-User-ID": "bob"})
        assert resp.status == 200
        await resp.content.read(16)  # a few chunks, then walk away
        resp.close()
        # The engine notices the disconnect and cancels.
        deadline = time.monotonic() + 20
        tl = None
        while time.monotonic() < deadline:
            r = await cl.get("/debug/requests?recent=10")
            body = await r.json()
            done = [rw for rw in body["recent"] if rw["user"] == "bob"]
            if done and done[0]["state"] == "cancelled":
                r = await cl.get(f"/debug/requests/{done[0]['req_id']}")
                tl = await r.json()
                break
            await asyncio.sleep(0.05)
        assert tl is not None, "cancelled request never reached the ring"
        total = sum(tl["phases_ms"].values())
        assert abs(total - tl["e2e_ms"]) <= max(0.05 * tl["e2e_ms"], 0.5), tl

    _serve(run, token_latency_s=0.02)


def test_debug_requests_unknown_id_404s():
    async def run(cl):
        r = await cl.get("/debug/requests/424242")
        assert r.status == 404
        r = await cl.get("/debug/requests/notanint")
        assert r.status == 400

    _serve(run)


def test_slo_burn_alert_fires_end_to_end():
    """A sub-microsecond TTFT objective makes every request a violation:
    the burn-rate alert fires, /health degrades, and the ollamamq_slo_*
    series land on /metrics."""
    async def run(cl):
        for _ in range(4):
            r = await cl.post("/api/generate", json={
                "model": "test-tiny", "prompt": "x", "stream": False,
                "options": {"num_predict": 4},
            }, headers={"X-User-ID": "alice"})
            assert r.status == 200
        # Health-thread cadence is slow by default; evaluate directly.
        cl.engine.slo.evaluate()
        r = await cl.get("/health")
        body = await r.json()
        assert body["status"] == "degraded", body
        names = [a["name"] for a in body["alerts"]]
        assert any(n.startswith("slo_ttft_burn") for n in names), names
        r = await cl.get("/metrics")
        text = await r.text()
        assert 'ollamamq_slo_violations_total{objective="ttft"}' in text
        assert 'ollamamq_slo_burn_rate{objective="ttft"' in text
        assert 'ollamamq_slo_alerts_firing{alert="slo_ttft_burn' in text
        # The bundle carries the same picture.
        r = await cl.get("/debug/bundle")
        bundle = await r.json()
        assert bundle["slo"]["enabled"] is True
        assert bundle["alerts"]["active"], bundle["alerts"]
        assert bundle["config"]["slo_ttft_ms"] == 1e-6

    _serve(run, ecfg=EngineConfig(model="test-tiny", max_slots=8,
                                  slo_ttft_ms=1e-6, slo_tpot_ms=None))


def test_engine_step_stall_watchdog_fires_and_recovers():
    """Acceptance chaos: wedge the fake backend's step mid-serving. The
    watchdog must flip /health to degraded with an engine_stall alert,
    count it in ollamamq_watchdog_stalls_total, expose it in the TUI
    alert feed — and resolve everything once the engine moves again."""
    async def run(cl):
        eng = cl.engine
        # Fast watchdog for the test (the default is 10 s cadence).
        eng.health.stop()
        eng.health = HealthMonitor(eng, period_s=0.05, stall_s=0.3,
                                   request_stall_s=0.4)
        eng.health.start()
        rt = eng.runtimes["test-tiny"]
        release = threading.Event()
        orig_step = rt.step

        def wedged_step(core):
            release.wait()  # the engine loop thread blocks right here
            return orig_step(core)

        rt.step = wedged_step
        # Traffic that will never progress while wedged.
        req = eng.enqueue_request("alice", "", "test-tiny",
                                  prompt_tokens=[1, 2, 3])
        deadline = time.monotonic() + 20
        body = None
        while time.monotonic() < deadline:
            r = await cl.get("/health")
            body = await r.json()
            if body["status"] == "degraded" and any(
                    a["name"] == "engine_stall" for a in body["alerts"]):
                break
            await asyncio.sleep(0.05)
        assert body and body["status"] == "degraded", body
        names = [a["name"] for a in body["alerts"]]
        assert "engine_stall" in names, names
        # The stuck request shows up too, with the phase it's stuck in.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            r = await cl.get("/health")
            body = await r.json()
            if any(a["name"] == "request_stall" for a in body["alerts"]):
                break
            await asyncio.sleep(0.05)
        assert any(a["name"] == "request_stall" for a in body["alerts"]), body
        r = await cl.get("/metrics")
        text = await r.text()
        assert 'ollamamq_watchdog_stalls_total{kind="engine_step"}' in text
        assert 'ollamamq_slo_alerts_firing{alert="engine_stall"' in text
        # The TUI alert feed (what the C++ panel renders) sees the same.
        from ollamamq_tpu.admin.tui import _engine_stats_brief

        brief = _engine_stats_brief(eng)
        assert any(a["name"] == "engine_stall" for a in brief["alerts"])
        # Recovery: release the wedge; the request completes and every
        # alert resolves.
        release.set()
        items = []
        while not items or items[-1].kind not in ("done", "error"):
            item = req.stream.get(timeout=10)
            assert item is not None, "request never finished after release"
            items.append(item)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            r = await cl.get("/health")
            body = await r.json()
            if body["status"] == "ok":
                break
            await asyncio.sleep(0.05)
        assert body["status"] == "ok", body

    _serve(run)


def test_worker_stale_hook_raises_alert():
    """The SPMD-host staleness seam: an engine whose stale_worker_hosts
    reports a dead peer gets a worker_stale alert on the next watchdog
    pass (the SPMD engine wires this to KV-store heartbeats)."""
    async def run(cl):
        eng = cl.engine
        eng.health.stop()
        eng.stale_worker_hosts = lambda: [3]
        hm = HealthMonitor(eng, period_s=3600)
        hm.check_once()
        names = [a.name for a in eng.alerts.active()]
        assert "worker_stale" in names
        eng.stale_worker_hosts = lambda: []
        hm.check_once()
        assert "worker_stale" not in [a.name for a in eng.alerts.active()]

    _serve(run)
