"""Router HA: warm-standby router with replayed takeover + epoch fencing.

The fleet router is the one process the rest of the fleet cannot route
around: members are interchangeable (eject, migrate, failover — PR 9/13)
but the router that places them is a single point of failure. This
module closes that hole with a PRIMARY/STANDBY pair:

  - the PRIMARY (--ha) exposes its replicated state over
    GET /admin/ha/sync: every WAL record (admit/tok/fin — the durability
    contract) and every decision-journal record, sequence-numbered into
    a bounded ring, plus a shadow-state blob (member roster, tiers,
    in-flight stream table, fleet size). The standby's poll cursor IS
    the ack: lag = head - last_acked;
  - the STANDBY (--standby-of URL) tails that stream into replica files
    in its own --wal-dir: `wal.jsonl` (byte-compatible with the WAL the
    recovery path reads — fsynced per batch, so promotion inherits the
    primary's fsync continuity) and `primary-journal.jsonl` (a
    spill-compatible journal replica the offline audits accept). Cold
    catch-up and ring overrun ship a whole-file WAL snapshot instead of
    records — compaction lines written by begin() bypass the mirror, so
    a record-only catch-up from seq 0 would silently miss them;
  - the standby detects primary death by heartbeat loss (polls failing
    for longer than --takeover-grace-s) and PROMOTES: bump a monotonic
    epoch (persisted in ha_state.json, so a revived standby never
    reuses one), re-register every member under the new epoch, re-admit
    every unfinished replica-WAL stream through the existing recovery
    path (byte-identical greedy replay — never drop), then serve. A
    promoted standby constructs its own HACoordinator, so chained HA
    (a standby of the promoted router) works;
  - epoch fencing: every member-facing call carries X-Router-Epoch;
    members ADOPT a higher epoch and REJECT (409) a lower one — a
    zombie primary that revives after takeover is fenced out of the
    fleet, not split-braining it;
  - graceful handover: SIGTERM on an HA primary flips the sync stream's
    handover flag; the standby then CATCHES UP — it keeps polling and
    applying until its cursor reaches the primary's head (covering
    records past the last routine poll: quiesce-drain tok/fin and any
    backlog beyond one batch) and only then sends a confirm poll
    (`confirm=1`). The primary's SIGTERM wait releases on that confirm
    alone — never on a routine poll, which at lag 0 would let the
    primary exit before the standby even started catching up — and the
    standby promotes with why="handover". The fleet changes routers
    without draining the world, and nothing durably ACKed is left out
    of the replica.

Fault site "router" (testing/faults.py) is drawn once per sync poll:
"exception" fails the poll as if the primary crashed, "slow" stalls the
observed heartbeat past the grace, "device_loss" keeps polls failing
until heal_after_s — the revive-and-fence chaos case.

Lock order is wal-lock -> ha-lock everywhere: the WAL mirror calls
_on_wal_record while holding the WAL lock, and the snapshot head-mark
callback runs under the WAL lock too — the coordinator never touches
the WAL while holding its own lock.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
import urllib.request
from typing import List, Optional

from ollamamq_tpu.durability.wal import WAL_NAME, load_wal_records
from ollamamq_tpu.telemetry import schema as tm
from ollamamq_tpu.telemetry.journal import DECISION_KINDS

log = logging.getLogger("ollamamq.ha")

# Replication ring: bounded memory on the primary no matter how far a
# standby falls behind — past this, catch-up degrades to a WAL snapshot.
SYNC_RING_CAPACITY = 8192
SYNC_MAX_RECORDS = 512       # records per sync batch
POLL_FLOOR_S = 0.05          # standby poll cadence floor (grace/4 above)
HA_STATE_NAME = "ha_state.json"
JOURNAL_REPLICA_NAME = "primary-journal.jsonl"


def load_ha_state(wal_dir: str) -> dict:
    """Persisted HA state (epoch + takeover-cost EMA) from a wal-dir.
    Missing/corrupt file reads as empty — first boot starts at epoch 1."""
    try:
        with open(os.path.join(wal_dir, HA_STATE_NAME),
                  encoding="utf-8") as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}


def save_ha_state(wal_dir: str, epoch: int,
                  takeover_ms_ema: Optional[float] = None) -> None:
    """Durably persist the epoch (write-new-then-rename + fsync): a
    promoted router must never come back up claiming an older epoch —
    that would un-fence the zombie it just fenced."""
    path = os.path.join(wal_dir, HA_STATE_NAME)
    tmp = path + ".new"
    try:
        # The coordinator persists its epoch at ROUTER construction,
        # before the WAL has opened (created) the directory.
        os.makedirs(wal_dir, exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"epoch": int(epoch),
                       "takeover_ms_ema": takeover_ms_ema}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        log.exception("HA state persist failed (epoch %d)", epoch)


class HACoordinator:
    """Primary-side half: taps the WAL and the decision journal into a
    sequence-numbered replication ring served over /admin/ha/sync."""

    def __init__(self, router):
        if router.durability is None:
            raise ValueError("--ha requires --wal-dir: the replication "
                             "stream ships WAL records")
        self.router = router
        self.ecfg = router.ecfg
        self.wal_dir = router.ecfg.wal_dir
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=SYNC_RING_CAPACITY)
        self.head = 0         # last sequence number assigned
        self.last_acked = 0   # highest from_seq any standby poll carried
        self._last_poll: Optional[float] = None  # monotonic, last sync poll
        self.handover = False
        self._handover_target = 0
        self._handover_acked = threading.Event()
        st = load_ha_state(self.wal_dir)
        self.epoch = max(1, int(st.get("epoch") or 1))
        save_ha_state(self.wal_dir, self.epoch, st.get("takeover_ms_ema"))
        router.epoch = self.epoch
        # Replication taps. The WAL mirror runs under the WAL lock; the
        # journal tap runs outside the journal lock (journal.py contains
        # tap exceptions). Both just stamp a seq and append to the ring.
        router.durability.wal.mirror = self._on_wal_record
        router.journal.tap = self._on_journal_record

    # -- taps (primary's hot paths; must stay cheap) -----------------------
    def _push(self, kind: str, rec: dict) -> None:
        with self._lock:
            self.head += 1
            self._ring.append((self.head, kind, rec))

    def _on_wal_record(self, rec: dict) -> None:
        self._push("wal", rec)

    def _on_journal_record(self, rec: dict) -> None:
        # Decision records only: the standby's shadow state and the
        # offline audits need placements/failovers/takeovers, not every
        # per-token scheduler record.
        if rec.get("kind") in DECISION_KINDS:
            self._push("journal", rec)

    # -- member registration ----------------------------------------------
    def on_router_start(self) -> None:
        """Stamp every member with this router's epoch (members adopt it
        and fence anything older). Down members adopt lazily: every
        member-facing call carries the epoch header anyway."""
        for m in self.router.members:
            m.register(self.epoch)

    # -- the sync endpoint's engine half -----------------------------------
    def sync_batch(self, from_seq: int,
                   max_records: int = SYNC_MAX_RECORDS, *,
                   want_snapshot: bool = False,
                   confirm_handover: bool = False) -> dict:
        """One standby poll: ack `from_seq`, return records past it (or
        a whole-file WAL snapshot on cold start / ring overrun) plus the
        shadow-state blob. The poll cursor is the ack — no second
        round-trip. `want_snapshot` is the standby's explicit one-time
        initial-snapshot request (it sends it until a snapshot lands).
        `confirm_handover` is the standby's caught-up handover confirm:
        only it releases the SIGTERM wait — a routine poll at lag 0
        would otherwise release the primary before the standby had even
        begun catching up, and the primary would exit under it."""
        from_seq = max(0, int(from_seq))
        now = time.monotonic()
        with self._lock:
            self.last_acked = max(self.last_acked,
                                  min(from_seq, self.head))
            self._last_poll = now
            oldest = self._ring[0][0] if self._ring else self.head + 1
            # Cold catch-up ALWAYS snapshots: begin()'s compaction lines
            # bypass the mirror, so seq-0 record replay would miss them.
            # head == 0 (idle/fresh primary) only snapshots when the
            # standby asks — otherwise every poll would re-ship and
            # re-fsync the whole replica until the first record lands.
            need_snapshot = (want_snapshot or from_seq + 1 < oldest
                             or (from_seq <= 0 < self.head))
            if (self.handover and confirm_handover
                    and from_seq >= self._handover_target):
                self._handover_acked.set()
        resp = {"role": "primary", "epoch": self.epoch,
                "handover": self.handover,
                "state": self._state_blob()}
        if need_snapshot:
            marker = {}

            def _mark():
                # Runs under the WAL lock: mirror pushes hold that lock
                # too, so this head is exactly the snapshot's edge —
                # every record <= it is in the file, every one past it
                # will be in the ring. (Lock order wal -> ha.)
                with self._lock:
                    marker["head"] = self.head

            lines = self.router.durability.wal.snapshot_lines(mark=_mark)
            snap_head = marker.get("head", self.head)
            resp.update(snapshot=lines, snapshot_head=snap_head,
                        head=snap_head, records=[])
            tm.HA_SYNC_LAG_RECORDS.set(0)
            return resp
        recs: List[dict] = []
        with self._lock:
            for seq, kind, rec in self._ring:
                if seq <= from_seq:
                    continue
                if len(recs) >= max_records:
                    break
                recs.append({"seq": seq, "kind": kind, "rec": rec})
            head = self.head
            lag = max(0, head - self.last_acked)
        for r in recs:
            tm.HA_SYNC_RECORDS_TOTAL.labels(kind=r["kind"]).inc()
        tm.HA_SYNC_LAG_RECORDS.set(lag)
        resp.update(head=head, records=recs)
        return resp

    def _state_blob(self) -> dict:
        """Shadow placement state: enough for the standby's /health and
        TUI to describe the fleet it would inherit. Authoritative
        recovery state is the WAL replica, not this."""
        r = self.router
        mems = []
        for m in r.members:
            mems.append({"name": m.name,
                         "url": getattr(m, "url", None),
                         "state": getattr(m, "state", None),
                         "tier": getattr(m, "tier", None)})
        inflight = []
        for fl in list(r.flights):  # loop-thread appends; snapshot read
            if not fl.done and fl.member is not None:
                inflight.append([fl.rid0, fl.member.name])
        return {"members": mems, "fleet": len(mems),
                "placement": r.placement, "inflight": inflight,
                "tiered": r.tiers is not None,
                "autoscale": r.autoscaler is not None}

    # -- handover (graceful SIGTERM on the primary) ------------------------
    def request_handover(self, timeout_s: float = 10.0) -> bool:
        """Advertise handover on the sync stream and wait for a
        caught-up standby confirm poll acking everything up to the
        current head (its promotion follows immediately). False = no
        standby ever connected, or it never confirmed in time — the
        caller falls back to draining."""
        with self._lock:
            if self._last_poll is None:
                return False
            self.handover = True
            self._handover_target = self.head
            self._handover_acked.clear()
        log.warning("HA handover requested: waiting for standby to ack "
                    "seq %d", self._handover_target)
        ok = self._handover_acked.wait(timeout_s)
        if not ok:
            with self._lock:
                self.handover = False  # stop advertising; we drain instead
            log.error("HA handover timed out after %.1fs — falling back "
                      "to drain", timeout_s)
        else:
            # The confirm poll's HTTP response is still being written on
            # the event loop (the ack fires in the handler, before the
            # write). Give it a beat so the standby sees the answer
            # instead of a socket cut by our exit.
            time.sleep(0.2)
        return ok

    def promote_eta_s(self) -> Optional[float]:
        return None  # a serving primary never sheds for promotion

    def status(self) -> dict:
        now = time.monotonic()
        with self._lock:
            lag = max(0, self.head - self.last_acked)
            seen = self._last_poll
        grace = float(getattr(self.ecfg, "takeover_grace_s", 3.0) or 3.0)
        connected = seen is not None and (now - seen) < max(2.0, 2 * grace)
        return {"role": "primary", "epoch": self.epoch,
                "sync_lag_records": lag if seen is not None else None,
                "standby_connected": connected,
                "handover": self.handover}


class HAStandby:
    """Standby-side half: tails the primary's sync stream into replica
    files, watches its heartbeat, and promotes the (unstarted) local
    FleetRouter when the primary dies or hands over."""

    def __init__(self, router, primary_url: str, fault_plan=None):
        if router.durability is None:
            raise ValueError("--standby-of requires --wal-dir: promotion "
                             "replays the replica WAL")
        self.router = router
        self.primary_url = primary_url.rstrip("/")
        self.wal_dir = router.ecfg.wal_dir
        self.grace = float(
            getattr(router.ecfg, "takeover_grace_s", 3.0) or 3.0)
        self.poll_s = max(POLL_FLOOR_S, min(0.25, self.grace / 4.0))
        self.fault_plan = (fault_plan if fault_plan is not None
                           else router.fault_plan)
        self.role = "standby"
        self.applied = 0        # last replication seq durably applied
        self.head = 0           # primary's head as of the last good poll
        self.epoch_seen = max(1, int(load_ha_state(self.wal_dir)
                                     .get("epoch") or 1))
        self.state: dict = {}   # latest shadow blob from the primary
        self.synced = False     # a snapshot has landed since start
        self.takeover_count = 0
        self.takeover_ms_ema = load_ha_state(self.wal_dir) \
            .get("takeover_ms_ema")
        self.last_error: Optional[str] = None
        self._never_synced_logged: Optional[float] = None
        self.promoted = threading.Event()
        self._promote_begin: Optional[float] = None
        self._last_ok = time.monotonic()
        self._had_failure = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._wal_path = os.path.join(self.wal_dir, WAL_NAME)
        self._journal_path = os.path.join(self.wal_dir,
                                          JOURNAL_REPLICA_NAME)
        self._wal_fh = None
        self._journal_fh = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._open_replicas()
        self._last_ok = time.monotonic()
        self._thread = threading.Thread(target=self._loop,
                                        name="ha-standby", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        self._close_replicas()

    def _open_replicas(self) -> None:
        os.makedirs(self.wal_dir, exist_ok=True)
        self._wal_fh = open(self._wal_path, "a", encoding="utf-8")
        self._journal_fh = open(self._journal_path, "a", encoding="utf-8")
        if self._journal_fh.tell() == 0:
            # Spill-compatible header: load_jsonl / the offline audits
            # read this replica exactly like a primary journal file.
            self._journal_fh.write(json.dumps({"journal_meta": {
                "version": 1, "opened_at": time.time(),
                "replica_of": self.primary_url}}) + "\n")
            self._journal_fh.flush()

    def _close_replicas(self) -> None:
        for name in ("_wal_fh", "_journal_fh"):
            fh = getattr(self, name)
            if fh is not None:
                try:
                    fh.flush()
                    os.fsync(fh.fileno())
                    fh.close()
                except OSError:
                    pass
                setattr(self, name, None)

    # -- the heartbeat/sync loop -------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            failed = self._fault_round()
            handover = False
            if not failed:
                try:
                    resp = self._poll()
                    self._apply(resp)
                    self._last_ok = time.monotonic()
                    handover = bool(resp.get("handover")) and self.synced
                    if self._had_failure:
                        self._had_failure = False
                        self.router.journal.record(
                            "standby_sync", seq=self.applied,
                            lag=max(0, self.head - self.applied),
                            records=0, epoch=self.epoch_seen,
                            why="reconnect")
                except Exception as e:  # noqa: BLE001 — primary down is
                    self.last_error = str(e)  # the expected failure mode
                    self._had_failure = True
            if handover:
                # Catch up to the primary's head BEFORE taking over:
                # records past our last routine poll (quiesce-drain
                # tok/fin, any backlog beyond one batch) must be in the
                # replica, or a durably-ACKed admission could vanish at
                # takeover. A caught-up confirm poll — never a routine
                # one — releases the primary's SIGTERM wait.
                if self._handover_catchup():
                    if self.promote(why="handover"):
                        return
                # Catch-up failed (primary died mid-handover, or it
                # timed out waiting and fell back to draining): stay
                # standby — a dead primary still promotes below once
                # the grace expires.
            if time.monotonic() - self._last_ok > self.grace:
                if not self.synced:
                    # NEVER promote off an empty replica: a standby that
                    # has not synced once (booted before the primary,
                    # wrong --standby-of URL, partitioned) would fence a
                    # possibly-healthy primary out of its own fleet and
                    # serve nothing — an outage caused by HA itself.
                    self._alert_never_synced()
                elif self.promote(why="primary_dead"):
                    return
            if self._stop.wait(self.poll_s):
                return

    def _handover_catchup(self, timeout_s: float = 30.0) -> bool:
        """Drain the sync stream to the primary's head, then send a
        confirm poll (confirm=1) — only that releases the primary's
        SIGTERM wait, so it cannot exit before the replica holds
        everything it shipped. False = the primary died mid-handover
        or withdrew the offer (its wait timed out and it is draining
        instead): the caller must NOT promote off it."""
        deadline = time.monotonic() + timeout_s
        failures = 0
        confirmed = False  # a confirm poll the primary answered
        while not self._stop.is_set() and time.monotonic() < deadline:
            confirm = self.applied >= self.head
            try:
                resp = self._poll(confirm=confirm)
                self._apply(resp)
                self._last_ok = time.monotonic()
                failures = 0
            except Exception as e:  # noqa: BLE001
                self.last_error = str(e)
                self._had_failure = True
                if confirmed:
                    # The primary exits the moment an answered confirm
                    # lands; a dead socket past that point IS the
                    # planned exit — the replica already holds
                    # everything it shipped.
                    return True
                failures += 1
                if failures >= 3:
                    return False  # primary died before confirming
                time.sleep(min(0.2, self.poll_s))
                continue
            if not resp.get("handover"):
                return False  # withdrawn: the primary drains itself
            if confirm:
                confirmed = True
                if (not resp.get("records")
                        and resp.get("snapshot") is None):
                    # Confirmed at head with nothing new: the primary
                    # released on this very poll.
                    return True
            # Records still flowing (backlog or quiesce-drain tok/fin):
            # keep draining and re-confirm once caught up again.
        return False

    def _alert_never_synced(self) -> None:
        """Grace expired but no snapshot ever landed: alert + log
        (throttled), keep polling — promotion stays refused."""
        now = time.monotonic()
        if (self._never_synced_logged is None
                or now - self._never_synced_logged > max(5.0, self.grace)):
            self._never_synced_logged = now
            log.error(
                "primary %s unreachable for %.1fs but this standby has "
                "NEVER synced — refusing to promote an empty replica "
                "(wrong --standby-of URL, primary not up yet, or a "
                "partition); will keep polling",
                self.primary_url, now - self._last_ok)
        alerts = getattr(self.router, "alerts", None)
        if alerts is not None:
            alerts.fire(
                "standby_never_synced", "page",
                "takeover grace expired before the first successful "
                "sync: promotion refused (an unsynced standby would "
                "fence the primary and serve an empty fleet) — check "
                f"--standby-of {self.primary_url}", source="ha")

    def _fault_round(self) -> bool:
        """Draw the "router" fault site for this poll round. True = the
        round counts as failed (heartbeat not observed)."""
        plan = self.fault_plan
        if plan is None:
            return False
        failed = False
        for kind, rule in plan.draw("router"):
            failed = True
            if kind == "slow" and rule is not None:
                time.sleep(rule.delay_s)  # stalls the observed heartbeat
        if failed:
            self._had_failure = True
            self.last_error = "injected router fault"
        return failed

    def _poll(self, confirm: bool = False) -> dict:
        # snap=1 until the first snapshot lands: the initial catch-up
        # must be whole-file (compaction lines bypass the record
        # mirror), and asking explicitly lets an idle primary (head 0)
        # serve it once instead of re-shipping on every cold poll.
        # confirm=1 is the caught-up handover ack (_handover_catchup).
        url = f"{self.primary_url}/admin/ha/sync?seq={self.applied}"
        if not self.synced:
            url += "&snap=1"
        if confirm:
            url += "&confirm=1"
        req = urllib.request.Request(
            url, headers={"Accept": "application/json"})
        timeout = max(0.2, min(2.0, self.grace))
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))

    def _apply(self, resp: dict) -> None:
        self.epoch_seen = max(self.epoch_seen, int(resp.get("epoch") or 1))
        if resp.get("state"):
            self.state = resp["state"]
        self.head = max(int(resp.get("head") or 0), self.applied)
        if resp.get("snapshot") is not None:
            self._apply_snapshot(resp)
        else:
            self._apply_records(resp.get("records") or [])
        tm.HA_SYNC_LAG_RECORDS.set(max(0, self.head - self.applied))

    def _apply_snapshot(self, resp: dict) -> None:
        """Whole-file WAL catch-up: write-new-then-rename the replica so
        a crash mid-catch-up leaves the previous consistent replica."""
        lines = resp["snapshot"]
        tmp = self._wal_path + ".new"
        with open(tmp, "w", encoding="utf-8") as f:
            for ln in lines:
                f.write(ln + "\n")
            f.flush()
            os.fsync(f.fileno())
        if self._wal_fh is not None:
            try:
                self._wal_fh.close()
            except OSError:
                pass
        os.replace(tmp, self._wal_path)
        self._wal_fh = open(self._wal_path, "a", encoding="utf-8")
        self.applied = int(resp.get("snapshot_head") or 0)
        self.head = max(self.head, self.applied)
        self.synced = True
        alerts = getattr(self.router, "alerts", None)
        if alerts is not None:
            alerts.resolve("standby_never_synced")
        for _ in lines:
            tm.HA_SYNC_RECORDS_TOTAL.labels(kind="wal").inc()
        self.router.journal.record(
            "standby_sync", seq=self.applied,
            lag=max(0, self.head - self.applied), records=len(lines),
            epoch=self.epoch_seen, why="snapshot")

    def _apply_records(self, records: List[dict]) -> None:
        wrote_wal = wrote_journal = 0
        for r in records:
            seq = int(r["seq"])
            if seq <= self.applied:
                continue  # duplicate delivery after a half-applied poll
            if r["kind"] == "wal":
                self._wal_fh.write(json.dumps(r["rec"]) + "\n")
                wrote_wal += 1
            else:
                self._journal_fh.write(json.dumps(r["rec"]) + "\n")
                wrote_journal += 1
            tm.HA_SYNC_RECORDS_TOTAL.labels(kind=r["kind"]).inc()
            self.applied = seq
        # fsync per batch: promotion inherits the primary's durability
        # contract — an ACKed admit is on THIS disk too within one poll.
        if wrote_wal:
            self._wal_fh.flush()
            os.fsync(self._wal_fh.fileno())
        if wrote_journal:
            self._journal_fh.flush()
            os.fsync(self._journal_fh.fileno())

    # -- promotion ---------------------------------------------------------
    def promote(self, why: str) -> bool:
        """The takeover ladder: fence (epoch bump + member re-register)
        -> replay (recovery re-admits every unfinished replica stream)
        -> serve. Returns True once this process is the primary."""
        if self.promoted.is_set():
            return True
        r = self.router
        t0 = time.perf_counter()
        self.role = "promoting"
        self._promote_begin = time.monotonic()
        from_epoch = self.epoch_seen
        new_epoch = from_epoch + 1
        lag = max(0, self.head - self.applied)
        r.journal.record("router_takeover", phase="begin", why=why,
                         epoch=new_epoch, from_epoch=from_epoch, lag=lag)
        log.warning("PROMOTING to primary (why=%s epoch %d -> %d, sync "
                    "lag %d record(s))", why, from_epoch, new_epoch, lag)
        # Final fsync + close the replica files: the promoted router's
        # own DurabilityManager takes over wal.jsonl from here.
        self._close_replicas()
        # Persist the epoch BEFORE serving under it — a crash between
        # here and the first placement must not revive at the old epoch.
        save_ha_state(self.wal_dir, new_epoch, self.takeover_ms_ema)
        r.epoch = new_epoch
        for m in r.members:
            m.register(new_epoch)  # fences the zombie primary out
        # rid-space fence: reserve past every replica rid BEFORE opening
        # admissions, so neither recovery re-admits nor racing client
        # enqueues can collide with the dead primary's request ids.
        prev, _torn = load_wal_records(self._wal_path)
        if prev:
            reserve = getattr(r.core, "reserve_req_ids", None)
            if reserve is not None:
                reserve(max(prev) + 1)
        r.accepting = True
        try:
            # start() runs durability recovery: every unfinished replica
            # stream re-enters the queue and re-places across surviving
            # members (affinity lands it back on the member whose radix
            # tree still holds its prefix — the warm-pool fast path).
            r.start()
        except Exception:  # noqa: BLE001
            # The fence side effects are already out: members were
            # re-registered under new_epoch, which no router serves
            # until a promotion lands. Journal that fact, and adopt
            # new_epoch as seen so the RETRY claims a strictly higher
            # one (epoch monotonicity holds even across aborts).
            log.exception(
                "promotion ABORTED: router start failed; returning to "
                "standby. %d member(s) remain claimed at epoch %d (no "
                "router serves it — the old primary is fenced until a "
                "promotion lands or it re-registers above it)",
                len(r.members), new_epoch)
            r.journal.record("router_takeover", phase="aborted", why=why,
                             epoch=new_epoch, from_epoch=from_epoch,
                             members_claimed=len(r.members))
            alerts = getattr(r, "alerts", None)
            if alerts is not None:
                alerts.fire(
                    "takeover_aborted", "page",
                    f"promotion to epoch {new_epoch} aborted after "
                    f"members were claimed at it: no router serves that "
                    "epoch until a retry lands", source="ha")
            self.epoch_seen = new_epoch
            r.accepting = False
            self.role = "standby"
            self._last_ok = time.monotonic()
            self._open_replicas()
            return False
        streams = int(getattr(r.durability, "recovered_streams", 0) or 0)
        ms = (time.perf_counter() - t0) * 1e3
        self.takeover_ms_ema = (
            ms if self.takeover_ms_ema is None
            else 0.3 * ms + 0.7 * float(self.takeover_ms_ema))
        save_ha_state(self.wal_dir, new_epoch, self.takeover_ms_ema)
        # The promoted router is a full primary: its own coordinator
        # (reading the epoch just persisted) accepts the next standby.
        r.ha = HACoordinator(r)
        r.ha.on_router_start()
        self.role = "primary"
        self.takeover_count += 1
        alerts = getattr(r, "alerts", None)
        if alerts is not None:
            alerts.resolve("takeover_aborted")
        self.promoted.set()
        tm.HA_TAKEOVERS_TOTAL.labels(why=why).inc()
        tm.HA_TAKEOVER_DURATION_MS.observe(ms)
        # migrated=0 is honest: the dead primary's member connections
        # died with it, so there are no frozen pools to export — every
        # stream comes back through recompute-replay (affinity reuse of
        # the member's cached prefix is the de-facto migration).
        r.journal.record("router_takeover", phase="done", why=why,
                         epoch=new_epoch, from_epoch=from_epoch,
                         streams=streams, migrated=0, replayed=streams,
                         takeover_ms=round(ms, 3), lag=lag)
        log.warning("PROMOTED: epoch %d, %d stream(s) re-admitted in "
                    "%.0fms", new_epoch, streams, ms)
        return True

    def promote_eta_s(self) -> Optional[float]:
        """Expected seconds until this process serves — the Retry-After
        a shed client gets. Seeded from the takeover grace until a real
        takeover has been measured (the EMA persists across processes
        in ha_state.json, like the autoscaler's spawn-cost EMA)."""
        if self.role == "primary":
            return None
        expect = (float(self.takeover_ms_ema) / 1e3
                  if self.takeover_ms_ema else max(1.0, self.grace))
        if self.role == "promoting" and self._promote_begin is not None:
            return max(0.5, expect - (time.monotonic()
                                      - self._promote_begin))
        return max(0.5, expect)

    def status(self) -> dict:
        s = {"role": self.role,
             "epoch": (self.router.epoch if self.role == "primary"
                       else self.epoch_seen),
             "sync_lag_records": max(0, self.head - self.applied),
             "primary": self.primary_url,
             "synced": self.synced,
             "takeovers": self.takeover_count}
        if self.takeover_ms_ema is not None:
            s["takeover_ms_ema"] = round(float(self.takeover_ms_ema), 3)
        if self.role == "promoting" and self._promote_begin is not None:
            s["promote_elapsed_s"] = round(
                time.monotonic() - self._promote_begin, 3)
        if self.last_error:
            s["last_error"] = self.last_error
        return s
