"""Admin TUI front: runs the native C++ dashboard (cpp/tui.cpp) on the
calling thread, feeding it engine stats through a callback.

Mirrors the reference lifecycle (main.rs:134-150): the HTTP server runs on
background threads, the TUI owns the terminal, and quitting the TUI ends
the whole process. All admin actions (VIP/boost/block/unblock) mutate the
shared native core directly — the scheduler sees them on its next pop.
"""

from __future__ import annotations

import ctypes
import json
import logging

from ollamamq_tpu.core.mqcore import _get_lib

log = logging.getLogger("ollamamq.tui")

# POINTER(c_char), NOT c_char_p: c_char_p would hand the callback an
# immutable bytes copy and memmove would scribble on interpreter memory.
_STATS_CB = ctypes.CFUNCTYPE(
    ctypes.c_longlong, ctypes.POINTER(ctypes.c_char), ctypes.c_longlong
)


_hbm_cache = {"ts": 0.0, "used": 0, "total": 0, "device": "", "chips": []}


def _engine_stats_brief(engine) -> dict:
    """Compact stats JSON for the chips panel.

    Called at the 10 Hz TUI cadence, so it must stay cheap: per-runtime
    stats only (no core.snapshot — the native TUI reads the queue state
    itself), with device/HBM numbers cached for 2 s (a memory_stats call
    can be a tunnel round-trip on remote TPU setups).
    """
    import time

    models = [rt.stats() for rt in list(engine.runtimes.values())]
    now = time.monotonic()
    if now - _hbm_cache["ts"] > 2.0:
        used = sum(m["param_bytes"] + m["kv_bytes"] for m in models)
        total = 0
        device = ""
        chips = []
        try:
            chips = engine.chip_stats()  # one row PER chip (pod-wide
            # under SPMD); aggregates below keep the summary line.
            if chips:
                device = chips[0]["device"]
                used = sum(c["hbm_used"] for c in chips) or used
                total = sum(c["hbm_total"] for c in chips)
        except Exception:
            pass
        _hbm_cache.update(ts=now, used=used, total=total, device=device,
                          chips=chips)
    # Firing alerts (SLO burn, watchdog stalls, device loss) for the
    # ALERTS panel — read from the engine's shared alert table at the
    # frame cadence (an in-memory list copy; cheap).
    alerts = []
    am = getattr(engine, "alerts", None)
    if am is not None:
        try:
            alerts = [{"name": a.name, "severity": a.severity,
                       "message": a.message,
                       "age_s": round(max(0.0, time.time() - a.since), 0)}
                      for a in am.active()]
        except Exception:
            alerts = []
    # Degradation chip: total sheds (admission caps / deadlines / kv
    # exhaustion, engine-side mirror of ollamamq_shed_total) and total
    # KV-pressure preemptions across runtimes.
    shed = sum(getattr(engine, "shed_counts", {}).values())
    preempt = sum(m.get("preemptions", 0) or 0 for m in models)
    # Scheduler chip: active policy + output-length predictor accuracy
    # ("acc n/a" in the TUI until the predictor warms up). Engines and
    # the fleet router both expose scheduler_stats().
    sched = None
    ss = getattr(engine, "scheduler_stats", None)
    if ss is not None:
        try:
            sched = ss()
        except Exception:
            sched = None
    # Flight-recorder last-decision line: the newest scheduler decision
    # (admit/shed/preempt/...) with the inputs that justified it — the
    # operator's at-a-glance "what did the scheduler just do".
    last_decision = ""
    jr = getattr(engine, "journal", None)
    if jr is not None:
        try:
            last_decision = jr.last_summary()
        except Exception:
            last_decision = ""
    out = {
        "models": models,
        "device": _hbm_cache["device"] or "no-device",
        "chips": _hbm_cache["chips"],
        "hbm_used": _hbm_cache["used"],
        "hbm_total": _hbm_cache["total"],
        "shed": shed,
        "preempt": preempt,
        "last_decision": last_decision,
        "alerts": alerts,
    }
    if sched is not None:
        out["sched"] = sched
    # Engine performance plane chip (`compiles N · step p99 X ms`):
    # compile-ladder count + rolling step p99 off the process-wide step
    # profiler — absent until the first dispatch/compile, so the chips
    # column stays quiet on an idle engine.
    try:
        from ollamamq_tpu.telemetry import stepprof

        sp = stepprof.PROFILER.brief()
        if sp is not None:
            out["stepprof"] = sp
    except Exception:
        pass
    # Fleet replicas chip (N healthy / M ejected / K draining): present
    # only when the engine is a fleet router.
    fleet = getattr(engine, "fleet_counts", None)
    if fleet is not None:
        try:
            out["replicas"] = fleet()
        except Exception:
            pass
    # Fleet-size chip (elastic fleets only): `fleet N (+P preemptible)`
    # with the autoscaler's min/max bounds.
    scaler = getattr(engine, "autoscaler", None)
    if scaler is not None:
        try:
            out["fleet_size"] = scaler.brief()
        except Exception:
            pass
    # Router-overhead chip (fleet router only): the windowed placement
    # p99 against its budget — red in the C++ renderer when the router
    # hot path itself is eating the latency budget.
    overhead = getattr(engine, "router_overhead_p99_ms", None)
    if overhead is not None:
        try:
            p99 = overhead()
            out["router_overhead"] = {
                "p99_ms": round(p99, 3) if p99 is not None else None,
                "budget_ms": getattr(engine.ecfg,
                                     "router_overhead_budget_ms", 0.0),
            }
        except Exception:
            pass
    # HA role chip (HA fleets only): `ha primary/3` = role + fencing
    # epoch. The C++ side renders it red while "promoting" (takeover in
    # flight) and for a standby that has lost its primary feed.
    ha_fn = getattr(engine, "ha_status", None)
    if ha_fn is not None:
        try:
            hs = ha_fn()
            if hs is not None:
                out["ha"] = {"role": hs.get("role", "?"),
                             "epoch": hs.get("epoch", 0),
                             "lag": hs.get("sync_lag_records"),
                             "synced": hs.get("synced", True)}
        except Exception:
            pass
    # Tiers line (tiered fleets only): healthy/total per tier — the C++
    # side renders it red when any tier has ZERO healthy members (that
    # tier's traffic is running cross-tier until a member heals in).
    tiers = getattr(engine, "tiers", None)
    if tiers is not None:
        try:
            out["tiers"] = tiers.counts()
        except Exception:
            pass
    return out


def run_tui(engine, registry, refresh_ms: int = 100) -> None:
    """Blocks until the operator quits (q/Esc). Returns then — the caller
    shuts the server down (TUI exit == process exit, like the reference)."""
    import signal

    lib = _get_lib()
    lib.mqtui_run.restype = ctypes.c_int
    lib.mqtui_run.argtypes = [ctypes.c_void_p, _STATS_CB, ctypes.c_int]

    # Ctrl-C must not raise inside the ctypes callback (an interrupt at
    # callback entry is uncatchable there and corrupts the return value);
    # instead a flag-setting handler turns it into a clean quit request.
    interrupted = {"flag": False}

    def _on_sigint(signum, frame):
        interrupted["flag"] = True

    prev_handler = signal.signal(signal.SIGINT, _on_sigint)

    def cb(buf, cap):
        if interrupted["flag"]:
            return -9  # tell the C loop to exit cleanly
        try:
            data = json.dumps(_engine_stats_brief(engine)).encode()
        except BaseException:
            return 0
        if len(data) >= cap:
            return 0
        ctypes.memmove(buf, data, len(data))
        return len(data)

    cb_ref = _STATS_CB(cb)  # keep alive for the whole run
    try:
        rc = lib.mqtui_run(engine.core._h, cb_ref, refresh_ms)
    finally:
        signal.signal(signal.SIGINT, prev_handler)
    if rc != 0:
        log.warning("TUI unavailable (not a TTY); running headless")
        import time

        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
