"""Partition specs for model params, KV cache, and activations.

Standard Megatron-style TP layout expressed as jax.sharding PartitionSpecs —
XLA inserts the allgather/reduce-scatter collectives over ICI when the jitted
step consumes these shardings (no explicit NCCL-style calls, unlike the
reference's HTTP fan-out):

  - wq/wk/wv  [D, heads*hd]  -> shard output (head) dim on "tensor"
  - wo        [heads*hd, D]  -> shard input  (head) dim on "tensor"
                                (row-parallel: psum happens via sharding)
  - w_gate/w_up [D, F]       -> shard F on "tensor"
  - w_down     [F, D]        -> shard F on "tensor"
  - embed     [V, D]         -> shard vocab on "tensor" (logits computed
                                shard-local then allgathered by XLA)
  - norms                    -> replicated
  - KV pages  [L, P, page, kv_heads, hd] -> shard kv_heads on "tensor"
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from ollamamq_tpu.parallel.mesh import AXIS_TENSOR


def param_partition_specs(params: Dict[str, Any]) -> Dict[str, Any]:
    """Map a params pytree (nested dicts keyed by layer/tensor name) to
    PartitionSpecs by leaf path name."""

    def spec_for(path: str, leaf) -> PS:
        name = path.split("/")[-1]
        nd = leaf.ndim
        # Layer weights are stacked on a leading num_layers axis (scan over
        # layers), so the sharded dim is addressed from the right.
        if name in ("wq", "wk", "wv", "w_gate", "w_up") and nd >= 2:
            return PS(*([None] * (nd - 1)), AXIS_TENSOR)  # column-parallel
        if name in ("wo", "w_down") and nd >= 2:
            return PS(*([None] * (nd - 2)), AXIS_TENSOR, None)  # row-parallel
        if name in ("bq", "bk", "bv") and nd >= 1:
            return PS(*([None] * (nd - 1)), AXIS_TENSOR)
        if name in ("embed", "lm_head"):
            return PS(AXIS_TENSOR, None)  # vocab-sharded
        return PS()  # norms: replicated

    return _named_map(spec_for, params)


def kv_cache_spec() -> PS:
    """KV slot pool [L, slots, kv_heads, head_dim]: heads on tensor axis."""
    return PS(None, None, AXIS_TENSOR, None)


def shard_params(params, mesh: Mesh):
    """Place a params pytree onto the mesh per the partition rules."""
    specs = param_partition_specs(params)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )


def _named_map(fn, tree, path=""):
    if isinstance(tree, dict):
        return {k: _named_map(fn, v, f"{path}/{k}") for k, v in tree.items()}
    return fn(path, tree)
