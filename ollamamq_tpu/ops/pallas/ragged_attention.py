"""Pallas TPU kernel: ragged mixed-batch paged attention.

One launch processes a single FLATTENED token stream holding any mix of
variable-length prefill spans and single decode tokens — the "Ragged
Paged Attention" design (PAPERS.md): no per-sequence bucket padding, no
separate prefill/decode kernels, HBM reads that scale with each
sequence's true context.

Layout contract (matches engine/kv_cache.py and the decode kernel in
ops/pallas/paged_attention.py):
    q:          [T, H, hd] flattened queries; sequence s owns rows
                [q_start[s], q_start[s] + q_len[s]) and its tokens sit at
                kv positions kv_len[s] - q_len[s] .. kv_len[s] - 1.
    k/v cache:  [S, Hk, hd] flat slot pool; page = page_size contiguous
                slots at page_id * page_size.
    page_table: [B, max_pages] int32 (trash page 0 padding).
    Spans are contiguous and ascending in stream order; padding rows
    carry q_len = 0 with q_start = T.

Grid: one program per G_TILE-token tile of the stream. A tile may span
several sequences (e.g. 8 decode tokens from 8 different sequences), so
per-tile scalar-prefetch metadata names the FIRST overlapping sequence
and the kernel walks forward over the (at most G_TILE) sequences that
intersect the tile, masking rows by span membership. Per sequence it
streams that sequence's pages HBM→VMEM double-buffered and accumulates a
flash-style online softmax per (row, query-group); the page loop is
bounded by the tile's deepest causal frontier, so an early prefill tile
reads only the prefix it can see.

Mosaic layout constraints follow the proven decode kernel: K/V move as
flattened [page_size, Hk*hd] rows, q arrives packed [T, group, Hk*hd]
(query-group-major, kv-segment lanes), and per-head segmentation uses
constant 0/1 segment matrices on the MXU so no in-kernel relayouts are
needed.

Int8 KV pages (`k_scale`/`v_scale` passed): the payload DMAs exactly as
bf16 pages do (half the bytes), each page's fp32 [page_size, Hk] scale
row rides a third/fourth DMA into its own VMEM buffer, and dequant
happens in-kernel right after the wait — scale rows expand to lane
segments with the same seg_t matmul the softmax bookkeeping uses, so the
int8 path adds no relayouts. Softmax/accumulation stay f32 as before. Cross-tile DMA prefetch (the decode kernel's cross-program
epilogue) is intentionally absent for now: sequence boundaries inside a
tile make the hand-off non-trivial, and the page loop already overlaps
DMA with compute within a sequence.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Tokens per grid program. 8 keeps the q/o blocks one sublane tile tall
# and bounds the worst case (8 distinct decode sequences) to the same
# page-loop total work as 8 decode-kernel programs.
G_TILE = 8


def _ragged_kernel(
    # scalar prefetch
    tile_seq_ref,  # [n_tiles] SMEM: first sequence overlapping each tile
    q_start_ref,  # [B] SMEM: stream offset of each sequence's span
    q_len_ref,  # [B] SMEM: span length (0 = padding row)
    kv_len_ref,  # [B] SMEM: context length incl. the span's tokens
    page_table_ref,  # [B, max_pages] SMEM
    # inputs (quantized pools append ks_hbm/vs_hbm scale planes)
    *refs,
    page_size: int,
    max_pages: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    ring: int,
    num_seqs: int,
    quantized: bool,
):
    if quantized:
        (q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref,
         k_buf, v_buf, ks_buf, vs_buf, acc, m_i, l_i, sems) = refs
    else:
        (q_ref, k_hbm, v_hbm, o_ref,
         k_buf, v_buf, acc, m_i, l_i, sems) = refs
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
    t = pl.program_id(0)
    tile_start = t * G_TILE
    group = num_heads // num_kv_heads
    lanes = num_kv_heads * head_dim
    scale = 1.0 / (head_dim ** 0.5)

    def page_dma(slot, row, page_idx):
        page_id = page_table_ref[row, page_idx]
        start = page_id * page_size
        copies = [
            pltpu.make_async_copy(
                k_hbm.at[pl.ds(start, page_size)], k_buf.at[slot],
                sems.at[slot, 0]),
            pltpu.make_async_copy(
                v_hbm.at[pl.ds(start, page_size)], v_buf.at[slot],
                sems.at[slot, 1]),
        ]
        if quantized:
            # Scale rows travel with their page: same slot indexing, a
            # [page_size, Hk] f32 plane per page.
            copies.append(pltpu.make_async_copy(
                ks_hbm.at[pl.ds(start, page_size)], ks_buf.at[slot],
                sems.at[slot, 2]))
            copies.append(pltpu.make_async_copy(
                vs_hbm.at[pl.ds(start, page_size)], vs_buf.at[slot],
                sems.at[slot, 3]))
        return copies

    def start_page(slot, row, page_idx):
        for dma in page_dma(slot, row, page_idx):
            dma.start()

    acc[...] = jnp.zeros_like(acc)
    m_i[...] = jnp.full_like(m_i, NEG_INF)
    l_i[...] = jnp.zeros_like(l_i)

    # Segment matrices: SEG[d, h] = 1 iff lane d belongs to kv head h
    # (the decode kernel's relayout-free per-head reduction trick).
    seg = (
        jax.lax.broadcasted_iota(jnp.int32, (lanes, num_kv_heads), 0)
        // head_dim
        == jax.lax.broadcasted_iota(jnp.int32, (lanes, num_kv_heads), 1)
    ).astype(jnp.float32)
    seg_t = (
        jax.lax.broadcasted_iota(jnp.int32, (num_kv_heads, lanes), 1)
        // head_dim
        == jax.lax.broadcasted_iota(jnp.int32, (num_kv_heads, lanes), 0)
    ).astype(jnp.float32)

    s0 = tile_seq_ref[t]
    # At most G_TILE sequences can have a token inside a G_TILE-token
    # tile (spans are contiguous, zero-length rows only trail the
    # stream), so a static walk of G_TILE successors covers every case.
    for j in range(G_TILE):
        s = jnp.minimum(s0 + j, num_seqs - 1)
        qs = q_start_ref[s]
        ql = q_len_ref[s]
        kv = kv_len_ref[s]
        overlaps = (
            (s0 + j < num_seqs)
            & (ql > 0)
            & (qs < tile_start + G_TILE)
            & (qs + ql > tile_start)
        )

        @pl.when(overlaps)
        def _(s=s, qs=qs, ql=ql, kv=kv):
            # Deepest causal frontier among this tile's rows of s bounds
            # the page walk: an early tile of a long prefill reads only
            # the prefix its own queries can see.
            last_tok = jnp.minimum(tile_start + G_TILE, qs + ql) - 1
            last_pos = kv - ql + (last_tok - qs)
            npages = jnp.minimum(
                pl.cdiv(last_pos + 1, page_size), max_pages
            )
            for i in range(ring):
                @pl.when(i < npages)
                def _(i=i):
                    start_page(i % ring, s, i)

            def body(p, _):
                slot = p % ring
                for dma in page_dma(slot, s, p):
                    dma.wait()
                k = k_buf[slot].astype(jnp.float32)  # [ps, lanes]
                v = v_buf[slot].astype(jnp.float32)
                if quantized:
                    # Dequantize in-kernel: per-head scale rows expand to
                    # lane segments via the same seg_t MXU trick the
                    # softmax bookkeeping uses (no relayouts).
                    k = k * jax.lax.dot_general(
                        ks_buf[slot], seg_t,
                        dimension_numbers=(((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    v = v * jax.lax.dot_general(
                        vs_buf[slot], seg_t,
                        dimension_numbers=(((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)

                @pl.when(p + ring < npages)
                def _():
                    start_page(slot, s, p + ring)

                pos = p * page_size + jax.lax.broadcasted_iota(
                    jnp.int32, (page_size, num_kv_heads), 0
                )
                for r in range(G_TILE):
                    g_tok = tile_start + r
                    in_span = (g_tok >= qs) & (g_tok < qs + ql)
                    row_pos = kv - ql + (g_tok - qs)

                    @pl.when(in_span)
                    def _(r=r, row_pos=row_pos):
                        # Causal within the span + bounded by the
                        # sequence's written context.
                        valid = (pos <= row_pos) & (pos < kv)  # [ps, Hk]
                        for g in range(group):
                            idx = r * group + g
                            qg = q_ref[r, g:g + 1, :].astype(jnp.float32)
                            sc = jax.lax.dot_general(
                                k * qg, seg,
                                dimension_numbers=(((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                            ) * scale  # [ps, Hk]
                            sc = jnp.where(valid, sc, NEG_INF)
                            m_prev = m_i[idx:idx + 1, :]  # [1, Hk]
                            m_new = jnp.maximum(
                                m_prev, jnp.max(sc, axis=0, keepdims=True)
                            )
                            # A page entirely beyond a row's causal
                            # frontier leaves every score at NEG_INF;
                            # guard the exps so the no-op update stays a
                            # no-op instead of adding exp(0) mass.
                            alpha = jnp.where(
                                m_prev <= NEG_INF / 2, 0.0,
                                jnp.exp(m_prev - m_new))
                            p_ij = jnp.where(
                                sc <= NEG_INF / 2, 0.0,
                                jnp.exp(sc - m_new))
                            l_i[idx:idx + 1, :] = (
                                l_i[idx:idx + 1, :] * alpha
                                + jnp.sum(p_ij, axis=0, keepdims=True))
                            e = jax.lax.dot_general(
                                p_ij, seg_t,
                                dimension_numbers=(((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                            )  # [ps, lanes]
                            contrib = jnp.sum(e * v, axis=0, keepdims=True)
                            alpha_l = jax.lax.dot_general(
                                alpha, seg_t,
                                dimension_numbers=(((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                            )  # [1, lanes]
                            acc[idx:idx + 1, :] = (
                                acc[idx:idx + 1, :] * alpha_l + contrib)
                            m_i[idx:idx + 1, :] = m_new
                return ()

            jax.lax.fori_loop(0, npages, body, ())

    denom = jax.lax.dot_general(
        jnp.maximum(l_i[...], 1e-20), seg_t,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [G_TILE*group, lanes]
    out = (acc[...] / denom).reshape(G_TILE, group, lanes)
    o_ref[...] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def ragged_paged_attention_pallas(
    q: jnp.ndarray,  # [T, H, hd] flattened mixed-batch queries
    k_cache: jnp.ndarray,  # [S, Hk, hd] (int8 when k_scale is passed)
    v_cache: jnp.ndarray,  # [S, Hk, hd]
    page_table: jnp.ndarray,  # [B, max_pages]
    q_start: jnp.ndarray,  # [B] span offset per sequence (T for padding)
    q_lens: jnp.ndarray,  # [B] span length per sequence (0 for padding)
    kv_lens: jnp.ndarray,  # [B] context length incl. the span
    page_size: int,
    interpret: bool = False,
    k_scale=None,  # [S, Hk] f32 per-slot per-head scales (int8 pools)
    v_scale=None,
) -> jnp.ndarray:
    quantized = k_scale is not None
    T, H, hd = q.shape
    B, max_pages = page_table.shape
    Hk = k_cache.shape[1]
    group = H // Hk
    lanes = Hk * hd

    Tp = -(-T // G_TILE) * G_TILE
    n_tiles = Tp // G_TILE
    # First sequence overlapping each tile: spans are contiguous and
    # ascending, so it is the first whose END lies past the tile start.
    ends = (q_start + q_lens).astype(jnp.int32)
    tile_first = jnp.searchsorted(
        ends, jnp.arange(n_tiles, dtype=jnp.int32) * G_TILE, side="right"
    ).astype(jnp.int32)

    ring = 4  # pages in flight per sequence (ring restarts per sequence)
    kernel = functools.partial(
        _ragged_kernel,
        page_size=page_size,
        max_pages=max_pages,
        num_heads=H,
        num_kv_heads=Hk,
        head_dim=hd,
        ring=ring,
        num_seqs=B,
        quantized=quantized,
    )

    in_specs = [
        pl.BlockSpec((G_TILE, group, lanes), lambda t, *_: (t, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pl.ANY),  # k stays in HBM
        pl.BlockSpec(memory_space=pl.ANY),  # v stays in HBM
    ]
    scratch = [
        pltpu.VMEM((ring, page_size, lanes), k_cache.dtype),
        pltpu.VMEM((ring, page_size, lanes), v_cache.dtype),
    ]
    if quantized:
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),  # k scale rows (HBM)
            pl.BlockSpec(memory_space=pl.ANY),  # v scale rows (HBM)
        ]
        scratch += [
            pltpu.VMEM((ring, page_size, Hk), jnp.float32),
            pltpu.VMEM((ring, page_size, Hk), jnp.float32),
        ]
    scratch += [
        pltpu.VMEM((G_TILE * group, lanes), jnp.float32),
        pltpu.VMEM((G_TILE * group, Hk), jnp.float32),
        pltpu.VMEM((G_TILE * group, Hk), jnp.float32),
        pltpu.SemaphoreType.DMA((ring, 4 if quantized else 2)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((G_TILE, group, lanes),
                               lambda t, *_: (t, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=scratch,
    )

    # Pack q head-group-major (see the decode kernel): row r holds every
    # kv head's group-g query in its lane segment.
    q_packed = (
        q.reshape(T, Hk, group, hd).transpose(0, 2, 1, 3).reshape(T, group, lanes)
    )
    if Tp != T:
        q_packed = jnp.pad(q_packed, ((0, Tp - T), (0, 0), (0, 0)))
    operands = [q_packed, k_cache.reshape(-1, lanes),
                v_cache.reshape(-1, lanes)]
    if quantized:
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Tp, group, lanes), q.dtype),
        interpret=interpret,
    )(tile_first, q_start.astype(jnp.int32), q_lens.astype(jnp.int32),
      kv_lens.astype(jnp.int32), page_table.astype(jnp.int32),
      *operands)
    return (
        out[:T].reshape(T, group, Hk, hd).transpose(0, 2, 1, 3).reshape(T, H, hd)
    )
