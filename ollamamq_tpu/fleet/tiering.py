"""Tiered fleet: SLO-aware replica tiers with adaptive TP regrouping.

Nitsum's observation ("Serving Tiered LLM Requests with Adaptive Tensor
Parallelism", PAPERS.md): a fleet that places latency-sensitive requests
on wide-TP low-latency replicas and bulk traffic on narrow-TP
high-throughput ones beats any homogeneous fleet on BOTH p99 TTFT and
aggregate tok/s — and the win compounds when the fleet REGROUPS as the
class mix shifts. This module is that policy layer over FleetRouter:

  Tier model     members carry a tier label (`interactive` / `bulk`,
                 --tiers spec, config.assign_tiers). Placement reads the
                 request class — VIP/boost users and deadlined requests
                 are `interactive`, everything else `bulk` — and routes
                 to the matching tier, with affinity and least-loaded
                 preserved WITHIN the tier. Cross-tier placement happens
                 only with explicit journaling (tier_overflow).

  SLO headroom   each tier owns a TTFT Objective (the PR-3 burn-rate
                 machinery, telemetry/slo.py) fed from the router at
                 first-token time. When a tier's fast-burn window fires,
                 the OTHER tier's members become eligible overflow
                 targets for its traffic — interactive load sheds onto
                 bulk under an interactive burn, bulk backlog (which
                 shows up as bulk TTFT burn) spills into interactive
                 headroom — each cross-tier placement journaled with the
                 burn that justified it. Overflow targets keep
                 `overflow_headroom` slots free for their own tier, so
                 spill never starves native traffic.

  Regrouping     TierBalancer watches the interactive-share EMA of
                 classified placements. Past the hysteresis deadband
                 (and a cooldown, and a minimum sample count — an
                 oscillating mix must NOT flap members back and forth)
                 it retiers one member toward the observed mix:
                 drain via the PR-9 machinery, live streams migrate off
                 via PR-11, hot-restart at the target tier's TP width
                 (LocalMember with an engine factory) or re-label
                 (HttpMember), rejoin the other tier — journaled
                 tier_regroup start/done/aborted. A crash mid-retier
                 aborts the regroup and the member rejoins its ORIGINAL
                 tier after healing; its streams already migrated off
                 during the drain, so the fallback ladder (migrate ->
                 recompute replay -> never drop) holds throughout.

Stdlib-only (telemetry + config imports): the router constructs one when
engine_cfg.tiers (or its own `tiers` kwarg) names a spec.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Tuple

from ollamamq_tpu.config import TIER_NAMES, assign_tiers
from ollamamq_tpu.telemetry import schema as tm
from ollamamq_tpu.telemetry.slo import DEFAULT_WINDOWS, Objective

# Default per-tier TTFT objectives (ms) when the operator configured no
# --slo-ttft-ms: interactive traffic is the latency-sensitive class; the
# bulk threshold is deliberately lax — its burn firing means BACKLOG
# (queued bulk work aging past any reasonable first-token wait), the
# signal that justifies spilling bulk into interactive headroom.
INTERACTIVE_TTFT_MS = 500.0
BULK_TTFT_MS_FACTOR = 8.0

# Balancer defaults (constructor-overridable; tests and bench shrink
# them). The deadband + cooldown + sample floor are the hysteresis that
# keeps an oscillating class mix from flapping members between tiers.
EMA_ALPHA = 0.05
BALANCE_DEADBAND = 0.18
BALANCE_COOLDOWN_S = 30.0
BALANCE_MIN_SAMPLES = 32

# Overflow targets must keep this many slots free for their OWN tier's
# traffic, so a spill never turns the other tier homogeneous again.
OVERFLOW_HEADROOM = 1

# Overflow burn evaluation cache TTL: placement is per-request, burn
# windows move at 1s-bucket granularity — recomputing per placement
# would be wasted work.
_BURN_TTL_S = 0.2
# vip/boost live in the native core; snapshot() builds JSON — cache it.
_CLASS_TTL_S = 0.5


def other_tier(tier: str) -> str:
    return "bulk" if tier == "interactive" else "interactive"


class TierManager:
    """Tier assignment + class-aware placement filter + per-tier SLO
    burn + the TierBalancer. Owned by FleetRouter; all methods are
    called from the router loop except status()/counts() (HTTP/TUI
    readers) — state that crosses that boundary sits behind a lock."""

    def __init__(self, members: List[object], spec: str, core,
                 journal, ecfg=None,
                 interactive_ttft_ms: Optional[float] = None,
                 bulk_ttft_ms: Optional[float] = None,
                 slo_target: float = 0.99,
                 windows: Tuple[tuple, ...] = DEFAULT_WINDOWS,
                 overflow_headroom: int = OVERFLOW_HEADROOM,
                 balance: bool = True,
                 ema_alpha: float = EMA_ALPHA,
                 deadband: float = BALANCE_DEADBAND,
                 cooldown_s: float = BALANCE_COOLDOWN_S,
                 min_samples: int = BALANCE_MIN_SAMPLES):
        self.spec = spec
        self.core = core
        self.journal = journal
        roster = [(m.name, getattr(m, "tp", None)) for m in members]
        assignment, widths = assign_tiers(spec, roster)  # raises TiersError
        self.widths = widths  # tier -> declared target TP width (or None)
        self._members = list(members)
        for mem in members:
            mem.tier = assignment[mem.name]
        # Per-tier TTFT objectives off the PR-3 burn-rate machinery.
        slo_ttft = getattr(ecfg, "slo_ttft_ms", None) if ecfg else None
        i_ms = (interactive_ttft_ms if interactive_ttft_ms is not None
                else (slo_ttft or INTERACTIVE_TTFT_MS))
        b_ms = (bulk_ttft_ms if bulk_ttft_ms is not None
                else i_ms * BULK_TTFT_MS_FACTOR)
        self.windows = windows
        horizon = max((w[1] for w in windows), default=3600.0)
        self.objectives: Dict[str, Objective] = {
            "interactive": Objective("tier_interactive", i_ms, slo_target,
                                     horizon_s=horizon),
            "bulk": Objective("tier_bulk", b_ms, slo_target,
                              horizon_s=horizon),
        }
        self.overflow_headroom = max(0, int(overflow_headroom))
        # Balancer state.
        self.balance = bool(balance)
        self.ema_alpha = float(ema_alpha)
        self.deadband = float(deadband)
        self.cooldown_s = float(cooldown_s)
        self.min_samples = max(1, int(min_samples))
        self.mix_ema: Optional[float] = None  # interactive share of placements
        self.samples_since_regroup = 0
        self.last_regroup_at = 0.0
        self.regroup_times: collections.deque = collections.deque(maxlen=64)
        self.regroup_counts = {"done": 0, "aborted": 0}
        self.overflow_count = 0
        # Elastic fleet (fleet/autoscaler.py): tiers the scaler has
        # DELIBERATELY emptied. A scaled-to-zero tier's traffic parks at
        # the router (tier isolation) instead of taking the empty-tier
        # cross-tier fallback — the parked backlog is the pending-work
        # signal that wakes the tier back up. Distinct from a tier whose
        # members all crashed: that one still spills cross-tier.
        self.scaled_to_zero: set = set()
        self._class_cache = (0.0, None, None)  # (ts, vip, boost)
        self._burn_cache: Dict[str, tuple] = {}  # tier -> (ts, active, burn)
        self._last_gauges = 0.0
        self.update_gauges()

    # ------------------------------------------------------- classification
    def _vip_boost(self) -> tuple:
        now = time.monotonic()
        ts, vip, boost = self._class_cache
        if now - ts > _CLASS_TTL_S:
            try:
                snap = self.core.snapshot()
                vip, boost = snap.get("vip"), snap.get("boost")
            except Exception:  # noqa: BLE001 — stale beats crashed
                pass
            self._class_cache = (now, vip, boost)
        return vip, boost

    def class_of(self, user: str, deadline) -> str:
        """Request class: vip / boost (the fair-share core's privileged
        users) / deadline (the request carries a latency contract) /
        default. The first three are the latency-sensitive classes the
        interactive tier exists for."""
        vip, boost = self._vip_boost()
        if vip is not None and user == vip:
            return "vip"
        if boost is not None and user == boost:
            return "boost"
        if deadline is not None:
            return "deadline"
        return "default"

    @staticmethod
    def tier_of_class(cls: str) -> str:
        return "bulk" if cls == "default" else "interactive"

    # ------------------------------------------------------------ overflow
    def record_ttft(self, tier: str, ttft_ms: float) -> None:
        """First-token latency observed at the router, recorded against
        the request's HOME tier (where it was classified, not where an
        overflow landed it — the home tier's SLO is what's burning)."""
        obj = self.objectives.get(tier)
        if obj is not None:
            obj.record(ttft_ms)

    def overflow_state(self, tier: str,
                       now: Optional[float] = None) -> Tuple[bool, float]:
        """(firing, burn) for `tier`'s fastest window pair — the PR-3
        multi-window rule: burning over BOTH the long and the short leg.
        Firing means the OTHER tier's members become eligible overflow
        targets for this tier's traffic."""
        now = time.monotonic() if now is None else now
        ts, active, burn = self._burn_cache.get(tier, (0.0, False, 0.0))
        if now - ts <= _BURN_TTL_S:
            return active, burn
        obj = self.objectives[tier]
        active, burn = False, 0.0
        for _label, long_w, short_w, factor, _sev in self.windows:
            burn_long = obj.burn_rate(long_w, now=now)
            burn_short = obj.burn_rate(short_w, now=now)
            if burn_long > factor and burn_short > factor:
                active, burn = True, max(burn, burn_long)
        self._burn_cache[tier] = (now, active, burn)
        return active, burn

    # ------------------------------------------------------------ placement
    def placement_filter(self, flight, elig: List[object],
                         load_of, slot_cap) -> Tuple[List[object], dict]:
        """Restrict an eligible-member list to the flight's home tier,
        widening to overflow targets when the tier's burn fires or the
        tier has no healthy members at all. Returns (members, info);
        info feeds journal_place once the router picks the winner. An
        empty return with a nonempty input means the home tier exists
        but is full: the stream WAITS (tier isolation is the point)
        rather than silently going cross-tier."""
        cls = self.class_of(flight.user, flight.req.deadline)
        tier = self.tier_of_class(cls)
        flight.cls, flight.tier = cls, tier
        self._note_mix(tier)
        info = {"tier": tier, "cls": cls, "overflow": False,
                "why": None, "burn": None}
        # Router-side slot bound: a local member's engine would happily
        # BUFFER placements past its slot count (its own queue), which
        # would let a bulk backlog bypass tier isolation before the
        # member's capacity view catches up. Tiered placement keeps the
        # backlog at the ROUTER, where burn-driven overflow (and drains,
        # and regroups) can actually act on it.
        elig = [m for m in elig if load_of(m) < slot_cap(m)]
        home = [m for m in elig if getattr(m, "tier", None) == tier]
        firing, burn = self.overflow_state(tier)
        if firing:
            # Burn overflow: widen to the other tier's members that keep
            # headroom for their own traffic; least-loaded picks among
            # the union, so in-tier capacity still wins when it exists.
            spill = [m for m in elig
                     if getattr(m, "tier", None) != tier
                     and load_of(m) + self.overflow_headroom < slot_cap(m)]
            if spill:
                info.update(why="burn", burn=round(burn, 2))
                return home + spill, info
        if home:
            return home, info
        # No ELIGIBLE home member. Empty tier (nothing healthy) falls
        # back cross-tier — explicitly journaled; a merely-full tier
        # waits in queue instead of leaking onto the other tier.
        home_alive = [m for m in self._members
                      if getattr(m, "tier", None) == tier
                      and m.state == "healthy"]
        if not home_alive and elig:
            if tier in self.scaled_to_zero:
                # Deliberately scaled to zero: PARK (the stream waits at
                # the router; its presence in the pending set is the
                # autoscaler's wake signal) instead of leaking onto the
                # other tier's members.
                info.update(why="parked")
                return [], info
            info.update(why="no_members")
            return list(elig), info
        return [], info

    def journal_place(self, flight, member, info) -> None:
        """One tier_place per tiered placement decision, plus a
        tier_overflow when the winner is cross-tier — the explicit
        journaling contract for every cross-tier fallback."""
        tier = info["tier"]
        crossed = getattr(member, "tier", None) not in (None, tier)
        self.journal.record(
            "tier_place", req_id=flight.rid0, user=flight.user,
            model=flight.model or None, tier=tier, cls=info["cls"],
            replica=member.name, overflow=True if crossed else None)
        if crossed:
            self.overflow_count += 1
            tm.FLEET_TIER_OVERFLOW_TOTAL.labels(
                **{"from": tier, "to": member.tier}).inc()
            self.journal.record(
                "tier_overflow", req_id=flight.rid0, user=flight.user,
                model=flight.model or None, from_tier=tier,
                to_tier=member.tier, why=info["why"] or "no_capacity",
                burn=info["burn"], replica=member.name,
                queued=self.core.total_queued())
            # Router-side span (tracing.ROUTER_EVENTS): the cross-tier
            # decision reads straight off the stitched client timeline.
            flight.req.trace_event("overflow", from_tier=tier,
                                   to_tier=member.tier,
                                   why=info["why"] or "no_capacity")

    def journal_failover_overflow(self, flight, member) -> None:
        """A failover/migration landed a stream cross-tier because its
        home tier had no capacity — same explicit journaling, different
        why."""
        tier = getattr(flight, "tier", None)
        if tier is None or getattr(member, "tier", None) in (None, tier):
            return
        self.overflow_count += 1
        tm.FLEET_TIER_OVERFLOW_TOTAL.labels(
            **{"from": tier, "to": member.tier}).inc()
        self.journal.record(
            "tier_overflow", req_id=flight.rid0, user=flight.user,
            model=flight.model or None, from_tier=tier,
            to_tier=member.tier, why="failover", replica=member.name)
        flight.req.trace_event("overflow", from_tier=tier,
                               to_tier=member.tier, why="failover")

    # ------------------------------------------------------------ balancing
    def _note_mix(self, tier: str) -> None:
        x = 1.0 if tier == "interactive" else 0.0
        self.mix_ema = (x if self.mix_ema is None
                        else self.ema_alpha * x
                        + (1.0 - self.ema_alpha) * self.mix_ema)
        self.samples_since_regroup += 1

    def _tier_members(self, tier: str) -> List[object]:
        return [m for m in self._members
                if getattr(m, "tier", None) == tier]

    def maybe_balance(self, router) -> None:
        """One balancer tick: regroup ONE member toward the observed
        class mix when the imbalance clears the hysteresis deadband, the
        cooldown elapsed, and enough placements were observed since the
        last regroup. Never empties a tier."""
        if not self.balance or self.mix_ema is None:
            return
        if self.samples_since_regroup < self.min_samples:
            return
        if time.monotonic() - self.last_regroup_at < self.cooldown_s:
            return
        if any(getattr(m, "retier_to", None) for m in self._members):
            return  # one regroup in flight at a time
        n = len(self._members)
        inter = len(self._tier_members("interactive"))
        frac = inter / n
        desired = min(n - 1, max(1, round(self.mix_ema * n)))
        if desired > inter and self.mix_ema > frac + self.deadband:
            donor_tier = "bulk"
        elif desired < inter and self.mix_ema < frac - self.deadband:
            donor_tier = "interactive"
        else:
            return
        if other_tier(donor_tier) in self.scaled_to_zero:
            return  # don't repopulate a tier the scaler emptied on purpose
        donors = [m for m in self._tier_members(donor_tier)
                  if m.state == "healthy"
                  and getattr(m, "retier_to", None) is None]
        if len(donors) < 1 or len(self._tier_members(donor_tier)) <= 1:
            return  # a tier never empties
        donor = min(donors, key=router._load_of)
        try:
            router.retier_replica(donor.name, other_tier(donor_tier),
                                  why="mix_shift")
        except (KeyError, ValueError, RuntimeError):
            pass  # raced with a drain/eject: retry a later tick

    def note_regroup(self, outcome: str) -> None:
        self.regroup_counts[outcome] = \
            self.regroup_counts.get(outcome, 0) + 1
        tm.FLEET_REGROUPS_TOTAL.labels(outcome=outcome).inc()
        self.regroup_times.append(time.monotonic())
        self.last_regroup_at = time.monotonic()
        self.samples_since_regroup = 0

    # ------------------------------------------------- elastic-fleet roster
    def note_member_added(self, mem, tier: str) -> None:
        """A scaler-provisioned member joined: label it, add it to the
        tier roster, and clear any scale-to-zero park on its tier (the
        wake)."""
        mem.tier = tier
        self._members.append(mem)
        self.scaled_to_zero.discard(tier)
        self.update_gauges()

    def note_member_removed(self, mem, to_zero: bool = False) -> None:
        """A member retired (scale-down / preemption). `to_zero` marks a
        DELIBERATE tier emptying: its traffic parks instead of spilling
        cross-tier until the scaler wakes the tier."""
        self._members = [m for m in self._members if m is not mem]
        tier = getattr(mem, "tier", None)
        if to_zero and tier is not None and not self._tier_members(tier):
            self.scaled_to_zero.add(tier)
        self.update_gauges()

    def regroup_rate_per_min(self, window_s: float = 60.0) -> float:
        """Regroups per minute over the trailing window — the health
        watchdog's regroup-storm signal (a flapping balancer burns every
        retier on drain+restart churn)."""
        cutoff = time.monotonic() - window_s
        n = sum(1 for t in self.regroup_times if t >= cutoff)
        return n * 60.0 / window_s

    # ------------------------------------------------------------- readouts
    def update_gauges(self) -> None:
        counts: Dict[tuple, int] = {}
        for tier in TIER_NAMES:
            for state in ("healthy", "ejected", "draining"):
                counts[(tier, state)] = 0
        for m in self._members:
            tier = getattr(m, "tier", None)
            if tier is not None:
                counts[(tier, m.state)] = counts.get((tier, m.state), 0) + 1
        for (tier, state), nn in counts.items():
            tm.FLEET_TIER_MEMBERS.labels(tier=tier, state=state).set(nn)

    def counts(self) -> dict:
        """{tier: {"healthy": n, "total": n}} for the TUI tiers line."""
        out: dict = {}
        for tier in TIER_NAMES:
            mems = self._tier_members(tier)
            out[tier] = {
                "healthy": sum(1 for m in mems if m.state == "healthy"),
                "total": len(mems),
            }
        return out

    def status(self) -> dict:
        """GET /admin/tiers payload: per-tier membership, burn, overflow
        state, and the balancer's live inputs."""
        now = time.monotonic()
        tiers: dict = {}
        for tier in TIER_NAMES:
            obj = self.objectives[tier]
            firing, burn = self.overflow_state(tier, now=now)
            tiers[tier] = {
                "members": [{"name": m.name, "state": m.state,
                             "tp": getattr(m, "tp", None),
                             "retiering_to": getattr(m, "retier_to", None)}
                            for m in self._tier_members(tier)],
                "target_tp": self.widths.get(tier),
                "ttft_threshold_ms": obj.threshold_ms,
                "burn_rate": round(burn, 3),
                "overflow_active": firing,
            }
        return {
            "spec": self.spec,
            "tiers": tiers,
            "mix_ema_interactive": (round(self.mix_ema, 4)
                                    if self.mix_ema is not None else None),
            "balance": self.balance,
            "deadband": self.deadband,
            "cooldown_s": self.cooldown_s,
            "overflows": self.overflow_count,
            "regroups": dict(self.regroup_counts),
        }
