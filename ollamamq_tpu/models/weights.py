"""Weight initialization and checkpoint loading.

Checkpoints load from either:
  - a safetensors directory in the HF layout (Llama/Qwen2 tensor names), or
  - an orbax checkpoint previously saved by `save_orbax`.

Weights land directly in their mesh sharding (each host/device only
materializes its shard) — the TPU analogue of the reference's
"models live inside Ollama" (it never touches weights at all).
"""

from __future__ import annotations

import os
import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ollamamq_tpu.config import ModelConfig
from ollamamq_tpu.models import llama
from ollamamq_tpu.ops.quant import QuantTensor, quantize_tensor


# Layer matmul weights quantized per-channel along their LAST axis (the
# einsum output channel); embed/lm_head quantize per vocab ROW (axis 0 —
# the logits einsum's output channel AND the embedding gather's row, so
# one scale vector serves both uses of a tied embedding).
QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
QUANT_ROW_KEYS = ("embed", "lm_head")


def quantize_params_int8(params: dict, cfg: ModelConfig) -> dict:
    """Per-channel symmetric int8 quantization of a loaded params tree
    (scales fp32; norms, biases, and q/k norms stay in the load dtype).
    Shapes are unchanged — each quantized leaf becomes a QuantTensor
    pytree node, and the dequant-fused helpers in ops/quant.py keep
    every forward's signature identical."""
    if cfg.num_experts:
        raise ValueError(
            "int8 weight quantization does not cover MoE expert stacks; "
            f"load {cfg.name} with --weights-dtype=bfloat16")
    out = dict(params)
    layers = dict(params["layers"])
    for k in QUANT_LAYER_KEYS:
        if k in layers:
            layers[k] = quantize_tensor(layers[k], axis=-1)
    out["layers"] = layers
    for k in QUANT_ROW_KEYS:
        if k in out:
            out[k] = quantize_tensor(out[k], axis=0)
    return out


# HF tensor name -> (our tree path, transpose?) for one layer.
_HF_LAYER_MAP = {
    "input_layernorm.weight": ("attn_norm", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.q_norm.weight": ("q_norm", False),
    "self_attn.k_norm.weight": ("k_norm", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    "post_attention_layernorm.weight": ("mlp_norm", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
}


def init_random(cfg: ModelConfig, seed: int = 0, dtype=jnp.bfloat16) -> dict:
    return llama.init_params(cfg, jax.random.PRNGKey(seed), dtype=dtype)


def load_safetensors(cfg: ModelConfig, path: str, dtype=jnp.bfloat16) -> dict:
    """Load an HF-layout safetensors checkpoint into the stacked-layer tree."""
    from safetensors import safe_open

    files = sorted(
        os.path.join(path, f) for f in os.listdir(path) if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")

    raw = {}
    for f in files:
        with safe_open(f, framework="np") as sf:
            for name in sf.keys():
                raw[name] = sf.get_tensor(name)

    def grab(name: str, transpose: bool) -> np.ndarray:
        t = raw[name]
        if t.dtype == np.uint16:  # bfloat16 stored raw
            t = t.view(np.uint16).astype(np.uint32) << 16
            t = t.view(np.float32)
        t = np.asarray(t, dtype=np.float32)
        return t.T if transpose else t

    layer_names = [k for k in raw if re.match(r"model\.layers\.\d+\.", k)]
    n_layers = 1 + max(int(k.split(".")[2]) for k in layer_names)
    if n_layers != cfg.num_layers:
        raise ValueError(f"checkpoint has {n_layers} layers, config {cfg.num_layers}")

    layers: dict = {}
    for hf_suffix, (ours, tr) in _HF_LAYER_MAP.items():
        key0 = f"model.layers.0.{hf_suffix}"
        if key0 not in raw:
            continue
        stack = np.stack(
            [grab(f"model.layers.{i}.{hf_suffix}", tr) for i in range(cfg.num_layers)]
        )
        layers[ours] = jnp.asarray(stack, dtype=dtype)

    if cfg.num_experts:
        # Mixtral layout: block_sparse_moe.gate + experts.N.w1/w3/w2
        # (gate/up/down). Stack experts on axis 1 -> [L, E, D, F] etc.
        # Host-RAM discipline: the expert stacks dominate the checkpoint
        # (~90% of an 8x7b), so cast each LAYER's expert stack to the
        # target dtype immediately and pop the consumed raw tensors —
        # peak host memory stays near one f32 layer-stack (~2 GB for
        # 8x7b) above the raw checkpoint, instead of ~2.5x it.
        def estack(w_name: str, transpose: bool):
            per_layer = []
            for i in range(cfg.num_layers):
                names = [f"model.layers.{i}.block_sparse_moe.experts."
                         f"{e}.{w_name}.weight"
                         for e in range(cfg.num_experts)]
                stack = np.stack([grab(n, transpose) for n in names])
                for n in names:
                    raw.pop(n, None)
                per_layer.append(jnp.asarray(stack, dtype=dtype))
            return jnp.stack(per_layer)

        layers["w_router"] = jnp.asarray(np.stack([
            grab(f"model.layers.{i}.block_sparse_moe.gate.weight", True)
            for i in range(cfg.num_layers)
        ]), dtype=dtype)
        layers["we_gate"] = estack("w1", True)
        layers["we_down"] = estack("w2", True)
        layers["we_up"] = estack("w3", True)

    params = {
        "embed": jnp.asarray(grab("model.embed_tokens.weight", False), dtype=dtype),
        "final_norm": jnp.asarray(grab("model.norm.weight", False), dtype=dtype),
        "layers": layers,
    }
    if "lm_head.weight" in raw and not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(grab("lm_head.weight", False), dtype=dtype)
    return params


def save_orbax(params: dict, path: str) -> None:
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), params)
    ckptr.wait_until_finished()


def load_orbax(path: str) -> dict:
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.abspath(path))


def load_params(
    cfg: ModelConfig,
    checkpoint_path: Optional[str] = None,
    seed: int = 0,
    dtype=jnp.bfloat16,
    weights_dtype: str = "bfloat16",
) -> dict:
    """Resolve weights: checkpoint dir (safetensors/orbax) or random init.
    `weights_dtype="int8"` quantizes the loaded tree at load time
    (per-channel symmetric, fp32 scales) — the checkpoint is still read
    in `dtype` and the full-precision copy is dropped immediately."""
    if checkpoint_path:
        entries = os.listdir(checkpoint_path)
        if any(e.endswith(".safetensors") for e in entries):
            params = load_safetensors(cfg, checkpoint_path, dtype=dtype)
        else:
            params = load_orbax(checkpoint_path)
    else:
        params = init_random(cfg, seed=seed, dtype=dtype)
    if weights_dtype == "int8":
        params = quantize_params_int8(params, cfg)
    return params


def replicate_kv_heads(params: dict, cfg, r: int) -> dict:
    """Duplicate each KV head r times (consecutively) so num_kv_heads grows
    to r * cfg.num_kv_heads — the replicated-group sharding for
    tp > num_kv_heads: every tensor-parallel shard then owns exactly one
    (duplicated) KV head. Numerics are exactly preserved: q head i maps to
    kv' head i // (H/Hk') and kv'[j] == kv[j // r], which composes to the
    original i // (H/Hk) assignment. Costs r x KV-cache memory."""
    import jax.numpy as jnp

    Hk, hd = cfg.num_kv_heads, cfg.head_dim

    def rep_w(w):  # [L, d, Hk*hd] -> [L, d, r*Hk*hd]
        if isinstance(w, QuantTensor):
            # Per-channel scales live on the duplicated axis: replicate
            # payload and scales in lockstep, numerics exactly preserved.
            return QuantTensor(rep_w(w.q), rep_b(w.s))
        L, d, _ = w.shape
        return jnp.repeat(
            w.reshape(L, d, Hk, hd), r, axis=2
        ).reshape(L, d, r * Hk * hd)

    def rep_b(b):  # [L, Hk*hd] -> [L, r*Hk*hd]
        L, _ = b.shape
        return jnp.repeat(b.reshape(L, Hk, hd), r, axis=1).reshape(L, -1)

    layers = dict(params["layers"])
    layers["wk"] = rep_w(layers["wk"])
    layers["wv"] = rep_w(layers["wv"])
    if "bk" in layers:
        layers["bk"] = rep_b(layers["bk"])
        layers["bv"] = rep_b(layers["bv"])
    out = dict(params)
    out["layers"] = layers
    return out


def _full_logits(params: dict, cfg: ModelConfig, tokens) -> jnp.ndarray:
    """Last-position logits of a full causal forward (no KV pool): the
    minimal teacher-forced probe the quantization guardrail runs on both
    the bf16 and int8 trees."""
    from ollamamq_tpu.ops.attention import causal_attention

    toks = jnp.asarray(tokens, jnp.int32)[None, :]  # [1, T]
    B, T = toks.shape
    seq_lens = jnp.full((B,), T, jnp.int32)
    x = llama.embed_lookup(params["embed"], toks, llama._adtype(params))
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    def body(carry, lp):
        x, _, _ = llama._layer_step(
            cfg, lp, carry, positions,
            lambda q, k, v: causal_attention(q, k, v, seq_lens))
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return llama._logits(params, cfg, x[:, -1:, :])[0, 0]  # [V] f32


def quant_guardrail(
    cfg: ModelConfig,
    base_params: Optional[dict] = None,
    q_params: Optional[dict] = None,
    seed: int = 0,
    dtype=jnp.bfloat16,
    prompt_len: int = 16,
    steps: int = 16,
) -> dict:
    """Greedy token-match-rate + max-logit-error of the int8 tree vs its
    bf16 source, teacher-forced on the bf16 model's own greedy rollout
    (so one early mismatch can't cascade into a meaningless diff).
    Publishes `ollamamq_quant_logit_err`; tier-1 pins the bounds and the
    bench density scenario reports them next to its A/B line."""
    from ollamamq_tpu.telemetry import schema as tm

    if base_params is None:
        base_params = init_random(cfg, seed=seed, dtype=dtype)
    if q_params is None:
        q_params = quantize_params_int8(base_params, cfg)
    rng = np.random.default_rng(seed)
    ctx = rng.integers(3, cfg.vocab_size, size=max(1, prompt_len)).tolist()
    step = jax.jit(_full_logits, static_argnums=(1,))
    matches, max_err = 0, 0.0
    for _ in range(steps):
        lb = np.asarray(step(base_params, cfg, ctx))
        lq = np.asarray(step(q_params, cfg, ctx))
        max_err = max(max_err, float(np.max(np.abs(lb - lq))))
        tb, tq = int(np.argmax(lb)), int(np.argmax(lq))
        matches += int(tb == tq)
        ctx = ctx + [tb]  # teacher-forced: both follow the bf16 stream
    out = {
        "steps": steps,
        "token_match_rate": round(matches / max(1, steps), 4),
        "max_logit_err": round(max_err, 6),
        # Scale-free companion: the same max error over the logit spread,
        # so one bound serves both toy and real-shaped configs.
        "rel_logit_err": round(max_err / max(1e-9, float(np.std(lb))), 6),
    }
    tm.QUANT_LOGIT_ERR.labels(model=cfg.name).set(out["max_logit_err"])
    return out
