"""Checkpoint loading: HF-layout safetensors and orbax round-trip."""

import numpy as np
import pytest

from ollamamq_tpu.config import MODEL_CONFIGS
from ollamamq_tpu.models import weights


def _fake_hf_checkpoint(cfg, tmp_path, with_bias=False):
    from safetensors.numpy import save_file

    rng = np.random.default_rng(0)
    d, qd, kvd, f, v = (cfg.hidden_size, cfg.q_dim, cfg.kv_dim,
                        cfg.intermediate_size, cfg.vocab_size)
    tensors = {
        "model.embed_tokens.weight": rng.normal(size=(v, d)).astype(np.float32),
        "model.norm.weight": np.ones((d,), np.float32),
        "lm_head.weight": rng.normal(size=(v, d)).astype(np.float32),
    }
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        tensors[p + "input_layernorm.weight"] = np.ones((d,), np.float32)
        tensors[p + "post_attention_layernorm.weight"] = np.ones((d,), np.float32)
        # HF stores projections as [out, in]; our tree wants [in, out].
        tensors[p + "self_attn.q_proj.weight"] = rng.normal(size=(qd, d)).astype(np.float32)
        tensors[p + "self_attn.k_proj.weight"] = rng.normal(size=(kvd, d)).astype(np.float32)
        tensors[p + "self_attn.v_proj.weight"] = rng.normal(size=(kvd, d)).astype(np.float32)
        tensors[p + "self_attn.o_proj.weight"] = rng.normal(size=(d, qd)).astype(np.float32)
        tensors[p + "mlp.gate_proj.weight"] = rng.normal(size=(f, d)).astype(np.float32)
        tensors[p + "mlp.up_proj.weight"] = rng.normal(size=(f, d)).astype(np.float32)
        tensors[p + "mlp.down_proj.weight"] = rng.normal(size=(d, f)).astype(np.float32)
        if with_bias:
            tensors[p + "self_attn.q_proj.bias"] = rng.normal(size=(qd,)).astype(np.float32)
            tensors[p + "self_attn.k_proj.bias"] = rng.normal(size=(kvd,)).astype(np.float32)
            tensors[p + "self_attn.v_proj.bias"] = rng.normal(size=(kvd,)).astype(np.float32)
    save_file(tensors, str(tmp_path / "model.safetensors"))
    return tensors


def test_safetensors_hf_layout(tmp_path):
    import jax.numpy as jnp

    cfg = MODEL_CONFIGS["test-tiny"]
    raw = _fake_hf_checkpoint(cfg, tmp_path)
    params = weights.load_safetensors(cfg, str(tmp_path), dtype=jnp.float32)
    assert params["layers"]["wq"].shape == (cfg.num_layers, cfg.hidden_size, cfg.q_dim)
    # Transposition check: our [in, out] equals HF [out, in].T for layer 0.
    np.testing.assert_allclose(
        np.asarray(params["layers"]["wq"][0]),
        raw["model.layers.0.self_attn.q_proj.weight"].T,
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(params["embed"]), raw["model.embed_tokens.weight"], rtol=1e-6
    )
    assert "lm_head" in params  # untied config keeps its head

    # And the loaded checkpoint actually runs.
    from ollamamq_tpu.models import llama
    import jax

    kc = jnp.zeros((cfg.num_layers, 64, cfg.num_kv_heads, cfg.head_dim), jnp.float32)
    from ollamamq_tpu.engine import kv_cache as kvc
    a = kvc.PageAllocator(8, 8, 4)
    pt = jnp.asarray(np.stack([kvc.make_page_table_row(a.alloc(4), 4)]))
    logits, _, _ = llama.forward_prefill(
        params, cfg, jnp.array([[1, 2, 3, 4]], jnp.int32), jnp.array([4]),
        kc, jnp.zeros_like(kc), pt, 8,
    )
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_safetensors_qwen_bias(tmp_path):
    import jax.numpy as jnp

    cfg = MODEL_CONFIGS["test-tiny-qwen"]
    _fake_hf_checkpoint(cfg, tmp_path, with_bias=True)
    params = weights.load_safetensors(cfg, str(tmp_path), dtype=jnp.float32)
    assert params["layers"]["bq"].shape == (cfg.num_layers, cfg.q_dim)


def test_layer_count_mismatch_rejected(tmp_path):
    import dataclasses

    cfg = MODEL_CONFIGS["test-tiny"]
    _fake_hf_checkpoint(cfg, tmp_path)
    wrong = dataclasses.replace(cfg, num_layers=cfg.num_layers + 1)
    with pytest.raises(ValueError, match="layers"):
        weights.load_safetensors(wrong, str(tmp_path))


def test_orbax_round_trip(tmp_path, tiny_cfg, tiny_params):
    weights.save_orbax(tiny_params, str(tmp_path / "ckpt"))
    restored = weights.load_orbax(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(
        np.asarray(restored["layers"]["wq"]),
        np.asarray(tiny_params["layers"]["wq"]),
        rtol=1e-6,
    )
    # load_params resolves an orbax dir automatically.
    via_resolver = weights.load_params(tiny_cfg, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(
        np.asarray(via_resolver["embed"]),
        np.asarray(tiny_params["embed"]), rtol=1e-6,
    )


def test_safetensors_mixtral_moe_layout(tmp_path):
    import jax.numpy as jnp

    cfg = MODEL_CONFIGS["test-tiny-moe"]
    rng = np.random.default_rng(1)
    from safetensors.numpy import save_file

    d, f, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    tensors = {
        "model.embed_tokens.weight": rng.normal(
            size=(cfg.vocab_size, d)).astype(np.float32),
        "model.norm.weight": np.ones((d,), np.float32),
        "lm_head.weight": rng.normal(
            size=(cfg.vocab_size, d)).astype(np.float32),
    }
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        tensors[p + "input_layernorm.weight"] = np.ones((d,), np.float32)
        tensors[p + "post_attention_layernorm.weight"] = np.ones((d,), np.float32)
        tensors[p + "self_attn.q_proj.weight"] = rng.normal(
            size=(cfg.q_dim, d)).astype(np.float32)
        tensors[p + "self_attn.k_proj.weight"] = rng.normal(
            size=(cfg.kv_dim, d)).astype(np.float32)
        tensors[p + "self_attn.v_proj.weight"] = rng.normal(
            size=(cfg.kv_dim, d)).astype(np.float32)
        tensors[p + "self_attn.o_proj.weight"] = rng.normal(
            size=(d, cfg.q_dim)).astype(np.float32)
        tensors[p + "block_sparse_moe.gate.weight"] = rng.normal(
            size=(E, d)).astype(np.float32)
        for e in range(E):
            ep = p + f"block_sparse_moe.experts.{e}."
            tensors[ep + "w1.weight"] = rng.normal(size=(f, d)).astype(np.float32)
            tensors[ep + "w2.weight"] = rng.normal(size=(d, f)).astype(np.float32)
            tensors[ep + "w3.weight"] = rng.normal(size=(f, d)).astype(np.float32)
    save_file(tensors, str(tmp_path / "model.safetensors"))

    params = weights.load_safetensors(cfg, str(tmp_path), dtype=jnp.float32)
    L = cfg.num_layers
    assert params["layers"]["w_router"].shape == (L, d, E)
    assert params["layers"]["we_gate"].shape == (L, E, d, f)
    assert params["layers"]["we_down"].shape == (L, E, f, d)
    assert "w_gate" not in params["layers"]  # no dense FFN in an MoE tree
    np.testing.assert_allclose(
        np.asarray(params["layers"]["we_gate"][0, 1]),
        tensors["model.layers.0.block_sparse_moe.experts.1.w1.weight"].T,
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(params["layers"]["w_router"][0]),
        tensors["model.layers.0.block_sparse_moe.gate.weight"].T,
        rtol=1e-6,
    )

    # The loaded MoE checkpoint actually runs a prefill.
    from ollamamq_tpu.engine import kv_cache as kvc
    from ollamamq_tpu.models import llama

    kc = jnp.zeros((L, 64, cfg.num_kv_heads, cfg.head_dim), jnp.float32)
    a = kvc.PageAllocator(8, 8, 4)
    pt = jnp.asarray(np.stack([kvc.make_page_table_row(a.alloc(4), 4)]))
    logits, _, _ = llama.forward_prefill(
        params, cfg, jnp.array([[1, 2, 3, 4]], jnp.int32), jnp.array([4]),
        kc, jnp.zeros_like(kc), pt, 8,
    )
    assert np.isfinite(np.asarray(logits)).all()
