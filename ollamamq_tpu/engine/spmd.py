"""SPMD multi-host serving: one engine, many hosts.

The reference scales by adding independent HTTP backends; a TPU pod is a
single SPMD machine instead: every host runs the same program, params and
KV pools are sharded over a GLOBAL mesh (tensor axis spanning hosts'
chips), and each jitted step executes on all hosts with XLA collectives
over ICI/DCN doing the cross-chip movement.

Control plane: the primary host (process 0) owns the scheduler, HTTP
front, and all admission decisions. Before every device step it
broadcasts a "step plan" via `multihost_utils.broadcast_one_to_all` in
two phases — a fixed-shape header (opcode + static dims), then the
op-specific payload (token ids, page tables, sampling params, raw RNG
key) — so both sides always issue matching collectives. Workers sit in
`run_worker`, receive plans, and issue the SAME jit call with their
local shards. Every value feeding the computation is broadcast, never
recomputed locally, so all hosts trace and execute identical steps.

Opcode header (int32[4]: [op, a, b, model_ordinal]):
    OP_SHUTDOWN = 0              -> workers exit (no payload)
    OP_PREFILL  = 1, a=bucket, b=B
    OP_CHUNK    = 2, a=chunk_size
    OP_DECODE   = 3, a=k_steps
    OP_ENCODE   = 4, a=B, b=bucket (embedding batch forward, stateless)
    OP_PREFILL_SP = 5, a=T (sequence-parallel long-prompt prefill)
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.engine import EncoderRuntime, ModelRuntime

log = logging.getLogger("ollamamq.spmd")

OP_SHUTDOWN = 0
OP_PREFILL = 1
OP_CHUNK = 2
OP_DECODE = 3
OP_ENCODE = 4
OP_PREFILL_SP = 5

KEY_SHAPE = (2,)  # raw uint32 threefry key data


def _bcast(tree):
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(tree)


def payload_spec(op, a, b, S, MP):
    """[(shape, dtype), ...] for an opcode's broadcast payload — the ONE
    place the wire order lives. Senders cast their positional values to
    this spec; workers build a zeros template from it. Broadcast matches
    on tree structure + shape/dtype, so both sides must agree exactly."""

    def samp(n):  # temp, top_k, top_p, repeat, presence, frequency, seed
        return [((n,), np.float32), ((n,), np.int32), ((n,), np.float32),
                ((n,), np.float32), ((n,), np.float32), ((n,), np.float32),
                ((n,), np.int32)]

    key = [(KEY_SHAPE, np.uint32)]
    if op == OP_PREFILL:
        bucket, B = a, b
        return [((B, bucket), np.int32), ((B,), np.int32), ((B,), np.int32),
                ((B, MP), np.int32)] + samp(B) + key
    if op == OP_CHUNK:
        return [((1, a), np.int32), ((1,), np.int32), ((1,), np.int32),
                ((1,), np.int32), ((1,), np.int32),
                ((1, MP), np.int32)] + samp(1) + key
    if op == OP_DECODE:
        return [((S,), np.int32), ((S,), np.int32), ((S,), np.int32),
                ((S, MP), np.int32)] + samp(S) + key
    if op == OP_PREFILL_SP:
        return [((1, a), np.int32), ((1,), np.int32), ((1,), np.int32),
                ((1, MP), np.int32)] + samp(1) + key
    if op == OP_ENCODE:
        B, bucket = a, b
        return [((B, bucket), np.int32), ((B,), np.int32)]
    raise ValueError(f"no payload spec for opcode {op}")


def _send(op, a, b, index, values, S, MP):
    spec = payload_spec(op, a, b, S, MP)
    assert len(values) == len(spec)
    cast = []
    for v, (shape, dt) in zip(values, spec):
        v = np.asarray(v, dt)
        # Shape drift would desync the broadcast tree across hosts with an
        # opaque cross-host error; fail at the send site instead.
        assert v.shape == shape, (op, v.shape, shape)
        cast.append(v)
    _bcast(np.asarray([op, a, b, index], np.int32))
    _bcast(tuple(cast))


def _recv(op, a, b, S, MP):
    spec = payload_spec(op, a, b, S, MP)
    return _bcast(tuple(np.zeros(shape, dt) for shape, dt in spec))


def broadcast_shutdown() -> None:
    """Release worker hosts. Sent exactly ONCE per deployment (the worker
    loop exits on the first shutdown header; further broadcasts would have
    no receiver and deadlock the sender)."""
    if jax.process_count() > 1:
        _bcast(np.asarray([OP_SHUTDOWN, 0, 0, 0], np.int32))


class SPMDModelRuntime(ModelRuntime):
    """ModelRuntime whose device dispatches are mirrored on every host.

    Single-process deployments behave exactly like ModelRuntime (the
    broadcast seam is skipped entirely).
    """

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._spmd = jax.process_count() > 1
        # Ordinal agreed with workers via the shared --models ordering;
        # carried in the opcode header so multi-model pods stay in step.
        self.spmd_index = 0

    def _dispatch_prefill(self, bucket, B, tokens, lens, slot_ids, pt_rows,
                          temp, tk, tp, pen, pres, freq, seeds, key):
        if self._spmd:
            _send(OP_PREFILL, bucket, B, self.spmd_index,
                  (tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen,
                   pres, freq, seeds, key),
                  self.ecfg.max_slots, self.ecfg.max_pages_per_seq)
        return super()._dispatch_prefill(
            bucket, B, tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen,
            pres, freq, seeds, key
        )

    def _dispatch_chunk(self, chunk, tokens, start, cl, slot_id, is_final,
                        pt_row, temp, tk, tp, pen, pres, freq, seeds, key):
        if self._spmd:
            _send(OP_CHUNK, chunk, 0, self.spmd_index,
                  (tokens, start, cl, slot_id, is_final, pt_row, temp, tk,
                   tp, pen, pres, freq, seeds, key),
                  self.ecfg.max_slots, self.ecfg.max_pages_per_seq)
        return super()._dispatch_chunk(
            chunk, tokens, start, cl, slot_id, is_final, pt_row, temp, tk,
            tp, pen, pres, freq, seeds, key
        )

    def _dispatch_decode(self, k_steps, tokens, positions, active, pt, temp,
                         tk, tp, pen, pres, freq, seeds, key):
        if self._spmd:
            _send(OP_DECODE, k_steps, 0, self.spmd_index,
                  (tokens, positions, active, pt, temp, tk, tp, pen, pres,
                   freq, seeds, key),
                  self.ecfg.max_slots, self.ecfg.max_pages_per_seq)
        return super()._dispatch_decode(
            k_steps, tokens, positions, active, pt, temp, tk, tp, pen,
            pres, freq, seeds, key
        )

    def _dispatch_prefill_sp(self, T, tokens, lens, slot_ids, pt_rows,
                             temp, tk, tp, pen, pres, freq, seeds, key):
        if self._spmd:
            _send(OP_PREFILL_SP, T, 0, self.spmd_index,
                  (tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen,
                   pres, freq, seeds, key),
                  self.ecfg.max_slots, self.ecfg.max_pages_per_seq)
        return super()._dispatch_prefill_sp(
            T, tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen, pres,
            freq, seeds, key
        )

class SPMDEncoderRuntime(EncoderRuntime):
    """EncoderRuntime whose batch-encode dispatches are mirrored on every
    host (OP_ENCODE), so embedding models serve under --spmd too."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._spmd = jax.process_count() > 1
        self.spmd_index = 0

    def _dispatch_encode(self, B, bucket, tokens, lens):
        if self._spmd:
            _send(OP_ENCODE, B, bucket, self.spmd_index, (tokens, lens),
                  self.ecfg.max_slots, self.ecfg.max_pages_per_seq)
        return super()._dispatch_encode(B, bucket, tokens, lens)


class SPMDEngine:
    """Factory + lifecycle glue for the primary host: a TPUEngine whose
    generative runtimes broadcast their dispatches, rejecting what the
    worker protocol can't replay yet, and releasing workers on stop."""

    def __new__(cls, *args, **kw):
        from ollamamq_tpu.engine.engine import TPUEngine

        class _Engine(TPUEngine):
            runtime_class = SPMDModelRuntime
            encoder_runtime_class = SPMDEncoderRuntime

            def load_model(self, name, checkpoint_path=None):
                if self.ecfg.dp > 1:
                    raise NotImplementedError(
                        "dp replica serving under --spmd is not supported "
                        "yet (the worker replay protocol carries no replica "
                        "ordinal); use dp on single-host deployments"
                    )
                if self._running and jax.process_count() > 1:
                    raise NotImplementedError(
                        "runtime model load (/api/pull) is not supported "
                        "under --spmd; list all models at startup"
                    )
                super().load_model(name, checkpoint_path)
                rt = self.runtimes.get(name)
                if isinstance(rt, (SPMDModelRuntime, SPMDEncoderRuntime)):
                    rt.spmd_index = list(self.runtimes).index(name)

            def stop(self):
                super().stop()
                broadcast_shutdown()  # exactly once, after dispatches ended

        return _Engine(*args, **kw)


def run_worker(
    models,
    engine_cfg: EngineConfig,
    mesh,
    dtype=jnp.bfloat16,
    max_steps: Optional[int] = None,
) -> int:
    """Worker-host loop (process_id != 0): replay the primary's dispatches.

    `models`: {name: checkpoint_path_or_None} in the SAME order as the
    primary's --models list — the opcode header routes by that ordinal.
    Returns the number of steps executed. `max_steps` bounds the loop for
    tests; production workers run until OP_SHUTDOWN.
    """
    from ollamamq_tpu.config import get_model_config

    runtimes = []
    for name, ckpt in models.items():
        cfg = get_model_config(name)
        if cfg is None:
            raise ValueError(f"model {name} not replayable under SPMD")
        cls = SPMDEncoderRuntime if cfg.is_encoder else SPMDModelRuntime
        runtimes.append(
            cls(name, cfg, engine_cfg, mesh=mesh,
                checkpoint_path=ckpt, dtype=dtype)
        )
    steps = 0
    S = engine_cfg.max_slots
    MP = engine_cfg.max_pages_per_seq

    while max_steps is None or steps < max_steps:
        header = _bcast(np.zeros(4, np.int32))
        op = int(header[0])
        if op == OP_SHUTDOWN:
            break
        rt = runtimes[int(header[3])] if int(header[3]) < len(runtimes) else runtimes[0]
        try:
            if op == OP_PREFILL:
                bucket, B = int(header[1]), int(header[2])
                (tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen, pres,
                 freq, seeds, key_data) = _recv(op, bucket, B, S, MP)
                key = jnp.asarray(key_data, jnp.uint32)
                _, rt.kc, rt.vc, rt.recent = ModelRuntime._dispatch_prefill(
                    rt, bucket, B, tokens, lens, slot_ids, pt_rows, temp,
                    tk, tp, pen, pres, freq, seeds, key
                )
            elif op == OP_CHUNK:
                chunk = int(header[1])
                (tokens, start, cl, slot_id, is_final, pt_row, temp, tk, tp,
                 pen, pres, freq, seeds, key_data) = _recv(op, chunk, 0, S, MP)
                key = jnp.asarray(key_data, jnp.uint32)
                _, rt.kc, rt.vc, rt.recent = ModelRuntime._dispatch_chunk(
                    rt, chunk, tokens, start, cl, slot_id, is_final, pt_row,
                    temp, tk, tp, pen, pres, freq, seeds, key
                )
            elif op == OP_DECODE:
                k_steps = int(header[1])
                (tokens, positions, active, pt, temp, tk, tp, pen, pres,
                 freq, seeds, key_data) = _recv(op, k_steps, 0, S, MP)
                key = jnp.asarray(key_data, jnp.uint32)
                _, rt.kc, rt.vc, rt.recent = ModelRuntime._dispatch_decode(
                    rt, k_steps, tokens, positions, active, pt, temp, tk,
                    tp, pen, pres, freq, seeds, key
                )
            elif op == OP_PREFILL_SP:
                T = int(header[1])
                (tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen, pres,
                 freq, seeds, key_data) = _recv(op, T, 0, S, MP)
                key = jnp.asarray(key_data, jnp.uint32)
                _, rt.kc, rt.vc, rt.recent = ModelRuntime._dispatch_prefill_sp(
                    rt, T, tokens, lens, slot_ids, pt_rows, temp, tk, tp,
                    pen, pres, freq, seeds, key
                )
            elif op == OP_ENCODE:
                B, bucket = int(header[1]), int(header[2])
                tokens, lens = _recv(op, B, bucket, S, MP)
                EncoderRuntime._dispatch_encode(rt, B, bucket, tokens, lens)
            else:
                log.error("unknown opcode %d; shutting down", op)
                break
        except Exception:
            # The primary recovers from a failed step (errors the batch and
            # keeps serving); the worker must stay in lock-step with it
            # rather than die and deadlock the next broadcast.
            log.exception("worker step failed (op=%d); continuing", op)
        steps += 1
    return steps
