from ollamamq_tpu.parallel.mesh import make_mesh, AXIS_DATA, AXIS_TENSOR, AXIS_SEQ
from ollamamq_tpu.parallel.sharding import (
    param_partition_specs,
    kv_cache_spec,
    shard_params,
)
