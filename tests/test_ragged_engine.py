"""Ragged token-budget batch composition, pinned without the oracle.

PR 6 shipped the ragged path with the legacy bucketed composer kept one
release as a live byte-identity oracle; PR 8 removed that oracle as
scheduled. The guarantees the oracle used to witness are pinned here
directly:

  - ragged greedy token streams match RECORDED expectations
    (tests/data/ragged_golden.json — regenerate with
    OLLAMAMQ_REGEN_GOLDEN=1 after an intentional numerics change);
  - streams are COMPOSITION-INVARIANT: prefix cache on/off and a
    mid-prefill cancel (which reshapes every subsequent mixed dispatch)
    leave the surviving requests' streams byte-identical;
  - the journal's batch records on the ragged path report padding waste
    <= 0.10 under a synthetic overload (seed baseline on the old
    bucketed path: 0.56) with occupancy above the 0.43 baseline;
  - _bucket_for (now serving only the pp>1 pipeline prefill path)
    REFUSES oversize pieces instead of silently answering the largest
    bucket;
  - a faulted ragged dispatch retries its implicated requests (prefill
    spans AND decode rows) and the streams still finish byte-identical.
"""

import itertools
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig
from ollamamq_tpu.core import MQCore
from ollamamq_tpu.engine.engine import ModelRuntime
from ollamamq_tpu.engine.request import Request
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.telemetry.journal import (Journal, batch_stats,
                                            check_invariants)
from ollamamq_tpu.testing.faults import FaultPlan

_IDS = itertools.count(1)

PS = 8
BUCKETS = (16, 64)  # boundaries the fuzz prompts straddle
GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "ragged_golden.json")


def make_rt(**kw):
    defaults = dict(
        model="test-tiny", max_slots=4, num_pages=96, page_size=PS,
        max_pages_per_seq=16, prefill_buckets=BUCKETS, max_new_tokens=8,
        decode_steps_per_iter=2, max_batch_tokens=48, token_granule=8,
    )
    defaults.update(kw)
    rt = ModelRuntime("test-tiny", MODEL_CONFIGS["test-tiny"],
                      EngineConfig(**defaults), dtype=jnp.float32)
    rt.tokenizer.eos_id = -1  # deterministic full-length streams
    return rt


def tick(rt, core):
    """One engine-loop-shaped tick (ragged is the only single-mesh mode)."""
    ran = rt.step_ragged(core)
    if not ran and any(r is not None for r in rt.slot_req):
        rt.step_decode(core, k_steps=1)


def run_all(rt, prompts, max_tokens=6, repeat_penalty=1.0,
            cancel_mid_prefill=None, max_ticks=800):
    """Drive a batch of prompts to completion; returns each request's
    generated ids (None for a cancelled one). `cancel_mid_prefill`
    names a request index to cancel as soon as its prefill is
    partially done (0 < _chunk_pos < n)."""
    core = MQCore(None)
    reqs = []
    for p in prompts:
        req = Request(next(_IDS), f"u{len(reqs) % 3}", "test-tiny", list(p),
                      SamplingParams(max_tokens=max_tokens,
                                     repeat_penalty=repeat_penalty))
        req._inc_decode = rt.tokenizer.make_incremental_decoder()
        rt.pending_prefill.append(req)
        reqs.append(req)
    victim = (reqs[cancel_mid_prefill]
              if cancel_mid_prefill is not None else None)
    for _ in range(max_ticks):
        if victim is not None and not victim.cancelled.is_set():
            pos = getattr(victim, "_chunk_pos", 0)
            if 0 < pos < len(victim.prompt_tokens):
                victim.cancelled.set()
        if all(r.stats.finished_at for r in reqs):
            break
        tick(rt, core)
    assert all(r.stats.finished_at for r in reqs), "requests wedged"
    return [None if r is victim else list(r.generated_ids) for r in reqs]


def _fuzz_prompts(rng, n):
    """Prompt lengths hugging/straddling the bucket boundaries plus a
    few randoms — the shapes the old bucketed composer split into
    separate batches and the ragged composer packs together."""
    straddle = [b + d for b in BUCKETS for d in (-1, 0, 1)]
    lens = [straddle[int(rng.integers(len(straddle)))]
            if rng.random() < 0.6 else int(rng.integers(2, 80))
            for _ in range(n)]
    return [rng.integers(3, 500, size=max(1, L)).tolist() for L in lens]


def _golden_case(repeat_penalty):
    """The fuzz workload the recorded expectations pin: 3 rounds of 6
    boundary-straddling prompts (seed 11) per penalty setting."""
    rng = np.random.default_rng(11)
    rounds = [_fuzz_prompts(rng, 6) for _ in range(3)]
    outs = [run_all(make_rt(), prompts, repeat_penalty=repeat_penalty)
            for prompts in rounds]
    return outs


@pytest.mark.parametrize("repeat_penalty", [1.0, 1.1],
                         ids=["greedy", "repeat-penalty"])
def test_ragged_matches_recorded_expectations(repeat_penalty):
    """The oracle's replacement: the exact token streams the ragged path
    produced when the bucketed path was retired, recorded. A diff here
    means the ragged composer/jit changed NUMERICS, not just schedule —
    regenerate (OLLAMAMQ_REGEN_GOLDEN=1) only for an intentional change."""
    key = "greedy" if repeat_penalty == 1.0 else "repeat-penalty"
    outs = _golden_case(repeat_penalty)
    if os.environ.get("OLLAMAMQ_REGEN_GOLDEN"):
        data = {}
        if os.path.exists(GOLDEN):
            with open(GOLDEN) as f:
                data = json.load(f)
        data[key] = outs
        os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
        with open(GOLDEN, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        pytest.skip("golden regenerated")
    with open(GOLDEN) as f:
        expected = json.load(f)[key]
    assert outs == expected, "ragged streams drifted from recorded run"


@pytest.mark.parametrize("prefix_cache", [False, True],
                         ids=["cache-off", "cache-on"])
def test_prefix_cache_leaves_streams_identical(prefix_cache):
    """Composition invariance: the SAME prompts produce byte-identical
    streams with the prefix cache off and on (cache hits reshape every
    span the composer packs — the tokens must not care)."""
    rng = np.random.default_rng(7)
    shared = rng.integers(3, 500, size=3 * PS).tolist()
    prompts = [shared + rng.integers(3, 500, size=t).tolist()
               for t in (5, 17, 40)] + _fuzz_prompts(rng, 2)
    base = run_all(make_rt(prefix_cache=False), prompts)
    out = run_all(make_rt(prefix_cache=prefix_cache), prompts)
    assert out == base


def test_mid_prefill_cancel_leaves_survivors_identical():
    """Cancelling a long prompt mid-prefill (its spans already dispatched)
    must not perturb the other requests' streams — the survivors match a
    clean run of the same prompts exactly — and the cancelled slot's
    pages must all return to the pool."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(3, 500, size=n).tolist()
               for n in (70, 15, 33)]  # 70 spans several mixed dispatches
    clean = run_all(make_rt(), prompts)
    rt = make_rt()
    out = run_all(rt, prompts, cancel_mid_prefill=0)
    assert out[0] is None
    assert out[1:] == clean[1:]
    assert rt.alloc.used_pages == 0
    assert not rt.reserved_slots and not rt.chunking


def test_bucket_for_refuses_oversize():
    rt = make_rt()
    assert rt._bucket_for(16) == 16
    assert rt._bucket_for(17) == 64
    with pytest.raises(ValueError):
        rt._bucket_for(BUCKETS[-1] + 1)


def test_ragged_dispatch_fault_retries_and_streams_survive():
    """An injected exception in the mixed dispatch retries BOTH its
    prefill spans and its decode rows (replay semantics): every stream
    still completes, byte-identical to an unfaulted run."""
    rng = np.random.default_rng(9)
    prompts = [rng.integers(3, 500, size=n).tolist() for n in (20, 7, 35)]
    clean = run_all(make_rt(), prompts)
    # The 2nd mixed dispatch carries a prefill tail AND live decode rows,
    # so the containment path must replay both kinds.
    plan = FaultPlan([{"site": "ragged", "kind": "exception", "at": [2]}])
    rt = make_rt(retry_backoff_s=0.0)
    rt.fault_plan = plan
    faulted = run_all(rt, prompts)
    assert plan.injected == 1
    assert faulted == clean
    assert rt.retry_count >= 1


# ------------------------------------------------ padding-waste regression
def _overload_trace(n_requests=24, seed=5):
    """Synthetic overload: arrivals outpace the drain so composition
    always has a backlog to pack; returns the journal's batch stats."""
    rng = np.random.default_rng(seed)
    rt = make_rt(max_slots=4, num_pages=160,
                 max_batch_tokens=64, token_granule=8)
    journal = Journal(capacity=65536)
    rt.journal = journal
    core = MQCore(None)
    reqs = []
    issued = 0
    guard = 0
    while True:
        while issued < n_requests and len(rt.pending_prefill) < 6:
            n = int(rng.integers(5, 70))
            req = Request(next(_IDS), f"ov{issued % 4}", "test-tiny",
                          rng.integers(3, 500, size=n).tolist(),
                          SamplingParams(max_tokens=4))
            req._inc_decode = rt.tokenizer.make_incremental_decoder()
            rt.pending_prefill.append(req)
            reqs.append(req)
            issued += 1
        tick(rt, core)
        if issued >= n_requests and all(r.stats.finished_at for r in reqs):
            break
        guard += 1
        assert guard < 5000, "overload trace wedged"
    recs = journal.tail(None)
    assert not check_invariants(recs)
    return batch_stats(recs)


def test_padding_waste_gate_ragged():
    """CI gate: the ragged path's padding waste must stay <= 0.10 under
    overload (seed baseline on the retired bucketed path: 0.56), with
    batch occupancy strictly above the 0.43 baseline."""
    stats = _overload_trace()
    assert stats["batches"] > 0
    assert stats["padding_waste"] <= 0.10, stats
    assert stats["mean_occupancy"] > 0.43, stats


def test_ragged_batch_records_carry_the_split():
    """Every ragged batch record carries mode/padded_tokens and the
    prefill/decode row split the schema promises."""
    rng = np.random.default_rng(2)
    rt = make_rt()
    journal = Journal(capacity=4096)
    rt.journal = journal
    core = MQCore(None)
    run_all_rt(rt, core, rng)
    recs = journal.tail(None, kind="batch")
    assert recs, "no batch records journaled"
    for r in recs:
        assert r["mode"] == "ragged"
        assert r["padded_tokens"] >= r["tokens"]
        assert r["n_prefill"] + r["n_decode"] == r["batch_size"]
        assert r["padded_tokens"] % 8 == 0  # the granule


def run_all_rt(rt, core, rng):
    reqs = []
    for n in (20, 5, 33):
        req = Request(next(_IDS), "u", "test-tiny",
                      rng.integers(3, 500, size=n).tolist(),
                      SamplingParams(max_tokens=4))
        req._inc_decode = rt.tokenizer.make_incremental_decoder()
        rt.pending_prefill.append(req)
        reqs.append(req)
    for _ in range(400):
        if all(r.stats.finished_at for r in reqs):
            return
        tick(rt, core)
    raise AssertionError("requests wedged")
