"""Speculative multi-token decoding on the ragged path.

The load-bearing guarantees pinned here:
  - greedy streams with speculation ON are BYTE-IDENTICAL to speculation
    OFF across a randomized fuzz matrix: chaotic and repetitive (copy-
    map) generation regimes, prefix cache on and off, an injected
    mid-stream fault at the new `spec_verify` site, and preemption under
    page pressure mid-speculation — and the penalty-ring device state
    ends identical too (the ring advances by the ACCEPTED count, never
    by k);
  - accept_prefix (ops/sampling.py) answers the longest verified prefix,
    including k=0 and all-rejected;
  - PageAllocator.rollback_to releases exactly the rejected tail's
    pages, never below the shared-prefix floor, conserving
    free + used + cached == pool under randomized alloc/rollback fuzz;
  - the journal vocabulary (speculate / spec_verify / spec_rollback)
    records with explanations, the accepted <= proposed invariant is
    checked, and page conservation holds through rollback;
  - an EXPIRED request never burns a k-token verification (the deadline
    is checked before the verify span is composed — regression test);
  - the per-user auto-throttle disables speculation for users whose
    drafts keep getting rejected.
"""

import itertools
import time

import jax.numpy as jnp
import numpy as np
import pytest

from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig
from ollamamq_tpu.core import MQCore
from ollamamq_tpu.engine import kv_cache as kvc
from ollamamq_tpu.engine.engine import ModelRuntime
from ollamamq_tpu.engine.request import FinishReason, Request
from ollamamq_tpu.ops.sampling import SamplingParams, accept_prefix
from ollamamq_tpu.telemetry.journal import (Journal, check_invariants,
                                            explain)
from ollamamq_tpu.testing.faults import FaultPlan

_IDS = itertools.count(1)

PS = 8


def make_rt(spec, copy_weights=False, **kw):
    defaults = dict(
        model="test-tiny", max_slots=4, num_pages=256, page_size=PS,
        max_pages_per_seq=32, prefill_buckets=(16, 64), max_new_tokens=96,
        decode_steps_per_iter=2,
        max_batch_tokens=64, token_granule=8, spec=spec, spec_k=4,
        spec_min_accept=0.0,
    )
    defaults.update(kw)
    rt = ModelRuntime("test-tiny", MODEL_CONFIGS["test-tiny"],
                      EngineConfig(**defaults), dtype=jnp.float32)
    rt.tokenizer.eos_id = -1  # deterministic full-length streams
    if copy_weights:
        # Copy-map regime: zeroing the residual output projections makes
        # the next token a pure function of the last, so greedy
        # generation enters a cycle — the repetitive regime where
        # n-gram lookup drafts actually verify (random weights generate
        # chaos no lookup can predict).
        rt.params["layers"]["wo"] = jnp.zeros_like(rt.params["layers"]["wo"])
        rt.params["layers"]["w_down"] = jnp.zeros_like(
            rt.params["layers"]["w_down"])
    return rt


def tick(rt, core):
    """One engine-loop-shaped tick: mixed/spec dispatch, else fused."""
    ran = rt.step_ragged(core)
    if not ran and any(r is not None for r in rt.slot_req):
        rt.step_decode(core, k_steps=1)


def run_all(rt, prompts, max_tokens=48, max_ticks=4000):
    core = MQCore(None)
    reqs = []
    for i, p in enumerate(prompts):
        req = Request(next(_IDS), f"u{i % 3}", "test-tiny", list(p),
                      SamplingParams(max_tokens=max_tokens))
        req._inc_decode = rt.tokenizer.make_incremental_decoder()
        rt.pending_prefill.append(req)
        reqs.append(req)
    for _ in range(max_ticks):
        if all(r.stats.finished_at for r in reqs):
            break
        tick(rt, core)
    assert all(r.stats.finished_at for r in reqs), "requests wedged"
    return [list(r.generated_ids) for r in reqs]


def _mixed_prompts(rng, n):
    """Half repetitive patterns (repetitions the lookup can match), half
    random, lengths straddling the page/budget boundaries."""
    out = []
    for i in range(n):
        if i % 2 == 0:
            pat = rng.integers(3, 500, size=int(rng.integers(3, 8))).tolist()
            L = int(rng.integers(12, 60))
            out.append((pat * (L // len(pat) + 1))[:L])
        else:
            out.append(rng.integers(3, 500,
                                    size=int(rng.integers(4, 60))).tolist())
    return out


# ------------------------------------------------------- accept_prefix unit
def test_accept_prefix_shapes_and_cases():
    draft = jnp.asarray([[5, 6, 7, 8],
                         [5, 6, 7, 8],
                         [5, 6, 7, 8],
                         [5, 6, 7, 8]], jnp.int32)
    greedy = jnp.asarray([[5, 6, 7, 8],   # all match
                          [9, 6, 7, 8],   # first rejected
                          [5, 6, 9, 8],   # partial prefix
                          [5, 6, 7, 8]], jnp.int32)
    dlen = jnp.asarray([4, 4, 4, 2], jnp.int32)
    out = np.asarray(accept_prefix(draft, greedy, dlen))
    # Row 3: matches everywhere but only 2 drafts are valid.
    assert out.tolist() == [4, 0, 2, 2]


def test_accept_prefix_k0_and_all_rejected():
    empty = jnp.zeros((3, 0), jnp.int32)
    assert np.asarray(accept_prefix(empty, empty,
                                    jnp.zeros(3, jnp.int32))).tolist() \
        == [0, 0, 0]
    draft = jnp.asarray([[1, 2, 3]], jnp.int32)
    greedy = jnp.asarray([[4, 5, 6]], jnp.int32)
    assert np.asarray(accept_prefix(draft, greedy,
                                    jnp.asarray([3]))).tolist() == [0]


def test_accept_prefix_match_after_mismatch_does_not_count():
    draft = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    greedy = jnp.asarray([[1, 9, 3, 4]], jnp.int32)  # 3,4 match but gap at 2
    assert np.asarray(accept_prefix(draft, greedy,
                                    jnp.asarray([4]))).tolist() == [1]


# ------------------------------------------------------ allocator rollback
def test_rollback_to_frees_rejected_tail_only():
    a = kvc.PageAllocator(32, 8, 16)
    pages = a.alloc(8 * 5)  # 5 pages = 40 token positions
    assert len(pages) == 5
    freed = a.rollback_to(pages, kv_len=18)  # needs 3 pages
    assert freed == 2 and len(pages) == 3
    assert a.free_pages + a.used_pages + a.cached_pages == a.num_pages - 1
    # Already-tight allocations are a no-op.
    assert a.rollback_to(pages, kv_len=24) == 0


def test_rollback_to_never_drops_below_shared_floor():
    a = kvc.PageAllocator(32, 8, 16)
    pages = a.alloc(8 * 4)
    # Pretend the first 3 pages are shared prefix-tree pages: even a
    # kv_len of 1 (1 page needed) must keep them.
    freed = a.rollback_to(pages, kv_len=1, keep=3)
    assert freed == 1 and len(pages) == 3


def test_rollback_fuzz_conserves_pages():
    rng = np.random.default_rng(13)
    a = kvc.PageAllocator(64, 8, 32)
    live = []
    for _ in range(300):
        op = rng.random()
        if op < 0.45 or not live:
            n = int(rng.integers(1, 80))
            pages = a.alloc(n)
            if pages is not None:
                live.append((pages, n))
        elif op < 0.8:
            i = int(rng.integers(len(live)))
            pages, n = live[i]
            new_len = int(rng.integers(1, n + 1))
            a.rollback_to(pages, new_len)
            live[i] = (pages, new_len)
        else:
            pages, _ = live.pop(int(rng.integers(len(live))))
            a.free(pages)
        assert a.free_pages + a.used_pages + a.cached_pages \
            == a.num_pages - 1
    for pages, _ in live:
        a.free(pages)
    assert a.used_pages == 0


# ------------------------------------------------------------ the proposer
def test_proposer_matches_repeated_pattern():
    rt = make_rt(True)
    pat = [11, 22, 33, 44, 55]
    req = Request(next(_IDS), "u", "test-tiny", (pat * 6)[:28],
                  SamplingParams(max_tokens=32))
    rt.slot_req[0] = req
    rt.seq_lens[0] = 28
    drafts = rt._propose_drafts(req, 0)
    # Trailing 3-gram of pat*6[:28] recurs one period earlier; the
    # proposal continues the pattern.
    assert drafts == list((pat * 7)[28:28 + 4])
    rt.slot_req[0] = None


def test_proposer_respects_remaining_budget_and_novel_context():
    rt = make_rt(True)
    pat = [7, 8, 9]
    req = Request(next(_IDS), "u", "test-tiny", pat * 5,
                  SamplingParams(max_tokens=3))
    req.generated_ids = [100, 101]  # 2 of 3 emitted: 0 budget for drafts
    rt.slot_req[0] = req
    rt.seq_lens[0] = 17
    assert rt._propose_drafts(req, 0) == []
    novel = Request(next(_IDS), "u", "test-tiny", list(range(3, 40)),
                    SamplingParams(max_tokens=32))
    rt.slot_req[1] = novel
    rt.seq_lens[1] = 37
    assert rt._propose_drafts(novel, 1) == []  # nothing repeats
    rt.slot_req[0] = rt.slot_req[1] = None


# ------------------------------------------- byte-identical stream fuzzing
@pytest.mark.parametrize("regime", ["chaotic", "copy"])
def test_spec_on_off_byte_identical_fuzz(regime):
    rng = np.random.default_rng(17)
    copy = regime == "copy"
    for round_ in range(2):
        # At most max_slots prompts: with more, which slot the overflow
        # request lands on depends on finish ORDER in wall ticks (which
        # speculation legitimately changes), and the final ring rows
        # would compare across different occupants.
        prompts = _mixed_prompts(rng, 4)
        off_rt = make_rt(False, copy_weights=copy)
        on_rt = make_rt(True, copy_weights=copy)
        off = run_all(off_rt, prompts)
        on = run_all(on_rt, prompts)
        assert off == on, f"{regime} round {round_}: streams diverged"
        # Ring state must match too: the spec path's penalty ring
        # advances by the accepted count, so the device state after the
        # run is indistinguishable from single-token stepping. (Rows
        # 0..S-1 only: the trash row collects padding garbage.)
        S = off_rt.ecfg.max_slots
        assert np.array_equal(np.asarray(off_rt.recent)[:S],
                              np.asarray(on_rt.recent)[:S])
        assert on_rt.alloc.used_pages == 0
        if copy:
            assert on_rt.spec_accepted > 0, "copy regime accepted nothing"


@pytest.mark.parametrize("prefix_cache", [False, True],
                         ids=["cache-off", "cache-on"])
def test_spec_on_off_identical_with_prefix_cache(prefix_cache):
    rng = np.random.default_rng(23)
    shared = rng.integers(3, 500, size=3 * PS).tolist()
    prompts = [shared + rng.integers(3, 500, size=t).tolist()
               for t in (5, 17, 30)]
    off = run_all(make_rt(False, copy_weights=True,
                          prefix_cache=prefix_cache), prompts)
    on_rt = make_rt(True, copy_weights=True, prefix_cache=prefix_cache)
    on = run_all(on_rt, prompts)
    assert off == on
    assert on_rt.alloc.used_pages == 0


def test_spec_verify_fault_retries_and_streams_survive():
    """An injected exception at the spec_verify site (a mixed dispatch
    carrying verify spans) retries its implicated rows with replay
    semantics: every stream completes byte-identical to unfaulted."""
    rng = np.random.default_rng(29)
    prompts = _mixed_prompts(rng, 4)
    clean = run_all(make_rt(True, copy_weights=True), prompts)
    plan = FaultPlan([{"site": "spec_verify", "kind": "exception",
                       "at": [2]}])
    rt = make_rt(True, copy_weights=True, retry_backoff_s=0.0)
    rt.fault_plan = plan
    faulted = run_all(rt, prompts)
    assert plan.injected == 1
    assert faulted == clean
    assert rt.retry_count >= 1


def test_preemption_during_speculation_resumes_byte_identical():
    """Page pressure mid-speculation: a tiny pool forces decode-time
    extends to fail while slots are actively speculating, driving the
    preempt-with-recompute path. Streams must still finish identical to
    an unpressured spec-off run, and the pool must balance after."""
    rng = np.random.default_rng(31)
    prompts = _mixed_prompts(rng, 4)
    baseline = run_all(make_rt(False, copy_weights=True), prompts,
                       max_tokens=32)
    rt = make_rt(True, copy_weights=True, num_pages=20, retry_backoff_s=0.0)

    def requeue(req):
        rt.pending_prefill.appendleft(req)
        return True

    rt.on_preempt = requeue
    pressured = run_all(rt, prompts, max_tokens=32, max_ticks=8000)
    assert pressured == baseline
    assert rt.preempt_count > 0, "pool never pressured: test is vacuous"
    assert rt.alloc.used_pages == 0
    assert rt.alloc.free_pages + rt.alloc.cached_pages \
        == rt.alloc.num_pages - 1


# ------------------------------------------------------- deadline bugfix
def test_expired_request_never_burns_a_verify_span():
    """Regression (satellite): the deadline must be checked BEFORE a
    speculative verify span is composed — an expired request drops with
    the explicit deadline reason instead of paying k verify tokens."""
    rt = make_rt(True)
    journal = Journal(capacity=4096)
    rt.journal = journal
    # Force a proposal whenever asked: if the deadline check were
    # missing, the speculate record below would exist.
    rt._propose_drafts = lambda req, slot: [1, 2, 3]
    core = MQCore(None)
    req = Request(next(_IDS), "dl", "test-tiny",
                  list(range(3, 20)), SamplingParams(max_tokens=32))
    req._inc_decode = rt.tokenizer.make_incremental_decoder()
    rt.pending_prefill.append(req)
    while not any(r is req for r in rt.slot_req):
        tick(rt, core)
    req.deadline = time.monotonic() - 1.0  # expired mid-decode
    tick(rt, core)
    assert req.stats.finished_at, "expired request kept decoding"
    items = [i for i in req.stream.drain() if i.kind in ("done", "error")]
    assert items and items[-1].finish_reason == FinishReason.DEADLINE
    recs = journal.tail(None)
    assert not [r for r in recs if r["kind"] == "speculate"
                and r.get("req_id") == req.req_id], \
        "a verify span was composed for an expired request"
    assert [r for r in recs if r["kind"] == "deadline_drop"
            and r.get("req_id") == req.req_id]
    assert rt.alloc.used_pages == 0


# ------------------------------------------------- journal + invariants
def test_spec_journal_records_explain_and_invariants():
    rng = np.random.default_rng(37)
    rt = make_rt(True, copy_weights=True)
    journal = Journal(capacity=65536)
    rt.journal = journal
    core = MQCore(None)
    reqs = []
    for p in _mixed_prompts(rng, 4):
        req = Request(next(_IDS), "ju", "test-tiny", p,
                      SamplingParams(max_tokens=32))
        req._inc_decode = rt.tokenizer.make_incremental_decoder()
        rt.pending_prefill.append(req)
        reqs.append(req)
    for _ in range(4000):
        if all(r.stats.finished_at for r in reqs):
            break
        tick(rt, core)
    assert all(r.stats.finished_at for r in reqs)
    recs = journal.tail(None)
    spec = [r for r in recs if r["kind"] == "speculate"]
    verify = [r for r in recs if r["kind"] == "spec_verify"]
    assert spec and verify, "speculation never journaled"
    assert all(v["accepted"] <= v["proposed"] for v in verify)
    for r in spec + verify:
        assert explain(r)  # every kind has human text
    batches = [r for r in recs if r["kind"] == "batch"
               and r.get("n_spec")]
    assert batches, "no batch record carried the spec split"
    assert all("spec_accepted" in r and "spec_tokens" in r
               for r in batches)
    # Page conservation holds through speculative alloc/rollback, and
    # every other invariant stays clean under speculation.
    assert check_invariants(recs) == []
    # Rollback records, when present, carry the full page post-state.
    for r in recs:
        if r["kind"] == "spec_rollback":
            assert r["kv_after"] <= r["kv_before"]
            assert r["free"] + r["used"] + r["cached"] == r["pool"]
            assert explain(r)


def test_invariant_checker_flags_accepted_over_proposed():
    bad = [{"seq": 0, "kind": "spec_verify", "req_id": 1, "slot": 0,
            "proposed": 2, "accepted": 3}]
    out = check_invariants(bad)
    assert out and "accepted 3 > proposed 2" in out[0]


def test_spec_metrics_and_stats_surface():
    from ollamamq_tpu.telemetry import schema as tm

    rng = np.random.default_rng(41)
    rt = make_rt(True, copy_weights=True)
    base = tm.SPEC_TOKENS_TOTAL.labels(model="test-tiny",
                                       outcome="proposed").value
    run_all(rt, _mixed_prompts(rng, 3), max_tokens=32)
    assert rt.spec_proposed > 0
    assert tm.SPEC_TOKENS_TOTAL.labels(model="test-tiny",
                                       outcome="proposed").value > base
    s = rt.stats()["spec"]
    assert s is not None
    assert s["proposed"] == rt.spec_proposed
    assert 0.0 <= s["accept_rate"] <= 1.0
    off = make_rt(False)
    assert off.stats()["spec"] is None


# ------------------------------------------------------- auto-throttle
def test_auto_throttle_disables_hopeless_users():
    rng = np.random.default_rng(43)
    rt = make_rt(True, spec_min_accept=0.5)
    rt.SPEC_THROTTLE_SAMPLE = 8  # shrink the warmup for the test
    journal = Journal(capacity=65536)
    rt.journal = journal
    # Garbage drafts: essentially always rejected, so the user's accept
    # rate pins near 0 and the throttle must fire.
    rt._propose_drafts = lambda req, slot: [2, 2, 2, 2]
    prompts = [rng.integers(3, 500, size=12).tolist() for _ in range(2)]
    core = MQCore(None)
    reqs = []
    for p in prompts:
        req = Request(next(_IDS), "hopeless", "test-tiny", p,
                      SamplingParams(max_tokens=48))
        req._inc_decode = rt.tokenizer.make_incremental_decoder()
        rt.pending_prefill.append(req)
        reqs.append(req)
    for _ in range(4000):
        if all(r.stats.finished_at for r in reqs):
            break
        tick(rt, core)
    assert all(r.stats.finished_at for r in reqs)
    assert "hopeless" in rt._spec_throttled
    # After the throttle fired, no further speculate records appear.
    recs = journal.tail(None)
    throttle_seq = max(r["seq"] for r in recs if r["kind"] == "spec_verify")
    late = [r for r in recs if r["kind"] == "speculate"
            and r["seq"] > throttle_seq]
    assert late == []


# --------------------------------------------------- fake engine + wire
def test_fake_runtime_journals_speculation_with_identical_stream():
    from ollamamq_tpu.engine.fake import FakeRuntime

    def drive(spec):
        ecfg = EngineConfig(model="test-tiny", spec=spec, spec_k=3)
        rt = FakeRuntime("test-tiny", ecfg)
        journal = Journal(capacity=4096)
        rt.journal = journal
        core = MQCore(None)
        req = Request(next(_IDS), "fk", "test-tiny", [1, 2, 3],
                      SamplingParams(max_tokens=10))
        rt.submit(req)
        for _ in range(64):
            if req.stats.finished_at:
                break
            rt.step(core)
        assert req.stats.finished_at
        text = "".join(i.text for i in req.stream.drain()
                       if i.kind == "token")
        return text, journal.tail(None)

    text_off, _ = drive(False)
    text_on, recs = drive(True)
    assert text_on == text_off  # stream content identical, pacing apart
    assert [r for r in recs if r["kind"] == "speculate"]
    assert [r for r in recs if r["kind"] == "spec_verify"]
    assert check_invariants(recs) == []


def test_op_spec_payload_roundtrip():
    """OP_SPEC's wire payload (the RAGGED payload + is_spec) packs and
    unpacks byte-exact — the worker decodes what the primary sent."""
    from ollamamq_tpu.engine.spmd import (OP_SPEC, _pack_payload,
                                          _unpack_payload, payload_spec)

    rng = np.random.default_rng(47)
    S, MP, W, T = 4, 8, 16, 24
    spec = payload_spec(OP_SPEC, T, 3, S, MP, W)
    values = []
    for shape, dt in spec:
        if np.dtype(dt) == np.uint32:
            values.append(rng.integers(0, 2**32, size=shape,
                                       dtype=np.uint32))
        elif np.dtype(dt) == np.float32:
            values.append(rng.random(shape).astype(np.float32))
        else:
            values.append(rng.integers(0, 100, size=shape).astype(dt))
    raw = _pack_payload([np.asarray(v, dt) for v, (_, dt)
                        in zip(values, spec)])
    out = _unpack_payload(raw, spec)
    assert len(out) == len(values)
    for a, b in zip(values, out):
        assert np.array_equal(np.asarray(a), b)
