"""Test config: force JAX onto CPU with 8 virtual devices BEFORE jax import,
so mesh/sharding logic is exercised without a TPU (SURVEY.md §4)."""

import os

# Force CPU even if the shell exports a TPU platform (e.g. JAX_PLATFORMS=axon).
# A sitecustomize may already have imported jax and registered a TPU plugin,
# so setting the env var alone is not enough — use jax.config as well.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_cfg():
    from ollamamq_tpu.config import MODEL_CONFIGS

    return MODEL_CONFIGS["test-tiny"]


@pytest.fixture(scope="session")
def tiny_params(tiny_cfg):
    import jax
    import jax.numpy as jnp
    from ollamamq_tpu.models import llama

    return llama.init_params(tiny_cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
