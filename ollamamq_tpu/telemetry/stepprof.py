"""Engine step profiler: the engine-hot-loop twin of the router's
always-on overhead plane (PR 14).

Every dispatch the engine makes — ragged mixed batch, pure-decode fused
scan, speculative verify, embed batch, FakeRuntime step; python, fake,
and SPMD-primary alike — records ONE schema'd sample into a bounded
ring: where that step's milliseconds went (`host_prep` → `dispatch` →
`collect` → `detok`, the CLOSED phase vocabulary below), under which
compiled shape (`(mode, T_pad, k_cap)`), over how many real vs padded
token positions, and whether the step paid a fresh XLA compile. The
same samples feed `ollamamq_step_phase_ms{phase,mode}` histograms, a
rolling per-shape p50/p99 table, `/debug/stepprof`, the TUI `compiles`
chip, and the `step_profile` block bench.py embeds in every BENCH
record (what `scripts/bench_compare.py` diffs across rounds).

Dependency-free (stdlib only — no jax, no numpy) like the rest of
`telemetry/`, so scripts/check_metrics_docs.py can import the phase
vocabulary in CI and bench's error path can always attach a summary.

Contracts the tests pin:

  * Phases are contiguous deltas between marks of one monotonic timer,
    so a sample's phase milliseconds sum EXACTLY to its recorded step
    wall clock — and instrumentation covers ≥95% of the measured
    dispatch wall (the 5% acceptance gate is coverage, not arithmetic).
  * The ring, the per-shape table, the compile-event ring, and the HBM
    timeline are all bounded — always-on means O(1) memory forever.
  * Self-overhead is metered: every profiler entry point times itself
    (perf_counter_ns) and `overhead_fraction()` must stay under 1% of
    profiled step time.
  * Compile events are recorded by the jit-getter seams exactly once
    per cache key (jax.jit traces+compiles synchronously on the first
    call of a fresh cache entry — timing that first call IS the compile
    wall); a recompile loop (ladder bug, pallas-probe thrash, injected
    `compile` fault) shows up as a climbing `rate_per_min` and trips
    the health monitor's `compile_storm` alert after warmup.

Module-global `PROFILER` (same pattern as metrics.REGISTRY): the
engine, FakeRuntime, and bench feed it; the server and TUI read it;
tests call `PROFILER.reset()` for isolation.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ollamamq_tpu.telemetry import schema as tm

# CLOSED phase vocabulary for ollamamq_step_phase_ms{phase} — pinned to
# the README "Engine performance plane" table by
# scripts/check_metrics_docs.py (gate 6). A new timed region of the
# dispatch path means a new entry HERE first.
PHASES = (
    "host_prep",   # python-side batch composition: admission bookkeeping,
    #                token/slot array builds, device_put staging — ends at
    #                the jit call
    "dispatch",    # issuing the jit'd computation: trace + XLA compile on
    #                a fresh cache key (the `compiled` flag), else just
    #                enqueue — returns with device arrays still in flight
    "collect",     # device wait + D2H materialization (np.asarray /
    #                block_until_ready) — on the split decode path this
    #                spans dispatch-issue to collect, i.e. the device
    #                compute the engine overlapped with other work
    "detok",       # host-side emit loop: sampling bookkeeping, detokenize,
    #                stream writes, per-request finish handling
)

# Step modes (the `mode` label + the first element of the shape key).
# Not a validation gate — a sample carries whatever the engine said —
# but the set the engine emits today, for readers.
MODES = ("ragged", "spec_verify", "decode", "embed", "fake")

_RING = 2048          # sample ring (like --journal-ring's default)
_SHAPE_KEYS = 64      # distinct (mode, T_pad, k_cap) keys kept
_SHAPE_WINDOW = 256   # rolling per-shape totals window
_COMPILE_RING = 256   # compile-event ring
_HBM_RING = 512       # HBM/allocator timeline ring
_RATE_WINDOW_S = 60.0  # compile-rate lookback


def _pctl(window, q: float) -> Optional[float]:
    if not window:
        return None
    s = sorted(window)
    return s[min(len(s) - 1, int(q * len(s)))]


class StepTimer:
    """One step's phase clock. `mark(phase)` charges everything since
    the previous mark to `phase`; `finish(**fields)` records the sample
    (or never call it — an abandoned timer leaves no trace, which is
    exactly what a faulted/preempted dispatch should leave). Phases may
    be marked more than once (chunked host prep); deltas accumulate."""

    __slots__ = ("_prof", "mode", "_t0", "_last", "phases", "_done")

    def __init__(self, prof: "StepProfiler", mode: str):
        self._prof = prof
        self.mode = mode
        self._t0 = time.perf_counter()
        self._last = self._t0
        self.phases: Dict[str, float] = {}
        self._done = False

    def mark(self, phase: str) -> None:
        t = time.perf_counter()
        self.phases[phase] = self.phases.get(phase, 0.0) + (t - self._last) * 1e3
        self._last = t
        # Self-overhead: the mark itself (two clock reads + a dict op).
        self._prof._overhead_ns += time.perf_counter_ns() - int(t * 1e9)

    def finish(self, **fields) -> Optional[dict]:
        if self._done:  # double-finish is a bug upstream; stay silent
            return None
        self._done = True
        t = time.perf_counter()
        # The step ends at its LAST mark: total is then the exact sum of
        # the phase deltas (one contiguous chain from _t0), and the
        # microseconds between that mark and this call — argument
        # evaluation at the finish() call site — are profiler overhead,
        # not step time.
        total_ms = (self._last - self._t0) * 1e3
        sample = {
            "ts": time.time(),
            "mode": self.mode,
            "total_ms": round(total_ms, 4),
        }
        for ph in PHASES:
            sample[ph + "_ms"] = round(self.phases.get(ph, 0.0), 4)
        sample.update(fields)
        self._prof._record(sample, total_ms)
        self._prof._overhead_ns += time.perf_counter_ns() - int(t * 1e9)
        return sample


class StepProfiler:
    """Always-on bounded-ring step profiler + compile ledger + HBM
    timeline. Thread-safe: runtimes append from the engine loop while
    HTTP readers snapshot."""

    def __init__(self, ring: int = _RING):
        self._lock = threading.Lock()
        self._ring_n = ring
        self._overhead_ns = 0  # time spent inside profiler calls
        self._reset_locked()

    def _reset_locked(self) -> None:
        self.samples: deque = deque(maxlen=self._ring_n)
        self.seq = 0
        self._step_ns = 0      # profiled step wall time (denominator)
        self._overhead_ns = 0
        # (mode, T_pad, k_cap) -> deque of total_ms; insertion-ordered so
        # the oldest shape key is evicted when the table fills.
        self._shapes: Dict[Tuple, deque] = {}
        self._phase_sum: Dict[Tuple[str, str], float] = {}
        self._tokens = 0
        self._padded = 0
        self.compiles: deque = deque(maxlen=_COMPILE_RING)
        self.compile_seq = 0
        self._compile_ts: deque = deque(maxlen=_COMPILE_RING)
        self.hbm: deque = deque(maxlen=_HBM_RING)

    def reset(self) -> None:
        with self._lock:
            self._reset_locked()

    # -- step samples ------------------------------------------------------
    def start(self, mode: str) -> StepTimer:
        return StepTimer(self, mode)

    def _record(self, sample: dict, total_ms: float) -> None:
        t0 = time.perf_counter_ns()
        key = (sample["mode"], sample.get("T_pad", 0), sample.get("k_cap", 0))
        with self._lock:
            self.seq += 1
            sample["seq"] = self.seq
            self.samples.append(sample)
            self._step_ns += int(total_ms * 1e6)
            win = self._shapes.get(key)
            if win is None:
                while len(self._shapes) >= _SHAPE_KEYS:  # bounded key table
                    self._shapes.pop(next(iter(self._shapes)))
                win = self._shapes[key] = deque(maxlen=_SHAPE_WINDOW)
            win.append(total_ms)
            mode = sample["mode"]
            for ph in PHASES:
                v = sample.get(ph + "_ms", 0.0)
                if v:
                    self._phase_sum[(mode, ph)] = \
                        self._phase_sum.get((mode, ph), 0.0) + v
            self._tokens += int(sample.get("tokens", 0) or 0)
            self._padded += int(sample.get("padded_tokens", 0) or 0)
        for ph in PHASES:
            v = sample.get(ph + "_ms", 0.0)
            if v:
                tm.STEP_PHASE_MS.labels(phase=ph, mode=sample["mode"]) \
                    .observe(v)
        self._overhead_ns += time.perf_counter_ns() - t0

    # -- compile ledger ----------------------------------------------------
    def record_compile(self, site: str, key, wall_ms: float,
                       cache_size: int) -> dict:
        t0 = time.perf_counter_ns()
        ev = {
            "ts": time.time(),
            "site": site,
            "key": str(key),
            "wall_ms": round(wall_ms, 3),
            "cache_size": cache_size,
        }
        with self._lock:
            self.compile_seq += 1
            ev["seq"] = self.compile_seq
            self.compiles.append(ev)
            self._compile_ts.append(time.monotonic())
        tm.COMPILE_TOTAL.labels(site=site).inc()
        tm.COMPILE_MS.observe(wall_ms)
        self._overhead_ns += time.perf_counter_ns() - t0
        return ev

    def compile_count(self) -> int:
        with self._lock:
            return self.compile_seq

    def compile_rate_per_min(self, window_s: float = _RATE_WINDOW_S) -> float:
        """Recompiles per minute over the trailing window — the health
        monitor's compile_storm input. A full ladder warmup is a burst
        that ages out of the window; a storm doesn't."""
        now = time.monotonic()
        with self._lock:
            n = sum(1 for t in self._compile_ts if now - t <= window_s)
        return n * 60.0 / window_s if window_s > 0 else 0.0

    # -- HBM / allocator timeline ------------------------------------------
    def hbm_record(self, sample: dict) -> None:
        t0 = time.perf_counter_ns()
        sample.setdefault("ts", time.time())
        with self._lock:
            self.hbm.append(sample)
        self._overhead_ns += time.perf_counter_ns() - t0

    def hbm_tail(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self.hbm)
        return out[-n:] if n else out

    # -- readers -----------------------------------------------------------
    def overhead_fraction(self) -> float:
        """Profiler-internal time / profiled step wall time. The <1%
        always-on budget; 0.0 before any sample."""
        with self._lock:
            if self._step_ns <= 0:
                return 0.0
            return self._overhead_ns / self._step_ns

    def shape_table(self) -> List[dict]:
        with self._lock:
            items = [(k, list(w)) for k, w in self._shapes.items()]
        out = []
        for (mode, t_pad, k_cap), win in items:
            out.append({
                "mode": mode, "T_pad": t_pad, "k_cap": k_cap,
                "n": len(win),
                "p50_ms": round(_pctl(win, 0.50) or 0.0, 4),
                "p99_ms": round(_pctl(win, 0.99) or 0.0, 4),
            })
        out.sort(key=lambda r: -r["n"])
        return out

    def phase_summary(self) -> Dict[str, Dict[str, dict]]:
        """Per-mode, per-phase p50/p99 milliseconds over the ring."""
        with self._lock:
            ring = list(self.samples)
        by_mode: Dict[str, Dict[str, list]] = {}
        for s in ring:
            m = by_mode.setdefault(s["mode"], {ph: [] for ph in PHASES})
            for ph in PHASES:
                m[ph].append(s.get(ph + "_ms", 0.0))
        out: Dict[str, Dict[str, dict]] = {}
        for mode, per in by_mode.items():
            out[mode] = {}
            for ph, vals in per.items():
                out[mode][ph] = {
                    "p50_ms": round(_pctl(vals, 0.50) or 0.0, 4),
                    "p99_ms": round(_pctl(vals, 0.99) or 0.0, 4),
                }
            totals = [s["total_ms"] for s in ring if s["mode"] == mode]
            out[mode]["step"] = {
                "n": len(totals),
                "p50_ms": round(_pctl(totals, 0.50) or 0.0, 4),
                "p99_ms": round(_pctl(totals, 0.99) or 0.0, 4),
            }
        return out

    def padding_waste(self) -> float:
        with self._lock:
            if self._padded <= 0:
                return 0.0
            return max(0.0, 1.0 - self._tokens / self._padded)

    def step_p99_ms(self) -> Optional[float]:
        with self._lock:
            totals = [s["total_ms"] for s in self.samples]
        return _pctl(totals, 0.99)

    def window(self, t0: float, t1: float) -> List[dict]:
        """Ring slice by wall-clock timestamp — links a /debug/profile
        capture window to the step samples taken during it."""
        with self._lock:
            return [s for s in self.samples if t0 <= s["ts"] <= t1]

    def tail(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self.samples)
        return out[-n:] if n else out

    def brief(self) -> Optional[dict]:
        """TUI chip payload: `compiles N · step p99 X ms`."""
        p99 = self.step_p99_ms()
        n = self.compile_count()
        if p99 is None and n == 0:
            return None
        out = {"compiles": n}
        if p99 is not None:
            out["p99_ms"] = round(p99, 3)
        return out

    def summary(self) -> dict:
        """The bench `step_profile` block / bundle section: per-mode
        phase p50/p99, compile count + rate, padding waste, overhead."""
        return {
            "samples": self.seq,
            "modes": self.phase_summary(),
            "compiles": self.compile_count(),
            "compile_rate_per_min": round(self.compile_rate_per_min(), 3),
            "padding_waste": round(self.padding_waste(), 4),
            "overhead_fraction": round(self.overhead_fraction(), 6),
        }

    def snapshot(self, n: int = 128) -> dict:
        """/debug/stepprof payload."""
        with self._lock:
            compiles = list(self.compiles)
        return {
            "summary": self.summary(),
            "shapes": self.shape_table(),
            "recent": self.tail(n),
            "compile_events": compiles[-n:],
            "hbm_samples": len(self.hbm),
        }


# THE process-wide profiler (metrics.REGISTRY pattern): engine + fake +
# bench write, server/TUI read, tests reset().
PROFILER = StepProfiler()
