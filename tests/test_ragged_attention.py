"""Ragged mixed-batch attention: jnp reference vs blockwise vs the
Pallas kernel (interpret mode on CPU), and forward_ragged vs the
bucketed forward composition it replaces."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollamamq_tpu.config import MODEL_CONFIGS
from ollamamq_tpu.engine import kv_cache as kvc
from ollamamq_tpu.models import llama
from ollamamq_tpu.ops.attention import (ragged_paged_attention,
                                        ragged_paged_attention_blockwise)
from ollamamq_tpu.ops.pallas.ragged_attention import (
    ragged_paged_attention_pallas)


def _case(spans, B, PS=8, MP=8, Hk=2, H=4, hd=16, seed=0):
    """Build one ragged batch: spans = [(q_len, kv_len), ...] laid out
    contiguously in stream order; trailing rows of B are padding."""
    rng = np.random.default_rng(seed)
    T = sum(s for s, _ in spans)
    S = (MP * B + 2) * PS
    q = jnp.asarray(rng.normal(size=(T, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(S, Hk, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(S, Hk, hd)), jnp.float32)
    pt = np.zeros((B, MP), np.int32)
    nxt = 1
    q_start = np.full(B, T, np.int32)
    q_len = np.zeros(B, np.int32)
    kv_len = np.zeros(B, np.int32)
    tok_seq = np.zeros(T, np.int32)
    tok_pos = np.full(T, -1, np.int32)
    off = 0
    for i, (ql, kv) in enumerate(spans):
        need = -(-kv // PS)
        pt[i, :need] = range(nxt, nxt + need)
        nxt += need
        q_start[i] = off
        q_len[i] = ql
        kv_len[i] = kv
        tok_seq[off:off + ql] = i
        tok_pos[off:off + ql] = np.arange(kv - ql, kv)
        off += ql
    return (q, k, v, jnp.asarray(pt), jnp.asarray(tok_seq),
            jnp.asarray(tok_pos), jnp.asarray(kv_len),
            jnp.asarray(q_start), jnp.asarray(q_len), PS)


MIXED_CASES = [
    # prefill span + decode rows + prefill tail, non-multiple-of-8 total
    dict(spans=[(11, 11), (1, 20), (5, 29), (1, 1)], B=6),
    # a whole tile of pure decode rows crossing a tile boundary
    dict(spans=[(1, 5 + 3 * i) for i in range(9)], B=10),
    # one long prefill spanning several tiles + mixed tail
    dict(spans=[(21, 21), (1, 9), (1, 17), (3, 30)], B=6),
]


@pytest.mark.parametrize("case", MIXED_CASES)
def test_blockwise_matches_reference(case):
    q, k, v, pt, tok_seq, tok_pos, kv_len, _qs, _ql, PS = _case(**case)
    ref = ragged_paged_attention(q, k, v, pt, tok_seq, tok_pos, kv_len, PS)
    blk = ragged_paged_attention_blockwise(
        q, k, v, pt, tok_seq, tok_pos, kv_len, PS, block_pages=2)
    np.testing.assert_allclose(np.asarray(blk), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("case", MIXED_CASES)
def test_pallas_matches_reference(case):
    q, k, v, pt, tok_seq, tok_pos, kv_len, qs, ql, PS = _case(**case)
    ref = ragged_paged_attention(q, k, v, pt, tok_seq, tok_pos, kv_len, PS)
    out = ragged_paged_attention_pallas(q, k, v, pt, qs, ql, kv_len, PS,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pallas_mqa_and_group1():
    for Hk, H in ((1, 4), (4, 4)):
        q, k, v, pt, tok_seq, tok_pos, kv_len, qs, ql, PS = _case(
            spans=[(6, 6), (1, 12)], B=3, Hk=Hk, H=H, seed=2)
        ref = ragged_paged_attention(q, k, v, pt, tok_seq, tok_pos,
                                     kv_len, PS)
        out = ragged_paged_attention_pallas(q, k, v, pt, qs, ql, kv_len,
                                            PS, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_forward_ragged_matches_bucketed_composition(tiny_cfg, tiny_params):
    """ONE mixed forward_ragged dispatch (decode row for seq A + full
    prefill span for seq B) must reproduce the bucketed composition
    forward_decode(A) then forward_prefill(B): same logits, same cache
    writes, same greedy argmax."""
    cfg, params = tiny_cfg, tiny_params
    PS, MP = 8, 8
    shape = (cfg.num_layers, 64 * PS, cfg.num_kv_heads, cfg.head_dim)
    rng = np.random.default_rng(3)
    a = kvc.PageAllocator(64, PS, MP)
    pagesA, pagesB = a.alloc(12), a.alloc(6)
    ptA = kvc.make_page_table_row(pagesA, MP)
    ptB = kvc.make_page_table_row(pagesB, MP)
    promptA = rng.integers(1, cfg.vocab_size, size=11).astype(np.int32)
    promptB = rng.integers(1, cfg.vocab_size, size=5).astype(np.int32)

    def prefill_a():
        kc = jnp.zeros(shape, jnp.float32)
        vc = jnp.zeros(shape, jnp.float32)
        _, kc, vc = llama.forward_prefill(
            params, cfg, jnp.asarray(promptA)[None], jnp.array([11]),
            kc, vc, jnp.asarray(ptA)[None], PS)
        return kc, vc

    kc, vc = prefill_a()
    logA_ref, kc_ref, vc_ref = llama.forward_decode(
        params, cfg, jnp.array([7], jnp.int32), jnp.array([11], jnp.int32),
        kc, vc, jnp.asarray(ptA)[None], PS, attn_impl="jnp")
    logB_ref, kc_ref, _ = llama.forward_prefill(
        params, cfg, jnp.asarray(promptB)[None], jnp.array([5]),
        kc_ref, vc_ref, jnp.asarray(ptB)[None], PS)

    kc2, vc2 = prefill_a()
    tokens = np.concatenate([[7], promptB]).astype(np.int32)
    tok_seq = np.array([0] + [1] * 5, np.int32)
    tok_pos = np.array([11, 0, 1, 2, 3, 4], np.int32)
    pt = np.stack([ptA, ptB])
    ws = np.array([pt[s][p // PS] * PS + p % PS
                   for s, p in zip(tok_seq, tok_pos)], np.int32)
    logits, kc2, _ = llama.forward_ragged(
        params, cfg, jnp.asarray(tokens), jnp.asarray(tok_seq),
        jnp.asarray(tok_pos), jnp.asarray(ws),
        jnp.asarray(np.array([0, 5], np.int32)), kc2, vc2,
        jnp.asarray(pt), jnp.asarray(np.array([0, 1], np.int32)),
        jnp.asarray(np.array([1, 5], np.int32)),
        jnp.asarray(np.array([12, 5], np.int32)), PS, attn_impl="jnp")

    np.testing.assert_allclose(np.asarray(logits[0]),
                               np.asarray(logA_ref[0]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(logits[1]),
                               np.asarray(logB_ref[0]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(kc2), np.asarray(kc_ref),
                               rtol=2e-4, atol=2e-4)
    assert int(jnp.argmax(logits[0])) == int(jnp.argmax(logA_ref[0]))
    assert int(jnp.argmax(logits[1])) == int(jnp.argmax(logB_ref[0]))


def test_forward_ragged_pallas_interpret_matches_jnp(tiny_cfg, tiny_params):
    """forward_ragged(attn_impl='pallas') == forward_ragged('jnp') via the
    interpret-mode kernel (compiled path needs a TPU)."""
    import ollamamq_tpu.ops.pallas.ragged_attention as ra

    cfg, params = tiny_cfg, tiny_params
    PS, MP = 8, 8
    shape = (cfg.num_layers, 64 * PS, cfg.num_kv_heads, cfg.head_dim)
    rng = np.random.default_rng(5)
    a = kvc.PageAllocator(64, PS, MP)
    pages = [a.alloc(10), a.alloc(4)]
    pt = np.stack([kvc.make_page_table_row(p, MP) for p in pages])
    tokens = rng.integers(1, cfg.vocab_size, size=13).astype(np.int32)
    tok_seq = np.array([0] * 9 + [1] * 4, np.int32)
    tok_pos = np.concatenate([np.arange(9), np.arange(4)]).astype(np.int32)
    ws = np.array([pt[s][p // PS] * PS + p % PS
                   for s, p in zip(tok_seq, tok_pos)], np.int32)
    meta = dict(
        last_idx=jnp.asarray(np.array([8, 12], np.int32)),
        page_table=jnp.asarray(pt),
        q_start=jnp.asarray(np.array([0, 9], np.int32)),
        q_len=jnp.asarray(np.array([9, 4], np.int32)),
        kv_len=jnp.asarray(np.array([9, 4], np.int32)),
    )

    orig = ra.ragged_paged_attention_pallas
    ra.ragged_paged_attention_pallas = (
        lambda *args, **kw: orig(*args, **{**kw, "interpret": True}))
    try:
        outs = {}
        for impl in ("jnp", "pallas"):
            kc = jnp.zeros(shape, jnp.float32)
            vc = jnp.zeros(shape, jnp.float32)
            logits, _, _ = llama.forward_ragged(
                params, cfg, jnp.asarray(tokens), jnp.asarray(tok_seq),
                jnp.asarray(tok_pos), jnp.asarray(ws), meta["last_idx"],
                kc, vc, meta["page_table"], meta["q_start"],
                meta["q_len"], meta["kv_len"], PS, attn_impl=impl)
            outs[impl] = np.asarray(logits)
    finally:
        ra.ragged_paged_attention_pallas = orig
    np.testing.assert_allclose(outs["pallas"], outs["jnp"],
                               rtol=5e-5, atol=5e-5)
