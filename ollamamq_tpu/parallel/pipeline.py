"""Pipeline parallelism: layers sharded over the mesh "pipe" axis.

The reference scales by adding whole HTTP backends (one full model copy
each — /root/reference/src/dispatcher.rs:434-482); it has no way to serve
a model LARGER than one backend's memory. Pipeline parallelism is that
missing axis: the stacked layer parameters [L, ...] (already the repo's
scan-over-layers layout, models/llama.py) shard their leading L dim over
the "pipe" mesh axis, so each chip group holds only L/P layers' weights
and L/P layers' KV pages — the per-chip HBM footprint drops by P.

TPU-native schedule (not a translation of GPU send/recv pipelines):
  - One `jax.shard_map` over the whole mesh; each pipe stage runs the
    SAME traced program (SPMD), scanning its local layer stack.
  - GPipe-style microbatching: the batch splits into M microbatches; at
    schedule step t, stage p works on microbatch (t - p). Activations
    hand off between stages via a single `lax.ppermute` per step — XLA
    lowers it to an ICI neighbor copy that overlaps the next stage's
    compute. M + P - 1 steps drain the pipeline.
  - Bubble steps (t - p outside [0, M)) compute on garbage and write
    their K/V to the allocator's trash page (slot 0 — engine/kv_cache.py
    TRASH_PAGE), keeping every step fully static-shaped: no cond, no
    dynamic shapes, one compiled program.
  - Composes with tensor parallelism INSIDE each stage: head/FFN dims
    stay sharded over "tensor" and the row-parallel matmuls (wo, w_down)
    reduce via `lax.psum` — identity when tp == 1, Megatron-style TP
    when tp > 1 (requires num_kv_heads % tp == 0; the replicated-group
    KV trick is a non-PP path). Embedding and lm_head stay vocab-sharded
    over "tensor" via masked local lookup + psum.

Numerics match the single-device forwards exactly (same per-layer math,
same f32 softmax); only the schedule is distributed — pinned by
tests/test_pipeline.py against forward_prefill/forward_decode.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ollamamq_tpu.config import ModelConfig
from ollamamq_tpu.models.llama import rmsnorm
from ollamamq_tpu.ops.attention import (
    causal_attention,
    flat_slot_indices,
    paged_decode_attention,
)
from ollamamq_tpu.ops.rope import apply_rope
from ollamamq_tpu.parallel.mesh import AXIS_PIPE, AXIS_TENSOR
from ollamamq_tpu.parallel.sharding import param_partition_specs


def pipeline_param_specs(params: dict) -> dict:
    """Partition specs for PP(xTP): the usual TP specs, plus every leaf of
    the stacked `layers` subtree sharded over "pipe" on its leading
    num_layers dim."""
    specs = param_partition_specs(params)

    def add_pipe(leaf, spec):
        dims = list(spec) + [None] * (leaf.ndim - len(spec))
        dims[0] = AXIS_PIPE
        return P(*dims)

    specs["layers"] = jax.tree_util.tree_map(
        add_pipe, params["layers"], specs["layers"]
    )
    return specs


def n_microbatches(batch: int, pipe: int, requested: Optional[int] = None) -> int:
    """Microbatch count: the largest divisor of `batch` that is <= the
    requested count (default: the stage count, which keeps every stage
    busy in steady state with the fewest handoffs)."""
    m = min(requested or pipe, batch)
    while batch % m:
        m -= 1
    return max(m, 1)


# ---------------------------------------------------------------------------
# Per-stage layer math (tensor-parallel inside the stage).
#
# Mirrors models/llama.py:_layer_step / forward_decode's body, except the
# head / FFN dims are tensor-LOCAL shards and the row-parallel outputs
# (wo, w_down) reduce with an explicit psum — under shard_map the
# collective XLA would otherwise infer from shardings must be written out.
# ---------------------------------------------------------------------------


def _tp_qkv(cfg: ModelConfig, lp: dict, h: jnp.ndarray):
    B, T, _ = h.shape
    hd = cfg.head_dim
    q = jnp.einsum("btd,de->bte", h, lp["wq"])
    k = jnp.einsum("btd,de->bte", h, lp["wk"])
    v = jnp.einsum("btd,de->bte", h, lp["wv"])
    if "bq" in lp:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(B, T, q.shape[-1] // hd, hd)
    k = k.reshape(B, T, k.shape[-1] // hd, hd)
    v = v.reshape(B, T, v.shape[-1] // hd, hd)
    return q, k, v


def _tp_mlp(lp: dict, h: jnp.ndarray) -> jnp.ndarray:
    gate = jnp.einsum("btd,df->btf", h, lp["w_gate"])
    up = jnp.einsum("btd,df->btf", h, lp["w_up"])
    down = jnp.einsum("btf,fd->btd", jax.nn.silu(gate) * up, lp["w_down"])
    return lax.psum(down, AXIS_TENSOR)


def _stage_prefill(cfg, layers, x, positions, seq_lens, kc, vc, slots):
    """Run this stage's local layer stack over one microbatch.

    x: [mb, T, D]; kc/vc: [Lp, S, Hk_loc, hd] local cache slices;
    slots: [mb, T] flat cache slots (trash-redirected on bubble steps).
    """
    B, T, _ = x.shape

    def body(carry, per_layer):
        x = carry
        lp, kcl, vcl = per_layer
        h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _tp_qkv(cfg, lp, h)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        kcl = kcl.at[slots].set(k)
        vcl = vcl.at[slots].set(v)
        attn = causal_attention(q, k, v, seq_lens)
        delta = jnp.einsum("bte,ed->btd", attn.reshape(B, T, -1), lp["wo"])
        x = x + lax.psum(delta, AXIS_TENSOR)
        h2 = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _tp_mlp(lp, h2)
        return x, (kcl, vcl)

    x, (kc, vc) = lax.scan(body, x, (layers, kc, vc))
    return x, kc, vc


def _stage_decode(cfg, layers, x, pos, write_slots, kc, vc, pt, seq_lens, ps):
    """One decode step through this stage's local layers.

    x: [mb, 1, D]; kc/vc: [Lp, S, Hk_loc, hd]; write_slots: [mb]
    (trash-redirected on bubbles); pt: [mb, max_pages]; seq_lens: [mb].
    """
    mb = x.shape[0]
    pos2 = pos[:, None]

    def body(carry, per_layer):
        x = carry
        lp, kcl, vcl = per_layer
        h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _tp_qkv(cfg, lp, h)
        q = apply_rope(q, pos2, cfg.rope_theta)
        k = apply_rope(k, pos2, cfg.rope_theta)
        kcl = kcl.at[write_slots].set(k[:, 0])
        vcl = vcl.at[write_slots].set(v[:, 0])
        attn = paged_decode_attention(q[:, 0], kcl, vcl, pt, seq_lens, ps)
        delta = jnp.einsum("be,ed->bd", attn.reshape(mb, -1), lp["wo"])
        x = x + lax.psum(delta, AXIS_TENSOR)[:, None, :]
        h2 = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _tp_mlp(lp, h2)
        return x, (kcl, vcl)

    x, (kc, vc) = lax.scan(body, x, (layers, kc, vc))
    return x, kc, vc


# ---------------------------------------------------------------------------
# Vocab-sharded embedding / logits under shard_map.
# ---------------------------------------------------------------------------


def _embed_lookup(embed_local: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Gather from a vocab-sharded embedding: each tensor shard looks up
    the ids it owns, everything else contributes zero, psum combines."""
    ti = lax.axis_index(AXIS_TENSOR)
    v_loc = embed_local.shape[0]
    loc = tokens - ti * v_loc
    ok = (loc >= 0) & (loc < v_loc)
    x = embed_local[jnp.clip(loc, 0, v_loc - 1)]
    x = jnp.where(ok[..., None], x, jnp.zeros((), embed_local.dtype))
    return lax.psum(x, AXIS_TENSOR)


def _final_logits(params: dict, cfg: ModelConfig, x_last: jnp.ndarray) -> jnp.ndarray:
    """x_last: [B, D] last-position hiddens (zero on every stage but the
    last). Returns replicated [B, V]: psum over pipe folds the stages
    (zeros elsewhere), all_gather over tensor stitches the vocab shards."""
    xf = rmsnorm(x_last, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head", params["embed"])
    logits = jnp.einsum(
        "bd,vd->bv", xf.astype(jnp.float32), head.astype(jnp.float32)
    )
    logits = lax.psum(logits, AXIS_PIPE)
    return lax.all_gather(logits, AXIS_TENSOR, axis=1, tiled=True)


# ---------------------------------------------------------------------------
# Pipelined forwards (drop-in signatures vs the llama.py single-mesh ones).
# ---------------------------------------------------------------------------


def pp_forward_prefill(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T] right-padded
    seq_lens: jnp.ndarray,  # [B]
    k_cache: jnp.ndarray,  # [L, S, Hk, hd], L sharded over "pipe"
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    page_size: int,
    mesh: Mesh,
    n_micro: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipelined prefill; returns (last_logits [B, V], k_cache', v_cache').
    Exact vs forward_prefill — schedule-only difference."""
    B, T = tokens.shape
    pipe = mesh.shape[AXIS_PIPE]
    M = n_microbatches(B, pipe, n_micro)
    mb = B // M
    kv_spec = P(AXIS_PIPE, None, AXIS_TENSOR, None)

    def body(params, tokens, seq_lens, kc, vc, pt):
        p = lax.axis_index(AXIS_PIPE)
        x = _embed_lookup(params["embed"], tokens)  # [B, T, D]
        x_all = x.reshape(M, mb, T, -1)
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (mb, T))
        pos_b = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
        slots_all = flat_slot_indices(pt, pos_b, page_size).reshape(M, mb, T)
        lens_all = seq_lens.reshape(M, mb)
        out_x = jnp.zeros((M, mb, x.shape[-1]), x.dtype)
        h0 = jnp.zeros((mb, T, x.shape[-1]), x.dtype)

        def step(t, carry):
            h_state, kc, vc, out_x = carry
            m = jnp.clip(t - p, 0, M - 1)
            valid = (t >= p) & (t - p < M)
            inp = jnp.where(
                p == 0,
                lax.dynamic_index_in_dim(x_all, m, 0, keepdims=False),
                h_state,
            )
            lens = lax.dynamic_index_in_dim(lens_all, m, 0, keepdims=False)
            slots = lax.dynamic_index_in_dim(slots_all, m, 0, keepdims=False)
            slots = jnp.where(valid, slots, 0)  # bubbles write to trash
            h_out, kc, vc = _stage_prefill(
                cfg, params["layers"], inp, positions, lens, kc, vc, slots
            )
            last = jnp.clip(lens - 1, 0, T - 1)
            x_last = jnp.take_along_axis(h_out, last[:, None, None], axis=1)[:, 0]
            prev = lax.dynamic_index_in_dim(out_x, m, 0, keepdims=False)
            row = jnp.where(valid & (p == pipe - 1), x_last, prev)
            out_x = lax.dynamic_update_index_in_dim(out_x, row, m, 0)
            perm = [(d, (d + 1) % pipe) for d in range(pipe)]
            h_nxt = lax.ppermute(h_out, AXIS_PIPE, perm)
            return h_nxt, kc, vc, out_x

        _, kc, vc, out_x = lax.fori_loop(
            0, M + pipe - 1, step, (h0, kc, vc, out_x)
        )
        logits = _final_logits(params, cfg, out_x.reshape(B, -1))
        return logits, kc, vc

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pipeline_param_specs(params), P(), P(), kv_spec, kv_spec, P()),
        out_specs=(P(), kv_spec, kv_spec),
        check_vma=False,
    )(params, tokens, seq_lens, k_cache, v_cache, page_table)


def pp_forward_decode(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] last generated token per slot
    positions: jnp.ndarray,  # [B]
    k_cache: jnp.ndarray,  # [L, S, Hk, hd], L sharded over "pipe"
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    page_size: int,
    mesh: Mesh,
    n_micro: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipelined single decode step; returns (logits [B, V], caches')."""
    B = tokens.shape[0]
    pipe = mesh.shape[AXIS_PIPE]
    M = n_microbatches(B, pipe, n_micro)
    mb = B // M
    kv_spec = P(AXIS_PIPE, None, AXIS_TENSOR, None)

    def body(params, tokens, positions, kc, vc, pt):
        p = lax.axis_index(AXIS_PIPE)
        x = _embed_lookup(params["embed"], tokens)  # [B, D]
        x_all = x.reshape(M, mb, 1, -1)
        ws_all = flat_slot_indices(pt, positions[:, None], page_size)[:, 0]
        ws_all = ws_all.reshape(M, mb)
        pos_all = positions.reshape(M, mb)
        pt_all = pt.reshape(M, mb, -1)
        lens_all = pos_all + 1
        out_x = jnp.zeros((M, mb, x.shape[-1]), x.dtype)
        h0 = jnp.zeros((mb, 1, x.shape[-1]), x.dtype)

        def step(t, carry):
            h_state, kc, vc, out_x = carry
            m = jnp.clip(t - p, 0, M - 1)
            valid = (t >= p) & (t - p < M)
            inp = jnp.where(
                p == 0,
                lax.dynamic_index_in_dim(x_all, m, 0, keepdims=False),
                h_state,
            )
            pos = lax.dynamic_index_in_dim(pos_all, m, 0, keepdims=False)
            lens = lax.dynamic_index_in_dim(lens_all, m, 0, keepdims=False)
            ptm = lax.dynamic_index_in_dim(pt_all, m, 0, keepdims=False)
            ws = lax.dynamic_index_in_dim(ws_all, m, 0, keepdims=False)
            ws = jnp.where(valid, ws, 0)  # bubbles write to trash
            h_out, kc, vc = _stage_decode(
                cfg, params["layers"], inp, pos, ws, kc, vc, ptm, lens,
                page_size,
            )
            prev = lax.dynamic_index_in_dim(out_x, m, 0, keepdims=False)
            row = jnp.where(valid & (p == pipe - 1), h_out[:, 0], prev)
            out_x = lax.dynamic_update_index_in_dim(out_x, row, m, 0)
            perm = [(d, (d + 1) % pipe) for d in range(pipe)]
            h_nxt = lax.ppermute(h_out, AXIS_PIPE, perm)
            return h_nxt, kc, vc, out_x

        _, kc, vc, out_x = lax.fori_loop(
            0, M + pipe - 1, step, (h0, kc, vc, out_x)
        )
        logits = _final_logits(params, cfg, out_x.reshape(B, -1))
        return logits, kc, vc

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pipeline_param_specs(params), P(), P(), kv_spec, kv_spec, P()),
        out_specs=(P(), kv_spec, kv_spec),
        check_vma=False,
    )(params, tokens, positions, k_cache, v_cache, page_table)
