"""Pipeline parallelism ACROSS hosts: 2 CPU processes, global mesh pp=2
with one stage per process; the primary serves a request while the worker
replays its dispatches (the GPipe shard_map's ppermute handoffs cross the
process boundary). Greedy tokens must equal a plain single-device run —
cross-host pipeline parallelism is numerically transparent."""

import json
import os
import subprocess
import sys

import pytest

from testutil import free_port

_SCRIPT = r"""
import json, os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly 1 local device per process
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
assert jax.device_count() == 2

from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig
from ollamamq_tpu.parallel.mesh import make_mesh
import jax.numpy as jnp

mesh = make_mesh(dp=1, sp=1, tp=1, pp=2)  # one pipeline stage per host
ecfg = EngineConfig(model="test-tiny", max_slots=2, num_pages=32, page_size=8,
                    max_pages_per_seq=8, prefill_buckets=(16,),
                    decode_steps_per_iter=2, pp=2)
MODELS = {"test-tiny": None}

if pid == 0:
    from ollamamq_tpu.engine.spmd import SPMDEngine
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = SPMDEngine(ecfg, models=MODELS, blocklist_path=None,
                     mesh=mesh, dtype=jnp.float32)
    eng.start()
    import time

    rt = eng.runtimes["test-tiny"]
    assert rt._pp == 2, rt._pp
    tok = rt.tokenizer
    req = eng.enqueue_request("u", "", "test-tiny",
                              prompt_tokens=tok.encode("pp across hosts"),
                              sampling=SamplingParams(max_tokens=6))
    deadline = time.monotonic() + 300
    item = None
    while time.monotonic() < deadline:
        item = req.stream.get(timeout=0.5)
        if item and item.kind in ("done", "error"):
            break
    eng.stop()
    print("RESULT " + json.dumps({
        "kind": item.kind if item else "timeout",
        "error": getattr(item, "error", "") if item else "",
        "tokens": req.generated_ids,
    }), flush=True)
else:
    from ollamamq_tpu.engine.spmd import run_worker

    steps = run_worker(MODELS, ecfg, mesh, dtype=jnp.float32)
    print("RESULT " + json.dumps({"steps": steps}), flush=True)
"""



def test_spmd_pipeline_parallel_across_processes(tmp_path):
    port = free_port()
    script = tmp_path / "spmd_pp_child.py"
    script.write_text(_SCRIPT)
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen([sys.executable, str(script), str(pid), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("SPMD pp processes hung")
        assert p.returncode == 0, f"child failed:\n{err[-2000:]}"
        outs.append(out)

    primary = json.loads(
        [l for l in outs[0].splitlines() if l.startswith("RESULT ")][0][7:]
    )
    worker = json.loads(
        [l for l in outs[1].splitlines() if l.startswith("RESULT ")][0][7:]
    )
    assert primary["kind"] == "done", primary
    assert worker["steps"] >= 2  # prefill + decode dispatches replayed
    assert len(primary["tokens"]) >= 1

    # Cross-host pp must be numerically transparent: same greedy tokens as
    # a plain single-device engine (pipeline exactness is schedule-only).
    import time

    import jax.numpy as jnp

    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.engine import TPUEngine
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = TPUEngine(
        EngineConfig(model="test-tiny", max_slots=2, num_pages=32,
                     page_size=8, max_pages_per_seq=8, prefill_buckets=(16,),
                     decode_steps_per_iter=2),
        models={"test-tiny": None}, blocklist_path=None, dtype=jnp.float32,
    )
    eng.start()
    try:
        tok = eng.runtimes["test-tiny"].tokenizer
        rid = eng.core.enqueue("u", "127.0.0.1", "test-tiny")
        req = Request(rid, "u", "test-tiny", tok.encode("pp across hosts"),
                      SamplingParams(max_tokens=6))
        eng.submit(req)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            item = req.stream.get(timeout=0.5)
            if item and item.kind in ("done", "error"):
                break
    finally:
        eng.stop()
    assert req.generated_ids == primary["tokens"], (
        req.generated_ids, primary["tokens"]
    )
