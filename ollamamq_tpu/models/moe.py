"""Mixture-of-experts FFN (Mixtral family) with expert parallelism.

The reference serves MoE models only by proxying to an Ollama backend that
happens to run one (llama.cpp does the routing on CPU/GPU); it has no
expert-parallel story at all. Here MoE is a first-class layer family:

  - Routing is token-choice top-k (Mixtral semantics: softmax over all
    experts, take top-k, renormalize the kept probabilities).
  - Dispatch/combine use the GShard dense formulation — one-hot
    position-in-expert tensors contracted with einsum — because that is
    the shape-static, compiler-friendly layout: no gather/scatter with
    data-dependent sizes, everything tiles onto the MXU, and XLA's SPMD
    partitioner turns the [E, C, D] dispatch einsum into the expert
    all-to-all when `we_*` are sharded over the mesh "expert" axis.
  - Per-expert capacity C = ceil(N*k/E * capacity_factor) is STATIC.
    Tokens routed past an expert's capacity contribute nothing for that
    expert slot (their combine weight is zero) and fall through to the
    residual stream — the standard token-dropping trade, bounded by the
    capacity factor (config.moe_capacity_factor, default 2.0).

Expert weights are stacked [L, E, ...] so the layer scan carries them like
every other layer param; the "expert" dim shards over AXIS_EXPERT and the
per-expert FFN dim over AXIS_TENSOR (parallel/sharding.py), composing
EP x TP without any code change here — GSPMD propagates from the weight
shardings.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ollamamq_tpu.config import ModelConfig


def init_moe_layer_params(cfg: ModelConfig, key: jax.Array, dtype) -> dict:
    """Router + stacked expert weights for every layer: contributes the
    FFN entries of the `layers` tree when cfg.num_experts > 0."""
    d, f = cfg.hidden_size, cfg.intermediate_size
    L, E = cfg.num_layers, cfg.num_experts
    keys = jax.random.split(key, 4)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                / jnp.sqrt(fan_in)).astype(dtype)

    return {
        "w_router": w(keys[0], (L, d, E), d),
        "we_gate": w(keys[1], (L, E, d, f), d),
        "we_up": w(keys[2], (L, E, d, f), d),
        "we_down": w(keys[3], (L, E, f, d), f),
    }


def expert_capacity(n_tokens: int, cfg: ModelConfig) -> int:
    """Static per-expert token capacity for a batch of n_tokens."""
    ideal = n_tokens * cfg.num_experts_per_tok / cfg.num_experts
    return max(1, int(math.ceil(ideal * cfg.moe_capacity_factor)))


def group_size(n_tokens: int, cap: int = 512) -> int:
    """Tokens per routing group: largest divisor of n_tokens <= cap.

    Without grouping, capacity C grows with N and the dispatch one-hots /
    einsums scale O(N^2) — a long-prefill HBM and FLOPs blowup. GShard's
    fix is a group dimension: capacity is computed per fixed-size group,
    so dispatch cost stays linear in tokens."""
    g = min(cap, n_tokens)
    while n_tokens % g:
        g -= 1
    return max(g, 1)


def moe_mlp(cfg: ModelConfig, lp: dict, h: jnp.ndarray,
            valid=None) -> jnp.ndarray:
    """Top-k routed expert FFN over [B, T, D] hiddens; returns [B, T, D].

    Same contract as llama._mlp (the residual add happens in the caller).
    `valid` ([B, T] bool, optional) marks real tokens: padding positions
    and inactive decode slots must not CLAIM expert capacity, or identical
    garbage rows (all routing alike) crowd real tokens out of their
    experts' queues and silently zero their FFN delta.

    Tokens route in groups of <= 512 (GShard's group dim): capacity and
    the dispatch/combine one-hots are per-group, keeping dispatch cost
    linear in sequence length.
    """
    B, T, D = h.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    N = B * T
    G = group_size(N)
    n_g = N // G
    C = expert_capacity(G, cfg)
    x = h.reshape(n_g, G, D)

    # Router in f32: the softmax is over a handful of experts and feeds
    # multiplicative gates — bf16 here costs real quality for no speed.
    logits = jnp.einsum(
        "gnd,de->gne", x.astype(jnp.float32), lp["w_router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)  # [g, G, E]
    gate_vals, expert_idx = jax.lax.top_k(probs, K)  # [g, G, K]
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # Position of each (token, k-slot) in its expert's per-group queue,
    # token-major (GShard "first C win"). sel: [g, G, K, E] one-hot on the
    # routed expert; invalid tokens select nothing (=> no capacity claim).
    sel = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)
    if valid is not None:
        sel = sel * valid.reshape(n_g, G).astype(jnp.int32)[..., None, None]
    pos = jnp.cumsum(sel.reshape(n_g, G * K, E), axis=1).reshape(sel.shape) - sel
    keep = (pos < C) & (sel > 0)  # [g, G, K, E]

    # One-hot (token, k-slot) -> (expert, capacity-slot); dropped and
    # unrouted entries point at index C, whose one-hot row is all zeros.
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, C), C, dtype=h.dtype)
    dispatch = jnp.sum(pos_oh, axis=2)  # [g, G, E, C] 0/1 (k-slots disjoint)
    combine = jnp.einsum(
        "gnkec,gnk->gnec", pos_oh, gate_vals.astype(h.dtype)
    )  # [g, G, E, C] gate weights

    # Expert compute on the dispatched [g, E, C, D] blocks — the einsums
    # XLA partitions over "expert"/"tensor" when we_* carry those
    # shardings (the group dim stays local).
    xe = jnp.einsum("gnec,gnd->gecd", dispatch, x)
    gate = jnp.einsum("gecd,edf->gecf", xe, lp["we_gate"])
    up = jnp.einsum("gecd,edf->gecf", xe, lp["we_up"])
    out_e = jnp.einsum("gecf,efd->gecd", jax.nn.silu(gate) * up, lp["we_down"])

    y = jnp.einsum("gnec,gecd->gnd", combine, out_e)  # gates applied here
    return y.reshape(B, T, D)
