"""Deterministic test instrumentation (fault injection) — importable by
the engine at serving time, not only by the test suite: `--fault-plan`
wires a plan into the live dispatch seams for chaos benching."""
