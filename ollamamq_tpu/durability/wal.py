"""Durable admission WAL: the disk half of crash-safe serving.

One JSONL file (`<wal-dir>/wal.jsonl`) of three record kinds:

    {"k": "admit", "rid": R, "user": ..., "model": ..., "kind": ...,
     "raw_prompt": ..., "prompt": [ids...], "ctx": [ids...],
     "sampling": {...}, "max_tokens_total": S, "t": wall}
    {"k": "tok", "rid": R, "items": [[token_id, text], ...]}
    {"k": "fin", "rid": R, "reason": "stop"}

`admit` is the durability contract: the writer BLOCKS until the record
reaches disk (group commit — one fsync covers every admit that arrived
in the same --wal-fsync-ms window), so an ACKed enqueue survives
`kill -9`. `tok`/`fin` records are appended from the engine thread's
stream tap and flushed on the same fsync cadence: a crash loses at most
one window of progress, never an admitted request — greedy decoding
regenerates the lost tail identically on recovery.

Crash tolerance on the read side: a torn final line (the crash landed
mid-write) is skipped, not fatal; every complete prefix of the file is
a consistent recovery state. Compaction happens at recovery: live
requests are rewritten into a fresh file (admit + one folded tok line)
via write-new-then-rename, so the old generation only retires after the
new one durably holds the same state.

Disk trouble must not take serving down: any OSError (or an injected
fault at site "wal") degrades the WAL loudly — an alert fires, appends
become no-ops, serving continues un-journaled.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ollamamq_tpu.telemetry import schema as tm

log = logging.getLogger("ollamamq.wal")

WAL_NAME = "wal.jsonl"


def load_wal_records(path: str) -> Tuple[Dict[int, dict], int]:
    """Parse one WAL file into per-request live state.

    Returns ({rid: {"admit": dict, "toks": [[id, text], ...],
                    "finished": reason|None}}, torn_lines).
    Malformed/torn lines are counted and skipped — a crash mid-write
    must leave every complete prefix loadable."""
    out: Dict[int, dict] = {}
    torn = 0
    if not os.path.exists(path):
        return out, torn
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                k = rec["k"]
                rid = int(rec["rid"])
            except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                torn += 1
                continue
            if k == "admit":
                out[rid] = {"admit": rec, "toks": [], "finished": None}
            elif k == "tok":
                ent = out.get(rid)
                if ent is not None:
                    try:
                        ent["toks"].extend(
                            [int(i), str(t)] for i, t in rec["items"])
                    except (KeyError, TypeError, ValueError):
                        torn += 1
            elif k == "fin":
                ent = out.get(rid)
                if ent is not None:
                    ent["finished"] = rec.get("reason", "stop")
    return out, torn


class RequestWAL:
    """Append-only request log with group-commit fsync.

    Writers append JSON lines into an in-memory buffer under a lock; a
    flusher thread drains the buffer, `flush()` + `os.fsync()` every
    `fsync_ms`, and signals waiters. `admit()` waits for the sync that
    covers its record (the durability ACK); `append_tokens()`/`finish()`
    are fire-and-forget (progress, not admission)."""

    def __init__(self, wal_dir: str, fsync_ms: float = 20.0,
                 fault_plan=None, on_degrade=None):
        self.dir = wal_dir
        self.path = os.path.join(wal_dir, WAL_NAME)
        self.fsync_ms = max(0.0, float(fsync_ms))
        self.fault_plan = fault_plan
        self.on_degrade = on_degrade
        self.dead = False
        self._fh = None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._buf: List[str] = []
        self._appended = 0   # lines handed to the WAL
        self._synced = 0     # lines known durable
        self._stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None
        self.bytes_written = 0
        self.fsyncs = 0
        # Optional replication mirror (fleet/ha.py): called with each
        # record dict as it is buffered, so a warm standby's WAL replica
        # tracks this one within a sync batch. Exceptions are contained
        # — replication trouble must not break the durability ACK path.
        self.mirror = None

    def _mirrored(self, rec: dict) -> dict:
        m = self.mirror
        if m is not None:
            try:
                m(rec)
            except Exception:  # noqa: BLE001
                pass
        return rec

    # -- lifecycle ---------------------------------------------------------
    def read_existing(self) -> Tuple[Dict[int, dict], int]:
        """The previous process generation's live state (recovery input).
        Call BEFORE begin() — begin() starts a fresh file."""
        return load_wal_records(self.path)

    def begin(self, initial: Optional[Dict[int, dict]] = None) -> None:
        """Open a fresh WAL generation. `initial` (the recovery pass's
        surviving live state) is compacted into it — admit + one folded
        tok line per request — via write-new-then-rename, so the old
        generation retires only once the new one is durable. The old
        file is kept one generation back (`wal.jsonl.1`) for forensics."""
        os.makedirs(self.dir, exist_ok=True)
        tmp = self.path + ".new"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for rid, ent in (initial or {}).items():
                    f.write(json.dumps(ent["admit"]) + "\n")
                    if ent["toks"]:
                        f.write(json.dumps(
                            {"k": "tok", "rid": rid,
                             "items": ent["toks"]}) + "\n")
                f.flush()
                os.fsync(f.fileno())
            if os.path.exists(self.path):
                os.replace(self.path, self.path + ".1")
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")
            self.bytes_written = self._fh.tell()
        except OSError as e:
            self._degrade(f"WAL open failed: {e}")
            return
        self._stop.clear()
        self._flusher = threading.Thread(target=self._flush_loop,
                                         name="wal-flusher", daemon=True)
        self._flusher.start()

    def close(self) -> None:
        """Final flush + fsync (graceful shutdown)."""
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        t = self._flusher
        if t is not None:
            t.join(timeout=5.0)
            self._flusher = None
        with self._lock:
            self._flush_locked()
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    # -- appends -----------------------------------------------------------
    def admit(self, rec: dict) -> float:
        """Durably record one admission; BLOCKS until the covering fsync
        lands (the enqueue ACK gate). Returns the wait in ms."""
        if self.dead:
            return 0.0
        t0 = time.monotonic()
        with self._cond:
            self._buf.append(json.dumps(self._mirrored(rec)))
            self._appended += 1
            target = self._appended
            if self._fh is None:
                # Not begun yet (recovery in flight): the record rides
                # the compaction fsync in begin(); don't park the caller.
                return 0.0
            if self.fsync_ms <= 0:
                self._flush_locked()
            else:
                self._cond.notify_all()  # wake the flusher early
                while self._synced < target and not self.dead:
                    if not self._cond.wait(timeout=5.0):
                        break  # wedged disk: degrade-by-timeout, serve on
        return (time.monotonic() - t0) * 1e3

    def append_tokens(self, rid: int, items: List[list]) -> None:
        """Buffer emitted-token progress ([id, text] pairs); the flusher
        makes it durable within one fsync window."""
        if self.dead or not items:
            return
        with self._lock:
            self._buf.append(json.dumps(self._mirrored(
                {"k": "tok", "rid": rid, "items": items})))
            self._appended += 1

    def finish(self, rid: int, reason: str) -> None:
        if self.dead:
            return
        with self._lock:
            self._buf.append(json.dumps(self._mirrored(
                {"k": "fin", "rid": rid, "reason": reason})))
            self._appended += 1

    def snapshot_lines(self, mark=None) -> List[str]:
        """(HA cold catch-up) Flush everything buffered, then return the
        current generation's raw JSONL lines. `mark` (optional callback)
        runs UNDER the WAL lock between the flush and the read: mirror
        calls also hold this lock, so a replication head captured there
        is exactly the snapshot's edge — records after the mark are in
        the ring, records at or before it are in these lines, never
        both."""
        with self._cond:
            self._flush_locked()
            if mark is not None:
                mark()
            lines: List[str] = []
            try:
                if os.path.exists(self.path):
                    with open(self.path, encoding="utf-8") as f:
                        lines = [ln.rstrip("\n") for ln in f
                                 if ln.strip()]
            except OSError:
                lines = []
            return lines

    # -- flusher -----------------------------------------------------------
    def _flush_locked(self) -> None:
        """(lock held) Write + fsync everything buffered."""
        if self._fh is None or self.dead:
            self._synced = self._appended
            self._cond.notify_all()
            return
        if not self._buf:
            return
        lines, self._buf = self._buf, []
        n = self._appended - self._synced
        t0 = time.monotonic()
        try:
            if self.fault_plan is not None:
                self.fault_plan.check("wal")
            data = "\n".join(lines) + "\n"
            self._fh.write(data)
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self.bytes_written += len(data)
            self.fsyncs += 1
            tm.WAL_FSYNC_MS.observe((time.monotonic() - t0) * 1e3)
        except Exception as e:  # noqa: BLE001 — disk trouble degrades
            self._degrade(f"WAL write failed: {e}")
        self._synced += n
        self._cond.notify_all()

    def _flush_loop(self) -> None:
        period = max(0.001, self.fsync_ms / 1e3)
        while not self._stop.is_set():
            with self._cond:
                if not self._buf:
                    self._cond.wait(timeout=period)
                self._flush_locked()
            if self._stop.wait(period):
                return

    def _degrade(self, msg: str) -> None:
        """Disk trouble must not take serving down: stop writing, tell
        the operator loudly, release every waiter."""
        if self.dead:
            return
        self.dead = True
        log.error("WAL degraded (serving continues WITHOUT crash "
                  "durability): %s", msg)
        fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass
        self._synced = self._appended
        try:
            self._cond.notify_all()  # only valid if lock held; best-effort
        except RuntimeError:
            pass
        cb = self.on_degrade
        if cb is not None:
            try:
                cb(msg)
            except Exception:  # noqa: BLE001
                log.exception("WAL degrade callback failed")

    def status(self) -> dict:
        return {"path": self.path, "fsync_ms": self.fsync_ms,
                "dead": self.dead, "fsyncs": self.fsyncs,
                "bytes": self.bytes_written}
