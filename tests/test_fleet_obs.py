"""Fleet observability plane: distributed tracing (one stitched
timeline per stream across router + member processes), metrics
federation (member series re-exported with a replica label), and the
router-overhead self-profiler (placement p99 measured and bounded).

The contract under test: a stream that crossed processes — placed by
the router, served by an HTTP member, failed over to a second member —
still reads as ONE timeline at /debug/trace/{rid}, whose fleet-wide
phase sum equals the client-observed end-to-end wall clock.
"""

import asyncio
import time

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.fleet import FleetRouter, HttpMember
from ollamamq_tpu.telemetry import REGISTRY
from ollamamq_tpu.telemetry import schema as tm
from ollamamq_tpu.telemetry import tracing
from test_fleet import TINY, _fake_fleet, _HttpBackend, _run, _text
from testutil import collect

TOL_MS = 0.5  # float noise on phase-sum == e2e (ms)


def _place_count() -> int:
    child = tm.ROUTER_OVERHEAD_MS.labels(site="place")
    return child.count


# ------------------------------------------------------------ trace context
def test_ctx_mint_and_validate():
    ctx = tracing.mint_ctx()
    assert tracing.valid_ctx(ctx)
    assert not tracing.valid_ctx("nope")
    assert not tracing.valid_ctx(None)
    assert not tracing.valid_ctx("00-xyz-abc-01")


def test_trace_ctx_propagates_in_process_and_stitches():
    """LocalMember fleet: the member-side attempt traces under the
    router's fleet context (un-metered), and the merged timeline's
    phase sum equals the router-observed e2e."""
    router = _fake_fleet(n=2, token_latency_s=0.01)
    try:
        req = _run(router, "tr-local", "trace me please", max_tokens=6)
        rid = req.req_id
        items = collect(req)
        assert items[-1].kind == "done"
        root = router.tracer.find(rid)
        assert root is not None and tracing.valid_ctx(root.ctx)
        # The member engine holds a span under the SAME ctx, origin'd
        # with the member name.
        member_spans = []
        for mem in router.members:
            member_spans += mem.trace_spans(root.ctx)
        assert member_spans, "no member-side spans for the fleet ctx"
        assert all(s["origin"] in ("r0", "r1") for s in member_spans)
        # Member traces never meter the shared registry (the router's
        # root trace already did).
        for mem in router.members:
            for tr in mem.engine.tracer.find_ctx(root.ctx):
                assert tr.metered is False
        # Stitched timeline: phase sum == client-observed e2e.
        spans = router.fleet_trace_spans(rid)
        assert {s["origin"] for s in spans} >= {"router"}
        merged = tracing.merged_chrome(spans, root_origin="router")
        st = merged["stitched"]
        assert st["outcome"] in ("stop", "length")
        assert st["e2e_ms"] > 0
        assert abs(st["phase_sum_ms"] - st["e2e_ms"]) < TOL_MS
        assert "router" in st["origins"]
        # Decode happened member-side: the stitched breakdown must see
        # member spans, not just router bookkeeping.
        assert st["phases_ms"].get("decode", 0) > 0
    finally:
        router.stop()


def test_debug_trace_rid_http_and_failover_keeps_trace_whole():
    """ACCEPTANCE: a greedy stream placed by the router, failed over
    mid-decode to a second real HTTP member, shows ONE merged trace at
    /debug/trace/{rid} whose fleet-wide phase sum equals the
    client-observed e2e wall clock."""
    member_cfg = EngineConfig(**TINY)
    backends = [
        _HttpBackend(FakeEngine(member_cfg, blocklist_path=None,
                                token_latency_s=0.05))
        for _ in range(2)
    ]
    for b in backends:
        b.engine.start()
    ecfg = EngineConfig(**TINY)
    members = [HttpMember(f"h{i}", b.url, timeout_s=30, poll_period_s=0.1)
               for i, b in enumerate(backends)]
    router = FleetRouter(members, ecfg, blocklist_path=None,
                         probe_period_s=0.05, eject_heartbeat_s=1.0,
                         reprobe_backoff_s=0.2, evac_grace_s=0.5)
    router.start()
    try:
        t0 = time.monotonic()
        req = _run(router, "tr-kill", "trace the victim", max_tokens=16)
        rid = req.req_id
        # Kill the serving backend once the stream is mid-decode.
        deadline = time.monotonic() + 30
        victim = None
        while time.monotonic() < deadline:
            f = next((f for f in list(router.flights) if f.req is req),
                     None)
            if f is not None and f.attempt is not None \
                    and f.attempt.n_items >= 2:
                victim = f.member
                break
            time.sleep(0.01)
        assert victim is not None
        backends[int(victim.name[1])].stop()
        items = collect(req, timeout=60)
        e2e_observed_ms = (time.monotonic() - t0) * 1e3
        assert items[-1].kind == "done"
        assert _text(items) == "".join(f"word{i} " for i in range(16))
        assert router.failover_count >= 1

        # Spans from the ROUTER process and the SURVIVING member
        # process (fetched over real HTTP /debug/trace?ctx=...)
        # stitch into one timeline.
        spans = router.fleet_trace_spans(rid)
        origins = {s["origin"] for s in spans}
        survivor = f"h{1 - int(victim.name[1])}"
        assert "router" in origins
        assert survivor in origins, f"no spans from {survivor}: {origins}"
        merged = tracing.merged_chrome(spans, root_origin="router")
        st = merged["stitched"]
        assert st["outcome"] in ("stop", "length")
        assert abs(st["phase_sum_ms"] - st["e2e_ms"]) < TOL_MS
        # The merged e2e is the client-observed wall clock (bounded by
        # what this test measured around the stream).
        assert st["e2e_ms"] <= e2e_observed_ms + TOL_MS
        names = [e["name"] for e in st["events"]]
        assert "failover" in names or "migrate" in names
        assert "first_token" in names
        # One row per origin in the Chrome export.
        tids = {e["tid"] for e in merged["traceEvents"]}
        assert len(tids) >= 2
    finally:
        router.stop()
        for b in backends:
            b.stop()


def test_traceparent_header_adopted_by_member_server():
    """The member-side HTTP server adopts a propagated traceparent: the
    wire contract HttpMember relies on for stitching."""
    from aiohttp.test_utils import TestClient, TestServer

    from ollamamq_tpu.server.app import Server

    eng = FakeEngine(EngineConfig(**TINY), blocklist_path=None)
    eng.start()
    ctx = tracing.mint_ctx()

    async def main():
        server = Server(eng, timeout_s=30)
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            resp = await client.post(
                "/api/generate",
                json={"model": "test-tiny", "prompt": "hello",
                      "stream": False, "options": {"num_predict": 3}},
                headers={tracing.TRACEPARENT_HEADER: ctx})
            assert resp.status == 200
            await resp.json()
            # The raw span export for the ctx (the stitching wire).
            resp = await client.get(f"/debug/trace?ctx={ctx}")
            assert resp.status == 200
            body = await resp.json()
            assert body["ctx"] == ctx
            assert len(body["spans"]) == 1
            assert body["spans"][0]["ctx"] == ctx
            # Junk ctx is a client error, not an empty result.
            resp = await client.get("/debug/trace?ctx=garbage")
            assert resp.status == 400
        finally:
            await client.close()

    asyncio.new_event_loop().run_until_complete(main())
    eng.stop()


# --------------------------------------------------------------- federation
def _wait(cond, budget=30.0, msg="condition"):
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {msg}")


def test_metrics_federation_replica_labels_under_eject_rejoin():
    """Member series re-export with a replica label; an ejected member
    drops out of the exposition and returns on rejoin."""
    member_cfg = EngineConfig(**TINY)
    backends = [
        _HttpBackend(FakeEngine(member_cfg, blocklist_path=None))
        for _ in range(2)
    ]
    for b in backends:
        b.engine.start()
    ecfg = EngineConfig(**TINY)
    members = [HttpMember(f"h{i}", b.url, timeout_s=30, poll_period_s=0.1)
               for i, b in enumerate(backends)]
    router = FleetRouter(members, ecfg, blocklist_path=None,
                         probe_period_s=0.05, eject_heartbeat_s=1.0,
                         reprobe_backoff_s=0.1, evac_grace_s=0.5)
    router.start()
    try:
        _wait(lambda: all(m.metric_snapshot() for m in members),
              msg="member metric snapshots")
        fed = router.member_metric_federation()
        assert {name for name, _ in fed} == {"h0", "h1"}
        text = REGISTRY.render(federated=fed)
        assert 'replica="h0"' in text
        assert 'replica="h1"' in text
        # The replica label lands on real member series, inside the
        # same family as the router's own (ONE HELP/TYPE block per
        # family even when local + federated series coexist).
        import re as _re

        m = _re.search(r'^(ollamamq_[a-z0-9_]+?)(?:_bucket|_sum|_count)?'
                       r'\{[^}]*replica="h0"', text, _re.M)
        assert m, "no federated series found"
        fam = m.group(1)
        assert text.count(f"# TYPE {fam} ") == 1

        # Eject h0: its series must leave the exposition.
        members[0].crash()
        _wait(lambda: members[0].state == "ejected", msg="h0 eject")
        text = REGISTRY.render(federated=router.member_metric_federation())
        assert 'replica="h0"' not in text
        assert 'replica="h1"' in text

        # Heal: the re-probe rejoins it and its series return.
        _wait(lambda: members[0].state == "healthy", budget=60,
              msg="h0 rejoin")
        _wait(lambda: any(n == "h0" for n, _ in
                          router.member_metric_federation()),
              msg="h0 snapshot back")
        text = REGISTRY.render(federated=router.member_metric_federation())
        assert 'replica="h0"' in text
    finally:
        router.stop()
        for b in backends:
            b.stop()


def test_federation_off_switch():
    router = _fake_fleet(n=2)
    try:
        router.ecfg.federate_metrics = False
        assert router.member_metric_federation() == []
    finally:
        router.stop()


# ---------------------------------------------------------- router overhead
def test_router_overhead_histogram_journal_and_alert():
    """Every placement lands in ollamamq_router_overhead_ms{site=place}
    AND on the place journal record; the windowed p99 feeds stats and
    the health monitor's overhead-storm alert (fires over budget,
    resolves under it)."""
    before = _place_count()
    router = _fake_fleet(n=2)
    try:
        reqs = [_run(router, f"ov{i}", max_tokens=4) for i in range(4)]
        for r in reqs:
            collect(r)
        assert _place_count() > before
        places = router.journal.tail(None, kind="place")
        assert places and any(p.get("overhead_ms") is not None
                              for p in places)
        p99 = router.router_overhead_p99_ms()
        assert p99 is not None and p99 >= 0
        stats = router.stats()["fleet"]["router_overhead"]
        assert stats["sites"]["place"]["count"] > 0
        assert stats["place_p99_ms"] is not None
        assert stats["budget_ms"] == router.ecfg.router_overhead_budget_ms
        # Journal self-timer: every router journal append is measured.
        jsite = tm.ROUTER_OVERHEAD_MS.labels(site="journal")
        assert jsite.count > 0

        # Overhead-storm alert: impossible budget -> fires; sane
        # budget -> resolves. (check_once also probes the device; CPU.)
        router.ecfg.router_overhead_budget_ms = 1e-9
        router.health.check_once()
        assert any(a.name == "router_overhead"
                   for a in router.alerts.active())
        router.ecfg.router_overhead_budget_ms = 1e9
        router.health.check_once()
        assert not any(a.name == "router_overhead"
                       for a in router.alerts.active())
    finally:
        router.stop()


# ------------------------------------------------- /debug endpoints + WAL
def test_debug_trace_rid_and_wal_cross_links_over_http():
    """Single-engine /debug/trace/{rid} (degenerate stitch) plus the
    satellite bugfix: /debug/requests cross-links wal_rid in BOTH
    directions so a recovered stream's pre-crash timeline is one click
    away instead of a 404 dead end."""
    from aiohttp.test_utils import TestClient, TestServer

    from ollamamq_tpu.server.app import Server

    eng = FakeEngine(EngineConfig(**TINY), blocklist_path=None)
    eng.start()

    async def main():
        server = Server(eng, timeout_s=30)
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            req = _run(eng, "walx", "cross link me", max_tokens=4)
            rid = req.req_id
            collect(req)
            resp = await client.get(f"/debug/trace/{rid}")
            assert resp.status == 200
            merged = await resp.json()
            st = merged["stitched"]
            assert abs(st["phase_sum_ms"] - st["e2e_ms"]) < TOL_MS
            resp = await client.get("/debug/trace/999999")
            assert resp.status == 404

            # Simulate a WAL recovery's aliasing record: old id 999001
            # was re-admitted as `rid`.
            old = 999001
            eng.journal.record("recover_replay", req_id=rid, user="walx",
                              tokens=2, outcome="replayed", wal_rid=old)
            resp = await client.get(f"/debug/requests/{rid}")
            body = await resp.json()
            assert body["wal_rid"] == old
            assert body["pre_crash_timeline"] == f"/debug/requests/{old}"
            # The pre-crash id has NO trace (tracer restarted empty in a
            # real crash) — the endpoint answers the cross-link, not 404.
            resp = await client.get(f"/debug/requests/{old}")
            assert resp.status == 200
            body = await resp.json()
            assert body["state"] == "recovered"
            assert body["recovered_as"] == rid
            # A genuinely unknown id still 404s.
            resp = await client.get("/debug/requests/424242")
            assert resp.status == 404
        finally:
            await client.close()

    asyncio.new_event_loop().run_until_complete(main())
    eng.stop()


def test_router_debug_bundle_gathers_member_sections():
    router = _fake_fleet(n=2)
    try:
        req = _run(router, "bun", max_tokens=3)
        collect(req)
        from ollamamq_tpu.server.app import Server

        bundle = Server(router, timeout_s=30)._build_bundle()
        assert set(bundle["members"]) == {"r0", "r1"}
        for row in bundle["members"].values():
            assert row.get("kind") == "local"
            assert "stats" in row and "journal" in row
        assert "router_overhead" in bundle["fleet"]
    finally:
        router.stop()


# ------------------------------------------------------------ journal merge
def test_journal_merge_interleaves_fleet_spills(tmp_path):
    from ollamamq_tpu.telemetry.journal import Journal, load_jsonl
    from ollamamq_tpu.tools import journal as tools

    ra, rb = str(tmp_path / "router.jsonl"), str(tmp_path / "member.jsonl")
    ja = Journal(capacity=64, path=ra)
    jb = Journal(capacity=64, path=rb)
    # Interleave writes so merged order must come from `t`, not file
    # order; a dead gap in the middle exercises the tick cap.
    ja.record("enqueue", req_id=1, user="u", n_prompt=4, queued=1)
    jb.record("install", req_id=101, user="u", slot=0)
    ja.record("admit", req_id=1, user="u", queued=0)
    time.sleep(1.2)  # >> MERGE_TICK_S * MAX_ARRIVAL_GAP_TICKS
    jb.record("finish", req_id=101, user="u", reason="stop", tokens=2)
    ja.record("finish", req_id=1, user="u", reason="stop", tokens=2)
    ja.close()
    jb.close()

    meta, merged = tools.merge_journals([ra, rb])
    assert [s["file"] for s in meta["merged_from"]] == ["router.jsonl",
                                                        "member.jsonl"]
    assert [r["seq"] for r in merged] == list(range(5))
    ts = [r["t"] for r in merged]
    assert ts == sorted(ts)
    assert {r["src"] for r in merged} == {"router.jsonl", "member.jsonl"}
    assert all("src_seq" in r and "src_tick" in r for r in merged)
    ticks = [r["tick"] for r in merged]
    assert ticks == sorted(ticks)
    # The 1.2s dead gap is capped at MAX_ARRIVAL_GAP_TICKS virtual ticks.
    assert max(ticks) <= tools.MAX_ARRIVAL_GAP_TICKS + 4

    # CLI roundtrip: merge --out, then tail/explain/stats consume the
    # merged file fleet-wide.
    out = str(tmp_path / "merged.jsonl")
    assert tools.main(["merge", "--out", out, ra, rb]) == 0
    m2, recs = load_jsonl(out)
    assert len(recs) == 5 and m2["merged_from"][0]["file"] == "router.jsonl"
    assert tools.main(["tail", out, "--kind", "finish", "--n", "0"]) == 0
    assert tools.main(["explain", out]) == 0
    assert tools.main(["stats", out]) == 0


# ------------------------------------------------------------ doc gate
def test_router_span_vocabulary_is_doc_gated(tmp_path):
    """Gate 5: the router span table and tracing.ROUTER_EVENTS must not
    drift (missing row and ghost row both fail)."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_metrics_docs",
        os.path.join(repo, "scripts", "check_metrics_docs.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with open(os.path.join(repo, "README.md"), encoding="utf-8") as f:
        full = f.read()
    assert mod.main(["check_metrics_docs.py"]) == 0
    missing = tmp_path / "README_nospan.md"
    missing.write_text(full.replace("| `failover` |", "| failover-less |",
                                    1))
    assert mod.main(["check_metrics_docs.py", str(missing)]) == 1
    ghost = tmp_path / "README_ghostspan.md"
    ghost.write_text(full.replace(
        mod.ROUTER_SPANS_END,
        "| `notaspan` | bogus |\n" + mod.ROUTER_SPANS_END, 1))
    assert mod.main(["check_metrics_docs.py", str(ghost)]) == 1
