"""Pipeline parallelism exactness: the pp forwards are schedule-only
transformations — logits and paged KV caches must match the single-mesh
forwards (models/llama.py) bit-for-bit up to f32 accumulation order.

Runs on the 8-virtual-CPU-device mesh (conftest), covering pp alone,
pp deeper than 2 stages, pp x tp composition, and the microbatch helper.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollamamq_tpu.config import MODEL_CONFIGS
from ollamamq_tpu.models import llama
from ollamamq_tpu.parallel import pipeline
from ollamamq_tpu.parallel.mesh import make_mesh

PAGE_SIZE = 8


def _setup(cfg, B=4, T=16, num_pages=64, seed=0):
    params = llama.init_params(cfg, jax.random.PRNGKey(seed), dtype=jnp.float32)
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(rng.randint(1, cfg.vocab_size, size=(B, T)), jnp.int32)
    seq_lens = jnp.asarray(rng.randint(T // 2, T + 1, size=(B,)), jnp.int32)
    S = num_pages * PAGE_SIZE
    kc = jnp.zeros((cfg.num_layers, S, cfg.num_kv_heads, cfg.head_dim),
                   jnp.float32)
    vc = jnp.zeros_like(kc)
    max_pages = T // PAGE_SIZE + 1
    pt = np.zeros((B, max_pages), np.int32)
    pid = 1  # page 0 is the trash page
    for b in range(B):
        for j in range(max_pages):
            pt[b, j] = pid
            pid += 1
    return params, tokens, seq_lens, kc, vc, jnp.asarray(pt)


def _real(c):
    """Cache slots excluding the trash page (bubble steps scribble there)."""
    return c[:, PAGE_SIZE:]


def _run_both(cfg, mesh, B=4, T=16):
    params, tokens, seq_lens, kc, vc, pt = _setup(cfg, B=B, T=T)

    ref_logits, ref_kc, ref_vc = llama.forward_prefill(
        params, cfg, tokens, seq_lens, kc, vc, pt, PAGE_SIZE
    )
    pp_logits, pp_kc, pp_vc = pipeline.pp_forward_prefill(
        params, cfg, tokens, seq_lens, kc, vc, pt, PAGE_SIZE, mesh
    )
    np.testing.assert_allclose(pp_logits, ref_logits, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(_real(pp_kc), _real(ref_kc), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(_real(pp_vc), _real(ref_vc), rtol=1e-5, atol=1e-5)

    # One decode step on top of the prefilled caches.
    next_tok = jnp.argmax(ref_logits, axis=-1).astype(jnp.int32)
    ref_d, ref_kc2, ref_vc2 = llama.forward_decode(
        params, cfg, next_tok, seq_lens, ref_kc, ref_vc, pt, PAGE_SIZE
    )
    pp_d, pp_kc2, pp_vc2 = pipeline.pp_forward_decode(
        params, cfg, next_tok, seq_lens, pp_kc, pp_vc, pt, PAGE_SIZE, mesh
    )
    np.testing.assert_allclose(pp_d, ref_d, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(_real(pp_kc2), _real(ref_kc2), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(_real(pp_vc2), _real(ref_vc2), rtol=1e-5, atol=1e-5)


def test_pp2_matches_single_mesh():
    cfg = MODEL_CONFIGS["test-tiny"]  # 2 layers -> 1 per stage
    _run_both(cfg, make_mesh(dp=1, pp=2, tp=1))


def test_pp4_deeper_pipeline():
    cfg = dataclasses.replace(
        MODEL_CONFIGS["test-tiny"], name="test-tiny-4l", num_layers=4
    )
    _run_both(cfg, make_mesh(dp=1, pp=4, tp=1))


def test_pp2_x_tp2_composition():
    # GQA config with kv_heads=4: tp=2 shards heads AND kv heads cleanly.
    cfg = MODEL_CONFIGS["test-tiny-gqa"]
    _run_both(cfg, make_mesh(dp=1, pp=2, tp=2))


def test_pp2_qwen3_qk_norm():
    # Per-head q/k RMSNorm must match inside the stage body too.
    cfg = MODEL_CONFIGS["test-tiny-qwen3"]
    _run_both(cfg, make_mesh(dp=1, pp=2, tp=2))


def test_pp2_batch_not_multiple_of_stages():
    # B=6 with pp=4 -> n_micro falls back to 3; schedule still exact.
    cfg = dataclasses.replace(
        MODEL_CONFIGS["test-tiny"], name="test-tiny-4l", num_layers=4
    )
    _run_both(cfg, make_mesh(dp=1, pp=4, tp=1), B=6)


def test_pp_engine_serves_generate_and_long_prompt():
    """Full serving path under --pp 2: bucketed prefill, fused decode, and
    the chunked long-prompt path all route through the pipelined forwards
    and produce the same greedy text as a pp=1 engine."""
    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.engine import TPUEngine
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.ops.sampling import SamplingParams
    from testutil import collect

    def mk(pp):
        cfg = EngineConfig(
            model="test-tiny", max_slots=4, num_pages=64, page_size=8,
            max_pages_per_seq=16, prefill_buckets=(16, 32, 64),
            max_new_tokens=16, decode_steps_per_iter=4, pp=pp,
            dtype="float32",
        )
        eng = TPUEngine(cfg, blocklist_path=None)
        eng.start()
        return eng

    ref, pp = mk(1), mk(2)
    try:
        assert pp.runtimes["test-tiny"]._pp == 2
        # A pp runtime serves generate only: embed over pipe-sharded layer
        # stacks would all-gather each stage's weights (OOM on the >HBM
        # models pp targets), so the kind-gate must reject it cleanly.
        assert pp.runtimes["test-tiny"].SERVES == ("generate",)
        assert ref.runtimes["test-tiny"].SERVES == ("generate", "embed")
        # /metrics reports the mesh layout (axis -> size).
        assert pp.stats()["mesh"]["pipe"] == 2
        # Short prompt (bucketed prefill) and a prompt past the largest
        # bucket (chunked prefill), both compared greedy-vs-greedy.
        for prompt in ("hello pipeline world", "long " * 20):
            texts = []
            for eng in (ref, pp):
                tok = eng.runtimes["test-tiny"].tokenizer
                rid = eng.core.enqueue("u", "127.0.0.1", "test-tiny")
                req = Request(rid, "u", "test-tiny", tok.encode(prompt),
                              SamplingParams(max_tokens=8))
                eng.submit(req)
                items = collect(req, timeout=180)
                assert items[-1].kind == "done", items[-1].error
                texts.append("".join(i.text for i in items
                                     if i.kind == "token"))
            assert texts[0] == texts[1], (prompt, texts)
    finally:
        ref.stop()
        pp.stop()


def test_pp2_decode_pallas_interpret_matches_reference():
    """The ragged Pallas kernel inside the shard_map decode stage
    (interpret mode on CPU) matches the jnp pipeline path exactly."""
    cfg = MODEL_CONFIGS["test-tiny"]
    mesh = make_mesh(dp=1, pp=2, tp=1)
    params, tokens, seq_lens, kc, vc, pt = _setup(cfg)
    logits, kc, vc = pipeline.pp_forward_prefill(
        params, cfg, tokens, seq_lens, kc, vc, pt, PAGE_SIZE, mesh
    )
    next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    ref_d, ref_kc, ref_vc = pipeline.pp_forward_decode(
        params, cfg, next_tok, seq_lens, kc, vc, pt, PAGE_SIZE, mesh
    )
    pal_d, pal_kc, pal_vc = pipeline.pp_forward_decode(
        params, cfg, next_tok, seq_lens, kc, vc, pt, PAGE_SIZE, mesh,
        attn_impl="pallas", interpret=True,
    )
    np.testing.assert_allclose(pal_d, ref_d, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(_real(pal_kc), _real(ref_kc), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(_real(pal_vc), _real(ref_vc), rtol=1e-5,
                               atol=1e-5)


def test_dp2_x_pp2_replica_serving():
    """dp=2 with pp=2: each ReplicaSet member owns a [1, 2, 1, 1, tp]
    submesh and runs its own 2-stage pipeline; both replicas serve."""
    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.engine import ReplicaSet, TPUEngine
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.ops.sampling import SamplingParams
    from testutil import collect

    cfg = EngineConfig(
        model="test-tiny", max_slots=2, num_pages=32, page_size=8,
        max_pages_per_seq=8, prefill_buckets=(16,), max_new_tokens=8,
        decode_steps_per_iter=2, dp=2, pp=2, dtype="float32",
    )
    eng = TPUEngine(cfg, blocklist_path=None)
    eng.start()
    try:
        rs = eng.runtimes["test-tiny"]
        assert isinstance(rs, ReplicaSet) and len(rs.replicas) == 2
        assert all(r._pp == 2 for r in rs.replicas)
        tok = rs.replicas[0].tokenizer
        reqs = []
        for i in range(4):  # enough to land work on both replicas
            rid = eng.core.enqueue(f"u{i}", "127.0.0.1", "test-tiny")
            req = Request(rid, f"u{i}", "test-tiny", tok.encode(f"hi {i}"),
                          SamplingParams(max_tokens=4))
            eng.submit(req)
            reqs.append(req)
        for req in reqs:
            items = collect(req, timeout=180)
            assert items[-1].kind == "done", items[-1].error
    finally:
        eng.stop()


def test_n_microbatches_helper():
    assert pipeline.n_microbatches(8, 4) == 4
    assert pipeline.n_microbatches(6, 4) == 3
    assert pipeline.n_microbatches(1, 4) == 1
    assert pipeline.n_microbatches(7, 4) == 1  # prime batch
    assert pipeline.n_microbatches(8, 4, requested=2) == 2
    assert pipeline.n_microbatches(4, 8) == 4  # never exceeds the batch
