"""Health monitor: device liveness + engine-step watchdog.

The reference polls each backend every 10 s (GET /api/tags | /api/ps | /
— dispatcher.rs:261-387) and logs online/offline transitions. The TPU
analogue watches the things that can actually fail here:

  - device liveness: a trivial jitted op must complete within a deadline
    (a wedged TPU runtime/tunnel hangs rather than erroring);
  - engine progress: if work exists but no step has completed recently,
    the engine is stalled — logged loudly, surfaced in /metrics;
  - HBM headroom: page-pool exhaustion pressure.

Transitions are logged like the reference's "Backend ... is now ONLINE /
OFFLINE" messages; the TUI and /metrics read `status()`.
"""

from __future__ import annotations

import logging
import threading
import time

log = logging.getLogger("ollamamq.health")

CHECK_PERIOD_S = 10.0  # reference cadence (dispatcher.rs:385)
DEVICE_DEADLINE_S = 30.0
STALL_DEADLINE_S = 30.0


class HealthMonitor:
    def __init__(self, engine, period_s: float = CHECK_PERIOD_S):
        self.engine = engine
        self.period_s = period_s
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.device_online = True
        self.engine_stalled = False
        self.last_device_check = 0.0
        self._last_progress = (0, time.monotonic())  # (tokens, ts)

    def start(self) -> None:
        if self._thread:
            return
        self._thread = threading.Thread(target=self._loop, name="health", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    # ------------------------------------------------------------------
    def _probe_device(self) -> bool:
        """Run a trivial computation with a deadline on a side thread — a
        hung runtime must not take the monitor down with it. While a probe
        thread is still blocked (runtime wedged), no new probe is spawned;
        the device stays marked offline."""
        prev = getattr(self, "_probe_thread", None)
        if prev is not None and prev.is_alive():
            self.last_device_check = time.time()
            return False
        result = {}

        def go():
            try:
                import jax.numpy as jnp

                x = jnp.ones((8, 8))
                (x @ x).block_until_ready()
                result["ok"] = True
            except Exception as e:  # noqa: BLE001
                result["err"] = str(e)

        t = threading.Thread(target=go, daemon=True)
        self._probe_thread = t
        t.start()
        t.join(timeout=DEVICE_DEADLINE_S)
        self.last_device_check = time.time()
        return result.get("ok", False)

    def _check_progress(self) -> bool:
        """True if the engine is making progress (or rightly idle)."""
        # Snapshot: /api/pull and /api/delete mutate runtimes concurrently.
        runtimes = list(self.engine.runtimes.values())
        tokens = sum(getattr(rt, "tokens_generated", 0) for rt in runtimes)
        has_work = any(rt.has_work() for rt in runtimes) or bool(
            self.engine.core.total_queued()
        )
        last_tokens, last_ts = self._last_progress
        now = time.monotonic()
        if tokens != last_tokens or not has_work:
            self._last_progress = (tokens, now)
            return True
        return (now - last_ts) < STALL_DEADLINE_S

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                ok = self._probe_device()
                if ok != self.device_online:
                    if ok:
                        log.info("TPU device is back ONLINE")
                    else:
                        log.error("TPU device probe FAILED (runtime hung or lost)")
                    self.device_online = ok

                progressing = self._check_progress()
                if not progressing and not self.engine_stalled:
                    log.error(
                        "engine STALLED: %d queued, work pending, no tokens for %ds",
                        self.engine.core.total_queued(), int(STALL_DEADLINE_S),
                    )
                self.engine_stalled = not progressing
            except Exception:
                # The watchdog must outlive anything it watches.
                log.exception("health check iteration failed")

    def status(self) -> dict:
        return {
            "device_online": self.device_online,
            "engine_stalled": self.engine_stalled,
            "last_device_check": self.last_device_check,
        }
