"""Crash-safe serving: durable admission WAL, cold-restart recovery,
client-resumable streams, graceful shutdown, sampled journaling, and
the fleet-wide journal audit roll-up.

The contract under test: a `kill -9` of the serving process loses at
most one fsync window of emitted-token progress and NO admitted
request — recovery re-admits every unfinished stream token-exact, a
reattaching client receives the remainder byte- and token-identical to
an uninterrupted run, and the journal audit attributes every recovered
stream to exactly one terminal across the pre- and post-crash spills.
"""

import json
import os
import random
import shutil
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.durability.wal import RequestWAL, load_wal_records
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.engine.request import FinishReason
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.telemetry import schema as tm
from ollamamq_tpu.telemetry.journal import (SAMPLED_KINDS, Journal,
                                            check_invariants)
from ollamamq_tpu.tools.journal import main as journal_main
from testutil import collect, free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fake(tmp_path, latency=0.0, **over):
    wal = str(tmp_path / "wal")
    cfg = dict(model="test-tiny", wal_dir=wal, wal_fsync_ms=2.0)
    cfg.update(over)
    eng = FakeEngine(EngineConfig(**cfg), blocklist_path=None,
                     token_latency_s=latency)
    eng.start()
    return eng


def _crash(eng):
    """Abrupt loop death — deliberately NOT stop(), which would flush
    and tidy the very state a real crash leaves behind. The WAL flusher
    is also stopped so the crash copy below is a stable snapshot."""
    eng._running = False
    eng.notify()
    time.sleep(0.1)
    eng.durability.wal._stop.set()
    t = eng.durability.wal._flusher
    if t is not None:
        t.join(timeout=5)


def _crash_copy(eng, tmp_path, name="wal-crash"):
    """Snapshot the crashed process's WAL dir for an independent
    recovery, then FULLY tear the corpse down — a real crash takes the
    health monitor and drainer threads with it; in-process they would
    keep logging stalls (and leak threads) for the rest of the run."""
    dst = str(tmp_path / name)
    shutil.copytree(eng.ecfg.wal_dir, dst)
    eng.stop()
    return dst


# ---------------------------------------------------------------- WAL basics
def test_wal_admit_is_durable_before_ack(tmp_path):
    """The admit record is on disk (fsynced) by the time enqueue_request
    returns, every emitted token follows within a flush window, and the
    journal carries the wal_admit decision with its fsync cost."""
    eng = _fake(tmp_path)
    try:
        req = eng.enqueue_request("alice", "", "test-tiny",
                                  prompt_tokens=[1, 2, 3],
                                  sampling=SamplingParams(max_tokens=4))
        # Durable BEFORE the ACK: the admit line is already readable.
        entries, torn = load_wal_records(
            os.path.join(eng.ecfg.wal_dir, "wal.jsonl"))
        assert torn == 0
        assert req.req_id in entries
        assert entries[req.req_id]["admit"]["prompt"] == [1, 2, 3]
        items = collect(req)
        assert items[-1].kind == "done"
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            entries, _ = load_wal_records(
                os.path.join(eng.ecfg.wal_dir, "wal.jsonl"))
            ent = entries[req.req_id]
            if ent["finished"] is not None:
                break
            time.sleep(0.02)
        assert ent["finished"] == "length"
        assert [i for i, _ in ent["toks"]] == [1, 2, 3, 4]
        assert "".join(t for _, t in ent["toks"]) \
            == "word0 word1 word2 word3 "
        wal_admits = eng.journal.tail(kind="wal_admit")
        assert len(wal_admits) == 1
        assert wal_admits[0]["fsync_ms"] >= 0
    finally:
        eng.stop()


def test_wal_embeds_not_logged(tmp_path):
    """Embeds recompute cheaply and carry no resumable stream: they are
    served normally but never WAL'd."""
    eng = _fake(tmp_path)
    try:
        req = eng.enqueue_request("e", "", "test-tiny",
                                  prompt_tokens=[1, 2], kind="embed",
                                  sampling=SamplingParams())
        collect(req)
        entries, _ = load_wal_records(
            os.path.join(eng.ecfg.wal_dir, "wal.jsonl"))
        assert req.req_id not in entries
    finally:
        eng.stop()


def test_wal_truncated_tail_is_loadable(tmp_path):
    """Randomized crash points: any byte-truncation of a WAL file loads
    without error into a consistent prefix of the full state."""
    eng = _fake(tmp_path)
    try:
        for i in range(3):
            collect(eng.enqueue_request(
                f"u{i}", "", "test-tiny", prompt_tokens=[1] * (i + 2),
                sampling=SamplingParams(max_tokens=3 + i)))
        time.sleep(0.2)  # let the flusher land everything
    finally:
        eng.stop()
    path = os.path.join(str(tmp_path / "wal"), "wal.jsonl")
    full, torn = load_wal_records(path)
    assert torn == 0 and len(full) == 3
    data = open(path, "rb").read()
    rng = random.Random(7)
    for _ in range(25):
        cut = rng.randrange(0, len(data))
        trunc = str(tmp_path / "trunc.jsonl")
        with open(trunc, "wb") as f:
            f.write(data[:cut])
        part, _torn = load_wal_records(trunc)  # must not raise
        for rid, ent in part.items():
            ref = full[rid]
            assert ent["admit"]["prompt"] == ref["admit"]["prompt"]
            # Token progress is a prefix of the full run's.
            assert ent["toks"] == ref["toks"][:len(ent["toks"])]


def test_wal_fault_degrades_loudly(tmp_path):
    """Injected disk trouble (fault site 'wal') degrades the WAL — the
    alert fires, serving continues un-journaled, nothing hangs."""
    from ollamamq_tpu.testing.faults import FaultPlan

    plan = FaultPlan([{"site": "wal", "kind": "exception", "at": [1]}])
    eng = _fake(tmp_path, fault_plan=plan)
    try:
        req = eng.enqueue_request("f", "", "test-tiny",
                                  prompt_tokens=[1, 2],
                                  sampling=SamplingParams(max_tokens=3))
        items = collect(req)
        assert items[-1].kind == "done"  # serving survived the disk
        deadline = time.monotonic() + 5
        while not eng.durability.wal.dead \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert eng.durability.wal.dead
        assert any(a.name == "wal_degraded" for a in eng.alerts.active())
        # Later requests still serve (and no longer block on the WAL).
        items = collect(eng.enqueue_request(
            "f", "", "test-tiny", prompt_tokens=[3],
            sampling=SamplingParams(max_tokens=2)))
        assert items[-1].kind == "done"
    finally:
        eng.stop()


# ------------------------------------------------------------------ recovery
def test_recovery_resumes_token_exact(tmp_path):
    """Crash mid-stream, recover on a fresh engine: the stream completes
    byte- AND token-identical to an uninterrupted run, the journal
    carries recover_replay, and the recovered metric counts it."""
    eng = _fake(tmp_path, latency=0.02)
    req = eng.enqueue_request("alice", "", "test-tiny",
                              prompt_tokens=[1, 2, 3],
                              sampling=SamplingParams(max_tokens=12))
    rid = req.req_id
    while len(req.generated_ids) < 5:
        time.sleep(0.005)
    _crash(eng)
    crash_dir = _crash_copy(eng, tmp_path)

    eng2 = _fake(tmp_path.joinpath("ignored"), wal_dir=crash_dir)
    try:
        dur = eng2.durability
        assert dur.recovered_streams == 1
        entry = dur.registry.find(rid)
        assert entry is not None and entry.recovered
        deadline = time.monotonic() + 20
        while entry.terminal is None and time.monotonic() < deadline:
            time.sleep(0.01)
        frames, term = entry.snapshot(0)
        assert term == {"reason": "length", "error": ""}
        assert "".join(t for _, t in frames) \
            == "".join(f"word{i} " for i in range(12))
        assert [i for i, _ in frames if i >= 0] == list(range(1, 13))
        recs = eng2.journal.tail(kind="recover_replay")
        assert len(recs) == 1
        assert recs[0]["outcome"] == "replayed"
        assert recs[0]["wal_rid"] == rid
        assert recs[0]["tokens"] == len(
            load_wal_records(os.path.join(crash_dir, "wal.jsonl.1")
                             )[0][rid]["toks"])
        # The new WAL generation compacted the survivor under its
        # ORIGINAL rid, so a second crash recovers cumulatively.
        entries, _ = load_wal_records(os.path.join(crash_dir, "wal.jsonl"))
        assert rid in entries
    finally:
        eng2.stop()


def test_recovery_finished_budget_surfaces_terminal(tmp_path):
    """A stream whose budget was already spent at crash time is NOT
    re-admitted (regenerating token 13 of 12 would fork the stream);
    its terminal is surfaced for any resuming client."""
    eng = _fake(tmp_path)
    req = eng.enqueue_request("b", "", "test-tiny", prompt_tokens=[1],
                              sampling=SamplingParams(max_tokens=4))
    rid = req.req_id
    items = collect(req)
    assert items[-1].kind == "done"
    # Forge the crash window: drop the fin record so the WAL says
    # "4/4 tokens emitted, no terminal".
    time.sleep(0.2)
    _crash(eng)
    crash_dir = _crash_copy(eng, tmp_path)
    path = os.path.join(crash_dir, "wal.jsonl")
    lines = [l for l in open(path) if '"fin"' not in l]
    open(path, "w").writelines(lines)

    eng2 = _fake(tmp_path.joinpath("ignored"), wal_dir=crash_dir)
    try:
        assert eng2.durability.recovered_streams == 0
        entry = eng2.durability.registry.find(rid)
        assert entry.terminal == {"reason": "length", "error": ""}
        assert entry.token_count() == 4
        recs = eng2.journal.tail(kind="recover_replay")
        assert recs and recs[0]["outcome"] == "finished"
    finally:
        eng2.stop()


def test_recovery_real_engine_page_conservation(tmp_path, tiny_cfg):
    """The acceptance shape on a REAL runtime: a greedy stream
    interrupted mid-decode recovers byte- and token-identical, with the
    page allocator conserving free+used+cached==pool after recovery and
    the journal invariant checker clean."""
    import jax.numpy as jnp

    from ollamamq_tpu.engine.engine import TPUEngine

    tiny = dict(model="test-tiny", max_slots=2, num_pages=64, page_size=8,
                max_pages_per_seq=8, prefill_buckets=(16, 32),
                decode_steps_per_iter=1)
    prompt = list(range(7, 19))
    # Golden: an uninterrupted greedy run.
    ref = TPUEngine(EngineConfig(**tiny), blocklist_path=None,
                    dtype=jnp.float32)
    ref.start()
    try:
        gr = ref.enqueue_request("g", "", "test-tiny",
                                 prompt_tokens=list(prompt),
                                 sampling=SamplingParams(max_tokens=10))
        golden_items = collect(gr, timeout=240)
        golden_text = "".join(i.text for i in golden_items
                              if i.kind == "token")
        golden_ids = list(gr.generated_ids)
    finally:
        ref.stop()
    assert len(golden_ids) == 10

    eng = TPUEngine(EngineConfig(wal_dir=str(tmp_path / "wal"),
                                 wal_fsync_ms=2.0, **tiny),
                    blocklist_path=None, dtype=jnp.float32)
    eng.start()
    req = eng.enqueue_request("g", "", "test-tiny",
                              prompt_tokens=list(prompt),
                              sampling=SamplingParams(max_tokens=10))
    rid = req.req_id
    deadline = time.monotonic() + 240
    while len(req.generated_ids) < 4 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(req.generated_ids) >= 4, "stream never got going"
    _crash(eng)
    crash_dir = _crash_copy(eng, tmp_path)

    eng2 = TPUEngine(EngineConfig(wal_dir=crash_dir, wal_fsync_ms=2.0,
                                  **tiny),
                     blocklist_path=None, dtype=jnp.float32)
    eng2.start()
    try:
        entry = eng2.durability.registry.find(rid)
        assert entry is not None
        deadline = time.monotonic() + 240
        while entry.terminal is None and time.monotonic() < deadline:
            time.sleep(0.02)
        frames, term = entry.snapshot(0)
        assert term is not None and term["reason"] in ("length", "stop")
        assert "".join(t for _, t in frames) == golden_text
        assert [i for i, _ in frames if i >= 0] == golden_ids
        # Page conservation after recovery, on the live allocators
        # (page 0 is reserved: free + used + cached == pool - 1).
        for rt in eng2._step_targets():
            alloc = getattr(rt, "alloc", None)
            if alloc is None:
                continue
            assert (alloc.free_pages + alloc.used_pages
                    + alloc.cached_pages == alloc.num_pages - 1)
        assert check_invariants(eng2.journal.tail(None)) == []
    finally:
        eng2.stop()


# ------------------------------------------------- resume endpoint (sockets)
class _Http:
    """Real-socket server over an engine (the test_fleet pattern)."""

    def __init__(self, engine, timeout_s=30):
        import asyncio

        from aiohttp import web

        from ollamamq_tpu.server.app import Server

        self.engine = engine
        self.port = free_port()
        started = threading.Event()

        def serve():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            app = Server(engine, timeout_s=timeout_s).build_app()
            runner = web.AppRunner(app, shutdown_timeout=1.0)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, "127.0.0.1", self.port)
            loop.run_until_complete(site.start())
            started.set()
            loop.run_forever()

        self._thread = threading.Thread(target=serve, daemon=True)
        self._thread.start()
        assert started.wait(15)

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def stop(self):
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10)
        self.engine.stop()


def _read_ndjson(resp):
    text, ids, done = "", [], None
    for raw in resp:
        obj = json.loads(raw)
        ids.extend(int(t) for t in obj.get("token_ids") or ())
        text += obj.get("response", "")
        if obj.get("done"):
            done = obj.get("done_reason")
            break
    return text, ids, done


def test_resume_endpoint_e2e(tmp_path):
    """GET /api/stream/{rid}?from=N over real sockets: mid-stream
    reattach follows live to the terminal; post-finish replay serves the
    archive; unknown rid is 404; /health carries the wal block."""
    eng = _fake(tmp_path, latency=0.03)
    srv = _Http(eng)
    try:
        h = json.loads(urllib.request.urlopen(
            srv.url + "/health", timeout=5).read())
        assert h["wal"]["enabled"] and h["status"] == "ok"

        body = json.dumps({"model": "test-tiny", "prompt": "x",
                           "stream": True,
                           "options": {"num_predict": 9}}).encode()
        main = urllib.request.urlopen(urllib.request.Request(
            srv.url + "/api/generate", data=body,
            headers={"Content-Type": "application/json"}), timeout=30)
        first = json.loads(next(iter(main)))
        rid = first["req_id"]
        # Reattach from token 1 while the stream is still live.
        text, ids, done = _read_ndjson(urllib.request.urlopen(
            srv.url + f"/api/stream/{rid}?from=1", timeout=30))
        assert done == "length"
        assert text == "".join(f"word{i} " for i in range(1, 9))
        assert ids == list(range(2, 10))
        main.close()
        # Full archive replay after the fact.
        text, ids, done = _read_ndjson(urllib.request.urlopen(
            srv.url + f"/api/stream/{rid}?from=0", timeout=30))
        assert text == "".join(f"word{i} " for i in range(9))
        assert ids == list(range(1, 10))
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(srv.url + "/api/stream/99999",
                                   timeout=5)
        assert e.value.code == 404
    finally:
        srv.stop()


# ------------------------------------------------- subprocess e2e (cli path)
def _spawn_cli(tmp_path, port, wal_dir, extra=(), latency="0.05"):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FAKE_TOKEN_LATENCY_S"] = latency
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    logf = open(str(tmp_path / f"server-{port}.log"), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ollamamq_tpu.cli", "--fake-engine",
         "--no-tui", "--models", "test-tiny", "--port", str(port),
         "--wal-dir", wal_dir, "--wal-fsync-ms", "2",
         "--blocklist", str(tmp_path / "bl.json"), *extra],
        stdout=logf, stderr=subprocess.STDOUT, env=env)
    proc._logf = logf
    return proc


def _wait_health(port, budget=90.0):
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=2).read())
            if body.get("status") != "recovering":
                return body
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.1)
    raise TimeoutError(f"server :{port} never became healthy")


def test_sigterm_drains_then_exits_zero(tmp_path):
    """SIGTERM mid-stream: admission stops (503), the live stream runs
    to completion for its client, the WAL records the finish, and the
    process exits 0 — `docker stop` is a zero-drop event."""
    port = free_port()
    wal_dir = str(tmp_path / "wal")
    proc = _spawn_cli(tmp_path, port, wal_dir,
                      extra=("--stop-grace-s", "30"))
    try:
        _wait_health(port)
        body = json.dumps({"model": "test-tiny", "prompt": "x",
                           "stream": True,
                           "options": {"num_predict": 12}}).encode()
        resp = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/api/generate", data=body,
            headers={"Content-Type": "application/json"}), timeout=60)
        first = json.loads(next(iter(resp)))
        assert first["req_id"] >= 1
        proc.send_signal(signal.SIGTERM)
        # Admission is closed almost immediately...
        deadline = time.monotonic() + 10
        shed = None
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/api/generate", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=5).read()
            except urllib.error.HTTPError as e:
                shed = e.code
                break
            except Exception:  # noqa: BLE001 — already gone = also fine
                break
            time.sleep(0.1)
        # ...while the live stream completes rather than being cut.
        text, _ids, done = _read_ndjson(resp)
        full = first.get("response", "") + text
        assert done == "length"
        assert full == "".join(f"word{i} " for i in range(12))
        assert proc.wait(timeout=60) == 0
        if shed is not None:
            assert shed == 503
        entries, _ = load_wal_records(os.path.join(wal_dir, "wal.jsonl"))
        assert all(e["finished"] is not None for e in entries.values())
    finally:
        proc.kill()
        proc._logf.close()


def test_kill9_restart_resume_byte_identical(tmp_path):
    """THE headline e2e: a greedy stream interrupted by kill -9 of the
    serving process mid-decode, restart on the same WAL, client
    reconnects via GET /api/stream/{rid}?from=N — the total delivery is
    byte- AND token-identical to an uninterrupted run."""
    port = free_port()
    wal_dir = str(tmp_path / "wal")
    proc = _spawn_cli(tmp_path, port, wal_dir)
    proc2 = None
    try:
        _wait_health(port)
        body = json.dumps({"model": "test-tiny", "prompt": "x",
                           "stream": True,
                           "options": {"num_predict": 12}}).encode()
        resp = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/api/generate", data=body,
            headers={"Content-Type": "application/json"}), timeout=60)
        rid, text, ids = None, "", []
        for raw in resp:
            obj = json.loads(raw)
            rid = obj.get("req_id", rid)
            ids.extend(int(t) for t in obj.get("token_ids") or ())
            text += obj.get("response", "")
            if len(ids) >= 5:
                break
        proc.kill()  # SIGKILL: no flush, no goodbye
        proc.wait(timeout=30)
        try:
            resp.close()
        except Exception:  # noqa: BLE001
            pass

        proc2 = _spawn_cli(tmp_path, port, wal_dir, latency="0.0")
        health = _wait_health(port)
        assert health["wal"]["recovered_streams"] == 1
        rtext, rids, done = _read_ndjson(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/stream/{rid}?from={len(ids)}",
            timeout=60))
        assert done == "length"
        assert text + rtext == "".join(f"word{i} " for i in range(12))
        assert ids + rids == list(range(1, 13))
    finally:
        proc.kill()
        if proc2 is not None:
            proc2.kill()
        proc._logf.close()


# ------------------------------------------------------- graceful quiesce
def test_quiesce_sheds_honestly(tmp_path):
    eng = _fake(tmp_path)
    try:
        eng.quiesce()
        from ollamamq_tpu.engine.engine import QueueFullError

        with pytest.raises(QueueFullError) as e:
            eng.enqueue_request("q", "", "test-tiny", prompt_tokens=[1],
                                sampling=SamplingParams(max_tokens=2))
        assert e.value.scope == "queue_full"
        sheds = eng.journal.tail(kind="shed")
        assert sheds and sheds[-1]["limit"] == 0
        assert check_invariants(eng.journal.tail(None)) == []
        assert eng.inflight_count() == 0
    finally:
        eng.stop()


# ------------------------------------------------------- sampled journaling
def test_sampled_journal_keeps_decisions(tmp_path):
    """--journal-sample: high-rate kinds thin out, decision-critical
    kinds all survive, per-record invariants stay checkable, and the
    offline checker understands the sampled spill."""
    path = str(tmp_path / "sampled.jsonl")
    j = Journal(capacity=8192, path=path, sample=0.1)
    for i in range(400):
        j.record("batch", slots=[0], batch_size=1, tokens=4,
                 occupancy=0.5, mode="fake", padded_tokens=4)
        j.record("page_alloc", n=1, free=10, used=5, cached=1, pool=16)
    for i in range(20):
        j.record("enqueue", req_id=i, user="u", n_prompt=3, queued=1)
        j.record("shed", user="u", reason="queue_full", queued=9, limit=8)
        j.record("finish", req_id=i, user="u", reason="stop", tokens=2)
    j.close()
    recs = j.tail(None)
    kinds = {}
    for r in recs:
        kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
    # Sampled kinds thinned hard (800 -> ~80 expected), decisions whole.
    assert kinds.get("batch", 0) + kinds.get("page_alloc", 0) < 300
    assert kinds["enqueue"] == kinds["shed"] == kinds["finish"] == 20
    assert j.sampled_out > 0
    assert j.snapshot()["sample"] == 0.1
    # Metrics still count every event, sampled-out included.
    batch_total = next(
        child.value for labels, child in
        tm.JOURNAL_EVENTS_TOTAL.series() if labels == ("batch",))
    assert batch_total >= 400
    # Surviving page records are self-contained: conservation holds.
    assert check_invariants(recs, starve_after=None) == []
    # The CLI checker reads the sampled meta and exits clean.
    assert journal_main(["check", path]) == 0


def test_sampled_journal_default_records_everything():
    j = Journal(capacity=64)
    for _ in range(30):
        j.record("batch", slots=[0], batch_size=1, tokens=1,
                 occupancy=0.1)
    assert len(j.tail(None)) == 30
    assert j.sampled_out == 0
    assert "sample" not in j.snapshot()


# ----------------------------------------------- fleet-wide audit roll-up
def _spill(path, records, meta=None):
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps({"journal_meta": {"version": 1,
                                             **(meta or {})}}) + "\n")
        for i, r in enumerate(records):
            f.write(json.dumps({"seq": i, "t": 0.0, "tick": i, **r}) + "\n")


def test_multi_file_check_rolls_up_across_crash(tmp_path):
    """The fleet roll-up: a stream cut off by the router's crash (its
    pre-crash spill ends with a failover and no terminal) is resolved by
    the restarted router's spill naming it in recover_replay.wal_rid —
    and stays a violation when the recovery spill is absent."""
    pre = str(tmp_path / "router1.jsonl")
    post = str(tmp_path / "router2.jsonl")
    _spill(pre, [
        {"kind": "enqueue", "req_id": 5, "user": "u", "n_prompt": 3,
         "queued": 1},
        {"kind": "replica_eject", "replica": "r0", "why": "crash"},
        {"kind": "replica_failover", "req_id": 5, "user": "u",
         "replica": "r0", "to_replica": "r1", "replayed_tokens": 2},
    ])
    _spill(post, [
        {"kind": "recover_replay", "req_id": 1, "user": "u", "tokens": 4,
         "outcome": "replayed", "wal_rid": 5},
        {"kind": "finish", "req_id": 1, "user": "u", "reason": "stop",
         "tokens": 6},
    ])
    # Alone, the cut spill shows a dropped stream...
    assert journal_main(["check", pre]) == 1
    # ...the roll-up resolves it across the crash.
    assert journal_main(["check", pre, post]) == 0
    # An unresolved recovery is still a drop.
    unres = str(tmp_path / "router3.jsonl")
    _spill(unres, [
        {"kind": "recover_replay", "req_id": 1, "user": "u", "tokens": 4,
         "outcome": "replayed", "wal_rid": 5},
    ])
    assert journal_main(["check", pre, unres]) == 1


def test_attribution_flags_double_terminal(tmp_path):
    path = str(tmp_path / "double.jsonl")
    _spill(path, [
        {"kind": "replica_failover", "req_id": 7, "user": "u",
         "replica": "a", "to_replica": "b", "replayed_tokens": 1},
        {"kind": "finish", "req_id": 7, "user": "u", "reason": "stop",
         "tokens": 3},
        {"kind": "finish", "req_id": 7, "user": "u", "reason": "stop",
         "tokens": 3},
    ])
    assert journal_main(["check", path]) == 1


def test_fleet_router_wal_recovery(tmp_path):
    """Fleet-wide recovery: the ROUTER owns the WAL; after a crash its
    streams re-place across the surviving members and the roll-up audit
    over both router generations is clean."""
    import dataclasses

    from ollamamq_tpu.fleet import FleetRouter, LocalMember

    def build(wal_dir, spill, members_n=2):
        ecfg = EngineConfig(model="test-tiny", max_slots=4,
                            wal_dir=wal_dir, wal_fsync_ms=2.0,
                            journal_file=spill)
        member_cfg = dataclasses.replace(ecfg, wal_dir=None,
                                         journal_file=None)
        members = [LocalMember(f"r{i}", FakeEngine(
            member_cfg, blocklist_path=None, token_latency_s=0.02))
            for i in range(members_n)]
        router = FleetRouter(members, ecfg, blocklist_path=None,
                             probe_period_s=0.05, eject_heartbeat_s=5.0,
                             reprobe_backoff_s=0.1, evac_grace_s=0.5)
        router.start()
        return router

    wal_dir = str(tmp_path / "wal")
    r1 = build(wal_dir, str(tmp_path / "r1.jsonl"))
    req = r1.enqueue_request("fl", "", "test-tiny", prompt_tokens=[1, 2],
                             sampling=SamplingParams(max_tokens=10))
    rid = req.req_id
    # The router-side Request never fills generated_ids (members own
    # generation); progress reads off the durability tap's frame log.
    live = r1.durability.registry.find(rid)
    deadline = time.monotonic() + 30
    while live.token_count() < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert live.token_count() >= 3
    # Crash the whole router process-equivalent: loop + members die.
    r1._running = False
    r1.notify()
    for m in r1.members:
        m.engine._running = False
        m.engine.notify()
    time.sleep(0.15)
    wal = r1.durability.wal
    wal._stop.set()
    if wal._flusher is not None:
        wal._flusher.join(timeout=5)
    r1.journal.close()

    crash_dir = str(tmp_path / "wal-crash")
    shutil.copytree(wal_dir, crash_dir)
    r1.stop()  # tear the corpse down (threads), post-snapshot
    r2 = build(crash_dir, str(tmp_path / "r2.jsonl"))
    try:
        assert r2.durability.recovered_streams == 1
        entry = r2.durability.registry.find(rid)
        deadline = time.monotonic() + 30
        while entry.terminal is None and time.monotonic() < deadline:
            time.sleep(0.02)
        frames, term = entry.snapshot(0)
        assert term is not None
        assert "".join(t for _, t in frames) \
            == "".join(f"word{i} " for i in range(10))
    finally:
        r2.stop()
    assert journal_main(["check", str(tmp_path / "r1.jsonl"),
                         str(tmp_path / "r2.jsonl")]) == 0


# ------------------------------------------------------------------- soak
@pytest.mark.slow
def test_recovery_crash_point_soak(tmp_path):
    """Randomized crash points x many streams: every recovery completes
    every stream byte-identical, never duplicates a token, and the WAL
    survives arbitrary interruption points."""
    rng = random.Random(11)
    for trial in range(6):
        base = tmp_path / f"t{trial}"
        base.mkdir()
        eng = _fake(base, latency=0.01)
        reqs = [eng.enqueue_request(
            f"u{i % 3}", "", "test-tiny", prompt_tokens=[1] * (2 + i),
            sampling=SamplingParams(max_tokens=rng.randrange(4, 14)))
            for i in range(5)]
        target = rng.randrange(1, 30)
        deadline = time.monotonic() + 30
        while sum(len(r.generated_ids) for r in reqs) < target \
                and time.monotonic() < deadline:
            time.sleep(0.002)
        _crash(eng)
        crash_dir = _crash_copy(eng, base)
        eng2 = _fake(base.joinpath("x"), wal_dir=crash_dir)
        try:
            for r in reqs:
                entry = eng2.durability.registry.find(r.req_id)
                assert entry is not None
                deadline = time.monotonic() + 60
                while entry.terminal is None \
                        and time.monotonic() < deadline:
                    time.sleep(0.01)
                frames, term = entry.snapshot(0)
                assert term is not None, f"trial {trial} req {r.req_id}"
                want = min(r.sampling.max_tokens, 16)
                assert [i for i, _ in frames if i >= 0] \
                    == list(range(1, want + 1))
                assert "".join(t for _, t in frames) \
                    == "".join(f"word{i} " for i in range(want))
            assert check_invariants(eng2.journal.tail(None),
                                    starve_after=None) == []
        finally:
            eng2.stop()
