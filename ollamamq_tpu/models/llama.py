"""Llama/Qwen-family decoder in pure functional JAX.

Design notes (TPU-first):
  - Layer parameters are STACKED along a leading `num_layers` axis and the
    forward is a `lax.scan` over layers — one traced layer body, fast XLA
    compile, and the KV cache ([L, S, Hk, hd]) scans naturally alongside.
  - Two entry points: `forward_prefill` (padded bucket, causal attention,
    writes the prompt's K/V into paged slots) and `forward_decode` (one
    token per slot, paged attention over the slot pool). Both are shape-
    static => jit once per (bucket, batch) and never recompile.
  - All matmuls run in the params dtype (bf16 on TPU => MXU), softmax and
    logits in f32.
  - Qwen2.5 support = `attn_bias=True` in ModelConfig; the same code path
    serves both families (capability parity with the reference's two
    stress-test models, /root/reference/test_dispatcher.sh:5-7).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ollamamq_tpu.config import ModelConfig
from ollamamq_tpu.ops.attention import (
    causal_attention,
    bidirectional_attention,
    flat_slot_indices,
    paged_chunk_attention_blockwise,
    paged_decode_attention_any,
    ragged_attention_any,
)
from ollamamq_tpu.ops.quant import embed_lookup, kv_write, logits_head, qeinsum
from ollamamq_tpu.ops.rope import apply_rope


def _adtype(params: dict):
    """Activation dtype for a forward: norm weights are never quantized,
    so final_norm carries the compute dtype even when embed/matmul
    weights are int8 QuantTensors."""
    return params["final_norm"].dtype


def rmsnorm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> dict:
    """Random-init a params pytree (layers stacked on axis 0)."""
    d, qd, kvd, f = cfg.hidden_size, cfg.q_dim, cfg.kv_dim, cfg.intermediate_size
    L, v = cfg.num_layers, cfg.vocab_size
    keys = jax.random.split(key, 10)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(dtype)

    layers = {
        "attn_norm": jnp.ones((L, d), dtype),
        "wq": w(keys[0], (L, d, qd), d),
        "wk": w(keys[1], (L, d, kvd), d),
        "wv": w(keys[2], (L, d, kvd), d),
        "wo": w(keys[3], (L, qd, d), qd),
        "mlp_norm": jnp.ones((L, d), dtype),
        "w_gate": w(keys[4], (L, d, f), d),
        "w_up": w(keys[5], (L, d, f), d),
        "w_down": w(keys[6], (L, f, d), f),
    }
    if cfg.attn_bias:
        layers["bq"] = jnp.zeros((L, qd), dtype)
        layers["bk"] = jnp.zeros((L, kvd), dtype)
        layers["bv"] = jnp.zeros((L, kvd), dtype)
    if cfg.qk_norm:
        # Qwen3: per-head RMSNorm on q/k (weight over head_dim).
        layers["q_norm"] = jnp.ones((L, cfg.head_dim), dtype)
        layers["k_norm"] = jnp.ones((L, cfg.head_dim), dtype)
    if cfg.num_experts:
        # MoE family: the dense FFN is replaced by routed experts.
        from ollamamq_tpu.models.moe import init_moe_layer_params

        for dense in ("w_gate", "w_up", "w_down"):
            del layers[dense]
        layers.update(init_moe_layer_params(cfg, keys[9], dtype))
    params = {
        "embed": w(keys[7], (v, d), d),
        "final_norm": jnp.ones((d,), dtype),
        "layers": layers,
    }
    if not cfg.tie_embeddings and not cfg.is_encoder:
        params["lm_head"] = w(keys[8], (v, d), d)
    return params


def _qkv(cfg: ModelConfig, lp: dict, h: jnp.ndarray):
    """Project hidden -> q,k,v with head reshape. h: [B, T, D]."""
    B, T, _ = h.shape
    q = qeinsum("btd,de->bte", h, lp["wq"])
    k = qeinsum("btd,de->bte", h, lp["wk"])
    v = qeinsum("btd,de->bte", h, lp["wv"])
    if cfg.attn_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(B, T, cfg.num_heads, cfg.head_dim)
    k = k.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rmsnorm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rmsnorm(k, lp["k_norm"], cfg.rms_norm_eps)
    return q, k, v


def _mlp(lp: dict, h: jnp.ndarray) -> jnp.ndarray:
    gate = qeinsum("btd,df->btf", h, lp["w_gate"])
    up = qeinsum("btd,df->btf", h, lp["w_up"])
    return qeinsum("btf,fd->btd", jax.nn.silu(gate) * up, lp["w_down"])


def _ffn(cfg: ModelConfig, lp: dict, h: jnp.ndarray,
         valid=None) -> jnp.ndarray:
    """Dense SwiGLU or routed mixture-of-experts, by model family.

    `valid` ([B, T] bool) marks real tokens; only MoE routing consumes it
    (padding/inactive rows must not claim expert capacity).
    """
    if cfg.num_experts:
        from ollamamq_tpu.models.moe import moe_mlp

        return moe_mlp(cfg, lp, h, valid=valid)
    return _mlp(lp, h)


def _logits(params: dict, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head", params["embed"])
    return logits_head(x, head)


def _layer_step(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
                positions: jnp.ndarray, attn_fn, valid=None):
    """One transformer layer over a full [B, T, D] sequence.

    The SINGLE definition of the layer math for every full-sequence
    forward (prefill, sequence-parallel prefill, encoder) — only the
    attention schedule differs, injected as `attn_fn(q, k, v) -> [B,T,H,hd]`.
    Returns (x', k, v) so callers can scatter K/V into the paged cache.
    (forward_decode keeps its own body: it must write K/V into the scan-
    carried cache BEFORE attending.)
    """
    B, T, _ = x.shape
    h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q, k, v = _qkv(cfg, lp, h)
    q = apply_rope(q, positions, cfg.rope_theta)
    k = apply_rope(k, positions, cfg.rope_theta)
    attn = attn_fn(q, k, v)
    x = x + qeinsum("bte,ed->btd", attn.reshape(B, T, cfg.q_dim), lp["wo"])
    h2 = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    return x + _ffn(cfg, lp, h2, valid=valid), k, v


def forward_prefill(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T] int32, right-padded
    seq_lens: jnp.ndarray,  # [B] valid lengths
    k_cache: jnp.ndarray,  # [L, S, Hk, hd] flat slot pool (donated)
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]; padding rows point at trash page
    page_size: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Process fresh prompts; returns (last_logits [B, V], k_cache', v_cache').

    Padding positions scatter into the allocator's reserved trash page, so
    the write is fully static-shaped — no dynamic trimming needed.
    """
    B, T = tokens.shape
    x = embed_lookup(params["embed"], tokens, _adtype(params))
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    slots = flat_slot_indices(page_table, positions, page_size)  # [B, T]

    def body(carry, per_layer):
        x = carry
        lp, kc, vc = per_layer
        x, k, v = _layer_step(
            cfg, lp, x, positions,
            lambda q, k, v: causal_attention(q, k, v, seq_lens),
            valid=positions < seq_lens[:, None],
        )
        kc = kv_write(kc, slots, k)
        vc = kv_write(vc, slots, v)
        return x, (kc, vc)

    x, (k_cache, v_cache) = jax.lax.scan(
        body, x, (params["layers"], k_cache, v_cache)
    )
    last = jnp.clip(seq_lens - 1, 0, T - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)  # [B,1,D]
    logits = _logits(params, cfg, x_last)[:, 0, :]  # [B, V]
    return logits, k_cache, v_cache


def forward_prefill_chunk(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, C] one chunk of the prompt, right-padded
    start: jnp.ndarray,  # [B] global position of the chunk's first token
    chunk_lens: jnp.ndarray,  # [B] valid tokens in this chunk
    k_cache: jnp.ndarray,  # [L, S, Hk, hd] (donated)
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages] — covers prefix AND chunk
    page_size: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One chunk of a long prompt: writes the chunk's K/V into its pages,
    attends over the previously-written prefix + the chunk itself
    (paged_chunk_attention). Chaining chunks reproduces forward_prefill
    exactly, lifting the prompt-length ceiling from the largest bucket to
    the full paged context. Returns (last-valid-position logits, caches').
    """
    B, C = tokens.shape
    x = embed_lookup(params["embed"], tokens, _adtype(params))
    positions = start[:, None] + jnp.broadcast_to(
        jnp.arange(C, dtype=jnp.int32), (B, C)
    )
    slots = flat_slot_indices(page_table, positions, page_size)  # [B, C]

    def body(carry, per_layer):
        x = carry
        lp, kc, vc = per_layer

        def attn_fn(q, k, v):
            nonlocal kc, vc
            kc = kv_write(kc, slots, k)
            vc = kv_write(vc, slots, v)
            # Block-wise online-softmax walk over real pages only — HBM
            # reads scale with the actual prefix length, not max context.
            return paged_chunk_attention_blockwise(
                q, kc, vc, page_table, start, chunk_lens, page_size
            )

        x, _, _ = _layer_step(
            cfg, lp, x, positions, attn_fn,
            valid=jnp.arange(tokens.shape[1])[None, :] < chunk_lens[:, None],
        )
        return x, (kc, vc)

    x, (k_cache, v_cache) = jax.lax.scan(
        body, x, (params["layers"], k_cache, v_cache)
    )
    last = jnp.clip(chunk_lens - 1, 0, C - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)
    logits = _logits(params, cfg, x_last)[:, 0, :]
    return logits, k_cache, v_cache


def forward_ragged(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [T] int32 flattened mixed-batch token stream
    tok_seq: jnp.ndarray,  # [T] int32 sequence (batch row) per token
    tok_pos: jnp.ndarray,  # [T] int32 kv position per token (-1 = pad)
    write_slots: jnp.ndarray,  # [T] int32 flat cache slot per token
    out_idx: jnp.ndarray,  # [B] or [B, O] int32 stream indices to read logits at
    k_cache: jnp.ndarray,  # [L, S, Hk, hd] (donated)
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    q_start: jnp.ndarray,  # [B] span offset per sequence
    q_len: jnp.ndarray,  # [B] span length (0 = padding row)
    kv_len: jnp.ndarray,  # [B] context length incl. the span
    page_size: int,
    attn_impl: str = "jnp",  # "jnp" reference | "pallas" ragged TPU kernel
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """ONE forward over a ragged mixed batch: variable-length prefill
    spans and single decode tokens share a flattened [T] token stream —
    no per-sequence bucket padding. Each layer writes the stream's K/V
    into its pages, then every token attends causally over its own
    sequence's paged context (generalizes forward_prefill_chunk to many
    sequences and forward_decode to multi-token spans). `out_idx` names
    the stream positions whose logits leave the forward: a [B] vector
    (each sequence's last token — the classic shape) returns [B, V];
    a [B, O] matrix (speculative verification reads a logit at EVERY
    draft position of a span) returns [B, O, V]. Padding rows
    (q_len == 0) yield garbage logits the caller ignores. Returns
    (logits, caches').
    """
    T = tokens.shape[0]
    x = embed_lookup(params["embed"], tokens, _adtype(params))[None]  # [1,T,D]
    positions = jnp.maximum(tok_pos, 0)[None, :]  # [1, T] RoPE positions
    valid = (tok_pos >= 0)[None, :]

    def body(carry, per_layer):
        x = carry
        lp, kc, vc = per_layer

        def attn_fn(q, k, v):  # [1, T, H, hd]
            nonlocal kc, vc
            kc = kv_write(kc, write_slots, k[0])
            vc = kv_write(vc, write_slots, v[0])
            out = ragged_attention_any(
                attn_impl, q[0], kc, vc, page_table, tok_seq, tok_pos,
                kv_len, q_start, q_len, page_size, interpret=interpret,
            )
            return out[None]

        x, _, _ = _layer_step(cfg, lp, x, positions, attn_fn, valid=valid)
        return x, (kc, vc)

    x, (k_cache, v_cache) = jax.lax.scan(
        body, x, (params["layers"], k_cache, v_cache)
    )
    if out_idx.ndim == 1:
        x_last = x[0][out_idx]  # [B, D]
        logits = _logits(params, cfg, x_last[None])[0]  # [B, V]
    else:
        x_last = x[0][out_idx]  # [B, O, D]
        logits = _logits(params, cfg, x_last)  # [B, O, V]
    return logits, k_cache, v_cache


def forward_decode(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] int32 — last generated token per slot
    positions: jnp.ndarray,  # [B] int32 — position of `tokens` in each seq
    k_cache: jnp.ndarray,  # [L, S, Hk, hd] (donated)
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    page_size: int,
    attn_impl: str = "jnp",  # "jnp" reference | "pallas" ragged TPU kernel
    active=None,  # [B] int32/bool — live decode slots (None = all live)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decode step for the whole batch; returns (logits [B,V], caches').

    `active` feeds MoE routing only: parked slots carry garbage tokens
    that must not claim expert capacity (models/moe.py).
    """
    B = tokens.shape[0]
    valid = None if active is None else (active > 0)[:, None]
    x = embed_lookup(params["embed"], tokens, _adtype(params))[:, None, :]  # [B,1,D]
    pos2 = positions[:, None]  # [B,1]
    write_slots = flat_slot_indices(page_table, pos2, page_size)[:, 0]  # [B]
    seq_lens = positions + 1

    def body(carry, per_layer):
        x = carry
        lp, kc, vc = per_layer
        h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(cfg, lp, h)  # [B,1,H,hd]
        q = apply_rope(q, pos2, cfg.rope_theta)
        k = apply_rope(k, pos2, cfg.rope_theta)
        kc = kv_write(kc, write_slots, k[:, 0])
        vc = kv_write(vc, write_slots, v[:, 0])
        attn = paged_decode_attention_any(
            attn_impl, q[:, 0], kc, vc, page_table, seq_lens, page_size
        )  # [B,H,hd]
        x = x + qeinsum("be,ed->bd", attn.reshape(B, cfg.q_dim), lp["wo"])[:, None, :]
        h2 = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _ffn(cfg, lp, h2, valid=valid)
        return x, (kc, vc)

    x, (k_cache, v_cache) = jax.lax.scan(
        body, x, (params["layers"], k_cache, v_cache)
    )
    logits = _logits(params, cfg, x)[:, 0, :]
    return logits, k_cache, v_cache


def forward_prefill_sp(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T] — T sharded over the mesh "seq" axis
    seq_lens: jnp.ndarray,  # [B]
    mesh,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sequence-parallel prefill for long contexts: activations sharded
    along T over the "seq" mesh axis, attention via ring attention
    (K/V blocks rotate over ICI). Returns (last_logits [B,V],
    k_stack [L,B,T,Hk,hd], v_stack) — the caller scatters K/V into the
    paged pool. Numerics match forward_prefill exactly (same f32 online
    softmax), only the schedule is distributed.
    """
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from ollamamq_tpu.parallel.mesh import AXIS_SEQ
    from ollamamq_tpu.parallel.ring_attention import ring_attention

    B, T = tokens.shape
    seq_sharded = NamedSharding(mesh, PS(None, AXIS_SEQ, None))
    x = embed_lookup(params["embed"], tokens, _adtype(params))
    x = jax.lax.with_sharding_constraint(x, seq_sharded)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    def body(carry, lp):
        x = carry
        x, k, v = _layer_step(
            cfg, lp, x, positions,
            lambda q, k, v: ring_attention(q, k, v, seq_lens, mesh),
            valid=positions < seq_lens[:, None],
        )
        x = jax.lax.with_sharding_constraint(x, seq_sharded)
        return x, (k, v)

    x, (k_stack, v_stack) = jax.lax.scan(body, x, params["layers"])
    last = jnp.clip(seq_lens - 1, 0, T - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)
    logits = _logits(params, cfg, x_last)[:, 0, :]
    return logits, k_stack, v_stack


def forward_embed(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T]
    seq_lens: jnp.ndarray,  # [B]
) -> jnp.ndarray:
    """Embeddings from a GENERATIVE model: causal forward (no KV write),
    masked mean pool of the final-norm hidden states, L2 norm — llama.cpp's
    default pooling for causal models, which is what the reference's Ollama
    backends run for /api/embed on e.g. llama3 (README.md /api/embed row).
    """
    B, T = tokens.shape
    x = embed_lookup(params["embed"], tokens, _adtype(params))
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    def body(carry, lp):
        x = carry
        x, _, _ = _layer_step(
            cfg, lp, x, positions,
            lambda q, k, v: causal_attention(q, k, v, seq_lens),
            valid=positions < seq_lens[:, None],
        )
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps).astype(jnp.float32)
    mask = (positions < seq_lens[:, None]).astype(jnp.float32)[:, :, None]
    pooled = jnp.sum(x * mask, axis=1) / jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)


def forward_encoder(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T]
    seq_lens: jnp.ndarray,  # [B]
) -> jnp.ndarray:
    """Embedding encoder: bidirectional attention + masked mean pool + L2 norm."""
    B, T = tokens.shape
    x = embed_lookup(params["embed"], tokens, _adtype(params))
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    def body(carry, lp):
        x = carry
        x, _, _ = _layer_step(
            cfg, lp, x, positions,
            lambda q, k, v: bidirectional_attention(q, k, v, seq_lens),
            valid=positions < seq_lens[:, None],
        )
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rmsnorm(x, params["final_norm"], cfg.rms_norm_eps).astype(jnp.float32)
    mask = (positions < seq_lens[:, None]).astype(jnp.float32)[:, :, None]
    pooled = jnp.sum(x * mask, axis=1) / jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)
