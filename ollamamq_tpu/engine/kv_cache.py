"""Paged KV cache: device slot pool + host-side page allocator.

Device side: two arrays per model, [num_layers, num_pages*page_size,
kv_heads, head_dim] for K and V, kv-heads sharded over the "tensor" mesh
axis. The pool is allocated ONCE at engine start (static shape => no
recompiles, no fragmentation in HBM).

Host side: a free-list allocator of page indices. Page 0 is RESERVED as the
trash page: page-table rows are padded with it so static-shaped prefill
scatter writes of padding tokens land harmlessly (see
models/llama.py:forward_prefill).

Cancellation reclaims pages immediately — the TPU analogue of the
reference dropping a disconnected client's stream
(/root/reference/src/dispatcher.rs:537-551) plus freeing the backend slot.
"""

from __future__ import annotations

import io
import json
import struct
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ollamamq_tpu.config import EngineConfig, ModelConfig

TRASH_PAGE = 0


class PageAllocator:
    """Free-list allocator over page indices [1, num_pages).

    With the prefix cache enabled (engine/prefix_cache.py) every page is
    exactly one of FREE (on the free list), USED (private to a decode
    slot), or CACHED (owned by the radix tree, possibly pinned by live
    requests); `cached_pages` tracks the third bucket so
    free + used + cached == num_pages - 1 always holds.
    """

    def __init__(self, num_pages: int, page_size: int, max_pages_per_seq: int):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self._free: List[int] = list(range(num_pages - 1, 0, -1))  # page 0 reserved
        self.cached_pages = 0  # tree-owned (prefix cache accounting)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - len(self._free) - self.cached_pages

    def pages_needed(self, num_tokens: int) -> int:
        return max(1, -(-num_tokens // self.page_size))

    def can_alloc(self, num_tokens: int) -> bool:
        return self.pages_needed(num_tokens) <= len(self._free)

    def alloc(self, num_tokens: int) -> Optional[List[int]]:
        """Allocate pages to hold num_tokens; None if pool exhausted or the
        request exceeds the per-sequence page cap."""
        return self.alloc_n(self.pages_needed(num_tokens))

    def alloc_n(self, n: int, held: int = 0) -> Optional[List[int]]:
        """Allocate exactly n pages for a sequence already holding `held`
        (cache-hit admission: shared prefix pages count against the
        per-sequence cap but come from the tree, not the free list)."""
        if n > len(self._free) or held + n > self.max_pages_per_seq:
            return None
        return [self._free.pop() for _ in range(n)]

    # -- prefix-cache ownership transfer -----------------------------------
    def adopt_cached(self, n: int = 1) -> None:
        """A slot's page(s) moved into the prefix-cache tree: no longer
        used, not free either."""
        self.cached_pages += n

    def reclaim_cached(self, page: int) -> None:
        """An evicted tree page returns to the free list."""
        self.cached_pages -= 1
        if page != TRASH_PAGE:
            self._free.append(page)

    def extend(self, pages: List[int], new_total_tokens: int) -> bool:
        """Grow an allocation to cover new_total_tokens. False if exhausted
        or per-seq page cap reached."""
        need = self.pages_needed(new_total_tokens)
        while len(pages) < need:
            if not self._free or len(pages) >= self.max_pages_per_seq:
                return False
            pages.append(self._free.pop())
        return True

    def rollback_to(self, pages: List[int], kv_len: int,
                    keep: int = 0) -> int:
        """Speculative rollback: shrink an allocation (in place) to the
        pages a sequence of `kv_len` WRITTEN tokens actually needs,
        returning the rejected tail pages to the free list. `keep` floors
        the truncation at the sequence's shared prefix-tree pages (they
        lead the list and are owned by the tree, never this allocator's
        free list). Returns the number of pages freed.

        The device-side "un-write" is free: rejected draft positions sit
        past the rolled-back kv_len, so attention masks them out and the
        next real decode step overwrites them — only the host-side page
        claim needs releasing."""
        target = max(self.pages_needed(max(1, kv_len)), keep)
        freed = 0
        while len(pages) > target:
            p = pages.pop()
            if p != TRASH_PAGE:
                self._free.append(p)
                freed += 1
        return freed

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p != TRASH_PAGE:
                self._free.append(p)
        pages.clear()


def make_page_table_row(pages: List[int], max_pages: int) -> np.ndarray:
    """Pad a page list with the trash page to the static table width."""
    row = np.full((max_pages,), TRASH_PAGE, dtype=np.int32)
    row[: len(pages)] = pages
    return row


def alloc_kv_pool(
    model_cfg: ModelConfig,
    engine_cfg: EngineConfig,
    sharding=None,
    dtype=jnp.bfloat16,
    kv_dtype: str = "bfloat16",
    scale_sharding=None,
):
    """Allocate the device K/V slot pools (zeros). Returns (k_cache,
    v_cache) — plain arrays, or QuantKV pairs when kv_dtype="int8": an
    int8 payload pool plus fp32 per-slot per-head scale rows stored
    page-aligned alongside it (slot = page * page_size + offset), so the
    page allocator, prefix tree, preemption, and rollback machinery are
    untouched while every page shrinks ~2x."""
    from ollamamq_tpu.ops.quant import QuantKV

    S = engine_cfg.num_pages * engine_cfg.page_size
    shape = (model_cfg.num_layers, S, model_cfg.num_kv_heads,
             model_cfg.head_dim)

    def zeros(shp, dt, shard):
        if shard is not None:
            return jax.jit(lambda: jnp.zeros(shp, dt), out_shardings=shard)()
        return jnp.zeros(shp, dt)

    if kv_dtype == "int8":
        sshape = shape[:-1]  # [L, S, Hk] scale rows
        k = QuantKV(zeros(shape, jnp.int8, sharding),
                    jnp.ones(sshape, jnp.float32) if scale_sharding is None
                    else jax.jit(lambda: jnp.ones(sshape, jnp.float32),
                                 out_shardings=scale_sharding)())
        v = QuantKV(zeros(shape, jnp.int8, sharding),
                    jnp.ones(sshape, jnp.float32) if scale_sharding is None
                    else jax.jit(lambda: jnp.ones(sshape, jnp.float32),
                                 out_shardings=scale_sharding)())
        return k, v
    k = zeros(shape, dtype, sharding)
    v = zeros(shape, dtype, sharding)
    return k, v


def kv_pool_bytes(model_cfg: ModelConfig, engine_cfg: EngineConfig,
                  bytes_per_el=2, kv_dtype: str = "bfloat16") -> int:
    """Planning-time pool size; int8 pools count 1 payload byte plus the
    4-byte fp32 scale each (slot, head) row carries."""
    per_tok_head = (model_cfg.head_dim + 4 if kv_dtype == "int8"
                    else model_cfg.head_dim * bytes_per_el)
    return (
        2
        * model_cfg.num_layers
        * engine_cfg.num_pages
        * engine_cfg.page_size
        * model_cfg.num_kv_heads
        * per_tok_head
    )


# ---------------------------------------------------------------------------
# KV page migration: extract a sequence's page run from the pool into a
# portable host-side blob (and write one back at new page indices), plus
# a self-describing wire format so the blob can cross a process boundary
# (fleet HttpMember /admin/migrate). int8 pools move the int8 payload +
# fp32 scale rows — ~2x cheaper on the wire than bf16 pages.
# ---------------------------------------------------------------------------

_WIRE_MAGIC = b"OMQMIG1\n"


def _page_index(pages: List[int], page_size: int) -> np.ndarray:
    """Slot-pool row indices covering `pages` in run order."""
    idx = np.empty((len(pages) * page_size,), np.int32)
    for i, p in enumerate(pages):
        idx[i * page_size:(i + 1) * page_size] = np.arange(
            p * page_size, (p + 1) * page_size, dtype=np.int32)
    return idx


def gather_page_run(kc, vc, pages: List[int], page_size: int) -> dict:
    """Copy a page run's K/V data to host numpy arrays. Returns
    {"k_pages", "v_pages"} shaped [n_pages*page_size, ...] sliced along
    the pool's slot axis (axis 1), plus {"k_scale", "v_scale"} for
    quantized pools. Read-only with respect to the pool."""
    from ollamamq_tpu.ops.quant import QuantKV

    idx = jnp.asarray(_page_index(pages, page_size))
    if isinstance(kc, QuantKV):
        return {
            "k_pages": np.asarray(jnp.take(kc.q, idx, axis=1)),
            "v_pages": np.asarray(jnp.take(vc.q, idx, axis=1)),
            "k_scale": np.asarray(jnp.take(kc.s, idx, axis=1)),
            "v_scale": np.asarray(jnp.take(vc.s, idx, axis=1)),
        }
    return {
        "k_pages": np.asarray(jnp.take(kc, idx, axis=1)),
        "v_pages": np.asarray(jnp.take(vc, idx, axis=1)),
    }


def scatter_page_run(kc, vc, pages: List[int], page_size: int, data: dict):
    """Write a gathered page run back into a (possibly different) pool at
    `pages`. Returns the updated (kc, vc) — functional update, caller
    reassigns."""
    from ollamamq_tpu.ops.quant import QuantKV

    idx = jnp.asarray(_page_index(pages, page_size))
    if isinstance(kc, QuantKV):
        k = QuantKV(kc.q.at[:, idx].set(jnp.asarray(data["k_pages"])),
                    kc.s.at[:, idx].set(jnp.asarray(data["k_scale"])))
        v = QuantKV(vc.q.at[:, idx].set(jnp.asarray(data["v_pages"])),
                    vc.s.at[:, idx].set(jnp.asarray(data["v_scale"])))
        return k, v
    k = kc.at[:, idx].set(jnp.asarray(data["k_pages"], dtype=kc.dtype))
    v = vc.at[:, idx].set(jnp.asarray(data["v_pages"], dtype=vc.dtype))
    return k, v


def migration_blob_bytes(blob: dict) -> int:
    """Approximate wire size of a blob (the payload arrays dominate) —
    the ollamamq_fleet_migrate_bytes_total accounting unit."""
    return sum(v.nbytes for v in blob.values()
               if isinstance(v, np.ndarray))


def pack_migration_blob(blob: dict) -> bytes:
    """Serialize a migration blob for the wire: magic + length-prefixed
    JSON header (scalars/lists) + an npz of the numpy arrays. Keys
    starting with "_" are in-process-only state (e.g. a live incremental
    detokenizer) and are dropped — the unpacker reconstructs them.

    Non-native dtypes (bfloat16 and friends come from ml_dtypes, which
    npz cannot round-trip) ship as raw uint8 byte views with the true
    dtype name recorded in the header."""
    header, arrays, exotic = {}, {}, {}
    for key, val in blob.items():
        if key.startswith("_"):
            continue
        if isinstance(val, np.ndarray):
            if val.dtype.kind not in "biufc":
                exotic[key] = val.dtype.name
                val = np.ascontiguousarray(val).view(np.uint8)
            arrays[key] = val
        else:
            header[key] = val
    if exotic:
        header["wire_dtypes"] = exotic
    hdr = json.dumps(header).encode()
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return _WIRE_MAGIC + struct.pack(">I", len(hdr)) + hdr + buf.getvalue()


def unpack_migration_blob(raw: bytes) -> dict:
    """Inverse of pack_migration_blob. Raises ValueError on a foreign or
    truncated payload (the import endpoint turns that into a 400)."""
    if not raw.startswith(_WIRE_MAGIC):
        raise ValueError("not a migration blob (bad magic)")
    off = len(_WIRE_MAGIC)
    if len(raw) < off + 4:
        raise ValueError("truncated migration blob header")
    (hlen,) = struct.unpack(">I", raw[off:off + 4])
    off += 4
    try:
        blob = json.loads(raw[off:off + hlen])
    except json.JSONDecodeError as e:
        raise ValueError(f"corrupt migration blob header: {e}")
    with np.load(io.BytesIO(raw[off + hlen:]), allow_pickle=False) as npz:
        for key in npz.files:
            blob[key] = npz[key]
    for key, name in (blob.pop("wire_dtypes", None) or {}).items():
        try:
            import ml_dtypes

            dt = np.dtype(getattr(ml_dtypes, name))
        except (AttributeError, ImportError, TypeError) as e:
            raise ValueError(f"unknown wire dtype {name!r}: {e}")
        if key in blob:
            blob[key] = blob[key].view(dt)
    return blob


def kv_page_bytes(model_cfg: ModelConfig, page_size: int,
                  bytes_per_el=2, kv_dtype: str = "bfloat16") -> int:
    """Bytes ONE page costs (K and V, all layers) — the density math's
    unit: equal-HBM pool sizing divides a byte budget by this."""
    per_tok_head = (model_cfg.head_dim + 4 if kv_dtype == "int8"
                    else model_cfg.head_dim * bytes_per_el)
    return (2 * model_cfg.num_layers * page_size
            * model_cfg.num_kv_heads * per_tok_head)
