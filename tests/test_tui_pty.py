"""Drive the native C++ admin TUI (cpp/tui.cpp) through a real pty.

PARITY: the reference TUI's admin verbs (tui.rs:153-216) — VIP star on
the selected user, block persisting to blocked_items.json, unblock, and
clean quit — exercised against the actual rendered frames and the actual
key loop, not the snapshot functions in isolation.

Harness notes: the TUI writes full frames at the refresh cadence; a
stalled reader fills the pty buffer and blocks the frame write, wedging
the key loop — so a drain thread consumes the master side for the whole
run.
"""

import fcntl
import json
import os
import pty
import struct
import subprocess
import sys
import termios
import threading
import time

import pytest

_CHILD = r"""
import sys
from ollamamq_tpu.core.mqcore import MQCore
from ollamamq_tpu.admin import tui as admin_tui

# The stats callback's HBM refresh imports jax; with a wedged remote TPU
# tunnel that import can hang the first frame indefinitely. The TUI test
# is about the key loop and persistence, not devices — pin the cache so
# the jax branch never runs.
admin_tui._hbm_cache.update(
    ts=float("inf"), used=0, total=0, device="test-device",
    # 8 chips across 2 simulated hosts: the chips panel must render one
    # row per chip (north star "per-chip HBM occupancy").
    chips=[{"device": f"cpu:{i}", "id": i, "process": i // 4,
            "hbm_used": (i + 1) << 20, "hbm_total": 16 << 20}
           for i in range(8)],
)

core = MQCore(sys.argv[1])
core.enqueue("alice", "10.0.0.1")
core.enqueue("bob", "10.0.0.2")


class Eng:
    pass


eng = Eng()
eng.core = core
eng.runtimes = {}
admin_tui.run_tui(eng, None, refresh_ms=50)
print("TUI_EXIT_OK", flush=True)
"""


class _PtyTui:
    def __init__(self, tmp_path, child_src=_CHILD):
        self.blockfile = str(tmp_path / "blocked_items.json")
        child = tmp_path / "tui_child.py"
        child.write_text(child_src)
        self.master, slave = pty.openpty()
        # A real terminal size so the 3-column layout renders.
        fcntl.ioctl(self.master, termios.TIOCSWINSZ,
                    struct.pack("HHHH", 40, 140, 0, 0))
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        # Force CPU: the stats callback imports jax, and probing a remote
        # TPU platform from this child could hang the first frame.
        env["JAX_PLATFORMS"] = "cpu"
        # stderr to a FILE: an unread pipe would fill with library logging
        # and block the child mid-frame.
        self.errfile = tmp_path / "tui_stderr.log"
        self.proc = subprocess.Popen(
            [sys.executable, str(child), self.blockfile],
            stdin=slave, stdout=slave, stderr=open(self.errfile, "w"),
            env=env,
        )
        os.close(slave)
        self.buf = bytearray()
        self._lock = threading.Lock()
        self._drain = threading.Thread(target=self._drain_loop, daemon=True)
        self._drain.start()

    def _drain_loop(self):
        while True:
            try:
                chunk = os.read(self.master, 65536)
            except OSError:
                return
            if not chunk:
                return
            with self._lock:
                self.buf += chunk

    def wait_output(self, needle: bytes, budget: float = 60.0) -> bool:
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            with self._lock:
                if needle in self.buf:
                    return True
            time.sleep(0.05)
        return False

    def clear(self):
        with self._lock:
            self.buf.clear()

    def send(self, keys: str):
        os.write(self.master, keys.encode())

    def close(self):
        try:
            os.close(self.master)
        except OSError:
            pass
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait(timeout=10)


def _blocked_items(path, budget=30.0, want=None):
    """Poll blocked_items.json until it exists (and contains `want`)."""
    deadline = time.monotonic() + budget
    items = None
    while time.monotonic() < deadline:
        try:
            with open(path) as f:
                data = json.load(f)
            items = data.get("blocked_users", []) + data.get("blocked_ips", [])
        except (OSError, ValueError):
            items = None
        if items is not None and (want is None or want in items):
            return items
        time.sleep(0.1)
    return items


@pytest.mark.skipif(sys.platform != "linux", reason="pty/termios test")
def test_tui_admin_verbs_via_pty(tmp_path):
    t = _PtyTui(tmp_path)
    try:
        # Frame renders with both users queued.
        assert t.wait_output(b"USERS"), _stderr(t)
        assert t.wait_output(b"alice") and t.wait_output(b"bob")

        # Per-chip rows: one line per chip, both hosts represented.
        assert t.wait_output(b"chip 0 (host 0)"), "per-chip rows missing"
        assert t.wait_output(b"chip 7 (host 1)"), "per-chip rows missing"

        # No runtime caches here => the throughput line says "cache n/a"
        # (a caching runtime renders a hit percentage instead).
        assert t.wait_output(b"cache n/a"), "prefix-cache field missing"

        # Panel 1, first user (sorted: alice), VIP toggle => star glyph.
        t.send("\t")
        t.send("p")
        assert t.wait_output("★".encode()), "VIP star never rendered"

        # Block => persists to blocked_items.json (reference-compatible).
        t.send("x")
        items = _blocked_items(t.blockfile, want="alice")
        assert items is not None and "alice" in items, items
        assert t.wait_output("✖".encode())  # blocked glyph in frames

        # Unblock from the blocked panel (Tab Tab => panel 3).
        t.send("ll")
        t.send("u")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            items = _blocked_items(t.blockfile)
            if items == []:
                break
            time.sleep(0.1)
        assert items == [], items

        # Quit: clean exit, like the reference (quit ends the app).
        t.clear()
        t.send("q")
        assert t.wait_output(b"TUI_EXIT_OK"), _stderr(t)
        assert t.proc.wait(timeout=30) == 0
    finally:
        t.close()


def _stderr(t):
    try:
        return t.errfile.read_text(errors="replace")[-2000:]
    except Exception:
        return "<no stderr>"


# Same harness, but the engine stub carries a live AlertManager with a
# firing SLO alert — the ALERTS panel must render it.
_CHILD_ALERTS = _CHILD.replace(
    'eng.runtimes = {}\nadmin_tui.run_tui(eng, None, refresh_ms=50)',
    '''eng.runtimes = {}
from ollamamq_tpu.telemetry.slo import AlertManager
eng.alerts = AlertManager()
eng.alerts.fire("slo_ttft_burn_fast", "page",
                "ttft SLO burning 20.0x budget over 300s", source="slo")
admin_tui.run_tui(eng, None, refresh_ms=50)''')
assert _CHILD_ALERTS != _CHILD, "alerts child patch failed to apply"


@pytest.mark.skipif(sys.platform != "linux", reason="pty/termios test")
def test_tui_alerts_panel_via_pty(tmp_path):
    """ISSUE 3 acceptance: a firing alert shows in the TUI alerts panel
    (rendered frames through a real pty, not the brief dict alone)."""
    t = _PtyTui(tmp_path, child_src=_CHILD_ALERTS)
    try:
        assert t.wait_output(b"ALERTS (1 firing)"), _stderr(t)
        assert t.wait_output(b"slo_ttft_burn_fast"), _stderr(t)
        assert t.wait_output(b"[page]")
        assert t.wait_output("⚠".encode())
        # Resolve -> the panel goes quiet ("(none)") on a later frame.
        # (The alert table is in the child process; quit instead.)
        t.clear()
        t.send("q")
        assert t.wait_output(b"TUI_EXIT_OK"), _stderr(t)
        assert t.proc.wait(timeout=30) == 0
    finally:
        t.close()


# Engine stub with a decision journal holding a preempt record: the
# chips panel must render the flight recorder's last-decision line.
_CHILD_JOURNAL = _CHILD.replace(
    'eng.runtimes = {}\nadmin_tui.run_tui(eng, None, refresh_ms=50)',
    '''eng.runtimes = {}
from ollamamq_tpu.telemetry.journal import Journal
eng.journal = Journal(capacity=32)
eng.journal.record("preempt", req_id=42, user="mallory", model="test-tiny",
                   slot=3, why="kv_pressure", n=1, free_pages=0,
                   victim_served=9, vip="alice")
admin_tui.run_tui(eng, None, refresh_ms=50)''')
assert _CHILD_JOURNAL != _CHILD, "journal child patch failed to apply"


@pytest.mark.skipif(sys.platform != "linux", reason="pty/termios test")
def test_tui_last_decision_line_via_pty(tmp_path):
    """ISSUE 5: the newest scheduler decision renders as a `last:` line
    in the chips panel, with the inputs that justified it."""
    t = _PtyTui(tmp_path, child_src=_CHILD_JOURNAL)
    try:
        assert t.wait_output(b"last: req 42 (mallory) preempted"), _stderr(t)
        assert t.wait_output(b"free_pages=0"), _stderr(t)
        t.send("q")
        assert t.wait_output(b"TUI_EXIT_OK"), _stderr(t)
        assert t.proc.wait(timeout=30) == 0
    finally:
        t.close()


# Engine stub shaped like a tiered fleet router: the chips panel must
# render the replicas line AND the tiers line (healthy/total per tier) —
# here with a starved interactive tier (0 healthy), the red case.
_CHILD_TIERS = _CHILD.replace(
    'eng.runtimes = {}\nadmin_tui.run_tui(eng, None, refresh_ms=50)',
    '''eng.runtimes = {}
class _Tiers:
    def counts(self):
        return {"interactive": {"healthy": 0, "total": 1},
                "bulk": {"healthy": 2, "total": 2}}
eng.tiers = _Tiers()
eng.fleet_counts = lambda: {"healthy": 2, "ejected": 1, "draining": 0}
admin_tui.run_tui(eng, None, refresh_ms=50)''')
assert _CHILD_TIERS != _CHILD, "tiers child patch failed to apply"


@pytest.mark.skipif(sys.platform != "linux", reason="pty/termios test")
def test_tui_tiers_line_via_pty(tmp_path):
    """Tiered-fleet TUI: the tiers line renders healthy/total per tier
    in the rendered frames (red when a tier has zero healthy members —
    asserted on content; the color is the C++ side's starved flag)."""
    t = _PtyTui(tmp_path, child_src=_CHILD_TIERS)
    try:
        assert t.wait_output(b"replicas 2 healthy / 1 ejected"), _stderr(t)
        assert t.wait_output(b"tiers"), _stderr(t)
        assert t.wait_output(b"interactive 0/1"), _stderr(t)
        assert t.wait_output(b"bulk 2/2"), _stderr(t)
        t.send("q")
        assert t.wait_output(b"TUI_EXIT_OK"), _stderr(t)
        assert t.proc.wait(timeout=30) == 0
    finally:
        t.close()


# Engine stub shaped like a fleet router with the overhead self-profiler:
# the replicas line must carry the `router p99` chip (the windowed
# placement-decision p99 the health monitor bounds against the budget).
_CHILD_OVERHEAD = _CHILD.replace(
    'eng.runtimes = {}\nadmin_tui.run_tui(eng, None, refresh_ms=50)',
    '''eng.runtimes = {}
class _Ecfg:
    router_overhead_budget_ms = 50.0
eng.ecfg = _Ecfg()
eng.router_overhead_p99_ms = lambda: 3.21
eng.fleet_counts = lambda: {"healthy": 2, "ejected": 0, "draining": 0}
admin_tui.run_tui(eng, None, refresh_ms=50)''')
assert _CHILD_OVERHEAD != _CHILD, "overhead child patch failed to apply"


@pytest.mark.skipif(sys.platform != "linux", reason="pty/termios test")
def test_tui_router_overhead_chip_via_pty(tmp_path):
    """Fleet-router TUI: the replicas line carries the router-overhead
    chip (windowed placement p99 in ms) in the rendered frames; red-
    over-budget is the C++ side's `over` flag, asserted on content."""
    t = _PtyTui(tmp_path, child_src=_CHILD_OVERHEAD)
    try:
        assert t.wait_output(b"replicas 2 healthy"), _stderr(t)
        assert t.wait_output(b"router p99 3.21ms"), _stderr(t)
        t.send("q")
        assert t.wait_output(b"TUI_EXIT_OK"), _stderr(t)
        assert t.proc.wait(timeout=30) == 0
    finally:
        t.close()


# Engine stub shaped like an elastic fleet router: the autoscaler's
# brief() feeds the fleet-size chip (`fleet N (+P preemptible)` with the
# scaler's [min..max] band).
_CHILD_FLEET_SIZE = _CHILD.replace(
    'eng.runtimes = {}\nadmin_tui.run_tui(eng, None, refresh_ms=50)',
    '''eng.runtimes = {}
class _Scaler:
    def brief(self):
        return {"n": 3, "preemptible": 1, "min": 1, "max": 4}
eng.autoscaler = _Scaler()
eng.fleet_counts = lambda: {"healthy": 3, "ejected": 0, "draining": 0}
admin_tui.run_tui(eng, None, refresh_ms=50)''')
assert _CHILD_FLEET_SIZE != _CHILD, "fleet-size child patch failed to apply"


@pytest.mark.skipif(sys.platform != "linux", reason="pty/termios test")
def test_tui_fleet_size_chip_via_pty(tmp_path):
    """Elastic-fleet TUI: the fleet-size chip renders the current size,
    the preemptible count, and the autoscaler's [min..max] band in the
    rendered frames."""
    t = _PtyTui(tmp_path, child_src=_CHILD_FLEET_SIZE)
    try:
        assert t.wait_output(b"replicas 3 healthy"), _stderr(t)
        assert t.wait_output(b"fleet 3 (+1 preemptible)  [1..4]"), _stderr(t)
        t.send("q")
        assert t.wait_output(b"TUI_EXIT_OK"), _stderr(t)
        assert t.proc.wait(timeout=30) == 0
    finally:
        t.close()


# Engine stub shaped like a warm standby (fleet/ha.py): ha_status()
# feeds the HA role chip — role + fencing epoch, standby-side with its
# replication lag in records.
_CHILD_HA = _CHILD.replace(
    'eng.runtimes = {}\nadmin_tui.run_tui(eng, None, refresh_ms=50)',
    '''eng.runtimes = {}
eng.ha_status = lambda: {"role": "standby", "epoch": 3,
                         "sync_lag_records": 12, "synced": True}
admin_tui.run_tui(eng, None, refresh_ms=50)''')
assert _CHILD_HA != _CHILD, "ha child patch failed to apply"


@pytest.mark.skipif(sys.platform != "linux", reason="pty/termios test")
def test_tui_ha_role_chip_via_pty(tmp_path):
    """Router-HA TUI: the role/epoch chip renders in the frames — a
    standby shows `ha standby/<epoch>` with its replication lag, so an
    operator can see at a glance which process owns the fleet."""
    t = _PtyTui(tmp_path, child_src=_CHILD_HA)
    try:
        assert t.wait_output(b"ha standby/3"), _stderr(t)
        assert t.wait_output(b"lag 12"), _stderr(t)
        t.send("q")
        assert t.wait_output(b"TUI_EXIT_OK"), _stderr(t)
        assert t.proc.wait(timeout=30) == 0
    finally:
        t.close()


# Engine stub with a seeded step profiler: the performance-plane chip
# (`compiles N · step p99 X ms`) reads the process-wide PROFILER, so
# the child seeds it with a deterministic sample + two compile events.
_CHILD_STEPPROF = _CHILD.replace(
    'eng.runtimes = {}\nadmin_tui.run_tui(eng, None, refresh_ms=50)',
    '''eng.runtimes = {}
from ollamamq_tpu.telemetry import stepprof
stepprof.PROFILER.reset()
tmr = stepprof.PROFILER.start("decode")
tmr.mark("dispatch")
tmr.phases["dispatch"] = 12.34     # pin the rendered p99 exactly
tmr._last = tmr._t0 + 0.01234
tmr.finish(T_pad=0, k_cap=2, n_prefill=0, n_decode=1, tokens=2,
           padded_tokens=4, compiled=True)
stepprof.PROFILER.record_compile("decode", "(2,)", 100.0, 1)
stepprof.PROFILER.record_compile("ragged", "(16,)", 200.0, 2)
admin_tui.run_tui(eng, None, refresh_ms=50)''')
assert _CHILD_STEPPROF != _CHILD, "stepprof child patch failed to apply"


@pytest.mark.skipif(sys.platform != "linux", reason="pty/termios test")
def test_tui_stepprof_chip_via_pty(tmp_path):
    """Engine-performance-plane TUI: the chips panel renders the compile
    count and rolling step p99 off the step profiler's brief()."""
    t = _PtyTui(tmp_path, child_src=_CHILD_STEPPROF)
    try:
        assert t.wait_output(b"compiles 2"), _stderr(t)
        assert t.wait_output(b"step p99 12.34ms"), _stderr(t)
        t.send("q")
        assert t.wait_output(b"TUI_EXIT_OK"), _stderr(t)
        assert t.proc.wait(timeout=30) == 0
    finally:
        t.close()


@pytest.mark.skipif(sys.platform != "linux", reason="pty/termios test")
def test_tui_no_alerts_renders_quiet_panel(tmp_path):
    """Without an alert table (or with it empty) the ALERTS section still
    renders, showing (none) — layout must not depend on alert state."""
    t = _PtyTui(tmp_path)
    try:
        assert t.wait_output(b"ALERTS"), _stderr(t)
        assert t.wait_output(b"(none)"), _stderr(t)
        t.send("q")
        assert t.wait_output(b"TUI_EXIT_OK"), _stderr(t)
    finally:
        t.close()
