"""Fleet members: the engine replicas a FleetRouter places streams on.

Two shapes, one protocol:

  LocalMember  wraps an in-process engine (TPUEngine / FakeEngine /
               SPMDEngine) — the replica runs its own scheduler loop,
               KV pool, and health monitor inside this process. Replay
               is exact: a failed-over stream carries its generated
               token ids, incremental detokenizer, and penalty context
               (the PR-4 preemption/replay semantics lifted to fleet
               level), so greedy resumed streams are byte-identical.
  HttpMember   wraps a subprocess/remote engine speaking the existing
               HTTP API (the docker-compose "two engine services"
               shape). Health rides the member's /health JSON polled on
               a heartbeat; streams ride /api/generate NDJSON consumed
               by a reader thread; replay is text-level (prompt +
               already-emitted text, token budget shrunk by the emitted
               count) — exact for byte-level tokenizers, best-effort
               where detokenization is context-dependent.

The router is the ONLY consumer of an attempt's TokenStream: member-side
terminal items (including the CANCELLED ack of an eviction) are routing
signals, not client output — the router decides what the client stream
sees.
"""

from __future__ import annotations

import copy
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from ollamamq_tpu.engine.request import FinishReason, Request, StreamItem
from ollamamq_tpu.telemetry.tracing import TRACEPARENT_HEADER

log = logging.getLogger("ollamamq.fleet")

# Alerts that mean a replica cannot be trusted with new placements (the
# /health JSON "degraded" status alone must NOT eject: an SLO burning is
# pressure, not death — app.py /health makes the same distinction).
FATAL_ALERTS = frozenset({"device_offline", "engine_stall"})

_REASONS = {r.value: r for r in FinishReason}


class Attempt:
    """One member-side serving attempt of a client stream. `req` is the
    member-side Request whose TokenStream the router drains; the client
    never sees this object."""

    __slots__ = ("req", "member", "acked", "closed", "transport_dead",
                 "base_n", "n_items", "text_mode", "prior_text",
                 "text_parts", "thread", "resp", "embedding_val",
                 "member_rid", "token_ids", "prior_ids", "context_ids")

    def __init__(self, req: Request, member) -> None:
        self.req = req
        self.member = member
        self.acked = False           # member confirmed our eviction
        self.closed = False          # router asked this attempt to stop
        self.transport_dead = False  # HTTP stream died mid-flight
        self.base_n = 0              # tokens emitted by PRIOR attempts
        self.n_items = 0             # token items this attempt emitted
        self.text_mode = False       # replay state is text, not token ids
        self.prior_text = ""         # text emitted by prior attempts
        self.text_parts: list = []
        self.thread: Optional[threading.Thread] = None
        self.resp = None
        self.embedding_val = None
        # HTTP attempts: the member-side request id (read off the NDJSON
        # frames; rotates with member-side requeues) — the handle the
        # /admin/migrate endpoints key on — plus the token ids the
        # frames carried, so resumed HTTP streams replay in TOKEN space
        # (verified token-identical) instead of re-tokenized text.
        self.member_rid: Optional[int] = None
        self.token_ids: list = []
        self.prior_ids: Optional[list] = None  # ids of PRIOR attempts
        self.context_ids: Optional[list] = None  # token-space HTTP replay

    def tokens_done(self) -> int:
        if self.text_mode:
            if self.prior_ids is not None and self.token_ids:
                return len(self.prior_ids) + len(self.token_ids)
            return self.base_n + self.n_items
        return len(self.req.generated_ids)

    def embedding(self):
        return self.embedding_val if self.text_mode else self.req.embedding

    def reader_dead(self) -> bool:
        return self.thread is not None and not self.thread.is_alive()

    def resume_state(self) -> dict:
        """Replay state for the NEXT attempt of this stream: everything a
        healthy replica needs to continue it seamlessly."""
        req = self.req
        if self.text_mode:
            text = self.prior_text + "".join(self.text_parts)
            # Token-space HTTP resume: when every attempt so far carried
            # its token ids on the wire, the next attempt replays exact
            # ids (byte-identical continuation, verified token-identical)
            # instead of re-tokenizing emitted text.
            if self.prior_ids is not None \
                    and (self.n_items == 0 or self.token_ids):
                gen = list(self.prior_ids) + [int(t) for t in
                                              self.token_ids]
                return {"gen_ids": gen, "n_gen": len(gen), "inc": None,
                        "detok": text, "emitted": len(text), "text": text}
            return {"gen_ids": None,
                    "n_gen": self.base_n + self.n_items,
                    "text": text}
        return {"gen_ids": list(req.generated_ids),
                "n_gen": len(req.generated_ids),
                "inc": req._inc_decode,
                "detok": req._detok_text,
                "emitted": req.emitted_len,
                # Full emitted text, for a cross-shape (local -> HTTP)
                # failover that can only replay in text space.
                "text": req._detok_text[:req.emitted_len]}


class _MemberBase:
    """State the router tracks per member regardless of shape."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.state = "healthy"       # healthy | ejected | draining
        self.backoff_s = 0.0         # set by the router at eject time
        self.next_probe_at = 0.0
        self.eject_count = 0
        self.drain_started_at = 0.0
        self.drain_deadline = 0.0
        self.forced_stale_until = 0.0  # fault site "replica", kind "slow"
        # Tiered fleet (fleet/tiering.py): which replica tier this
        # member serves (None = untiered fleet), and — while a regroup's
        # drain is in flight — the tier it is moving to. The tier
        # commits only when the retier restart succeeds; an abort
        # (crash mid-retier, restart failure) leaves the ORIGINAL tier.
        self.tier: Optional[str] = None
        self.retier_to: Optional[str] = None
        # Elastic fleet (fleet/autoscaler.py): `preemptible` marks
        # spot-style capacity that accepts a termination notice
        # (migrate-off-then-retire within the notice window instead of
        # failover); `retiring` is set while a scale-down/preempt drain
        # is in flight — when the drain empties, the router STOPS the
        # member and removes it from the roster instead of restarting
        # it. An eject mid-retire aborts the retire (scale_down
        # aborted); the member heals back through the normal re-probe
        # path and stays in rotation.
        self.preemptible: bool = False
        self.retiring: bool = False
        self.retire_why: Optional[str] = None
        # Scaler-provisioned members carry their provisioner handle so
        # retire can tear down what provision built (a subprocess, a
        # cloud VM) — operator-defined members have None and just stop.
        self.provisioned_by = None
        # Router HA (fleet/ha.py): the fencing epoch every member-facing
        # call carries (X-Router-Epoch). None = HA off, no header, the
        # member-side check passes — non-HA fleets are unchanged.
        self.router_epoch: Optional[int] = None
        # Set when this member 409'd a call carrying OUR epoch: a newer
        # router registered a higher one, i.e. WE are the zombie. A
        # fenced member fails streams terminally instead of feeding the
        # failover loop — without this a revived dead primary retries
        # every rejected placement forever (a 409 storm against the
        # whole fleet).
        self.fenced = False

    def force_stale(self, delay_s: float) -> None:
        self.forced_stale_until = time.monotonic() + float(delay_s)

    def register(self, epoch: int) -> bool:
        """Adopt a (new) router epoch. In-process members need no wire
        fencing — a LocalMember dies with its router, so a zombie
        primary can never reach it; HttpMember overrides this with the
        /admin/ha/register POST."""
        self.router_epoch = int(epoch)
        return True

    # -- fleet observability (overridden per shape) ------------------------
    def trace_spans(self, ctx: str) -> list:
        """This member's exported trace spans for one fleet context —
        the stitching wire behind GET /debug/trace/{rid}."""
        return []

    def metric_snapshot(self):
        """Registry snapshot for metrics federation (None = nothing to
        re-export: LocalMembers share the router process's registry)."""
        return None

    def bundle(self) -> dict:
        """Per-member diagnostics for the router's /debug/bundle."""
        return {}


class LocalMember(_MemberBase):
    """An in-process engine replica. The engine was constructed by the
    caller (cli/tests) and is started/stopped through this wrapper."""

    kind_label = "local"
    router_bounded = False  # the engine's own capacity gate bounds intake

    def __init__(self, name: str, engine, engine_factory=None) -> None:
        super().__init__(name)
        self.engine = engine
        # Tier regrouping: `engine_factory(tp)` builds a replacement
        # engine at a different TP width (same models/fairness — the
        # CLI closes over its construction args). Without one, a retier
        # that declares a width change falls back to a re-label +
        # same-width hot restart.
        self.engine_factory = engine_factory
        # Member-side spans stitch under this member's name, not the
        # generic "engine" origin.
        if getattr(engine, "tracer", None) is not None:
            engine.tracer.origin = name

    @property
    def tp(self) -> Optional[int]:
        return getattr(self.engine.ecfg, "tp", None)

    def slot_cap(self) -> int:
        return int(getattr(self.engine.ecfg, "max_slots", 0))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.engine.start()

    def stop(self) -> None:
        self.engine.stop()

    def crash(self) -> None:
        """Abrupt loop death (fault injection / observed failure): the
        loop thread exits after its current iteration — deliberately NOT
        a clean stop(), which would join and tidy up the very state a
        real crash leaves behind."""
        self.engine._running = False
        self.engine.notify()

    def restart(self) -> None:
        """Hot restart after a crash or heal: the loop thread (and the
        member's health monitor) come back over the SAME runtimes —
        weights stay resident. The OLD loop thread must be fully dead
        first: it may still be inside a long iteration (a compile, a
        wedged dispatch), and starting a second loop would reset
        _running to True — the zombie then keeps looping, and two loops
        dispatching over the same donated KV buffers poison the runtime
        ("Array has been deleted"). Waits briefly for the first liveness
        tick so the caller's health evaluation sees a fresh heartbeat."""
        old = self.engine._thread
        if old is not None and old.is_alive():
            old.join(timeout=5.0)
            if old.is_alive():
                return  # still wedged: stay ejected, re-probe later
        self.engine._thread = None
        self.engine.start()
        deadline = time.monotonic() + 1.0
        while (time.monotonic() - self.engine.last_tick_at > 0.5
               and time.monotonic() < deadline):
            time.sleep(0.01)

    def hot_restart(self) -> None:
        """Drain-complete restart: clean stop (nothing in flight) then
        start — the rolling-restart primitive."""
        self.engine.stop()
        self.engine.start()

    def retier(self, tp: Optional[int] = None) -> Optional[int]:
        """Drain-complete retier restart: rebuild the engine at the
        target tier's TP width (the drain already emptied it — weights
        reload, KV pool reallocates at the new sharding). No factory or
        no width change => a plain hot restart (re-label only). Returns
        the width the member now runs at. On a failed rebuild the OLD
        engine restarts and the error propagates — the caller aborts
        the regroup and the member keeps its original tier."""
        if tp is None or tp == self.tp or self.engine_factory is None:
            self.hot_restart()
            return self.tp
        old = self.engine
        old.stop()
        try:
            fresh = self.engine_factory(tp)
        except Exception:
            old.start()  # the member must not stay dead over a bad width
            raise
        self.engine = fresh
        if getattr(fresh, "tracer", None) is not None:
            fresh.tracer.origin = self.name
        fresh.start()
        return self.tp

    # -- health ------------------------------------------------------------
    def alive(self) -> bool:
        eng = self.engine
        return bool(eng._running and eng._thread is not None
                    and eng._thread.is_alive())

    def heartbeat_age(self) -> float:
        now = time.monotonic()
        if now < self.forced_stale_until:
            return float("inf")
        return now - self.engine.last_tick_at

    def fatal_alerts(self) -> list:
        alerts = getattr(self.engine, "alerts", None)
        if alerts is None:
            return []
        return [a.name for a in alerts.active() if a.name in FATAL_ALERTS]

    def active_alerts(self) -> list:
        alerts = getattr(self.engine, "alerts", None)
        if alerts is None:
            return []
        return [(a.name, a.severity) for a in alerts.active()]

    # -- placement ---------------------------------------------------------
    def can_take(self, model: str, kind: str) -> bool:
        eng = self.engine
        rt = eng.resolve_runtime(model, kind=kind)
        if rt is None:
            return False
        probe = rt.replicas[0] if hasattr(rt, "replicas") else rt
        if kind not in getattr(probe, "SERVES", ("generate",)):
            return False
        return rt.has_capacity(kind)

    def affinity_pages(self, model: str, tokens) -> int:
        fn = getattr(self.engine, "prefix_match_pages", None)
        return fn(model, tokens) if fn is not None else 0

    # -- streams -----------------------------------------------------------
    def _tokenize(self, model: str, text: str):
        rt = self.engine.resolve_runtime(model)
        if rt is None:
            from ollamamq_tpu.engine.tokenizer import ByteTokenizer

            return ByteTokenizer().encode(text, add_bos=True)
        return rt.tokenizer.encode(text, add_bos=True)

    def begin(self, flight, resume: Optional[dict], on_item=None) -> Attempt:
        sampling = flight.sampling
        if resume and resume.get("gen_ids") is not None:
            # Token-space replay: prompt + every already-emitted token,
            # generation state carried over — the engine's own
            # preemption-replay convention (generated_ids pre-filled, so
            # LENGTH accounting and the fake engine's resume-awareness
            # both hold; the incremental detokenizer never re-sees the
            # replayed ids).
            gen = list(resume["gen_ids"])
            req = Request(0, flight.user, flight.model,
                          list(flight.prompt_tokens) + gen, sampling,
                          kind=flight.kind, raw_prompt=flight.raw_prompt)
            req.generated_ids = list(gen)
            req._replay_gen = len(gen)
            req._inc_decode = resume.get("inc")
            req._detok_text = resume.get("detok", "")
            req.emitted_len = resume.get("emitted", 0)
        elif resume:
            # Text-space replay (stream previously served over HTTP):
            # fold the emitted text into the prompt and shrink the budget.
            n_gen = int(resume.get("n_gen", 0))
            tokens = self._tokenize(
                flight.model, flight.raw_prompt + resume.get("text", ""))
            sampling = copy.copy(sampling)  # copy.copy skips __post_init__
            sampling.max_tokens = max(1, sampling.max_tokens - n_gen)
            req = Request(0, flight.user, flight.model, tokens, sampling,
                          kind=flight.kind, raw_prompt=flight.raw_prompt)
        else:
            req = Request(0, flight.user, flight.model,
                          list(flight.prompt_tokens), sampling,
                          kind=flight.kind, raw_prompt=flight.raw_prompt)
        # The client's deadline is absolute; the attempt must not get a
        # fresh budget just because it re-enqueued later.
        req.deadline = flight.req.deadline
        if on_item is not None:
            req.stream.on_item = on_item
        att = Attempt(req, self)
        if resume and resume.get("gen_ids") is None:
            att.text_mode = True
            att.base_n = int(resume.get("n_gen", 0))
            att.prior_text = resume.get("text", "")
        # trace_meter=False: the router's root trace already meters this
        # stream in the SHARED process registry — the member-side copy
        # exists only so its prefill/decode spans stitch under the
        # client rid.
        self.engine.inject_request(req, ip=flight.ip, family=flight.family,
                                   trace_ctx=flight.ctx, trace_meter=False)
        return att

    def cancel(self, att: Attempt) -> None:
        att.closed = True
        att.req.cancelled.set()
        try:
            self.engine.cancel(att.req.req_id)
        except Exception:  # noqa: BLE001 — a dead member must not block evac
            log.exception("cancel on member %s failed", self.name)

    # -- KV page migration (in-process handoff) ----------------------------
    def export_stream(self, att: Attempt,
                      deadline: Optional[float] = None):
        """Phase 1: detach the attempt's decode slot into a blob. Works
        even on a member whose loop just died (a crashed engine's state
        is frozen, not gone — exactly when migration beats recompute).
        None = not exportable; the router falls back to recompute."""
        return self.engine.export_stream(att.req.req_id, deadline)

    def resolve_export(self, att: Attempt, commit: bool,
                       why: str = "") -> None:
        """Phase 2: release the parked source state (commit after the
        target acked the import, abort otherwise)."""
        self.engine.resolve_export(att.req.req_id, commit=commit, why=why)

    def import_stream(self, blob: dict, flight, on_item=None) -> Attempt:
        """Target side: land the shipped state straight into a decode
        slot (raises MigrationError when it cannot — the ack the source
        commit waits on is this returning)."""
        req = self.engine.import_stream(
            blob, ip=flight.ip, family=flight.family,
            deadline=flight.req.deadline,
            trace_ctx=flight.ctx, trace_meter=False)
        if on_item is not None:
            req.stream.on_item = on_item
        return Attempt(req, self)

    def export_prefix(self, model: str, tokens):
        fn = getattr(self.engine, "export_prefix", None)
        return fn(model, tokens) if fn is not None else None

    def import_prefix(self, model: str, blob: dict) -> int:
        fn = getattr(self.engine, "import_prefix", None)
        return fn(model, blob) if fn is not None else 0

    # -- fleet observability ----------------------------------------------
    def trace_spans(self, ctx: str) -> list:
        tracer = getattr(self.engine, "tracer", None)
        if tracer is None:
            return []
        return tracer.export_spans(tracer.find_ctx(ctx))

    def bundle(self) -> dict:
        """Compact per-member diagnostics for the router's bundle: an
        in-process member needs no HTTP round-trip — read its surfaces
        directly (error containment lives at the router's section
        builder)."""
        eng = self.engine
        out: dict = {"kind": "local", "tier": self.tier}
        out["stats"] = eng.stats()
        alerts = getattr(eng, "alerts", None)
        out["alerts"] = alerts.to_dict() if alerts is not None else None
        journal = getattr(eng, "journal", None)
        if journal is not None:
            out["journal"] = {**journal.snapshot(),
                              "events": journal.tail(n=100)}
        return out


class HttpMember(_MemberBase):
    """A remote engine replica speaking the existing HTTP API. Health is
    the member's /health JSON polled on a heartbeat cadence; staleness =
    no successful poll recently."""

    kind_label = "http"
    router_bounded = True  # no capacity introspection over HTTP

    def __init__(self, name: str, url: str, timeout_s: float = 300.0,
                 poll_period_s: float = 1.0) -> None:
        super().__init__(name)
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s
        self.poll_period_s = poll_period_s
        self._forced_down = False
        self._last_ok = time.monotonic()
        self._status: dict = {}
        # Metrics federation: the member's registry snapshot, scraped on
        # the SAME health heartbeat (one extra GET per poll) so the
        # router's /metrics re-exports every member series with a
        # replica label. None until the first successful scrape.
        self._metric_snapshot: Optional[dict] = None
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._poller is None:
            self._stop.clear()
            self._poller = threading.Thread(
                target=self._poll_loop, name=f"fleet-poll-{self.name}",
                daemon=True)
            self._poller.start()

    def stop(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=2)
            self._poller = None

    def crash(self) -> None:
        # Fault injection can't kill a remote process; it marks the
        # member down so the router's eject/failover path still runs.
        self._forced_down = True

    def restart(self) -> None:
        self._forced_down = False

    def hot_restart(self) -> None:
        # The remote process restarts itself (rolling deploy); drain's
        # job here was only to quiesce placements first.
        self._forced_down = False

    @property
    def tp(self) -> Optional[int]:
        return None  # no TP introspection over HTTP

    def slot_cap(self) -> int:
        return 0  # the router's own bound applies (router_bounded)

    def retier(self, tp: Optional[int] = None) -> Optional[int]:
        # Re-label only: the remote service owns its own TP width (a
        # rolling redeploy at the new width is the operator's move).
        self.hot_restart()
        return None

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_period_s):
            try:
                with urllib.request.urlopen(self.url + "/health",
                                            timeout=2.0) as resp:
                    self._status = json.loads(resp.read())
                self._last_ok = time.monotonic()
            except Exception:  # noqa: BLE001 — staleness IS the signal
                continue
            self._repair_epoch()
            # Federation scrape rides the SAME heartbeat: a member whose
            # /health answers but whose snapshot endpoint fails (old
            # member build, transient error) keeps its LAST snapshot —
            # health and federation degrade independently.
            try:
                with urllib.request.urlopen(
                        self.url + "/metrics/snapshot",
                        timeout=2.0) as resp:
                    self._metric_snapshot = json.loads(resp.read())
            except Exception:  # noqa: BLE001
                pass

    # -- router HA ---------------------------------------------------------
    def _repair_epoch(self) -> None:
        """Heartbeat fence repair: a member that RESTARTED after a
        takeover reports an epoch below ours on /health (a fresh
        process holds 0 unless it persisted the fence) — until it
        re-adopts, a zombie ex-primary's retried calls would pass its
        fence again. Re-register it under our epoch within one poll."""
        if self.router_epoch is None or self.fenced:
            return
        try:
            seen = int(self._status.get("epoch") or 0)
        except (TypeError, ValueError):
            return
        if seen < self.router_epoch:
            self.register(self.router_epoch)

    def _epoch_headers(self, headers: dict) -> dict:
        if self.router_epoch is not None:
            headers["X-Router-Epoch"] = str(self.router_epoch)
        return headers

    def register(self, epoch: int) -> bool:
        """Re-register this member under a (new) router epoch: the
        member adopts the highest epoch it has seen and fences every
        later call carrying a lower one. Returns False when the member
        rejected US as stale (a newer router already registered) or is
        unreachable — the caller decides whether that is fatal."""
        self.router_epoch = int(epoch)
        try:
            self._post_json("/admin/ha/register",
                            {"epoch": int(epoch)}, timeout=5.0).close()
            self.fenced = False
            return True
        except urllib.error.HTTPError as e:
            if e.code == 409:
                self.fenced = True  # a newer epoch holds this member
            return False
        except Exception:  # noqa: BLE001 — down members re-register on
            return False    # the next placement's begin()

    # -- health ------------------------------------------------------------
    def alive(self) -> bool:
        return not self._forced_down

    def heartbeat_age(self) -> float:
        now = time.monotonic()
        if now < self.forced_stale_until or self._forced_down:
            return float("inf")
        return now - self._last_ok

    def fatal_alerts(self) -> list:
        return [a.get("name") for a in self._status.get("alerts", ())
                if a.get("name") in FATAL_ALERTS]

    def active_alerts(self) -> list:
        return [(a.get("name"), a.get("severity"))
                for a in self._status.get("alerts", ())]

    # -- placement ---------------------------------------------------------
    def can_take(self, model: str, kind: str) -> bool:
        return True  # the router bounds in-flight per HTTP member

    def affinity_pages(self, model: str, tokens) -> int:
        return 0  # no cross-process radix probe; falls back to least-loaded

    # -- fleet observability ----------------------------------------------
    def metric_snapshot(self) -> Optional[dict]:
        return self._metric_snapshot

    def trace_spans(self, ctx: str) -> list:
        """Fetch this member process's spans for one fleet context
        (GET /debug/trace?ctx=...). The member's generic 'engine' origin
        is relabeled with the member NAME so the stitched timeline says
        which replica served each span."""
        try:
            with urllib.request.urlopen(
                    f"{self.url}/debug/trace?ctx={ctx}",
                    timeout=5.0) as resp:
                spans = json.loads(resp.read()).get("spans") or []
        except Exception:  # noqa: BLE001 — a dead member has no spans
            return []
        for span in spans:
            if span.get("origin") in (None, "engine"):
                span["origin"] = self.name
        return spans

    def bundle(self) -> dict:
        """The member's own /debug/bundle, fetched whole (it is already
        redacted and section-error-contained member-side)."""
        with urllib.request.urlopen(self.url + "/debug/bundle",
                                    timeout=10.0) as resp:
            out = json.loads(resp.read())
        out["kind"] = "http"
        out["tier"] = self.tier
        return out

    # -- streams -----------------------------------------------------------
    def begin(self, flight, resume: Optional[dict], on_item=None) -> Attempt:
        n_prior = int(resume.get("n_gen", 0)) if resume else 0
        prior_text = resume.get("text", "") if resume else ""
        gen_ids = resume.get("gen_ids") if resume else None
        if gen_ids is not None:
            # Token-space resume: the already-emitted ids ride the wire
            # as Ollama's `context` field — the member re-prefills
            # prompt + exact ids and continues, so greedy resumed HTTP
            # streams are token-identical, not re-tokenized best-effort.
            raw_prompt = flight.raw_prompt
        else:
            raw_prompt = flight.raw_prompt + prior_text
        req = Request(0, flight.user, flight.model, [], flight.sampling,
                      kind=flight.kind, raw_prompt=raw_prompt)
        if on_item is not None:
            req.stream.on_item = on_item
        att = Attempt(req, self)
        att.text_mode = True
        att.base_n = n_prior
        att.prior_text = prior_text
        if gen_ids is not None:
            att.context_ids = [int(t) for t in gen_ids]
            att.prior_ids = list(att.context_ids)
        elif resume is None:
            att.prior_ids = []  # fresh stream: the frames' ids are all
        att.thread = threading.Thread(
            target=self._reader, args=(att, flight, n_prior),
            name=f"fleet-{self.name}-r{flight.rid0}", daemon=True)
        att.thread.start()
        return att

    def _options(self, sampling, remaining: int) -> dict:
        opts = {
            "num_predict": remaining,
            "temperature": sampling.temperature,
            "top_k": sampling.top_k,
            "top_p": sampling.top_p,
            "repeat_penalty": sampling.repeat_penalty,
            "presence_penalty": sampling.presence_penalty,
            "frequency_penalty": sampling.frequency_penalty,
        }
        if sampling.stop:
            opts["stop"] = list(sampling.stop)
        if sampling.seed:
            opts["seed"] = sampling.seed
        return opts

    def _reader(self, att: Attempt, flight, n_prior: int) -> None:
        """(reader thread) Drive one streamed member request, pushing
        items into the attempt stream. A transport failure pushes
        NOTHING terminal: a dead connection is the failover trigger, not
        a client-visible error — the router notices transport_dead and
        re-dispatches the stream. When `att.resp` is already open (a
        migration import whose status line WAS the ack) this only
        consumes the body."""
        stream = att.req.stream
        try:
            if att.resp is None and flight.kind == "embed":
                body = {"model": flight.model, "input": flight.raw_prompt}
                httpreq = urllib.request.Request(
                    self.url + "/api/embed",
                    data=json.dumps(body).encode(),
                    headers=self._epoch_headers(
                        {"Content-Type": "application/json",
                         "X-User-ID": flight.user}), method="POST")
                with urllib.request.urlopen(httpreq,
                                            timeout=self.timeout_s) as resp:
                    out = json.loads(resp.read())
                vecs = out.get("embeddings") or []
                att.embedding_val = vecs[0] if vecs else []
                stream.push(StreamItem("done", finish_reason=FinishReason.STOP))
                return
            if att.resp is None:
                remaining = max(1, flight.sampling.max_tokens - n_prior)
                body = {"model": flight.model, "prompt": att.req.raw_prompt,
                        "stream": True,
                        "options": self._options(flight.sampling, remaining)}
                if att.context_ids is not None:
                    body["context"] = att.context_ids
                headers = {"Content-Type": "application/json",
                           "X-User-ID": flight.user}
                if flight.ctx:
                    # Fleet trace propagation: the member adopts the
                    # router's context so its spans stitch under the
                    # client rid at GET /debug/trace/{rid}.
                    headers[TRACEPARENT_HEADER] = flight.ctx
                if flight.req.deadline is not None:
                    left_ms = (flight.req.deadline - time.monotonic()) * 1e3
                    headers["X-Deadline-Ms"] = str(max(1.0, left_ms))
                httpreq = urllib.request.Request(
                    self.url + "/api/generate",
                    data=json.dumps(body).encode(),
                    headers=self._epoch_headers(headers), method="POST")
                att.resp = urllib.request.urlopen(httpreq,
                                                  timeout=self.timeout_s)
            for raw in att.resp:
                if att.closed:
                    return
                try:
                    obj = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if obj.get("req_id") is not None:
                    # The member-side id: the migration-export handle
                    # (tracked live — member-side requeues rotate it).
                    att.member_rid = int(obj["req_id"])
                if obj.get("error"):
                    reason = _REASONS.get(obj.get("done_reason", ""),
                                          FinishReason.ERROR)
                    stream.push(StreamItem("error", finish_reason=reason,
                                           error=str(obj["error"])))
                    return
                ids = obj.get("token_ids") or ()
                att.token_ids.extend(int(t) for t in ids)
                txt = obj.get("response", "")
                if txt:
                    att.n_items += 1
                    att.text_parts.append(txt)
                    stream.push(StreamItem(
                        "token", text=txt,
                        token_id=int(ids[0]) if len(ids) == 1 else -1))
                if obj.get("done"):
                    reason = _REASONS.get(obj.get("done_reason", "stop"),
                                          FinishReason.STOP)
                    stream.push(StreamItem("done", finish_reason=reason))
                    return
            # Stream ended without a done line: the member died mid-write.
            att.transport_dead = True
        except Exception as e:  # noqa: BLE001
            if (isinstance(e, urllib.error.HTTPError) and e.code == 409
                    and self.router_epoch is not None and not att.closed):
                # Stale-epoch fence: the member rejected OUR epoch — a
                # newer router owns the fleet. Terminal, not a failover
                # trigger: re-dispatching would 409 on every member
                # until the heat death of the fleet, and the stream is
                # already being served (or recovered) by the successor.
                self.fenced = True
                log.error(
                    "member %s fenced router epoch %s for req %s: a "
                    "newer router has taken over; failing the stream "
                    "instead of retrying", self.name, self.router_epoch,
                    flight.rid0)
                stream.push(StreamItem(
                    "error", finish_reason=FinishReason.ERROR))
                return
            if not att.closed:
                log.warning("member %s stream for req %s died: %s",
                            self.name, flight.rid0, e)
                att.transport_dead = True
        finally:
            resp = att.resp
            if resp is not None:
                try:
                    resp.close()
                except Exception:  # noqa: BLE001
                    pass

    def cancel(self, att: Attempt) -> None:
        att.closed = True
        resp = att.resp
        if resp is not None:
            try:
                resp.close()  # member sees the disconnect and cancels
            except Exception:  # noqa: BLE001
                pass

    # -- KV page migration (/admin/migrate wire) ---------------------------
    def _post_json(self, path: str, body: dict, timeout: float):
        httpreq = urllib.request.Request(
            self.url + path, data=json.dumps(body).encode(),
            headers=self._epoch_headers(
                {"Content-Type": "application/json"}), method="POST")
        return urllib.request.urlopen(httpreq, timeout=timeout)

    def export_stream(self, att: Attempt,
                      deadline: Optional[float] = None):
        """Phase 1 over the wire: ask the member service to snapshot +
        park the stream's decode slot, keyed by the member-side request
        id the NDJSON frames carried. None = not exportable (unknown id,
        member unreachable, nothing installed) — recompute fallback."""
        if att.member_rid is None:
            return None
        from ollamamq_tpu.engine import kv_cache as kvc

        left = (deadline - time.monotonic() if deadline is not None
                else 10.0)
        if left <= 0.05:
            return None
        try:
            with self._post_json(
                    "/admin/migrate/export",
                    {"req_id": att.member_rid, "timeout_s": left},
                    timeout=left) as resp:
                return kvc.unpack_migration_blob(resp.read())
        except Exception:  # noqa: BLE001 — export failure means fallback
            return None

    def resolve_export(self, att: Attempt, commit: bool,
                       why: str = "") -> None:
        if att.member_rid is None:
            return
        path = "/admin/migrate/" + ("commit" if commit else "abort")
        try:
            self._post_json(path, {"req_id": att.member_rid, "why": why},
                            timeout=5.0).close()
        except Exception:  # noqa: BLE001 — a dead source resolves itself
            pass

    def import_stream(self, blob: dict, flight, on_item=None) -> Attempt:
        """Target side over the wire: POST the packed blob; a 2xx status
        line IS the import ack (the member installs the slot before it
        starts streaming), then the continuation rides the same NDJSON
        reader as a normal stream. Raises on any failure so the router
        aborts the handoff and falls back to recompute."""
        from ollamamq_tpu.engine import kv_cache as kvc

        state = blob.get("request") or {}
        gen = [int(t) for t in state.get("generated_ids", ())]
        req = Request(0, flight.user, flight.model, [], flight.sampling,
                      kind=flight.kind, raw_prompt=flight.raw_prompt)
        if on_item is not None:
            req.stream.on_item = on_item
        att = Attempt(req, self)
        att.text_mode = True
        att.base_n = len(gen)
        att.prior_ids = gen
        att.prior_text = state.get("detok_text",
                                   "")[:int(state.get("emitted_len", 0))]
        headers = {"Content-Type": "application/octet-stream",
                   "X-User-ID": flight.user}
        if flight.ctx:
            headers[TRACEPARENT_HEADER] = flight.ctx
        if flight.req.deadline is not None:
            left_ms = (flight.req.deadline - time.monotonic()) * 1e3
            headers["X-Deadline-Ms"] = str(max(1.0, left_ms))
        httpreq = urllib.request.Request(
            self.url + "/admin/migrate/import",
            data=kvc.pack_migration_blob(blob),
            headers=self._epoch_headers(headers), method="POST")
        att.resp = urllib.request.urlopen(httpreq, timeout=self.timeout_s)
        att.thread = threading.Thread(
            target=self._reader, args=(att, flight, att.base_n),
            name=f"fleet-{self.name}-m{flight.rid0}", daemon=True)
        att.thread.start()
        return att
