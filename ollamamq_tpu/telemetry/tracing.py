"""Request-lifecycle tracing: span events per request, bounded ring.

Every request the engine accepts carries a Trace; the engine drops span
events at each lifecycle boundary (enqueue -> admit -> place -> prefill
[per chunk] -> first_token -> decode [sampled] -> stop/cancelled/error).
Consecutive events define contiguous phase spans — gapless by
construction — so a wedged or slow request reads straight off the
timeline in chrome://tracing / Perfetto via GET /debug/trace.

Finished traces live in a bounded ring (oldest evicted); in-flight
traces are exported too — those are exactly the ones an operator
debugging a wedge needs to see.

Fleet-wide distributed tracing: the ROUTER mints a fleet-stable trace
context (a `traceparent`-style id) at admission and propagates it to
every member attempt — in-process for LocalMember, as the TRACEPARENT
header for HttpMember — so each process's spans carry the same ctx and
`GET /debug/trace/{rid}` on the router can stitch them into ONE
timeline under the client's stable rid. Cross-process timestamps rebase
through each process's wall clock (same-host fleets share it; skew on a
multi-host fleet shows up as span overlap, never a lost span).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional

from ollamamq_tpu.telemetry import attribution
from ollamamq_tpu.telemetry import schema as tm

# Per-trace event cap: a 100k-token generation must not grow its trace
# unboundedly. Terminal events always land (the chain must end).
MAX_EVENTS = 256
# Sample cadence for decode-progress events after the first token.
DECODE_EVENT_EVERY = 16

# Propagation header for HttpMember requests (W3C traceparent shape:
# version-traceid-spanid-flags). The member's enqueue path adopts it so
# its spans stitch under the router's fleet-stable context.
TRACEPARENT_HEADER = "traceparent"

# The CLOSED vocabulary of span events the FLEET ROUTER drops into a
# request's trace (members keep the engine's phase vocabulary —
# prefill/first_token/decode/... — pinned by the attribution table).
# scripts/check_metrics_docs.py pins this tuple against the README
# router-span table the same way it pins phases: a router decision site
# that emits an undocumented span name fails tier-1 CI.
ROUTER_EVENTS = (
    "enqueue",      # admitted into the router's fair-share queue
    "admit",        # popped for placement
    "requeue",      # returned to the queue front (unplaceable/failover)
    "place",        # member chosen (carries the placement overhead_ms)
    "first_token",  # first client-visible token forwarded
    "overflow",     # placed cross-tier (per-tier SLO burn / empty tier)
    "failover",     # re-dispatched after a member death (recompute replay)
    "migrate",      # KV state shipped to another member (zero recompute)
    "regroup",      # evacuated off a member that is changing tiers
)


def mint_ctx() -> str:
    """Fleet-stable trace context, traceparent-shaped:
    00-<32hex trace id>-<16hex span id>-01."""
    return f"00-{os.urandom(16).hex()}-{os.urandom(8).hex()}-01"


def valid_ctx(ctx) -> bool:
    if not isinstance(ctx, str):
        return False
    parts = ctx.split("-")
    return (len(parts) == 4 and len(parts[1]) == 32
            and len(parts[2]) == 16
            and all(all(c in "0123456789abcdef" for c in p)
                    for p in parts))


class Trace:
    __slots__ = ("req_id", "user", "model", "kind", "events", "dropped",
                 "finished", "outcome", "ctx", "origin", "metered",
                 "_tracer")

    def __init__(self, tracer: "Tracer", req_id: int, user: str, model: str,
                 kind: str, ctx: Optional[str] = None, metered: bool = True):
        self._tracer = tracer
        self.req_id = req_id
        self.user = user
        self.model = model
        self.kind = kind
        # Fleet trace context: adopted from the router/client when
        # propagated, minted fresh at the root otherwise — the key the
        # cross-process stitcher matches member spans on.
        self.ctx = ctx if valid_ctx(ctx) else mint_ctx()
        self.origin = tracer.origin
        # False for a LocalMember attempt sharing the router's process:
        # the router's root trace already counts this stream into
        # requests_inflight/total and the phase histogram — the member
        # copy must not double it.
        self.metered = metered
        self.events: List[tuple] = []  # (name, t_monotonic, args|None)
        self.dropped = 0
        self.finished = False
        self.outcome: Optional[str] = None

    def event(self, name: str, _force: bool = False, **args) -> None:
        if self.finished:
            return
        if len(self.events) >= MAX_EVENTS and not _force:
            self.dropped += 1
            return
        self.events.append((name, time.monotonic(), args or None))

    def finish(self, outcome: str) -> None:
        """Terminal event + hand the trace to the ring. Idempotent — the
        cancel and finish paths can race to it."""
        if self.finished:
            return
        self.event(outcome, _force=True)
        self.finished = True
        self.outcome = outcome
        self._tracer._finished(self, outcome)


class Tracer:
    """Owner of the live-trace table and the finished-trace ring."""

    def __init__(self, capacity: int = 512, origin: str = "engine"):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=max(1, capacity))
        self._live: Dict[int, Trace] = {}
        self.epoch = time.monotonic()
        # Which process/role this tracer's spans belong to in a stitched
        # fleet timeline ("router", a member name, or "engine").
        self.origin = origin
        # Monotonic finish instants of recent requests: the observed
        # completion rate behind load-shedding Retry-After estimates.
        self.finish_times: collections.deque = collections.deque(maxlen=256)

    def begin(self, req_id: int, user: str, model: str,
              kind: str = "generate", ctx: Optional[str] = None,
              metered: bool = True) -> Trace:
        tr = Trace(self, req_id, user, model, kind, ctx=ctx, metered=metered)
        tr.event("enqueue")
        with self._lock:
            self._live[id(tr)] = tr
        if metered:
            tm.REQUESTS_INFLIGHT.inc()
        return tr

    def _finished(self, tr: Trace, outcome: str) -> None:
        with self._lock:
            self._live.pop(id(tr), None)
            self._ring.append(tr)
            self.finish_times.append(time.monotonic())
        if not tr.metered:
            return
        tm.REQUESTS_INFLIGHT.dec()
        tm.REQUESTS_TOTAL.labels(model=tr.model or "?", outcome=outcome).inc()
        # Latency attribution: fold the finished timeline's per-phase
        # totals into ollamamq_request_phase_ms.
        attribution.observe_phases(tr.model, list(tr.events))

    def traces(self) -> List[Trace]:
        with self._lock:
            return list(self._ring) + list(self._live.values())

    def find(self, req_id: int) -> Optional[Trace]:
        """Latest trace for a request id: the in-flight table first, then
        the finished ring newest-first (ids can recur across requeues —
        the newest holder is the one an operator is asking about)."""
        with self._lock:
            for tr in self._live.values():
                if tr.req_id == req_id:
                    return tr
            for tr in reversed(self._ring):
                if tr.req_id == req_id:
                    return tr
        return None

    def find_ctx(self, ctx: str) -> List[Trace]:
        """Every trace carrying this fleet context, oldest first — one
        stream's member attempts (requeues/failovers/migrations each
        begin a fresh member-side trace under the SAME ctx)."""
        with self._lock:
            out = [tr for tr in self._ring if tr.ctx == ctx]
            out += [tr for tr in self._live.values() if tr.ctx == ctx]
        return out

    def export_spans(self, traces: List[Trace]) -> List[dict]:
        """JSON-able span export for cross-process stitching: event
        timestamps rebased onto the WALL clock (the only axis two
        processes share), one dict per trace."""
        offset = time.time() - time.monotonic()
        out = []
        for tr in traces:
            evs = list(tr.events)  # engine thread may still append; copy
            out.append({
                "req_id": tr.req_id, "user": tr.user, "model": tr.model,
                "kind": tr.kind, "ctx": tr.ctx, "origin": tr.origin,
                "outcome": tr.outcome, "finished": tr.finished,
                "dropped": tr.dropped,
                "events": [[name, t + offset, args]
                           for name, t, args in evs],
            })
        return out

    def export_chrome(self) -> dict:
        """Chrome trace-event JSON (the chrome://tracing 'JSON Array
        Format' wrapped in an object): consecutive events of a request
        become complete ("X") spans named after the phase they open; the
        terminal event is an instant ("i") mark. tid = req_id, so each
        request renders as its own row."""
        events: List[dict] = []
        for tr in self.traces():
            evs = list(tr.events)  # engine thread may still append; copy
            tid = tr.req_id
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": f"req {tr.req_id} {tr.user} "
                                 f"{tr.model or '?'} [{tr.kind}]"},
            })
            for i, (name, t, args) in enumerate(evs):
                ts = (t - self.epoch) * 1e6  # Chrome wants microseconds
                ev = {"name": name, "pid": 1, "tid": tid, "ts": ts,
                      "cat": tr.kind}
                if args:
                    ev["args"] = args
                if i + 1 < len(evs):
                    ev["ph"] = "X"
                    ev["dur"] = (evs[i + 1][1] - t) * 1e6
                else:
                    ev["ph"] = "i"
                    ev["s"] = "t"
                events.append(ev)
            if tr.dropped:
                events.append({
                    "name": f"{tr.dropped} events dropped", "ph": "i",
                    "s": "t", "pid": 1, "tid": tid,
                    "ts": (evs[-1][1] - self.epoch) * 1e6 if evs else 0,
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Fleet stitching: merge one stream's spans from every process into ONE
# timeline under the client's stable rid (GET /debug/trace/{rid}).
# ---------------------------------------------------------------------------

def stitch_events(spans: List[dict], root_origin: str) -> List[tuple]:
    """One contiguous (name, t_wall, args) event list from a stream's
    exported spans. The ROOT span (the router's, under the client rid)
    contributes everything including its terminal; member spans
    contribute their lifecycle events but NOT their terminals (a member
    attempt's `cancelled` is a routing ack — eviction, migration commit
    — not the client outcome) and not their `enqueue` duplicates. The
    result is sorted with the root terminal pinned last, so
    attribution.phase_totals over it sums EXACTLY to the client-observed
    end-to-end wall clock: the fleet-wide attribution invariant,
    handoffs included."""
    root_events: List[tuple] = []
    member_events: List[tuple] = []
    for span in spans:
        is_root = span.get("origin") == root_origin
        for name, t, args in span.get("events", ()):
            tagged = dict(args or {})
            tagged.setdefault("origin", span.get("origin", "?"))
            if is_root:
                root_events.append((name, t, tagged))
            elif name not in attribution.TERMINAL_EVENTS \
                    and name != "enqueue":
                member_events.append((name, t, tagged))
    if not root_events:
        # No root span (a member asked about its own rid): fall back to
        # the raw union so the timeline is still readable.
        merged = sorted(member_events, key=lambda e: e[1])
        return merged
    terminal = None
    if root_events and root_events[-1][0] in attribution.TERMINAL_EVENTS:
        terminal = root_events.pop()
    merged = sorted(root_events + member_events, key=lambda e: e[1])
    if terminal is not None:
        # The terminal closes the chain; clock skew must never let a
        # member event trail it (phase_totals stops at the terminal).
        t_end = max([terminal[1]] + [t for _, t, _ in merged])
        merged.append((terminal[0], t_end, terminal[2]))
    return merged


def merged_chrome(spans: List[dict], root_origin: str = "router") -> dict:
    """Chrome trace-event JSON over a stream's spans from EVERY process:
    one row (tid) per origin, plus a `stitched` summary whose phases_ms
    sum to the client-observed e2e (the fleet attribution invariant)."""
    origins = sorted({s.get("origin", "?") for s in spans},
                     key=lambda o: (o != root_origin, o))
    t0 = min((ev[1] for s in spans for ev in s.get("events", ())),
             default=0.0)
    events: List[dict] = []
    for s in spans:
        tid = origins.index(s.get("origin", "?")) + 1
        evs = s.get("events", ())
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": f"{s.get('origin', '?')} req "
                             f"{s.get('req_id')} {s.get('user', '')}"},
        })
        for i, (name, t, args) in enumerate(evs):
            ev = {"name": name, "pid": 1, "tid": tid,
                  "ts": (t - t0) * 1e6, "cat": s.get("kind", "generate")}
            if args:
                ev["args"] = args
            if i + 1 < len(evs):
                ev["ph"] = "X"
                ev["dur"] = max(0.0, (evs[i + 1][1] - t) * 1e6)
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            events.append(ev)
    stitched_events = stitch_events(spans, root_origin)
    phases = attribution.phase_totals(stitched_events)
    outcome = None
    root = next((s for s in spans if s.get("origin") == root_origin), None)
    if root is not None:
        outcome = root.get("outcome")
    e2e_ms = ((stitched_events[-1][1] - stitched_events[0][1]) * 1e3
              if len(stitched_events) >= 2 else 0.0)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "stitched": {
            "ctx": spans[0].get("ctx") if spans else None,
            "origins": origins,
            "outcome": outcome,
            "e2e_ms": round(e2e_ms, 3),
            "phases_ms": {p: round(ms, 3) for p, ms in phases.items()},
            "phase_sum_ms": round(sum(phases.values()), 3),
            "events": [
                {"name": name, "t_ms": round((t - t0) * 1e3, 3),
                 **({"args": args} if args else {})}
                for name, t, args in stitched_events
            ],
        },
    }
