"""Continuous-batching engine: end-to-end generation, batching, cancellation."""

import time

import numpy as np
import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.engine import TPUEngine
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.engine.request import FinishReason, Request
from ollamamq_tpu.ops.sampling import SamplingParams
from testutil import collect


def small_cfg(**kw):
    defaults = dict(
        model="test-tiny", max_slots=4, num_pages=64, page_size=8,
        max_pages_per_seq=16, prefill_buckets=(16, 32, 64),
        max_new_tokens=8, decode_steps_per_iter=4,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


@pytest.fixture(scope="module")
def engine():
    eng = TPUEngine(small_cfg(), blocklist_path=None)
    eng.start()
    yield eng
    eng.stop()


def run_request(eng, user="u", model="test-tiny", prompt="hello world",
                max_tokens=8, stop=(), timeout=60):
    tok = eng.runtimes[next(iter(eng.runtimes))].tokenizer
    rid = eng.core.enqueue(user, "127.0.0.1", model)
    req = Request(rid, user, model, tok.encode(prompt),
                  SamplingParams(max_tokens=max_tokens, stop=tuple(stop)))
    eng.submit(req)
    return collect(req, timeout), req


def test_generate_end_to_end(engine):
    items, req = run_request(engine, prompt="abc", max_tokens=6)
    assert items[-1].kind == "done"
    assert items[-1].finish_reason in (FinishReason.LENGTH, FinishReason.STOP)
    assert len(req.generated_ids) <= 6
    assert req.stats.ttft_ms > 0
    # All pages reclaimed after finish.
    rt = engine.runtimes["test-tiny"]
    assert rt.active_count() == 0


def test_deterministic_greedy(engine):
    i1, r1 = run_request(engine, prompt="determinism", max_tokens=5)
    i2, r2 = run_request(engine, prompt="determinism", max_tokens=5)
    assert r1.generated_ids == r2.generated_ids  # greedy => identical


def test_concurrent_requests_share_batch(engine):
    """Multiple in-flight requests are decoded together (continuous batching)."""
    tok = engine.runtimes["test-tiny"].tokenizer
    reqs = []
    for i in range(4):
        user = f"user{i}"
        rid = engine.core.enqueue(user, "", "test-tiny")
        req = Request(rid, user, "test-tiny", tok.encode(f"prompt {i}"),
                      SamplingParams(max_tokens=12))
        reqs.append(req)
    for r in reqs:
        engine.submit(r)
    for r in reqs:
        items = collect(r)
        assert items[-1].kind == "done"
        assert len(r.generated_ids) <= 12
    snap = engine.core.snapshot()
    for i in range(4):
        assert snap["users"][f"user{i}"]["processed"] >= 1


def test_cancellation_reclaims_pages():
    # Dedicated engine with a long context so generation is still in flight
    # when the cancel lands (the shared engine's 128-token ctx drains too
    # fast on CPU).
    eng = TPUEngine(
        small_cfg(num_pages=512, max_pages_per_seq=128, decode_steps_per_iter=1),
        blocklist_path=None,
    )
    eng.start()
    try:
        rt = eng.runtimes["test-tiny"]
        rt.tokenizer.eos_id = -1  # never sample EOS: keep the seq running
        free_before = rt.alloc.free_pages
        tok = rt.tokenizer
        rid = eng.core.enqueue("canceller", "", "test-tiny")
        req = Request(rid, "canceller", "test-tiny", tok.encode("to be cancelled"),
                      SamplingParams(max_tokens=10_000))
        eng.submit(req)
        # Wait until it's actually generating, then cancel.
        deadline = time.monotonic() + 60
        while not req.stats.first_token_at and time.monotonic() < deadline:
            time.sleep(0.01)
        assert req.stats.first_token_at, "never started generating"
        eng.cancel(rid)
        items = collect(req)
        assert items[-1].finish_reason == FinishReason.CANCELLED
        deadline = time.monotonic() + 10
        while rt.alloc.free_pages < free_before and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rt.alloc.free_pages == free_before  # KV pages reclaimed
        snap = eng.core.snapshot()
        assert snap["users"]["canceller"]["dropped"] >= 1
    finally:
        eng.stop()


def test_late_block_drops_queued_and_midgen():
    """Blocking a user AFTER their requests are enqueued drops every one of
    them — the mid-generation slot and the queued request — with pages
    reclaimed and dropped counted (reference late re-check,
    dispatcher.rs:503-512)."""
    eng = TPUEngine(
        small_cfg(max_slots=1, num_pages=512, max_pages_per_seq=128,
                  decode_steps_per_iter=1),
        blocklist_path=None,
    )
    eng.start()
    try:
        rt = eng.runtimes["test-tiny"]
        rt.tokenizer.eos_id = -1  # keep the mid-gen sequence running
        free_before = rt.alloc.free_pages
        tok = rt.tokenizer
        rid1 = eng.core.enqueue("mallory", "", "test-tiny")
        r1 = Request(rid1, "mallory", "test-tiny", tok.encode("one"),
                     SamplingParams(max_tokens=10_000))
        eng.submit(r1)
        deadline = time.monotonic() + 60
        while not r1.stats.first_token_at and time.monotonic() < deadline:
            time.sleep(0.01)
        assert r1.stats.first_token_at, "never started generating"
        # Second request queues behind the single busy slot.
        rid2 = eng.core.enqueue("mallory", "", "test-tiny")
        r2 = Request(rid2, "mallory", "test-tiny", tok.encode("two"),
                     SamplingParams(max_tokens=10_000))
        eng.submit(r2)
        eng.core.block_user("mallory")
        eng.notify()
        i1 = collect(r1)
        i2 = collect(r2)
        assert i1[-1].finish_reason == FinishReason.CANCELLED
        assert i2[-1].finish_reason == FinishReason.CANCELLED
        deadline = time.monotonic() + 10
        while rt.alloc.free_pages < free_before and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rt.alloc.free_pages == free_before  # KV pages reclaimed
        snap = eng.core.snapshot()
        assert snap["users"]["mallory"]["dropped"] >= 2
        assert snap["users"]["mallory"]["queued"] == 0
    finally:
        eng.stop()


def test_cancel_while_queued(engine):
    """Cancel before admission: dropped, never prefilled (late re-check)."""
    tok = engine.runtimes["test-tiny"].tokenizer
    rid = engine.core.enqueue("early-cancel", "", "test-tiny")
    req = Request(rid, "early-cancel", "test-tiny", tok.encode("x"))
    req.cancelled.set()
    engine.submit(req)
    items = collect(req)
    assert items[-1].finish_reason == FinishReason.CANCELLED
    assert req.generated_ids == []


def test_per_request_seed_reproducible(engine):
    """Same seed + temperature>0 => identical tokens across runs; different
    seed => different stream (VERDICT r1 item 7: OpenAI `seed` semantics)."""
    tok = engine.runtimes["test-tiny"].tokenizer

    def run_seeded(user, seed):
        rid = engine.core.enqueue(user, "", "test-tiny")
        req = Request(rid, user, "test-tiny", tok.encode("seeded"),
                      SamplingParams(max_tokens=8, temperature=1.0, seed=seed))
        engine.submit(req)
        collect(req)
        return req.generated_ids

    a = run_seeded("seed-a", 1234)
    b = run_seeded("seed-b", 1234)
    c = run_seeded("seed-c", 4321)
    assert a == b, f"same seed diverged: {a} vs {b}"
    assert a != c, f"different seeds collided: {a}"


def test_unknown_model_stuck_then_cancelled(engine):
    """A request for an unloaded model waits in queue rather than failing
    ("stuck in queue", dispatcher.rs:467-473); cancel drains it."""
    tok = engine.runtimes["test-tiny"].tokenizer
    rid = engine.core.enqueue("stuck-user", "", "no-such-model")
    req = Request(rid, "stuck-user", "no-such-model", tok.encode("hi"))
    engine.submit(req)
    time.sleep(0.3)  # give the engine loop time — it must NOT serve this
    assert req.stream.get_nowait() is None
    snap = engine.core.snapshot()
    assert snap["users"]["stuck-user"]["queued"] == 1
    engine.cancel(rid)
    items = collect(req, timeout=10)
    assert items[-1].finish_reason == FinishReason.CANCELLED


def test_pallas_failure_falls_back_to_jnp():
    """An unproven Pallas decode path must not take serving down: the first
    failing dispatch flips the runtime to jnp attention and the request
    completes (VERDICT r1 weak #2 — serving-path fallback). On CPU the
    pallas kernel genuinely fails to compile, which is exactly the injected
    fault."""
    eng = TPUEngine(small_cfg(), blocklist_path=None)
    eng.start()
    try:
        rt = eng.runtimes["test-tiny"]
        rt.attn_impl = "pallas"  # pretend auto-select picked the kernel
        items, req = run_request(eng, user="pallas-u", max_tokens=4)
        assert items[-1].kind == "done", items[-1]
        assert rt.attn_impl == "jnp"  # compile probe failed => fell back
        assert not rt._pallas_proven
        # And it stays healthy for the next request.
        items2, _ = run_request(eng, user="pallas-u2", max_tokens=4)
        assert items2[-1].kind == "done"
    finally:
        eng.stop()


def test_embed_admitted_while_decode_saturated():
    """An embed request must be served while every decode slot is busy —
    embeds are stateless forwards with their own capacity pool, so a full
    decode batch must not park them in the queue."""
    eng = TPUEngine(small_cfg(max_slots=1, decode_steps_per_iter=1),
                    blocklist_path=None)
    eng.start()
    try:
        tok = eng.runtimes["test-tiny"].tokenizer
        # Occupy the ONLY decode slot with a long generation.
        gen = eng.enqueue_request("genuser", "", "test-tiny",
                                  prompt_tokens=tok.encode("long"),
                                  sampling=SamplingParams(max_tokens=100))
        deadline = time.monotonic() + 60
        rt = eng.runtimes["test-tiny"]
        while rt.active_count() == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rt.active_count() == 1 and not rt.has_capacity("generate")
        # The embed must complete while that generation still runs.
        emb = eng.enqueue_request("embuser", "", "test-tiny",
                                  prompt_tokens=tok.encode("embed me"),
                                  sampling=SamplingParams(), kind="embed")
        items = collect(emb, timeout=60)
        assert items[-1].kind == "done" and emb.embedding is not None
        assert gen.stats.finished_at == 0.0, \
            "generation finished first: embed waited on a decode slot"
        gen.cancelled.set()
    finally:
        eng.stop()


def test_stats_reports_every_chip(engine):
    """stats()['chips'] carries one row PER local device — not device 0
    standing in for the pod (VERDICT r3 weak #6)."""
    import jax

    chips = engine.stats()["chips"]
    assert len(chips) == len(jax.local_devices()) == 8
    assert [c["id"] for c in chips] == sorted(c["id"] for c in chips)
    for c in chips:
        assert {"device", "id", "process", "hbm_used", "hbm_total"} <= set(c)


def test_real_engine_embed_on_generative():
    """The REAL engine serves /api/embed on a GENERATIVE model (causal
    forward + mean pool, ModelRuntime.step_embed) — the reference's Ollama
    backends embed with llama models, so embed-on-llama must work, and the
    fake engine's serving both kinds now mirrors the real one."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from ollamamq_tpu.server.app import Server

    async def main():
        eng = TPUEngine(small_cfg(), blocklist_path=None)
        eng.start()
        cl = TestClient(TestServer(Server(eng, timeout_s=60).build_app()))
        await cl.start_server()
        try:
            r = await cl.post("/api/embed",
                              json={"model": "test-tiny", "input": ["a", "bb"]})
            assert r.status == 200
            body = await r.json()
            assert len(body["embeddings"]) == 2
            v = np.asarray(body["embeddings"][0])
            assert v.shape[0] > 0
            np.testing.assert_allclose(np.linalg.norm(v), 1.0, rtol=1e-4)
            # Unknown model still rejects at the API layer.
            r = await cl.post("/api/embed",
                              json={"model": "no-such", "input": "a"})
            assert r.status in (400, 404)
        finally:
            await cl.close()
            eng.stop()

    asyncio.run(main())


def test_embed_input_too_long_errors_only_that_request():
    """An oversized embed input errors THAT request; other users' pending
    embeds still succeed (no _fail_runtime blast radius — ADVICE r1)."""
    eng = TPUEngine(small_cfg(model="test-tiny-embed"),
                    models={"test-tiny-embed": None}, blocklist_path=None)
    eng.start()
    try:
        rt = eng.runtimes["test-tiny-embed"]
        max_len = rt.cfg.max_seq_len  # 512 for test-tiny-embed
        rid1 = eng.core.enqueue("big", "", "test-tiny-embed")
        r1 = Request(rid1, "big", "test-tiny-embed",
                     list(range(3, 3 + max_len + 10)), SamplingParams(),
                     kind="embed")
        rid2 = eng.core.enqueue("ok", "", "test-tiny-embed")
        r2 = Request(rid2, "ok", "test-tiny-embed", [3, 4, 5],
                     SamplingParams(), kind="embed")
        eng.submit(r1)
        eng.submit(r2)
        i1 = collect(r1)
        i2 = collect(r2)
        assert i1[-1].kind == "error" and "exceeds" in i1[-1].error
        assert i2[-1].kind == "done" and r2.embedding
    finally:
        eng.stop()


def test_prompt_too_long_errors(engine):
    items, req = run_request(engine, prompt="x" * 500)  # > largest bucket 64
    assert items[-1].kind == "error"
    assert "exceeds" in items[-1].error


def test_max_context_finishes_length(engine):
    items, req = run_request(engine, prompt="ctx", max_tokens=10_000)
    assert items[-1].kind == "done"
    assert items[-1].finish_reason == FinishReason.LENGTH
    # max context = min(max_pages_per_seq*page_size, model max) = 128
    assert len(req.prompt_tokens) + len(req.generated_ids) <= 128 + 1


def test_fake_engine_stream_and_embed():
    eng = FakeEngine(small_cfg(), models={"test-tiny": None})
    eng.start()
    try:
        rid = eng.core.enqueue("u", "", "test-tiny")
        tok = eng.runtimes["test-tiny"].tokenizer
        req = Request(rid, "u", "test-tiny", tok.encode("hi"),
                      SamplingParams(max_tokens=5))
        eng.submit(req)
        items = collect(req, timeout=10)
        text = "".join(i.text for i in items if i.kind == "token")
        assert text == "word0 word1 word2 word3 word4 "
        assert items[-1].kind == "done"

        rid2 = eng.core.enqueue("u", "", "test-tiny")
        req2 = Request(rid2, "u", "test-tiny", tok.encode("embed me"), kind="embed")
        eng.submit(req2)
        collect(req2, timeout=10)
        assert req2.embedding is not None
        assert abs(sum(x * x for x in req2.embedding) - 1.0) < 1e-6
    finally:
        eng.stop()


def test_fake_engine_stop_string():
    eng = FakeEngine(small_cfg(), models={"test-tiny": None})
    eng.start()
    try:
        tok = eng.runtimes["test-tiny"].tokenizer
        rid = eng.core.enqueue("u", "", "test-tiny")
        req = Request(rid, "u", "test-tiny", tok.encode("hi"),
                      SamplingParams(max_tokens=16, stop=("word3",)))
        eng.submit(req)
        items = collect(req, timeout=10)
        text = "".join(i.text for i in items if i.kind == "token")
        assert text == "word0 word1 word2 "
        assert items[-1].finish_reason == FinishReason.STOP
    finally:
        eng.stop()


def test_vip_priority_through_engine():
    """VIP user's requests jump the queue end-to-end (slow fake engine)."""
    eng = FakeEngine(small_cfg(max_slots=1), models={"test-tiny": None},
                     token_latency_s=0.01)
    eng.start()
    try:
        tok = eng.runtimes["test-tiny"].tokenizer
        eng.core.set_vip("vip")
        order = []
        reqs = []
        for user in ("a", "b", "vip", "c"):
            rid = eng.core.enqueue(user, "", "test-tiny")
            req = Request(rid, user, "test-tiny", tok.encode(user),
                          SamplingParams(max_tokens=2))
            reqs.append((user, req))
        for _, r in reqs:
            eng.submit(r)
        for user, r in reqs:
            collect(r, timeout=20)
            order.append((user, r.stats.first_token_at))
        by_start = [u for u, _ in sorted(order, key=lambda x: x[1])]
        assert by_start[0] == "vip"
    finally:
        eng.stop()


def test_oversized_prompt_rejected_cleanly(engine):
    """A prompt over max_context must error its own request only — no page
    leak, no collateral damage to other requests (code-review regression)."""
    rt = engine.runtimes["test-tiny"]
    free_before = rt.alloc.free_pages
    # 200 tokens: fits the shared engine's largest bucket (64)? No — but use
    # a prompt that fits the bucket yet exceeds max_context if possible;
    # here max_context=128 > bucket 64, so the bucket check fires. Both
    # paths must produce a clean ERROR.
    items, req = run_request(engine, prompt="y" * 300)
    assert items[-1].kind == "error"
    assert rt.alloc.free_pages == free_before
    # Engine still serves new work afterwards.
    items2, _ = run_request(engine, prompt="ok", max_tokens=3)
    assert items2[-1].kind == "done"


def test_stream_overflow_treated_as_disconnect():
    """A consumer that never reads must not wedge the engine (bounded
    stream; overflow == client-gone)."""
    from ollamamq_tpu.engine.request import TokenStream, StreamItem

    s = TokenStream(maxsize=4)
    for i in range(10):
        s.push(StreamItem("token", text=f"t{i}"))
    assert s.overflowed
    s.push(StreamItem("done"))
    items = s.drain()
    assert items[-1].kind == "done"  # terminal item still delivered


def test_processing_gauge_not_corrupted_by_precancel():
    """Dropping a never-started request must not decrement another
    request's processing count (code-review regression)."""
    eng = FakeEngine(small_cfg(), models={"test-tiny": None}, token_latency_s=0.05)
    eng.start()
    try:
        tok = eng.runtimes["test-tiny"].tokenizer
        # One long-running request...
        rid1 = eng.core.enqueue("gauge-user", "", "test-tiny")
        r1 = Request(rid1, "gauge-user", "test-tiny", tok.encode("a"),
                     SamplingParams(max_tokens=16))
        eng.submit(r1)
        deadline = time.monotonic() + 10
        while not r1.stats.first_token_at and time.monotonic() < deadline:
            time.sleep(0.01)
        # ...and a second one cancelled before admission.
        rid2 = eng.core.enqueue("gauge-user", "", "test-tiny")
        r2 = Request(rid2, "gauge-user", "test-tiny", tok.encode("b"))
        r2.cancelled.set()
        eng.submit(r2)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if any(i.kind in ("done", "error") for i in r2.stream.drain()):
                break
            time.sleep(0.01)
        snap = eng.core.snapshot()
        u = snap["users"]["gauge-user"]
        assert u["processing"] == 1  # r1 still counted as processing
        assert u["dropped"] == 1
        collect(r1, timeout=20)
    finally:
        eng.stop()


def test_long_prompt_chunked_prefill(engine):
    """Prompts beyond the largest bucket stream through chunked prefill
    (ceiling is now the paged context, not the bucket)."""
    # buckets max 64; max_context 128 => a 100-token prompt must work.
    items, req = run_request(engine, prompt="z" * 97, max_tokens=4)  # 98 tokens
    assert items[-1].kind == "done"
    assert len(req.generated_ids) >= 1
    # Deterministic equivalence: same text via the short path is impossible
    # (>bucket), but the engine must still be consistent run to run.
    items2, req2 = run_request(engine, prompt="z" * 97, max_tokens=4)
    assert req.generated_ids == req2.generated_ids


def test_chunked_prefill_interleaves_with_decode():
    """A long-prompt prefill must not starve concurrent decode streams:
    chunks advance one per tick while other slots keep decoding."""
    eng = TPUEngine(
        small_cfg(num_pages=256, max_pages_per_seq=32, prefill_buckets=(16,),
                  decode_steps_per_iter=1),
        blocklist_path=None,
    )
    eng.start()
    try:
        rt = eng.runtimes["test-tiny"]
        rt.tokenizer.eos_id = -1
        tok = rt.tokenizer
        # A short request starts decoding first...
        r1 = eng.enqueue_request("short", "", "test-tiny",
                                 prompt_tokens=tok.encode("hi"),
                                 sampling=SamplingParams(max_tokens=200))
        deadline = time.monotonic() + 60
        while not r1.stats.first_token_at and time.monotonic() < deadline:
            time.sleep(0.01)
        assert r1.stats.first_token_at
        n_before = len(r1.generated_ids)
        # ...then a long prompt (> bucket 16) arrives and chunk-prefills.
        r2 = eng.enqueue_request("long", "", "test-tiny",
                                 prompt_tokens=tok.encode("w" * 120),
                                 sampling=SamplingParams(max_tokens=3))
        items2 = collect(r2)
        assert items2[-1].kind == "done"
        # The short request kept decoding during the chunked prefill.
        assert len(r1.generated_ids) > n_before
        eng.cancel(r1.req_id)
        collect(r1)
    finally:
        eng.stop()


def test_cancel_during_chunked_prefill():
    """Cancelling mid-chunk frees the reserved slot and its pages."""
    eng = TPUEngine(
        small_cfg(num_pages=256, max_pages_per_seq=32, prefill_buckets=(16,)),
        blocklist_path=None,
    )
    eng.start()
    try:
        rt = eng.runtimes["test-tiny"]
        tok = rt.tokenizer
        free_before = rt.alloc.free_pages
        req = eng.enqueue_request("c", "", "test-tiny",
                                  prompt_tokens=tok.encode("w" * 200),
                                  sampling=SamplingParams(max_tokens=3))
        # Wait until chunking started, then cancel.
        deadline = time.monotonic() + 60
        while not rt.chunking and time.monotonic() < deadline:
            time.sleep(0.005)
        eng.cancel(req.req_id)
        items = collect(req)
        assert items[-1].finish_reason in (FinishReason.CANCELLED,)
        deadline = time.monotonic() + 10
        while rt.alloc.free_pages < free_before and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rt.alloc.free_pages == free_before
        assert not rt.reserved_slots
    finally:
        eng.stop()


def test_batched_prefill_same_results_as_serial():
    """A burst of same-bucket prompts prefilled together must produce the
    same tokens as when submitted one by one (greedy, deterministic)."""
    def run(burst: bool):
        eng = TPUEngine(small_cfg(max_slots=8, num_pages=128), blocklist_path=None)
        eng.start()
        try:
            tok = eng.runtimes["test-tiny"].tokenizer
            reqs = []
            prompts = [f"prompt number {i}" for i in range(4)]
            for i, p in enumerate(prompts):
                req = eng.enqueue_request(f"u{i}", "", "test-tiny",
                                          prompt_tokens=tok.encode(p),
                                          sampling=SamplingParams(max_tokens=5))
                reqs.append(req)
                if not burst:
                    collect(req)  # serialize: finish before next submit
            for r in reqs:
                # Serial requests were fully collected at submit time —
                # re-collecting their consumed streams would just burn
                # the full collect timeout per request.
                if not burst:
                    continue
                if not any(i.kind in ("done", "error") for i in r.stream.drain()):
                    collect(r)
            return [r.generated_ids for r in reqs]
        finally:
            eng.stop()

    serial = run(burst=False)
    burst = run(burst=True)
    assert serial == burst


def test_kv_pool_pressure_waits_and_recovers():
    """More demand than KV pages: excess requests wait (not fail), then get
    served as pages free — the capacity analogue of 'stuck in queue'."""
    # Pool: 15 usable pages; each request needs ~2 (prompt+headroom), and
    # decode extends. 8 concurrent requests oversubscribe the pool.
    eng = TPUEngine(
        small_cfg(max_slots=8, num_pages=16, max_pages_per_seq=4,
                  decode_steps_per_iter=1),
        blocklist_path=None,
    )
    eng.start()
    try:
        tok = eng.runtimes["test-tiny"].tokenizer
        reqs = []
        for i in range(8):
            reqs.append(eng.enqueue_request(
                f"p{i}", "", "test-tiny",
                prompt_tokens=tok.encode(f"pressure {i}"),
                sampling=SamplingParams(max_tokens=12),
            ))
        done = 0
        for r in reqs:
            items = collect(r, timeout=120)
            assert items[-1].kind == "done", items[-1]
            done += 1
        assert done == 8
        rt = eng.runtimes["test-tiny"]
        assert rt.alloc.used_pages == 0  # everything reclaimed
        snap = eng.core.snapshot()
        assert all(snap["users"][f"p{i}"]["processed"] == 1 for i in range(8))
    finally:
        eng.stop()


def test_repeat_penalty_suppresses_repeats():
    """With an extreme repeat_penalty, greedy decode never re-emits a token
    already in the context (prompt or generated) — llama.cpp semantics."""
    eng = TPUEngine(small_cfg(num_pages=128, max_pages_per_seq=16),
                    blocklist_path=None)
    eng.start()
    try:
        rt = eng.runtimes["test-tiny"]
        rt.tokenizer.eos_id = -1
        tok = rt.tokenizer
        prompt = tok.encode("penalty check")
        req = eng.enqueue_request(
            "p", "", "test-tiny", prompt_tokens=prompt,
            sampling=SamplingParams(max_tokens=20, repeat_penalty=1e6))
        items = collect(req)
        assert items[-1].kind == "done"
        gen = req.generated_ids
        assert len(gen) == len(set(gen)), f"repeated token in {gen}"
        assert not (set(gen) & set(prompt)), "re-emitted a prompt token"

        # Control: penalty off CAN repeat (greedy on random weights loops).
        req2 = eng.enqueue_request(
            "p2", "", "test-tiny", prompt_tokens=prompt,
            sampling=SamplingParams(max_tokens=20, repeat_penalty=1.0))
        collect(req2)
        assert req2.generated_ids != gen
    finally:
        eng.stop()
