"""ollamamq_tpu — a TPU-native LLM serving framework.

A brand-new framework with the capabilities of Chleba/ollamaMQ (per-user FIFO
queuing, fair-share scheduling with VIP/Boost, model-aware routing, dual
Ollama `/api/*` + OpenAI `/v1/*` API surfaces, streaming, health monitoring,
user/IP blocking, admin TUI) — but the pool of HTTP-proxied backends is
replaced by an in-tree JAX/XLA continuous-batching inference engine running
on TPU: prefill + paged-KV decode, tensor-parallel collectives over ICI,
a token-level batch scheduler fed by the per-user fair-share queues.

Reference capability map: /root/reference/src/{main,dispatcher,tui}.rs
(studied for behavior only; architecture here is TPU-first).
"""

__version__ = "0.5.0"


def __getattr__(name):
    """Lazy public API (importing the engine pulls in jax; keep bare
    `import ollamamq_tpu` cheap for tooling)."""
    if name == "TPUEngine":
        from ollamamq_tpu.engine.engine import TPUEngine

        return TPUEngine
    if name == "FakeEngine":
        from ollamamq_tpu.engine.fake import FakeEngine

        return FakeEngine
    if name == "Server":
        from ollamamq_tpu.server.app import Server

        return Server
    if name == "EngineConfig":
        from ollamamq_tpu.config import EngineConfig

        return EngineConfig
    if name == "MODEL_CONFIGS":
        from ollamamq_tpu.config import MODEL_CONFIGS

        return MODEL_CONFIGS
    raise AttributeError(name)
