"""Offline decision-journal analyzer + deterministic replay harness.

    python -m ollamamq_tpu.tools.journal <command> [args]

Commands over a spilled journal (--journal-file JSONL, or a file written
by `record`):

    tail FILE      raw records (filters: --n/--req-id/--user/--kind)
    explain FILE   per-decision human explanations (same filters)
    stats FILE     batch occupancy + padding-waste + fair-share audit
    merge FILE...  interleave multiple fleet spills (router + members)
                   into ONE arrival-normalized timeline (--out FILE,
                   default stdout): records sort on their shared
                   monotonic clock, re-sequence, carry src/src_seq/
                   src_tick provenance, and get a rebased virtual tick
                   (the PR-11 gap-capped normalization) — so tail/
                   explain/stats run FLEET-WIDE over the merged file,
                   the live-journal roll-up next to `check`'s audit
    check FILE...  invariant checker (exit 1 on any violation); fleet
                   journals additionally pin zero-drop: every stream a
                   replica_eject/replica_failover touched must reach a
                   terminal record (check_no_dropped_streams), and each
                   recovered/migrated stream exactly ONE terminal
                   (check_stream_attribution). Multiple files run the
                   audit across the union — the fleet roll-up: pass the
                   router's spill AND every member's. Sampled spills
                   (--journal-sample < 1) are detected off the journal
                   meta; the batch-ordinal starvation check is skipped
                   for them (batch records are sampled), everything
                   else — page conservation, slot assignment, zero-drop
                   — reads self-contained records and stays binding.

Record/replay (the determinism acceptance loop):

    record FILE [--seed N] [--requests N]
        drive a seeded chaos run — bursty arrivals over a bounded queue
        against a FakeRuntime engine with a seeded fault plan (injected
        step faults => retries and poisons; admission caps => sheds) —
        SYNCHRONOUSLY (one virtual tick at a time, no engine thread), and
        spill the journal to FILE. Synchronous driving is what makes the
        decision stream a pure function of (seed, arrival sequence).

    replay FILE
        re-drive a `record`-ed run from the journal's own arrival
        sequence (enqueue + admission-shed records) under the same fault
        plan, and assert the replayed decision sequence is IDENTICAL
        (telemetry/journal.py decision_signature). Exit 0 on a perfect
        match, 1 with the first divergence printed otherwise.

    simulate FILE --scheduler X
        the offline policy evaluator: re-drive a recorded run's arrival
        sequence under an ALTERNATIVE scheduling policy (fcfs/srpt/edf)
        and report counterfactual p50/p99 TTFT/TPOT and queue-wait (in
        virtual ticks) against the recorded run, plus the simulated
        run's invariant check and decision-signature digest. Running the
        same simulate twice is deterministic (identical signature), and
        `simulate --scheduler fcfs` of an fcfs recording IS a replay —
        so the promotion story is: record a trace, simulate every
        policy, ship the winner behind --scheduler. Accepts LIVE
        --journal-file spills too (not just `record` traces): arrivals
        are tick-normalized relative to the first one (idle gaps capped)
        and the engine shape is read off the spill's journal_meta, so
        the counterfactual runs over production traffic.

Stdlib + engine imports only on demand: tail/explain/stats/check need no
jax and no engine.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple

from ollamamq_tpu.config import SCHEDULERS
from ollamamq_tpu.telemetry.journal import (EVENTS, Journal, batch_stats,
                                            check_invariants,
                                            decision_signature, explain,
                                            fair_share_audit, load_jsonl)

# The chaos scenario's engine shape: small on purpose (4 slots, bounded
# queue) so a couple dozen arrivals saturate it and every degradation
# decision — shed, retry, poison — shows up in the journal.
_SCENARIO_ENGINE = {"max_slots": 4, "max_queued": 6,
                    "max_queued_per_user": 3, "step_retries": 1}
# Injected step faults: the whole fake step raises, driving the engine's
# retry-then-poison containment path deterministically (call-count
# triggered, so wall-clock never enters the decision stream).
_SCENARIO_FAULTS = {"seed": 0, "faults": [
    {"site": "step", "kind": "exception", "every": 7, "times": 4},
]}

# The bimodal scenario: many short interactive requests + a few long
# batch ones over a tiny slot pool and an UNBOUNDED queue — the regime
# where SRPT-style shortest-predicted-remaining-first beats FIFO on p99
# TTFT (a long output parked in a slot makes the shorts behind it wait).
# No injected faults: the counterfactual readout is pure ordering.
_BIMODAL_ENGINE = {"max_slots": 4, "max_queued": 0,
                   "max_queued_per_user": 0, "step_retries": 1}
_BIMODAL_FAULTS = {"seed": 0, "faults": []}


def check_no_dropped_streams(records: List[dict]) -> List[str]:
    """Fleet zero-drop invariant (end-of-run semantics): every stream a
    replica failure OR a KV migration touched must reach a terminal
    record. The fleet router journals under each stream's ORIGINAL
    router request id — stable across failovers, requeues, and
    migrations — so the audit is a straight pairing:

      - a `replica_failover` / `migrate_export` / `migrate_import` /
        `recover_replay` (outcome "replayed") whose req never reaches
        finish / shed / deadline_drop / poison by the end of the journal
        is a dropped stream;
      - a `migrate_export` resolved by NEITHER `migrate_import` nor
        `migrate_abort` nor a terminal for its req is an orphaned
        two-phase handoff (source state parked forever).

    Run this on COMPLETE journals (a finished bench/chaos run, a drained
    spill) — a live ring mid-failover would report in-flight streams as
    violations, which is why this lives here and not in the health
    monitor's live invariant sweep. A journal cut short by a process
    crash legitimately leaves touched streams pending — the multi-file
    `check` roll-up resolves those against the RESTARTED process's
    spill (a `recover_replay` whose wal_rid names the cut stream)."""
    pending, open_handoff = _dropped_streams(records)
    bad = [
        f"req {rid} stream DROPPED: replica_failover/migration at seq {seq}"
        " with no terminal record (finish/shed/deadline_drop/poison) by "
        "journal end"
        for rid, seq in sorted(pending.items())
    ]
    bad += [
        f"req {rid} migration ORPHANED: migrate_export at seq {seq} never "
        "resolved by migrate_import/migrate_abort or a terminal record"
        for rid, seq in sorted(open_handoff.items())
    ]
    return bad


def _dropped_streams(records: List[dict]) -> Tuple[dict, dict]:
    """(pending, open_handoff) rid->seq maps behind the zero-drop audit."""
    pending: dict = {}  # rid -> seq of the last failover/migration touch
    open_handoff: dict = {}  # rid -> seq of an unresolved migrate_export
    terminal = ("finish", "shed", "deadline_drop", "poison")
    for r in records:
        kind = r.get("kind")
        rid = r.get("req_id")
        if rid is None:
            continue
        if kind == "replica_failover":
            pending[rid] = r.get("seq", "?")
        elif kind == "recover_replay" and r.get("outcome") == "replayed":
            # The WAL zero-drop contract: a recovered stream must reach
            # its terminal like any other (outcome "finished"/"failed"
            # records ARE the terminal story for their streams).
            pending[rid] = r.get("seq", "?")
        elif kind == "migrate_export":
            pending[rid] = r.get("seq", "?")
            open_handoff[rid] = r.get("seq", "?")
        elif kind == "migrate_import":
            pending[rid] = r.get("seq", "?")
            open_handoff.pop(rid, None)
        elif kind == "migrate_abort":
            open_handoff.pop(rid, None)
        elif kind in terminal:
            pending.pop(rid, None)
            open_handoff.pop(rid, None)
    return pending, open_handoff


def check_regroup_pairing(records: List[dict]) -> List[str]:
    """Tiered-fleet regroup audit (end-of-run semantics, like the
    zero-drop checker): every `tier_regroup` phase="start" must resolve
    to a "done" or an "aborted" for the same replica by journal end — a
    start left hanging is a member parked in `draining` with its tier
    move never committed nor rolled back. A done/aborted with no start
    in the window is tolerated (the start may have rotated out of a
    ring tail); the pairing only binds on full spills."""
    open_regroups: dict = {}  # replica -> seq of the unresolved start
    bad: List[str] = []
    for r in records:
        if r.get("kind") != "tier_regroup":
            continue
        rep = r.get("replica")
        phase = r.get("phase")
        if phase == "start":
            prev = open_regroups.get(rep)
            if prev is not None:
                bad.append(
                    f"replica {rep} regroup started at seq "
                    f"{r.get('seq', '?')} while the start at seq {prev} "
                    "was never resolved (one regroup at a time)")
            open_regroups[rep] = r.get("seq", "?")
        elif phase in ("done", "aborted"):
            open_regroups.pop(rep, None)
    bad += [
        f"replica {rep} regroup UNRESOLVED: tier_regroup start at seq "
        f"{seq} never reached done/aborted by journal end"
        for rep, seq in sorted(open_regroups.items())
    ]
    return bad


def check_scale_pairing(records: List[dict]) -> List[str]:
    """Elastic-fleet scale audit (end-of-run semantics, like the
    regroup pairing): every `scale_up` / `scale_down` phase="start"
    must resolve to a "done" or an "aborted" for the same replica by
    journal end — a scale_up left hanging is a spawn that never joined
    (nor journaled its failure); a scale_down left hanging is a member
    parked in `draining` that never left the fleet. A `preempt_notice`
    must be followed by a scale_down start for the same replica — a
    notice with no retire means the reclamation window lapsed with the
    member still serving. Resolutions with no start in the window are
    tolerated (ring tails); the pairing binds on full spills."""
    open_scales: dict = {}   # (direction, replica) -> seq of the start
    notices: dict = {}       # replica -> seq of an unresolved notice
    bad: List[str] = []
    for r in records:
        kind = r.get("kind")
        rep = r.get("replica")
        if kind == "preempt_notice":
            notices[rep] = r.get("seq", "?")
            continue
        if kind not in ("scale_up", "scale_down"):
            continue
        phase = r.get("phase")
        key = (kind, rep)
        if phase == "start":
            prev = open_scales.get(key)
            if prev is not None:
                bad.append(
                    f"replica {rep} {kind} started at seq "
                    f"{r.get('seq', '?')} while the start at seq {prev} "
                    "was never resolved (one scale op at a time)")
            open_scales[key] = r.get("seq", "?")
            if kind == "scale_down":
                notices.pop(rep, None)
        elif phase in ("done", "aborted"):
            open_scales.pop(key, None)
    bad += [
        f"replica {rep} {kind} UNRESOLVED: start at seq {seq} never "
        "reached done/aborted by journal end"
        for (kind, rep), seq in sorted(open_scales.items(),
                                       key=lambda kv: str(kv[0]))
    ]
    bad += [
        f"replica {rep} preemption UNRESOLVED: preempt_notice at seq "
        f"{seq} never followed by a scale_down (the termination notice "
        "lapsed with the member still in the fleet)"
        for rep, seq in sorted(notices.items())
    ]
    return bad


def check_takeover_pairing(records: List[dict]) -> List[str]:
    """Router-HA takeover audit (end-of-run semantics, like the regroup
    pairing): every `router_takeover` phase="begin" must resolve to a
    "done" or an "aborted" by journal end — a begin left hanging is a
    promotion that crashed mid-ladder, which means the fleet may have
    members re-registered to an epoch no live router serves. Takeovers
    are serial per process (a standby promotes at most once, a chained
    standby journals into its own spill), so a begin while another is
    open is a bug outright. Resolutions with no begin are tolerated
    (ring tails); the pairing binds on full spills."""
    open_seq = None
    bad: List[str] = []
    for r in records:
        if r.get("kind") != "router_takeover":
            continue
        phase = r.get("phase")
        if phase == "begin":
            if open_seq is not None:
                bad.append(
                    f"router takeover began at seq {r.get('seq', '?')} "
                    f"while the begin at seq {open_seq} was never "
                    "resolved (one promotion at a time)")
            open_seq = r.get("seq", "?")
        elif phase in ("done", "aborted"):
            open_seq = None
    if open_seq is not None:
        bad.append(
            f"router takeover UNRESOLVED: begin at seq {open_seq} never "
            "reached done/aborted by journal end (promotion crashed "
            "mid-ladder; members may be fenced to an unserved epoch)")
    return bad


def check_epoch_monotonicity(records: List[dict]) -> List[str]:
    """Fencing-epoch audit: the epoch is the fleet's split-brain guard,
    so a takeover "done" must carry an epoch strictly above the epoch
    it took over from, successive takeovers in one spill must strictly
    increase, and a member may only fence callers STRICTLY older than
    the epoch it holds (`stale_epoch < epoch` on every `epoch_fence`)
    — a fence at equal epochs would reject the live router itself.
    Runs per spill; `check_files` adds the cross-spill duplicate check
    (the same epoch completed by two different routers)."""
    bad: List[str] = []
    last_done = None
    for r in records:
        kind = r.get("kind")
        seq = r.get("seq", "?")
        if kind == "router_takeover" and r.get("phase") == "done":
            epoch = r.get("epoch")
            if epoch is None:
                bad.append(
                    f"router_takeover done at seq {seq} carries no "
                    "epoch (fencing unverifiable)")
                continue
            frm = r.get("from_epoch")
            if frm is not None and epoch <= frm:
                bad.append(
                    f"router_takeover done at seq {seq} did not advance "
                    f"the epoch ({frm} -> {epoch}): a promoted standby "
                    "serving an old epoch cannot fence the zombie "
                    "primary")
            if last_done is not None and epoch <= last_done:
                bad.append(
                    f"router_takeover done at seq {seq} epoch {epoch} "
                    f"not above the previous takeover's epoch "
                    f"{last_done} (epochs must be strictly monotonic)")
            last_done = epoch if last_done is None else max(last_done,
                                                            epoch)
        elif kind == "epoch_fence":
            epoch = r.get("epoch")
            stale = r.get("stale_epoch")
            if epoch is not None and stale is not None and stale >= epoch:
                bad.append(
                    f"epoch_fence at seq {seq} rejected epoch {stale} "
                    f"while holding {epoch}: a member may only fence "
                    "strictly older epochs")
    return bad


def check_stream_attribution(records: List[dict]) -> List[str]:
    """Every stream a recovery touched must reach exactly ONE terminal:
    a failed-over/migrated/WAL-recovered stream with two `finish`
    records was served twice (a zombie attempt survived its handoff),
    one with zero is a drop (check_no_dropped_streams reports those).
    Keyed per journal: request-id spaces are process-local, so callers
    merging multiple spills run this per file, not on the raw union."""
    touched = set()
    finishes: dict = {}
    for r in records:
        rid = r.get("req_id")
        if rid is None:
            continue
        kind = r.get("kind")
        if kind in ("replica_failover", "migrate_export") \
                or (kind == "recover_replay"
                    and r.get("outcome") == "replayed") \
                or (kind == "migrate_import" and r.get("what") != "prefix"):
            touched.add(rid)
        elif kind == "finish":
            finishes[rid] = finishes.get(rid, 0) + 1
    return [
        f"req {rid} has {finishes[rid]} terminal finish records: a "
        "recovered/migrated stream must be attributed to exactly one "
        "terminal"
        for rid in sorted(touched)
        if finishes.get(rid, 0) > 1
    ]


def _gen_arrivals(seed: int, n: int) -> List[dict]:
    import random

    rng = random.Random(seed)
    out, tick = [], 0
    for _ in range(n):
        # Bursty: several arrivals share a tick, then a small gap.
        if rng.random() < 0.4:
            tick += rng.randrange(1, 4)
        out.append({"tick": tick, "user": f"u{rng.randrange(4)}",
                    "n_prompt": rng.randrange(3, 40),
                    "max_tokens": rng.choice((2, 4, 8, 12))})
    return out


def _gen_bimodal(seed: int, n: int) -> List[dict]:
    """Bimodal arrivals: ~1 in 5 is a long batch request (the fake
    runtime's 16-token ceiling, long prompt), the rest short interactive
    ones (2 tokens, short prompt). Longs bias EARLY so FIFO parks them
    in the tiny slot pool ahead of the short burst — exactly the regime
    the SRPT counterfactual is supposed to win."""
    import random

    rng = random.Random(seed)
    out, tick = [], 0
    for i in range(n):
        if rng.random() < 0.5:
            tick += 1
        # Front-loaded longs: the first arrivals of each burst are the
        # batch jobs, mirroring "one long request parked ahead of a
        # burst of short interactive ones".
        long = rng.random() < (0.5 if i < n // 6 else 0.12)
        if long:
            out.append({"tick": tick, "user": f"batch{rng.randrange(2)}",
                        "n_prompt": rng.randrange(24, 60),
                        "max_tokens": 16})
        else:
            out.append({"tick": tick, "user": f"chat{rng.randrange(6)}",
                        "n_prompt": rng.randrange(3, 10),
                        "max_tokens": 2})
    return out


def _arrivals_from_records(records: List[dict]) -> List[dict]:
    """The recorded arrival sequence: every accepted enqueue AND every
    admission-shed attempt (a shed arrival never became a Request, but
    replay must re-attempt it to reproduce the shed decision)."""
    out = []
    for r in records:
        if r["kind"] == "enqueue" or (
                r["kind"] == "shed"
                and r.get("reason") in ("queue_full", "user_queue_full")):
            out.append({"tick": r.get("tick", 0), "user": r.get("user", "?"),
                        "n_prompt": int(r.get("n_prompt") or 4),
                        "max_tokens": int(r.get("max_tokens") or 8)})
    return out


# A live engine's tick is its loop-iteration counter: it starts wherever
# the process happens to be and idles forward between arrivals, so a raw
# spill's tick axis is offset and full of dead gaps. Normalization caps
# each inter-arrival gap here — wide enough that the engine drains
# between genuinely separated bursts, bounded so a quiet hour in a spill
# doesn't cost a million empty virtual ticks.
MAX_ARRIVAL_GAP_TICKS = 16


def normalize_arrival_ticks(arrivals: List[dict]) -> List[dict]:
    """Arrival-RELATIVE tick normalization for live spilled journals:
    rebase the first arrival to tick 0 and clamp every inter-arrival gap
    to MAX_ARRIVAL_GAP_TICKS, preserving order and coincidence (arrivals
    sharing a recorded tick still share a virtual one). Synthetic
    `record` traces are already compact and are replayed verbatim — this
    only runs when a journal carries no scenario meta."""
    out = []
    vtick = 0
    prev = None
    for a in arrivals:
        t = int(a.get("tick", 0))
        if prev is not None:
            vtick += min(max(0, t - prev), MAX_ARRIVAL_GAP_TICKS)
        prev = t
        out.append({**a, "tick": vtick})
    return out


# One merged virtual tick per this many seconds of wall-clock gap when
# interleaving fleet spills: per-process `tick` counters advance at
# each process's own loop rate, so the merged axis derives from the
# shared monotonic clock instead (≈ the router's 20ms idle wait per
# tick), with idle gaps capped like the PR-11 arrival normalization.
MERGE_TICK_S = 0.02


def merge_journals(paths: List[str]) -> Tuple[dict, List[dict]]:
    """Interleave several spilled journals (one fleet run's router +
    member files) into ONE timeline: records sort on their recorded
    monotonic `t` (CLOCK_MONOTONIC is system-wide on Linux, so spills
    from co-located processes share the axis; cross-host skew shows as
    interleave error, never record loss), re-sequence from 0, keep
    provenance (`src` = source file, `src_seq`/`src_tick` = original
    coordinates), and rebase `tick` onto one arrival-normalized virtual
    axis (gaps capped at MAX_ARRIVAL_GAP_TICKS). The result loads like
    any spill: tail/explain/stats consume it fleet-wide."""
    import os as _os

    rows = []
    sources = []
    for path in paths:
        meta, records = load_jsonl(path)
        src = _os.path.basename(path)
        src_meta = {"file": src, "records": len(records)}
        if meta.get("sample") is not None:
            src_meta["sample"] = meta["sample"]
        sources.append(src_meta)
        for r in records:
            rows.append((float(r.get("t") or 0.0), src, r))
    rows.sort(key=lambda x: x[0])  # stable: equal t keeps per-file order
    merged: List[dict] = []
    vtick = 0
    prev_t: Optional[float] = None
    for seq, (t, src, r) in enumerate(rows):
        if prev_t is not None and t > prev_t:
            vtick += min(MAX_ARRIVAL_GAP_TICKS,
                         int((t - prev_t) / MERGE_TICK_S))
        prev_t = t
        rec = dict(r)
        rec["src"] = src
        rec["src_seq"] = r.get("seq")
        rec["src_tick"] = r.get("tick")
        rec["seq"] = seq
        rec["tick"] = vtick
        merged.append(rec)
    return {"version": 1, "merged_from": sources}, merged


def drive_chaos(arrivals: List[dict], fault_plan: dict, engine: dict,
                journal: Journal):
    """Synchronously drive a FakeRuntime engine through the arrival
    sequence, journaling every decision into `journal`. Deterministic by
    construction: virtual ticks, zero retry backoff, call-count-triggered
    faults — wall-clock never reaches a decision site."""
    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.engine import QueueFullError
    from ollamamq_tpu.engine.fake import FakeEngine
    from ollamamq_tpu.ops.sampling import SamplingParams
    from ollamamq_tpu.testing.faults import FaultPlan

    # A fault-free scenario (the bimodal scheduling trace) passes an
    # empty rule list; FaultPlan requires >= 1 rule, so that means "no
    # plan" rather than an empty one.
    plan = (FaultPlan.from_dict(fault_plan)
            if (fault_plan or {}).get("faults") else None)
    ecfg = EngineConfig(model="test-tiny", retry_backoff_s=0.0,
                        fault_plan=plan, **engine)
    eng = FakeEngine(ecfg, blocklist_path=None)
    eng.journal = journal  # the caller's journal (file spill, meta)
    for rt in eng._step_targets():
        rt.journal = journal
    by_tick: dict = {}
    for a in arrivals:
        by_tick.setdefault(int(a["tick"]), []).append(a)
    last = max(by_tick) if by_tick else 0
    tick, guard = 0, 0
    while True:
        journal.tick = tick
        for a in by_tick.get(tick, ()):
            try:
                eng.enqueue_request(
                    a["user"], "", "test-tiny",
                    prompt_tokens=[1] * int(a["n_prompt"]),
                    sampling=SamplingParams(max_tokens=int(a["max_tokens"])))
            except QueueFullError:
                pass  # the shed decision is already journaled
        eng._admit()
        for rt in eng._step_targets():
            rt.check_cancellations(eng.core)
            if rt.has_work():
                try:
                    rt.step(eng.core)
                except Exception:
                    # Same containment contract as FakeEngine._loop.
                    eng._fail_runtime(rt, "engine step failed")
        busy = (eng.core.total_queued() > 0
                or any(rt.has_work() for rt in eng._step_targets()))
        if tick >= last and not busy:
            break
        tick += 1
        guard += 1
        if guard > 100_000:
            raise RuntimeError("chaos drive did not converge")
    journal.close()
    return eng


def record_chaos(path: str, seed: int = 0, requests: int = 24,
                 trace: str = "chaos", scheduler: str = "fcfs") -> Journal:
    """Record one seeded run to `path` (JSONL + scenario meta); returns
    the in-memory journal. trace="chaos" is the degradation storm
    (bounded queue + injected step faults); trace="bimodal" is the
    scheduling workload (short interactive + long batch arrivals, no
    faults) the `simulate` counterfactual evaluator feeds on. The
    scheduler lands in the scenario meta so replay re-drives under the
    SAME policy."""
    if trace == "bimodal":
        arrivals = _gen_bimodal(seed, requests)
        engine, faults = dict(_BIMODAL_ENGINE), dict(_BIMODAL_FAULTS)
    else:
        arrivals = _gen_arrivals(seed, requests)
        engine, faults = dict(_SCENARIO_ENGINE), dict(_SCENARIO_FAULTS)
    engine["scheduler"] = scheduler
    meta = {"scenario": {"seed": seed, "requests": requests,
                         "trace": trace, "engine": engine,
                         "fault_plan": faults}}
    journal = Journal(capacity=max(4096, requests * 64), path=path,
                      meta=meta)
    drive_chaos(arrivals, faults, engine, journal)
    return journal


def simulate_journal(path: str, scheduler: str):
    """Counterfactually re-drive a recorded run's arrival sequence under
    `scheduler` (the offline policy evaluator behind the promotion
    workflow). Returns (recorded_records, simulated_records). Same
    machinery as replay — synchronous virtual-tick driving — so the
    simulated decision stream is a pure function of (recording, policy):
    the same simulate twice yields an identical decision_signature.

    Works on BOTH journal shapes: a `record`-ed trace replays its
    scenario verbatim (engine shape + fault plan from the meta), and a
    LIVE engine's spill is re-driven over its normalized arrival
    sequence (arrival-relative ticks, the engine shape read off the
    spill's own journal_meta, no faults) — so the promotion workflow
    runs over production traffic, not just synthetic traces."""
    meta, records = load_jsonl(path)
    scenario = meta.get("scenario")
    if scenario:
        arrivals = _arrivals_from_records(records)
        engine = dict(scenario["engine"])
        faults = scenario["fault_plan"]
    else:
        # Live spill: no scenario meta. Arrival-relative ticks + the
        # journal header's engine shape make it re-drivable; injected
        # faults are not (wall-clock device failures don't replay).
        arrivals = normalize_arrival_ticks(_arrivals_from_records(records))
        if not arrivals:
            raise SystemExit(
                f"{path} holds no enqueue records: nothing to simulate")
        engine = {"max_slots": int(meta.get("max_slots") or 4),
                  "max_queued": 0, "max_queued_per_user": 0,
                  "step_retries": 1}
        faults = {}
    engine["scheduler"] = scheduler
    fresh = Journal(capacity=max(4096, len(records) * 4 + 64))
    drive_chaos(arrivals, faults, engine, fresh)
    return records, fresh.tail(None)


def counterfactual_stats(records: List[dict]) -> dict:
    """Per-request latency stats in VIRTUAL TICKS off a synchronously
    driven journal: TTFT = enqueue -> install tick (the fake runtime
    emits the first token in its install tick), queue wait = enqueue ->
    admission pop, TPOT = decode ticks per emitted token. Tick deltas,
    not wall-clock — the whole point of the synchronous driver is that
    wall-clock never reaches a decision."""
    enq: dict = {}
    adm: dict = {}
    inst: dict = {}
    fin: dict = {}
    toks: dict = {}
    for r in records:
        rid = r.get("req_id")
        if rid is None:
            continue
        t = int(r.get("tick", 0))
        kind = r.get("kind")
        if kind == "enqueue":
            enq.setdefault(rid, t)
        elif kind == "admit":
            adm.setdefault(rid, t)
        elif kind == "install":
            inst.setdefault(rid, t)
        elif kind == "finish":
            fin.setdefault(rid, t)
            toks.setdefault(rid, int(r.get("tokens") or 0))

    def pctl(xs, q):
        if not xs:
            return None
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    ttfts = [inst[r] - enq[r] for r in inst if r in enq]
    waits = [adm[r] - enq[r] for r in adm if r in enq]
    tpots = [(fin[r] - inst[r]) / max(1, toks.get(r, 1))
             for r in fin if r in inst]
    return {
        "served": len(ttfts),
        "ttft_p50": pctl(ttfts, 0.5),
        "ttft_p99": pctl(ttfts, 0.99),
        "ttft_mean": (round(sum(ttfts) / len(ttfts), 2) if ttfts else None),
        "tpot_p50": (round(pctl(tpots, 0.5), 3) if tpots else None),
        "tpot_p99": (round(pctl(tpots, 0.99), 3) if tpots else None),
        "queue_wait_mean": (round(sum(waits) / len(waits), 2)
                            if waits else None),
    }


def replay_journal(path: str):
    """Re-drive the recorded run; returns (ok, recorded_sig, replayed_sig,
    first_divergence_index_or_None)."""
    meta, records = load_jsonl(path)
    scenario = meta.get("scenario")
    if not scenario:
        raise SystemExit(
            f"{path} carries no scenario meta: replay needs a journal "
            "written by `tools/journal record` (a live engine's spill "
            "lacks the engine shape + fault plan to re-drive)")
    arrivals = _arrivals_from_records(records)
    fresh = Journal(capacity=max(4096, len(records) + 64))
    drive_chaos(arrivals, scenario["fault_plan"], scenario["engine"], fresh)
    rec_sig = decision_signature(records)
    rep_sig = decision_signature(fresh.tail(None))
    if rec_sig == rep_sig:
        return True, rec_sig, rep_sig, None
    div = next((i for i, (a, b) in enumerate(zip(rec_sig, rep_sig))
                if a != b), min(len(rec_sig), len(rep_sig)))
    return False, rec_sig, rep_sig, div


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _filtered(records: List[dict], args) -> List[dict]:
    if args.req_id is not None:
        records = [r for r in records if r.get("req_id") == args.req_id]
    if args.user:
        records = [r for r in records if r.get("user") == args.user]
    if args.kind:
        records = [r for r in records if r.get("kind") == args.kind]
    if args.n and args.n > 0:
        records = records[-args.n:]
    return records


def _cmd_tail(args) -> int:
    _meta, records = load_jsonl(args.file)
    for r in _filtered(records, args):
        print(json.dumps(r))
    return 0


def _cmd_explain(args) -> int:
    _meta, records = load_jsonl(args.file)
    for r in _filtered(records, args):
        src = f" {r['src']}" if r.get("src") else ""  # merged spills
        print(f"[{r.get('seq', '?'):>6} t{r.get('tick', '?')}{src}] "
              f"{explain(r)}")
    return 0


def _cmd_merge(args) -> int:
    meta, merged = merge_journals(args.file)
    lines = [json.dumps({"journal_meta": meta})]
    lines += [json.dumps(r, default=str) for r in merged]
    if args.out and args.out != "-":
        with open(args.out, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
        srcs = ", ".join(s["file"] for s in meta["merged_from"])
        print(f"merged {len(merged)} records from {len(args.file)} "
              f"spill(s) ({srcs}) -> {args.out}")
    else:
        for line in lines:
            print(line)
    return 0


def _cmd_stats(args) -> int:
    _meta, records = load_jsonl(args.file)
    bs = batch_stats(records)
    print("batch stats:")
    for k, v in bs.items():
        print(f"  {k}: {v}")
    print("fair-share audit (per user):")
    audit = fair_share_audit(records)
    for user in sorted(audit):
        row = audit[user]
        cells = "  ".join(f"{k}={v}" for k, v in row.items())
        print(f"  {user}: {cells}")
    kinds: dict = {}
    for r in records:
        kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
    print("events by kind:")
    for k in sorted(kinds, key=kinds.get, reverse=True):
        print(f"  {k}: {kinds[k]}")
    return 0


def check_files(paths: List[str]) -> Tuple[List[str], int]:
    """The fleet-wide audit roll-up over one or more spills (router +
    member journals of one run). Per-spill: the invariant checker
    (starvation skipped on sampled traces — batch records are sampled;
    everything else reads self-contained records and stays binding),
    the zero-drop audit, and the exactly-one-terminal attribution.
    Across the union: a stream left pending by a spill that ends in a
    process crash is resolved by the RESTARTED process's spill when a
    `recover_replay` names it via wal_rid — that is the WAL zero-drop
    contract spanning the crash. Returns (violations, total_records)."""
    from ollamamq_tpu.telemetry.journal import STARVATION_BATCHES

    loaded = []
    notes: List[str] = []
    per_file_recovered: List[set] = []
    for path in paths:
        meta, records = load_jsonl(path)
        sampled = float(meta.get("sample") or 1.0) < 1.0
        loaded.append((path, records, sampled, meta))
        per_file_recovered.append({
            int(r["wal_rid"]) for r in records
            if r.get("kind") == "recover_replay"
            and r.get("wal_rid") is not None
            and r.get("outcome") in ("replayed", "finished")})
    bad: List[str] = []
    total = 0
    for idx, (path, records, sampled, _meta) in enumerate(loaded):
        tag = f"{path}: " if len(paths) > 1 else ""
        total += len(records)
        # Cross-crash resolution set: wal_rids recovered by OTHER spills
        # (a restarted process's journal resolves the crashed one's cut
        # streams — never its own: rid counters restart at 1, so a
        # spill's own wal_rids can collide with its fresh rids).
        recovered_wal_rids = set().union(
            *(s for j, s in enumerate(per_file_recovered) if j != idx),
            set())
        if sampled:
            notes.append(f"{tag}sampled trace (journal meta): "
                         "batch-ordinal starvation check skipped, all "
                         "other invariants binding")
        bad += [tag + v for v in check_invariants(
            records, starve_after=None if sampled else STARVATION_BATCHES)]
        if any(r.get("kind") == "tier_regroup" for r in records):
            bad += [tag + v for v in check_regroup_pairing(records)]
        if any(r.get("kind") in ("scale_up", "scale_down",
                                 "preempt_notice") for r in records):
            bad += [tag + v for v in check_scale_pairing(records)]
        if any(r.get("kind") == "router_takeover" for r in records):
            bad += [tag + v for v in check_takeover_pairing(records)]
        if any(r.get("kind") in ("router_takeover", "epoch_fence")
               for r in records):
            bad += [tag + v for v in check_epoch_monotonicity(records)]
        if not any(r.get("kind", "").startswith(("replica_", "migrate_",
                                                 "recover_"))
                   for r in records):
            continue
        pending, open_handoff = _dropped_streams(records)
        for rid, seq in sorted(pending.items()):
            if rid in recovered_wal_rids:
                continue  # resolved across the crash by WAL recovery
            bad.append(
                f"{tag}req {rid} stream DROPPED: replica_failover/"
                f"migration/recovery at seq {seq} with no terminal "
                "record by journal end and no recover_replay for it in "
                "any companion spill")
        bad += [
            f"{tag}req {rid} migration ORPHANED: migrate_export at seq "
            f"{seq} never resolved by migrate_import/migrate_abort or a "
            "terminal record"
            for rid, seq in sorted(open_handoff.items())
        ]
        bad += [tag + v for v in check_stream_attribution(records)]
    # Cross-spill epoch audit: the same epoch completed ("done") by two
    # different spills is split brain — two routers both believe they
    # own the fleet at that epoch. Standby replica files (journal_meta
    # carries replica_of) are byte copies of another spill and would
    # duplicate every record, so they are excluded here; the per-file
    # checks above still bind on them.
    done_epochs: dict = {}  # epoch -> path of the spill that did it
    for path, records, _sampled, meta in loaded:
        if meta.get("replica_of"):
            continue
        for r in records:
            if (r.get("kind") == "router_takeover"
                    and r.get("phase") == "done"
                    and r.get("epoch") is not None):
                ep = r["epoch"]
                prev = done_epochs.get(ep)
                if prev is not None and prev != path:
                    bad.append(
                        f"epoch {ep} taken over TWICE: router_takeover "
                        f"done in {prev} and {path} (split brain — two "
                        "routers promoted into the same epoch)")
                else:
                    done_epochs.setdefault(ep, path)
    for n in notes:
        print(n)
    return bad, total


def _cmd_check(args) -> int:
    files = args.file if isinstance(args.file, list) else [args.file]
    bad, total = check_files(files)
    if bad:
        print(f"{len(bad)} invariant violation(s):", file=sys.stderr)
        for b in bad:
            print(f"  - {b}", file=sys.stderr)
        return 1
    scope = (f"{len(files)} journal(s), " if len(files) > 1 else "")
    print(f"ok: {scope}{total} records, all invariants hold "
          "(pages conserved, no slot double-assignment, victim never VIP, "
          "sheds only over bounds, no starvation, no dropped streams, "
          "every recovered stream attributed to exactly one terminal)")
    return 0


def _cmd_simulate(args) -> int:
    import hashlib

    recorded, simulated = simulate_journal(args.file, args.scheduler)
    base = counterfactual_stats(recorded)
    cf = counterfactual_stats(simulated)
    sig = decision_signature(simulated)
    digest = hashlib.sha256(repr(sig).encode()).hexdigest()[:16]
    print(f"simulate --scheduler {args.scheduler}: {len(simulated)} "
          f"records, {len(sig)} decisions, "
          f"decision_signature {digest}")
    print("counterfactual vs recorded (virtual ticks):")
    print(f"  {'metric':<16} {'recorded':>10} {'simulated':>10} "
          f"{'delta':>10}")
    for k in ("served", "ttft_p50", "ttft_p99", "ttft_mean",
              "tpot_p50", "tpot_p99", "queue_wait_mean"):
        a, b = base.get(k), cf.get(k)
        delta = (round(b - a, 3)
                 if isinstance(a, (int, float)) and isinstance(b, (int, float))
                 else "-")
        print(f"  {k:<16} {str(a):>10} {str(b):>10} {str(delta):>10}")
    bad = check_invariants(simulated)
    if bad:
        print(f"{len(bad)} invariant violation(s) in the simulated run:",
              file=sys.stderr)
        for b in bad:
            print(f"  - {b}", file=sys.stderr)
        return 1
    print("simulated run invariant-clean")
    return 0


def _cmd_record(args) -> int:
    journal = record_chaos(args.file, seed=args.seed,
                           requests=args.requests, trace=args.trace,
                           scheduler=args.scheduler)
    recs = journal.tail(None)
    kinds: dict = {}
    for r in recs:
        kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
    print(f"recorded {journal.seq} decision records to {args.file} "
          f"(seed={args.seed}, {args.requests} arrivals)")
    print("  " + "  ".join(f"{k}={kinds[k]}" for k in sorted(kinds)))
    bad = check_invariants(recs)
    if bad:
        print(f"WARNING: {len(bad)} invariant violation(s) in the recorded "
              "run", file=sys.stderr)
        return 1
    return 0


def _cmd_replay(args) -> int:
    ok, rec_sig, rep_sig, div = replay_journal(args.file)
    if ok:
        print(f"replay deterministic: {len(rep_sig)} decisions identical")
        return 0
    print(f"REPLAY DIVERGED at decision {div} "
          f"(recorded {len(rec_sig)}, replayed {len(rep_sig)}):",
          file=sys.stderr)
    lo, hi = max(0, div - 2), div + 3
    for i in range(lo, hi):
        a = rec_sig[i] if i < len(rec_sig) else "<end>"
        b = rep_sig[i] if i < len(rep_sig) else "<end>"
        mark = " " if a == b else "!"
        print(f" {mark} [{i}] recorded={a}  replayed={b}", file=sys.stderr)
    return 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m ollamamq_tpu.tools.journal",
        description="decision-journal analyzer + deterministic replay")
    sub = p.add_subparsers(dest="cmd", required=True)

    def add_filters(sp):
        sp.add_argument("file")
        sp.add_argument("--n", type=int, default=0,
                        help="tail length (0 = all)")
        sp.add_argument("--req-id", type=int, default=None)
        sp.add_argument("--user", default="")
        sp.add_argument("--kind", default="", choices=("",) + EVENTS)

    for name, fn in (("tail", _cmd_tail), ("explain", _cmd_explain)):
        sp = sub.add_parser(name)
        add_filters(sp)
        sp.set_defaults(fn=fn)
    for name, fn in (("stats", _cmd_stats), ("replay", _cmd_replay)):
        sp = sub.add_parser(name)
        sp.add_argument("file")
        sp.set_defaults(fn=fn)
    sp = sub.add_parser("check")
    sp.add_argument("file", nargs="+",
                    help="one or more spilled journals; several run the "
                         "fleet-wide roll-up (router + member spills "
                         "audited as one run)")
    sp.set_defaults(fn=_cmd_check)
    sp = sub.add_parser("merge")
    sp.add_argument("file", nargs="+",
                    help="two or more spilled journals of ONE fleet run "
                         "(router + members) to interleave into a "
                         "single arrival-normalized timeline")
    sp.add_argument("--out", default="-",
                    help="merged JSONL destination ('-' = stdout); "
                         "tail/explain/stats then run fleet-wide over "
                         "it")
    sp.set_defaults(fn=_cmd_merge)
    sp = sub.add_parser("record")
    sp.add_argument("file")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--requests", type=int, default=24)
    sp.add_argument("--trace", choices=("chaos", "bimodal"),
                    default="chaos",
                    help="arrival workload: 'chaos' (degradation storm, "
                         "injected faults) or 'bimodal' (short "
                         "interactive + long batch requests, no faults "
                         "— the scheduling counterfactual's input)")
    sp.add_argument("--scheduler", choices=SCHEDULERS, default="fcfs",
                    help="policy the RECORDED run schedules under "
                         "(lands in the scenario meta so replay "
                         "re-drives it identically)")
    sp.set_defaults(fn=_cmd_record)
    sp = sub.add_parser("simulate")
    sp.add_argument("file")
    sp.add_argument("--scheduler", choices=SCHEDULERS, default="srpt",
                    help="counterfactual policy to re-drive the "
                         "recorded arrival sequence under; reports "
                         "p50/p99 TTFT/TPOT + queue-wait deltas vs the "
                         "recorded run")
    sp.set_defaults(fn=_cmd_simulate)
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
