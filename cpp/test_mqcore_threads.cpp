/* ThreadSanitizer harness for the native scheduler core: 8 threads hammer
 * every exported call concurrently for a fixed iteration budget. Built and
 * run by tests/test_tsan.py with -fsanitize=thread; any data race fails
 * the run. (The reference leaned on rustc for this assurance; a C++ core
 * needs TSAN.) */

#include <atomic>
#include <cstdio>
#include <string>
#include <thread>
#include <vector>

#include "mqcore.h"

int main() {
  mq_state *s = mq_new(nullptr);
  std::atomic<long> popped{0};
  std::vector<std::thread> ts;

  for (int t = 0; t < 4; ++t) {
    ts.emplace_back([s, t] {
      char user[32];
      std::snprintf(user, sizeof user, "user%d", t);
      for (int i = 0; i < 2000; ++i) {
        long long rid = mq_enqueue(s, user, "10.0.0.1", "llama3:8b", 1);
        if (rid > 0 && i % 7 == 0) mq_cancel(s, rid);
        if (i % 5 == 0) mq_mark_done(s, user, 17);
      }
    });
  }
  for (int c = 0; c < 2; ++c) {  // two competing consumers: pop-vs-pop races
    ts.emplace_back([s, &popped] {
      char u[512], m[512];
      for (int i = 0; i < 6000; ++i) {
        long long rid = mq_next(s, "llama3:8b\nqwen2.5:7b", u, sizeof u, m, sizeof m);
        if (rid > 0) {
          mq_mark_started(s, u);
          mq_mark_done(s, u, 3);
          popped.fetch_add(1);
        }
      }
    });
  }
  ts.emplace_back([s] {
    for (int i = 0; i < 500; ++i) {
      mq_block_user(s, "mallory");
      mq_is_user_blocked(s, "mallory");
      mq_unblock_user(s, "mallory");
      mq_set_vip(s, i % 2 ? "user1" : nullptr);
      mq_set_boost(s, i % 3 ? "user2" : nullptr);
      mq_set_fairness_mode(s, i % 2);
    }
  });
  ts.emplace_back([s] {
    std::string buf(1 << 20, '\0');
    for (int i = 0; i < 500; ++i) {
      mq_snapshot_json(s, buf.data(), (long long)buf.size());
      mq_total_queued(s);
      mq_queue_len(s, "user0");
    }
  });

  for (auto &th : ts) th.join();
  std::printf("OK popped=%ld total_queued=%lld\n", popped.load(),
              mq_total_queued(s));
  mq_destroy(s);
  return 0;
}
