"""Device mesh construction.

The reference's only parallelism is request-level load balancing across
HTTP backends (/root/reference/src/dispatcher.rs:434-482). Here parallelism
is a jax.sharding.Mesh over TPU chips with named axes:

  - "data":   replica/data parallelism (independent batches / model replicas)
  - "tensor": tensor parallelism within a replica — attention heads and MLP
              hidden dim sharded; XLA emits allgather/reduce-scatter over ICI
  - "seq":    sequence/context parallelism for long-context ring attention

Multi-host: `jax.distributed.initialize` is handled in
ollamamq_tpu.parallel.distributed; this module only arranges whatever
`jax.devices()` reports into a mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_TENSOR = "tensor"
AXIS_SEQ = "seq"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"


def make_mesh(
    dp: int = 1,
    tp: int = -1,
    sp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, pipe, seq, expert, tensor) mesh.

    `tp=-1` means "all devices not consumed by dp*pp*sp*ep". The tensor
    axis is innermost so TP collectives ride the fastest ICI links
    (adjacent chips); the pipe axis sits next to data (stage handoffs are
    one ppermute per microbatch step — the lowest-bandwidth traffic).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp == -1:
        if n % (dp * pp * sp * ep) != 0:
            raise ValueError(
                f"{n} devices not divisible by dp*pp*sp*ep={dp * pp * sp * ep}")
        tp = n // (dp * pp * sp * ep)
    k = dp * pp * sp * ep * tp
    if k > n:
        raise ValueError(f"dp*pp*sp*ep*tp={k} > {n} available devices")
    nproc = jax.process_count()
    if dp > 1 and nproc > 1:
        # Multi-host dp replica serving slices the mesh along the data axis
        # (one submesh per replica). jax.devices() is process-major, so the
        # default dp-outermost layout would give each replica the chips of
        # ONE host — a submesh the other processes can't participate in
        # (multi-controller jit requires every process to own addressable
        # shards). Give each dp slice (devices_per_process / dp) chips from
        # EVERY process instead; that requires dp to divide the per-process
        # chip count — fail loudly otherwise (a replica smaller than one
        # chip per process cannot span every process at all).
        if k % nproc != 0:
            raise ValueError(
                f"{k} mesh devices not divisible by {nproc} processes")
        per_proc = k // nproc
        if per_proc % dp != 0:
            raise ValueError(
                f"multi-host dp={dp} needs dp to divide the per-process "
                f"device count ({per_proc}): each replica must own chips "
                "on every process for its jit to be a valid "
                "multi-controller computation")
        arr = (np.asarray(_pick_per_process(devices, k, nproc, per_proc))
               .reshape(nproc, dp, per_proc // dp)
               .transpose(1, 0, 2)
               .reshape(dp, pp, sp, ep, tp))
    else:
        arr = np.asarray(devices[:k]).reshape(dp, pp, sp, ep, tp)
    return Mesh(arr, (AXIS_DATA, AXIS_PIPE, AXIS_SEQ, AXIS_EXPERT, AXIS_TENSOR))


def _pick_per_process(devices, k: int, nproc: int, per_proc: int):
    """The k devices for a multi-host dp mesh, process-major with exactly
    per_proc devices FROM EACH PROCESS. `devices[:k]` alone is wrong when
    k < len(devices): jax.devices() is process-major, so the first k could
    all come from the first host(s) and the (nproc, dp, ...) relabeling
    would silently produce replicas that don't span every process (ADVICE
    r3). Falls back to the positional split only when the device list
    doesn't actually carry nproc distinct process_indexes (single-process
    simulations of a process count, e.g. tests)."""
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
    if len(by_proc) != nproc:
        return devices[:k]
    short = {p: len(v) for p, v in by_proc.items() if len(v) < per_proc}
    if short:
        raise ValueError(
            f"multi-host dp mesh needs {per_proc} devices from every "
            f"process; process(es) {sorted(short)} have only "
            f"{sorted(short.values())}")
    return [d for p in sorted(by_proc) for d in by_proc[p][:per_proc]]


def replica_submesh(mesh: Mesh, r: int) -> Mesh:
    """Replica r's slice of the data axis (a [1, sp, tp] submesh) — THE
    derivation, shared by the engine's replica construction and the SPMD
    worker's reload path, which must agree on every host."""
    return Mesh(mesh.devices[r:r + 1], mesh.axis_names)


def single_device_mesh() -> Mesh:
    return make_mesh(dp=1, sp=1, tp=1, devices=jax.devices()[:1])


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def validate_tp_for_model(tp: int, num_kv_heads: int, num_heads: int) -> None:
    """TP must divide the head counts so shards stay aligned (MXU tiling).

    tp > num_kv_heads is allowed when tp % num_kv_heads == 0: the runtime
    duplicates each KV head tp/num_kv_heads times at load
    (weights.replicate_kv_heads) so every shard owns one copy — the
    replicated-group sharding, at the cost of that factor in KV-cache
    memory (e.g. qwen2.5's 4 KV heads on tp=8 cost 2x KV HBM)."""
    if num_heads % tp != 0:
        raise ValueError(f"num_heads={num_heads} not divisible by tp={tp}")
    if num_kv_heads % tp != 0 and tp % num_kv_heads != 0:
        raise ValueError(
            f"num_kv_heads={num_kv_heads} incompatible with tp={tp}: "
            "needs kv_heads % tp == 0 (sharded) or tp % kv_heads == 0 "
            "(replicated groups)"
        )
