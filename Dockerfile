# ollamaMQ-TPU runtime image.
#
# Unlike the reference's musl-static two-stage build (~10 MB runtime), a
# TPU serving image necessarily carries the JAX/XLA stack; the native
# serving core (cpp/) is compiled in a separate build stage.
#
# Build:  docker build -t ollamamq-tpu .
# Run:    see docker-compose.yml (TPU device access + env configuration)
FROM python:3.12-slim AS build

RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY cpp/ cpp/
RUN make -C cpp

FROM python:3.12-slim

# jax[tpu] pulls libtpu; pinned loosely — the serving code tracks jax>=0.9.
RUN pip install --no-cache-dir "jax[tpu]" aiohttp tokenizers safetensors \
    orbax-checkpoint numpy \
    -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

WORKDIR /app
COPY ollamamq_tpu/ ollamamq_tpu/
COPY cpp/*.h cpp/*.cpp cpp/Makefile cpp/
COPY --from=build /app/cpp/libmqcore.so cpp/
COPY scripts/ scripts/
COPY docker-entrypoint.sh .
RUN chmod +x docker-entrypoint.sh scripts/*.sh

EXPOSE 11434
ENTRYPOINT ["./docker-entrypoint.sh"]
