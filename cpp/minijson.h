/* Minimal recursive-descent JSON parser for the TUI's two data feeds
 * (the core snapshot from mq_snapshot_json and the engine-stats callback).
 * Not a general-purpose library: enough JSON for our own wire shapes. */
#ifndef MINIJSON_H
#define MINIJSON_H

#include <cctype>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace mj {

struct Value;
using ValuePtr = std::shared_ptr<Value>;

struct Value {
  enum Type { NUL, BOOL, NUM, STR, ARR, OBJ } type = NUL;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<ValuePtr> arr;
  std::map<std::string, ValuePtr> obj;

  bool is_null() const { return type == NUL; }
  double as_num(double d = 0) const { return type == NUM ? num : d; }
  long long as_int(long long d = 0) const {
    return type == NUM ? (long long)num : d;
  }
  const std::string &as_str(const std::string &d = "") const {
    static const std::string empty;
    return type == STR ? str : (d.empty() ? empty : d);
  }
  ValuePtr get(const std::string &k) const {
    auto it = obj.find(k);
    return it == obj.end() ? nullptr : it->second;
  }
};

class Parser {
 public:
  explicit Parser(const std::string &s) : s_(s) {}

  ValuePtr parse() {
    skip();
    return value();
  }

 private:
  const std::string &s_;
  size_t i_ = 0;

  void skip() {
    while (i_ < s_.size() && std::isspace((unsigned char)s_[i_])) ++i_;
  }
  char peek() { return i_ < s_.size() ? s_[i_] : '\0'; }
  char next() { return i_ < s_.size() ? s_[i_++] : '\0'; }

  ValuePtr value() {
    skip();
    char c = peek();
    if (c == '{') return object();
    if (c == '[') return array();
    if (c == '"') return string_v();
    if (c == 't' || c == 'f') return boolean();
    if (c == 'n') {
      i_ += 4;
      return std::make_shared<Value>();
    }
    return number();
  }

  ValuePtr object() {
    auto v = std::make_shared<Value>();
    v->type = Value::OBJ;
    next();  // {
    skip();
    if (peek() == '}') {
      next();
      return v;
    }
    while (i_ < s_.size()) {
      skip();
      auto key = string_v();
      skip();
      next();  // :
      v->obj[key->str] = value();
      skip();
      if (peek() == ',') {
        next();
        continue;
      }
      next();  // }
      break;
    }
    return v;
  }

  ValuePtr array() {
    auto v = std::make_shared<Value>();
    v->type = Value::ARR;
    next();  // [
    skip();
    if (peek() == ']') {
      next();
      return v;
    }
    while (i_ < s_.size()) {
      v->arr.push_back(value());
      skip();
      if (peek() == ',') {
        next();
        continue;
      }
      next();  // ]
      break;
    }
    return v;
  }

  ValuePtr string_v() {
    auto v = std::make_shared<Value>();
    v->type = Value::STR;
    next();  // "
    while (i_ < s_.size()) {
      char c = next();
      if (c == '"') break;
      if (c == '\\' && i_ < s_.size()) {
        char e = next();
        switch (e) {
          case 'n': v->str += '\n'; break;
          case 't': v->str += '\t'; break;
          case 'r': v->str += '\r'; break;
          case 'u': {
            // Keep it simple: skip the 4 hex digits, emit '?' for
            // non-ASCII escapes (TUI-safe).
            unsigned code = 0;
            for (int k = 0; k < 4 && i_ < s_.size(); ++k)
              code = code * 16 + (std::isdigit((unsigned char)s_[i_])
                                      ? s_[i_] - '0'
                                      : (std::tolower((unsigned char)s_[i_]) - 'a' + 10)),
              ++i_;
            if (code < 0x80) v->str += (char)code;
            else v->str += '?';
            break;
          }
          default: v->str += e;
        }
      } else {
        v->str += c;
      }
    }
    return v;
  }

  ValuePtr boolean() {
    auto v = std::make_shared<Value>();
    v->type = Value::BOOL;
    if (peek() == 't') {
      v->b = true;
      i_ += 4;
    } else {
      v->b = false;
      i_ += 5;
    }
    return v;
  }

  ValuePtr number() {
    auto v = std::make_shared<Value>();
    v->type = Value::NUM;
    size_t start = i_;
    while (i_ < s_.size() &&
           (std::isdigit((unsigned char)s_[i_]) || s_[i_] == '-' ||
            s_[i_] == '+' || s_[i_] == '.' || s_[i_] == 'e' || s_[i_] == 'E'))
      ++i_;
    v->num = std::stod(s_.substr(start, i_ - start));
    return v;
  }
};

inline ValuePtr parse(const std::string &s) {
  try {
    return Parser(s).parse();
  } catch (...) {
    return std::make_shared<Value>();
  }
}

}  // namespace mj
#endif
